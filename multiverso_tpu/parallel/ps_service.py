"""Cross-process async parameter server over DCN (host TCP service).

This is the reference's core architecture at multi-node scale — SURVEY.md §7
"hard part (a)": N worker processes push deltas / pull parameters against
tables sharded across server processes, per-request, asynchronously. Roles:

* :class:`PSService` — the Server+Communicator analog: a listener thread
  accepts peer connections; per-connection reader threads deserialize
  requests and dispatch to the owning shard (which applies the jitted
  updater on the local device), then reply on the same connection.
* :class:`PeerClient` — the Worker-side Communicator: one persistent
  connection per server process, a reader thread routing replies to
  waiters by msg_id (the reference's Waiter contract: a request completes
  when ALL touched servers replied).
* :class:`DistributedArrayTable` / :class:`DistributedMatrixTable` — worker
  handles that partition requests with the reference's offset arithmetic
  (contiguous / row ranges), serve the local shard directly (LocalForward),
  and fan out the rest over the wire.

Consistency contract = the reference's async mode: adds are applied by the
owning server in arrival order; gets see whatever has been applied (no
clocks). BSP across processes should use the collective path instead.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import os
import queue as _queue_mod
import selectors
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from multiverso_tpu.core.actor import Message, MsgType
from multiverso_tpu.core.options import AddOption, GetOption
from multiverso_tpu.core.table import ServerStore
from multiverso_tpu.core.updater import get_updater
from multiverso_tpu.core.zoo import Zoo
from multiverso_tpu.parallel.mesh import reference_server_offsets
from multiverso_tpu.parallel.net import recv_message, send_message
from multiverso_tpu.runtime.ffi import DeltaBuffer
from multiverso_tpu.telemetry import counter, gauge
from multiverso_tpu.telemetry.sketch import record_keys
from multiverso_tpu.utils.configure import get_flag
from multiverso_tpu.utils.dashboard import monitor
from multiverso_tpu.utils.log import check, log
from multiverso_tpu.utils.quantization import OneBitsFilter, SparseFilter
from multiverso_tpu.utils.locks import make_lock, make_rlock


class _TableSyncGate:
    """SyncServer clock gating for one table shard (ref src/server.cpp:68-222).

    Per-worker vector clocks for adds and gets; a request that is ahead of
    the lagging workers is CACHED (per-worker FIFO, preserving each
    worker's program order) and drained when the laggards catch up — the
    reference's cache-and-drain, which is mandatory here for the same
    reason it was there: the single dispatcher thread must never block on
    a gate whose unlocking traffic arrives through the same loop. Worker
    identity travels in the request exactly as the reference's
    AddOption/GetOption worker_id does (``updater.h:10-110``,
    ``sparse_matrix_table.cpp:36-43``): Adds carry it in the option
    scalars, BSP Gets append a worker-id blob; a message without either
    falls back to the sending rank.
    """

    def __init__(self, num_workers: int):
        from multiverso_tpu.core.sync_coordinator import VectorClock
        self._n = num_workers
        self._adds = VectorClock(num_workers)
        self._gets = VectorClock(num_workers)
        self.cached: Dict[int, "collections.deque"] = \
            collections.defaultdict(collections.deque)
        # Elastic membership (Control_Elastic): retired slots reusable by
        # later joins, and a version stamp drills can watch re-form on.
        self._free: List[int] = []
        self.version = 0

    def worker_of(self, msg: Message) -> int:
        if msg.type == MsgType.Request_Add and len(msg.data) > 1:
            w = int(msg.data[1][0])         # AddOption scalars, worker_id
        elif msg.type == MsgType.Request_Get and len(msg.data) > 1 \
                and msg.data[1].size:
            w = int(msg.data[1][0])         # GetOption analog blob
        else:
            w = max(msg.src, 0)
        return w % self._n    # an unstamped id must not kill the dispatcher

    def admissible(self, msg: Message) -> bool:
        if self.cached[self.worker_of(msg)]:
            return False        # program order: earlier ops still cached
        return self.head_admissible(msg)

    def head_admissible(self, msg: Message) -> bool:
        """Clock algebra only (used when draining a worker's queue head).

        Add from w applies only while w's get count is not ahead of the
        global min (ref ProcessAdd caching rule); Get from w serves only
        while w's add count is not ahead (ref ProcessGet) — so every
        worker's i-th Get sees identical parameters (src/server.cpp:61-67).
        """
        w = self.worker_of(msg)
        if msg.type == MsgType.Request_Add:
            return self._gets.value(w) <= self._gets.min()
        return self._adds.value(w) <= self._adds.min()

    def tick(self, msg: Message) -> None:
        if msg.type == MsgType.Request_Add:
            self._adds.tick(self.worker_of(msg))
        else:
            self._gets.tick(self.worker_of(msg))

    def finish(self, worker: int) -> None:
        """Server_Finish_Train: clocks to infinity so stragglers drain
        (ref src/server.cpp:190-213)."""
        self._adds.finish(worker % self._n)
        self._gets.finish(worker % self._n)

    # -- elastic membership (mirrors SyncCoordinator.join/leave) ----------
    def join(self, worker: "Optional[int]" = None) -> int:
        """Admit one worker into the LIVE clock group at the epoch floor;
        returns its slot id. All calls run on the single dispatcher
        thread, so membership flips atomically between ops. With an
        explicit ``worker`` the slot chosen by the membership LEADER
        (server 0) is adopted verbatim — every server must agree on the
        joiner's identity, so only the leader allocates ids."""
        inf = float("inf")
        add_floor, get_floor = self._adds.min(), self._gets.min()
        if add_floor == inf:            # group fully retired: newcomer
            add_floor = 0.0             # restarts the clocks from zero
        if get_floor == inf:
            get_floor = 0.0
        # Join at the COMMON floor, not each vector's independent min:
        # the independent mins can describe a mid-round hybrid state no
        # worker occupies, and a joiner initialized there deadlocks the
        # gates by issuing one op out of phase (see
        # SyncCoordinator.join — the elastic fuzz caught this).
        add_floor = get_floor = min(add_floor, get_floor)
        if worker is None:
            if self._free:
                w = min(self._free)     # deterministic reuse order
                self._free.remove(w)
            else:
                w = self._adds.add_slot()
                self._gets.add_slot()
        else:
            w = int(worker)
            while self._adds.size() <= w:   # pad to the leader's slot
                s = self._adds.add_slot(inf)    # count with retired
                self._gets.add_slot(inf)        # (joinable) slots
                self._free.append(s)
            if w in self._free:
                self._free.remove(w)
        self._adds.set(w, add_floor)
        self._gets.set(w, get_floor)
        self._n = self._adds.size()
        self.version += 1
        return w

    def leave(self, worker: int) -> None:
        """Retire a worker's clocks (the finish_train algebra) and free
        its slot for a later :meth:`join`. The leaver's still-gated cached
        ops are DROPPED: once its clocks are infinite they can never
        drain, and a graceful leaver has already waited out its ops (a
        SIGKILL-shaped one has no waiter left to answer)."""
        w = worker % self._n
        self._adds.finish(w)
        self._gets.finish(w)
        self.cached.pop(w, None)
        if w not in self._free:
            self._free.append(w)
        self.version += 1

    def status(self) -> Dict[str, object]:
        """Membership snapshot for drills/rollups (slots incl. retired)."""
        return {"slots": self._n, "free": sorted(self._free),
                "version": self.version}


# Dispatch-queue sentinel: re-examine deferred (early-arrival) requests.
_RECHECK = object()


class _SnapshotReq:
    """Dispatch-queue item: capture ``(store_state(), wal lsn)`` ON the
    dispatcher thread, atomically with respect to applies — the only
    thread that both applies adds and assigns WAL lsns. A snapshot taken
    anywhere else could include an add the captured lsn excludes (replay
    would double-apply it) or vice versa (replay would lose it)."""

    __slots__ = ("table_id", "event", "out")

    def __init__(self, table_id: int):
        self.table_id = table_id
        self.event = threading.Event()
        self.out: Dict[str, object] = {}

# Row-key sentinel on a Request_Get: BSP clock tick only, serve no rows
# (sent by row-routed tables to servers owning none of the touched rows so
# every worker's clock advances on every server uniformly).
TICK_GET_KEY = -2

# Row-key sentinel on a Request_Get: serve exactly the rows STALE for the
# requesting worker and mark them fresh — the reference SparseMatrixTable's
# server-side incremental whole-table Get (src/table/
# sparse_matrix_table.cpp:184-258) carried here over DCN. The worker id
# rides in the GetOption blob (msg.data[1]).
STALE_GET_KEY = -3

# Keyed variant: data = [[-4], [wid], keys] — serve only the STALE subset
# of the requested rows and mark those fresh (the reference's keyed
# UpdateGetState branch, :244-253). Reply carries the served rows' GLOBAL
# ids so the client knows which of its cached rows were refreshed.
STALE_ROWS_GET_KEY = -4


@functools.lru_cache(maxsize=256)
def _sketch_surface(table_id: int, kind: str) -> str:
    """Cached traffic-sketch surface name for one table shard's op
    stream (no per-request f-string on the dispatch path; surface
    cardinality = 2 x registered tables, hub-bounded)."""
    return f"ps.table_{table_id}.{kind}"


class _SparseShardState:
    """Per-worker staleness bitmap for one sparse table shard (ref
    ``up_to_date_[worker][row]``, sparse_matrix_table.cpp:184-197 — there
    per server process, here per PSService shard). All access is on the
    single dispatcher thread; no lock needed.

    Add semantics are the reference's EXACT UpdateAddState (:199-223):
    touched rows go stale for every worker EXCEPT the writer, whose bits
    are LEFT UNCHANGED. Forcing the writer's rows fresh would be a race:
    if another worker wrote the row after the writer's last pull, the
    writer's cache is missing that delta and only a re-pull can fix it —
    an own-write must not mask it. Plain-add clients additionally mirror
    their own delta into their cache (so rows that WERE fresh stay both
    fresh and correct); for stale rows and stateful updaters the next
    pull ships server truth, own delta included.
    """

    def __init__(self, num_workers: int, num_rows: int):
        self.stale = np.ones((num_workers, num_rows), dtype=bool)

    def on_add(self, local_rows: np.ndarray, worker: int) -> None:
        if 0 <= worker < self.stale.shape[0]:
            keep = self.stale[worker, local_rows].copy()
            self.stale[:, local_rows] = True
            self.stale[worker, local_rows] = keep
        else:       # unattributable writer: everyone is stale
            self.stale[:, local_rows] = True

    def take_stale(self, worker: int) -> np.ndarray:
        """Rows stale for ``worker``; marks them fresh (ref
        UpdateGetState, :226-258)."""
        w = worker % self.stale.shape[0]
        rows = np.flatnonzero(self.stale[w]).astype(np.int32)
        self.stale[w, rows] = False
        return rows

    def take_stale_among(self, worker: int,
                         local_rows: np.ndarray) -> np.ndarray:
        """The STALE subset of ``local_rows`` for ``worker``; marks those
        fresh (the reference's keyed UpdateGetState branch, :244-253)."""
        w = worker % self.stale.shape[0]
        local_rows = np.asarray(local_rows, dtype=np.int64)
        stale = local_rows[self.stale[w, local_rows]]
        self.stale[w, stale] = False
        return stale.astype(np.int32)


class PSService:
    """Owns local table shards; serves Get/Add requests from peers.

    Thread budget is FIXED at two regardless of world size (VERDICT r1
    weak #5 hardening): a selector IO thread reads every connection via the
    incremental frame decoder, and ONE dispatcher thread applies requests
    and writes replies. Single-threaded dispatch is also the reference's
    ordering model (the Server actor's mailbox loop, ``src/actor.cpp:14-55``
    — requests apply in arrival order). Backpressure: the IO→dispatch queue
    is bounded; when it fills, the IO thread stops draining sockets and TCP
    flow control pushes back on the senders.
    """

    MAX_QUEUE = 256       # undispatched requests before backpressure
    MAX_CONNS = 1024      # accepted connections (beyond: refused)
    MAX_WRITE_BUF = 64 << 20   # per-connection unread replies; beyond: drop
    DEDUP_WINDOW = 256         # remembered served msg_ids PER SOURCE rank
    DEDUP_MAX_BYTES = 32 << 20  # per-source reply-cache byte budget

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 register_timeout: float = 30.0):
        self._tables: Dict[int, Tuple[ServerStore, int]] = {}
        self._sync: Dict[int, _TableSyncGate] = {}
        self._sparse: Dict[int, _SparseShardState] = {}
        self._directory: Dict[int, Tuple[str, int]] = {}
        self.rank: Optional[int] = None
        self._lock = make_lock("ps.service")
        self._register_timeout = register_timeout
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self.address = self._listener.getsockname()
        self._running = True
        self._reg_stop = threading.Event()   # interrupts registration retry
        self._selector = selectors.DefaultSelector()
        self._selector.register(self._listener, selectors.EVENT_READ, None)
        self._decoders: Dict[socket.socket, bytearray] = {}
        # Sockets the dispatcher wants torn down; only the IO thread touches
        # the selector/_decoders (single-writer rule — a foreign-thread
        # unregister during select() is a race).
        self._to_drop: "collections.deque[socket.socket]" = \
            collections.deque()
        # Reply bytes the dispatcher wants written. The IO thread owns ALL
        # socket writes (per-connection buffers + EVENT_WRITE), so one
        # stalled peer can only fill its own buffer — it can never block
        # the dispatcher and freeze other clients' tables (VERDICT r3
        # weak #2). The wake socketpair interrupts select() so replies
        # don't wait out the poll interval.
        self._to_send: "collections.deque[Tuple[socket.socket, bytes]]" = \
            collections.deque()
        self._write_bufs: Dict[socket.socket, list] = {}
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        # Non-blocking writer too: with the socketpair buffer full a wake
        # is already pending, and a blocking send here could deadlock the
        # dispatcher against an IO thread stuck on the bounded queue.
        self._wake_w.setblocking(False)
        self._selector.register(self._wake_r, selectors.EVENT_READ, None)
        # Table ops that arrived before their shard registered: parked here
        # (never blocking the dispatcher) and re-examined on registration
        # or deadline expiry.
        self._deferred: "collections.deque[Tuple[socket.socket, Message, float]]" = \
            collections.deque()
        # Connections with a parked message: LATER messages on the same
        # connection defer behind it, preserving the per-connection FIFO
        # that read-your-writes rests on.
        self._deferred_socks: set = set()
        self._next_sweep = 0.0
        # Exactly-once elastic retries: an Add that was applied but whose
        # reply was lost (peer resends the SAME msg on a new connection) is
        # answered from this cache instead of re-applied (VERDICT r3
        # weak #3). Windows are PER SOURCE so one busy peer's traffic
        # can't evict another's entry before its retransmit lands.
        self._applied: "Dict[int, collections.OrderedDict[int, Message]]" \
            = {}
        self._applied_bytes: Dict[int, int] = {}
        self._queue: "_queue_mod.Queue" = _queue_mod.Queue(
            maxsize=self.MAX_QUEUE)
        # Telemetry: pending-request depth + per-worker add-stream lag
        # (docs/OBSERVABILITY.md). Counts/gauges are dispatcher-thread only.
        self._g_queue_depth = gauge("ps_service.queue_depth")
        self._g_deferred_depth = gauge("ps_service.deferred_depth")
        self._worker_add_counts: Dict[int, int] = {}
        self._top_add_count = 0
        self._staleness_gauges: Dict[int, object] = {}
        # Workers that declared Finish_Train: their add stream has
        # legitimately stopped, so the leader sweep must not keep growing
        # their published lag (a phantom ps.straggler alert would latch
        # FOREVER — the lag only ever grows and the alert can never
        # resolve). A crashed worker that never said goodbye keeps
        # aging on purpose: from this layer it is indistinguishable from
        # a wedge, which is exactly what the straggler alert is for.
        self._retired_staleness: set = set()
        # Write-ahead delta log (core/wal.py; armed via attach_wal).
        # _wal_restore_lsn: per-table "checkpoint covers lsn <= L" marks
        # from load_state; _wal_snapshot_lsn: per-table lsn of the last
        # snapshot taken (what wal_checkpoint prunes up to);
        # _wal_replayed_upto makes replay_wal idempotent.
        self._wal = None
        self._wal_sync = False
        self._wal_replaying = False
        self._wal_restore_lsn: Dict[int, int] = {}
        self._wal_snapshot_lsn: Dict[int, int] = {}
        self._wal_replayed_upto = 0
        self._io_thread = threading.Thread(target=self._io_loop, daemon=True)
        self._dispatch_thread = threading.Thread(target=self._dispatch_loop,
                                                 daemon=True)
        self._io_thread.start()
        self._dispatch_thread.start()

    @property
    def num_service_threads(self) -> int:
        """Observable bound for tests: always 2 (IO + dispatch)."""
        return sum(t.is_alive() for t in (self._io_thread,
                                          self._dispatch_thread))

    # -- shard registry -----------------------------------------------------
    def register_shard(self, table_id: int, store: ServerStore,
                       row_offset: int = 0, sync_workers: int = 0,
                       sparse_workers: int = 0,
                       sparse_rows: int = 0) -> None:
        """``sync_workers > 0`` arms BSP clock gating for this table
        (SyncServer mode, selected by ``-sync=true`` exactly as the
        reference chooses its server subclass, src/server.cpp:224-231).
        ``sparse_workers > 0`` arms server-side per-worker staleness
        tracking over ``sparse_rows`` REAL shard rows (not the padded
        store height — an empty shard must track 0 rows, or its padding
        row would ship as a phantom global row)."""
        with self._lock:
            # Gate BEFORE table: _gate_for's lock-free fast path treats
            # "in _tables but not in _sync" as a registered async table.
            if sync_workers > 0:
                self._sync.setdefault(table_id, _TableSyncGate(sync_workers))
            if sparse_workers > 0:
                self._sparse.setdefault(
                    table_id,
                    _SparseShardState(sparse_workers, max(sparse_rows, 0)))
            self._tables[table_id] = (store, row_offset)
        # Wake the dispatcher so any requests parked on this table replay.
        try:
            self._queue.put_nowait(_RECHECK)
        except _queue_mod.Full:
            pass    # dispatcher is busy; the periodic sweep will catch up

    # -- write-ahead delta log (core/wal.py; docs/DURABILITY.md) -------------
    @property
    def wal_active(self) -> bool:
        return self._wal is not None

    def attach_wal(self, directory: str, flush_interval_ms: float = 25.0,
                   sync_acks: bool = False):
        """Arm the write-ahead delta log: every accepted ``Request_Add``
        appends one CRC-framed record. ``sync_acks`` fsyncs before the
        reply (no acked-write-loss window, per-record fsync cost);
        default is group commit every ``flush_interval_ms`` (an abrupt
        kill may lose at most that window of ACKED adds — the documented
        trade). Call BEFORE announcing this seat (``enable_directory``),
        like checkpoint restore: recovery order is attach -> restore ->
        replay -> announce."""
        from multiverso_tpu.core import wal as wal_mod
        check(self._wal is None, "WAL already attached")
        self._wal = wal_mod.WriteAheadLog(
            directory, flush_interval_ms=flush_interval_ms)
        self._wal_sync = bool(sync_acks)
        return self._wal

    def note_wal_restore(self, table_id: int, lsn: int) -> None:
        """A checkpoint restore covered this table's deltas up to ``lsn``
        (from the payload's ``wal_meta``): replay must skip them — and
        the appender must never RE-ISSUE them (the checkpoint may cover
        lsns whose records died unfsynced in the crash; fresh adds
        assigned those numbers would be skipped by the NEXT recovery)."""
        self._wal_restore_lsn[table_id] = max(
            self._wal_restore_lsn.get(table_id, 0), int(lsn))
        if self._wal is not None:
            self._wal.ensure_lsn_at_least(lsn)

    def _wal_log_add(self, msg: Message, opt: AddOption,
                     stamped: bool = False) -> None:
        """Log one APPLIED add, with the option AS APPLIED (staleness
        stamped server-side must replay bitwise, so the record carries
        the stamped value, not the wire original). Dispatcher-thread
        only, immediately after the apply — record order IS apply order.
        Fast path: the option was NOT rewritten, so the frame the IO
        loop pinned (``msg.raw``) IS the record — no re-serialization."""
        if self._wal is None or self._wal_replaying:
            return
        try:
            if not stamped and msg.raw is not None:
                self._wal.append(msg.raw, sync=self._wal_sync)
                return
            from multiverso_tpu.parallel.net import pack_message
            logged = Message(src=msg.src, dst=msg.dst, type=msg.type,
                             table_id=msg.table_id, msg_id=msg.msg_id,
                             data=[msg.data[0], _opt_to_array(opt),
                                   *msg.data[2:]])
            self._wal.append(pack_message(logged), sync=self._wal_sync)
        except (OSError, ValueError) as e:
            # The delta is ALREADY APPLIED: letting a failed append
            # (ENOSPC, EIO on the sync-ack fsync) unwind would drop the
            # connection before the reply/dedup cache land, and the
            # peer's retransmit would DOUBLE-APPLY — trading a bounded,
            # loudly-counted durability hole for silent state
            # divergence on the exactly-once plane. Consistency wins:
            # ack proceeds, the gap is visible in ps.wal.append_errors.
            counter("ps.wal.append_errors").inc()
            log.error("wal: append failed (add applied, NOT journaled — "
                      "durability gap until next checkpoint): %s", e)

    def replay_wal(self) -> Dict[str, int]:
        """Recovery: replay the attached WAL's tail through the normal
        dispatch path. Per-record filter: skip records a checkpoint
        restore already covers (``note_wal_restore``) and records already
        replayed (idempotent — replay twice == replay once). Replayed
        adds also repopulate the exactly-once reply cache, so a peer that
        never saw its ack retransmits into a dedup hit instead of a
        double-apply. MUST run after every shard registered + restored
        and BEFORE this seat is announced (no concurrent live traffic)."""
        from multiverso_tpu.core import wal as wal_mod
        from multiverso_tpu.parallel.net import parse_frame
        check(self._wal is not None, "no WAL attached")
        applied = skipped = 0
        self._wal_replaying = True
        try:
            for lsn, payload in wal_mod.replay(
                    self._wal.directory,
                    since_lsn=self._wal_replayed_upto):
                try:
                    msg, _ = parse_frame(bytearray(payload))
                except Exception:  # noqa: BLE001 - CRC passed but the
                    # payload codec failed (version skew): drop the
                    # record loudly rather than kill recovery.
                    log.error("wal: unparseable record at lsn %d "
                              "dropped", lsn)
                    continue
                if msg is None or msg.type != MsgType.Request_Add:
                    skipped += 1
                    continue
                if lsn <= self._wal_restore_lsn.get(msg.table_id, 0):
                    # The checkpoint already holds this delta — but the
                    # PEER may never have seen its ack (snapshot landed,
                    # reply died with the process). Cache a reply WITHOUT
                    # re-applying, so its retransmit dedups instead of
                    # double-applying on top of the restored state.
                    self._remember_reply(msg, msg.create_reply())
                    skipped += 1
                    continue
                per = self._applied.get(msg.src)
                if per is not None and msg.msg_id in per:
                    skipped += 1    # duplicate within the log
                    continue
                reply = self._dispatch(msg)
                if reply is not None:
                    self._remember_reply(msg, reply)
                applied += 1
        finally:
            self._wal_replaying = False
        self._wal_replayed_upto = max(self._wal_replayed_upto,
                                      self._wal.lsn)
        counter_val = {"applied": applied, "skipped": skipped}
        gauge("ps.wal.replayed").set(applied)
        log.info("wal: replay applied %d records, skipped %d",
                 applied, skipped)
        return counter_val

    def snapshot_table(self, table_id: int,
                       timeout: float = 120.0) -> Tuple[Dict, int]:
        """``(store_state payload, wal lsn)`` captured atomically on the
        dispatcher thread (see :class:`_SnapshotReq`). Falls back to a
        direct (non-lsn) snapshot when no WAL is attached."""
        entry = self._tables.get(table_id)
        check(entry is not None, f"unknown table {table_id}")
        if self._wal is None:
            return entry[0].store_state(), 0
        req = _SnapshotReq(table_id)
        try:
            # Bounded put: a wedged dispatcher behind a FULL queue must
            # surface as the timeout error below, not hang the caller
            # forever in the enqueue itself.
            self._queue.put(req, timeout=timeout)
        except _queue_mod.Full:
            check(False, "snapshot request could not be enqueued "
                  "(dispatch queue full — dispatcher wedged?)")
        check(req.event.wait(timeout), "snapshot request timed out "
              "(dispatcher dead or wedged)")
        err = req.out.get("error")
        if err is not None:
            raise RuntimeError(f"snapshot of table {table_id} failed: "
                               f"{err}")
        lsn = int(req.out["lsn"])
        self._wal_snapshot_lsn[table_id] = lsn
        return req.out["payload"], lsn

    def wal_checkpoint(self) -> None:
        """Post-checkpoint log truncation: rotate to a fresh segment and
        prune sealed segments every table's newest snapshot covers.
        Purely space reclamation — recovery filters by lsn, so a crash
        between checkpoint and prune (or a prune that never runs) can
        never double-apply."""
        if self._wal is None:
            return
        self._wal.rotate()
        lsns = [self._wal_snapshot_lsn.get(t, 0) for t in self._tables]
        self._wal.prune(min(lsns) if lsns else 0)

    # -- server loops --------------------------------------------------------
    def _io_loop(self) -> None:
        from multiverso_tpu.parallel.net import parse_frame
        from multiverso_tpu.telemetry import watchdog_scope
        # Wedge watchdog (telemetry/flight.py). Generous timeout: the
        # bounded-queue put below legitimately blocks while the
        # dispatcher digests a backlog — that is backpressure, and only
        # minutes of it is a wedge worth a postmortem.
        with watchdog_scope("ps-io", timeout_s=120.0) as wd:
            self._run_io(parse_frame, wd)

    def _run_io(self, parse_frame, wd) -> None:
        while self._running:
            wd.beat()
            while self._to_drop:
                self._drop_conn(self._to_drop.popleft())
            self._stage_outgoing()
            try:
                events = self._selector.select(timeout=0.2)
            except OSError:
                return
            for key, mask in events:
                sock = key.fileobj
                if sock is self._listener:
                    try:
                        conn, _ = self._listener.accept()
                    except OSError:
                        continue
                    if len(self._decoders) >= self.MAX_CONNS:
                        conn.close()    # refuse: connection cap reached
                        continue
                    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY,
                                    1)
                    # Non-blocking is load-bearing: a blocking send() to a
                    # stalled peer would freeze the whole IO thread.
                    conn.setblocking(False)
                    self._decoders[conn] = bytearray()
                    self._selector.register(conn, selectors.EVENT_READ,
                                            None)
                    continue
                if sock is self._wake_r:
                    try:
                        while sock.recv(4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
                    continue
                if mask & selectors.EVENT_WRITE:
                    self._flush_writes(sock)
                if not mask & selectors.EVENT_READ:
                    continue
                try:
                    chunk = sock.recv(1 << 18)
                except (BlockingIOError, InterruptedError):
                    continue    # spurious readiness on a non-blocking conn
                except OSError:
                    chunk = b""
                if not chunk:
                    self._drop_conn(sock)
                    continue
                buf = self._decoders.get(sock)
                if buf is None:     # dropped between select() and here
                    continue
                buf.extend(chunk)
                while True:
                    try:
                        msg, consumed = parse_frame(buf)
                    except Exception:  # noqa: BLE001 - ANY malformed frame
                        # (bad magic raises IOError, but a bogus dtype tag
                        # or shape raises TypeError/ValueError from numpy)
                        # must cost the sender its connection, never the
                        # IO thread.
                        self._drop_conn(sock)
                        break
                    if msg is None:
                        break
                    if self._wal is not None and \
                            msg.type == MsgType.Request_Add:
                        # Pin the received frame so the WAL can append
                        # the wire bytes VERBATIM (one memcpy here vs a
                        # ~14us re-serialization on the dispatch hot
                        # path — measured 2x the whole remaining append
                        # cost).
                        msg.raw = bytes(buf[:consumed])
                    del buf[:consumed]
                    # Bounded queue: blocks when the dispatcher lags, which
                    # stops socket draining -> TCP backpressure upstream.
                    self._queue.put((sock, msg))

    # Compact a write buffer's consumed prefix only once it exceeds this
    # (amortized O(1) drain — `del buf[:sent]` per send would be O(n^2)
    # on the single IO thread while a connection is backlogged).
    _COMPACT_AT = 8 << 20

    def _stage_outgoing(self) -> None:
        """Move dispatcher-produced reply bytes into per-connection write
        buffers and arm EVENT_WRITE. IO-thread only. Entries are
        ``[bytearray, offset]`` — offset marks the already-sent prefix."""
        while self._to_send:
            sock, payload = self._to_send.popleft()
            if sock not in self._decoders:
                continue    # connection already gone
            entry = self._write_bufs.get(sock)
            if entry is None:
                entry = self._write_bufs[sock] = [bytearray(), 0]
            unread = len(entry[0]) - entry[1]
            if unread > self.MAX_WRITE_BUF:
                # The peer had ALREADY let more than the cap pile up before
                # this reply (so one legitimately huge reply — a >64MB
                # shard Get — never trips this on a healthy, draining
                # connection): it is not reading. Cut it loose; its
                # waiters fail fast client-side.
                log.warning("ps_service: dropping stalled peer "
                            "(%d reply bytes unread)", unread)
                self._drop_conn(sock)
                continue
            entry[0].extend(payload)
            try:
                self._selector.modify(
                    sock, selectors.EVENT_READ | selectors.EVENT_WRITE, None)
            except (KeyError, ValueError, OSError):
                self._drop_conn(sock)

    def _flush_writes(self, sock: socket.socket) -> None:
        """Write as much buffered reply data as the socket accepts; disarm
        EVENT_WRITE when drained. IO-thread only."""
        entry = self._write_bufs.get(sock)
        if entry is None:
            try:
                self._selector.modify(sock, selectors.EVENT_READ, None)
            except (KeyError, ValueError, OSError):
                pass
            return
        buf, off = entry
        try:
            sent = sock.send(memoryview(buf)[off:off + (1 << 20)])
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop_conn(sock)
            return
        off += sent
        if off >= len(buf):
            # pop, not del: close() runs _drop_conn from the caller's
            # thread and may race this entry away mid-shutdown.
            self._write_bufs.pop(sock, None)
            try:
                self._selector.modify(sock, selectors.EVENT_READ, None)
            except (KeyError, ValueError, OSError):
                self._drop_conn(sock)
            return
        if off > self._COMPACT_AT:
            del buf[:off]
            off = 0
        entry[1] = off

    def _wake_io(self) -> None:
        try:
            self._wake_w.send(b"\0")
        except OSError:
            pass

    def _drop_conn(self, sock: socket.socket) -> None:
        try:
            self._selector.unregister(sock)
        except (KeyError, OSError, ValueError):
            pass    # already closed/unregistered (shutdown races)
        self._decoders.pop(sock, None)
        self._write_bufs.pop(sock, None)
        try:
            sock.close()
        except OSError:
            pass

    def _maybe_stamp_staleness(self, store, opt: AddOption) -> AddOption:
        """DCN leg of ``-staleness_adaptive`` (docs/DESIGN.md): stamp the
        server-observed add lag of this worker (the same counts feeding
        the ``ps_service.staleness.worker_<w>`` gauges) onto the option a
        staleness-aware updater will see — the async-mode analog of the
        sync coordinator's vector-clock lag. Dispatcher-thread only; an
        already-stamped option (client measured it closer to the source)
        passes through."""
        updater = getattr(store, "updater", None)   # host KV maps have none
        if (opt.staleness >= 0 or not get_flag("staleness_adaptive")
                or not getattr(updater, "staleness_aware", False)):
            return opt
        lag = self._top_add_count - self._worker_add_counts.get(
            opt.worker_id, 0)
        return dataclasses.replace(opt, staleness=float(max(lag, 0)))

    def _note_worker_add(self, worker: int) -> None:
        """Per-worker staleness: how many applied Adds the slowest push
        stream trails the fastest by — the async-mode analog of the BSP
        vector-clock lag (in sync mode the gated apply order makes the two
        coincide). Dispatcher-thread only. The full-sweep refresh (which
        keeps a stalled straggler's lag growing in snapshots) runs only
        when the LEADER advances; otherwise just the sender's gauge moves
        — O(1) amortized on the throughput-critical dispatch thread."""
        self._retired_staleness.discard(worker)   # an add un-retires
        n = self._worker_add_counts.get(worker, 0) + 1
        self._worker_add_counts[worker] = n
        g = self._staleness_gauges.get(worker)
        if g is None:
            g = self._staleness_gauges[worker] = gauge(
                f"ps_service.staleness.worker_{worker}")
        if n > self._top_add_count:
            self._top_add_count = n
            for w, c in self._worker_add_counts.items():
                gw = self._staleness_gauges.get(w)
                if gw is None:
                    gw = self._staleness_gauges[w] = gauge(
                        f"ps_service.staleness.worker_{w}")
                gw.set(0.0 if w in self._retired_staleness else n - c)
        else:
            g.set(self._top_add_count - n)

    def _retire_worker_staleness(self, worker: int) -> None:
        """A worker said Finish_Train (for ANY table): its add stream is
        winding down, so stop publishing its lag — zero the gauge now and
        skip it in leader sweeps, or the ps.straggler alert latches a
        permanently-firing phantom naming a worker that left cleanly.
        A worker still training OTHER tables un-retires on its very next
        add (``_note_worker_add``) and the sweep restores its true lag.
        Dispatcher-thread only, like all staleness accounting."""
        self._retired_staleness.add(worker)
        g = self._staleness_gauges.get(worker)
        if g is not None:
            g.set(0.0)

    def _dispatch_loop(self) -> None:
        from multiverso_tpu.telemetry import watchdog_scope
        # Wedge watchdog: the dispatcher applies device updates — a
        # kernel that never returns wedges every table this shard
        # serves. 120s rides out any legitimate big-table dispatch.
        with watchdog_scope("ps-dispatcher", timeout_s=120.0) as wd:
            self._run_dispatch(wd)

    def _run_dispatch(self, wd) -> None:
        while True:
            wd.beat()
            self._g_queue_depth.set(self._queue.qsize())
            self._g_deferred_depth.set(len(self._deferred))
            # Sweep parked requests on EVERY pass (rate-limited), not just
            # on queue lulls — sustained traffic must not starve deferred
            # deadlines/replays (their Reply_Error is what keeps BSP's
            # no-deadline waiters from hanging silently).
            if self._deferred and time.monotonic() >= self._next_sweep:
                self._replay_deferred()
                self._next_sweep = time.monotonic() + 0.25
            try:
                # Bounded get (was: block forever on an idle queue) so an
                # idle dispatcher still beats its watchdog, and parked
                # requests' deadlines expire even with no new traffic.
                item = self._queue.get(timeout=0.5)
            except _queue_mod.Empty:
                continue
            if item is None:
                return
            if item is _RECHECK:
                self._replay_deferred()
                continue
            if isinstance(item, _SnapshotReq):
                # Atomic (payload, lsn) capture: no add can interleave —
                # this thread is the only one that applies them.
                try:
                    store, _ = self._tables[item.table_id]
                    item.out["payload"] = store.store_state()
                    item.out["lsn"] = self._wal.lsn if self._wal else 0
                except Exception as e:  # noqa: BLE001 - surface to the
                    item.out["error"] = e   # waiter, keep dispatching
                finally:
                    item.event.set()
                continue
            sock, msg = item
            try:
                self._dispatch_one(sock, msg)
            except Exception as e:  # noqa: BLE001 - malformed request must
                log.error("ps_service: dispatch of type %d failed: %s",
                          msg.type, e)   # not kill the dispatcher thread
                self._to_drop.append(sock)
                self._wake_io()

    def _dispatch_one(self, sock: socket.socket, msg: Message) -> None:
        unregistered = msg.table_id not in self._tables and (
            msg.type in (MsgType.Request_Add, MsgType.Request_Get)
            or (msg.type in (MsgType.Server_Finish_Train,
                             MsgType.Control_Elastic)
                and msg.table_id >= 0))
        if unregistered or sock in self._deferred_socks:
            # Peers may send traffic before this process registers the
            # table (the reference serializes this with a barrier after
            # MV_CreateTable). Park the request — NEVER block the
            # dispatcher on registration (VERDICT r3 weak #2) — and replay
            # it when register_shard wakes us. A connection with a parked
            # message parks EVERYTHING behind it: serving a later Get
            # before an earlier parked Add would break the per-connection
            # FIFO read-your-writes contract.
            self._deferred.append(
                (sock, msg, time.monotonic() + self._register_timeout))
            self._deferred_socks.add(sock)
            return
        if msg.type in (MsgType.Request_Add, MsgType.Request_Get):
            # Exactly-once for elastic retries: a resent, already-served
            # request is answered from the reply cache, not re-applied —
            # Adds would corrupt updater state, and EITHER type would
            # double-tick a BSP clock.
            per_src = self._applied.get(msg.src)
            cached = per_src.get(msg.msg_id) if per_src else None
            if cached is not None:
                self._send_reply(sock, cached)
                return
        gate = self._gate_for(msg)
        if gate is not None and not gate.admissible(msg):
            q = gate.cached[gate.worker_of(msg)]
            for i, (_, queued) in enumerate(q):
                if (queued.src, queued.msg_id) == (msg.src, msg.msg_id):
                    # Retransmit of a still-cached op (client reconnected):
                    # refresh the reply socket, don't queue a second copy.
                    q[i] = (sock, msg)
                    return
            q.append((sock, msg))
            return
        self._serve(sock, msg, gate)
        if gate is not None or msg.type in (MsgType.Server_Finish_Train,
                                            MsgType.Control_Elastic):
            self._drain_sync_caches()

    def _replay_deferred(self) -> None:
        """Re-dispatch parked requests whose table registered; expire the
        rest past their deadline with an explicit error reply so the
        peer's waiter fails LOUDLY even under BSP's no-deadline waits."""
        now = time.monotonic()
        pending = list(self._deferred)
        self._deferred.clear()
        self._deferred_socks.clear()
        for sock, msg, deadline in pending:
            if sock in self._deferred_socks:
                # An earlier message on this connection is still parked:
                # keep program order, re-park this one behind it.
                self._deferred.append((sock, msg, deadline))
                continue
            is_table_op = (
                msg.type in (MsgType.Request_Add, MsgType.Request_Get)
                or (msg.type in (MsgType.Server_Finish_Train,
                                 MsgType.Control_Elastic)
                    and msg.table_id >= 0))
            if not is_table_op or msg.table_id in self._tables:
                # Table op whose shard arrived, or a control message that
                # was parked purely for connection ordering: serve it.
                try:
                    self._dispatch_one(sock, msg)
                except Exception as e:  # noqa: BLE001 - keep the thread
                    log.error("ps_service: deferred dispatch of type %d "
                              "failed: %s", msg.type, e)
                    self._to_drop.append(sock)
                    self._wake_io()
            elif now > deadline:
                log.error("ps_service: unknown table %d (no registration "
                          "within %.0fs)", msg.table_id,
                          self._register_timeout)
                err = Message(src=msg.dst, dst=msg.src,
                              type=MsgType.Reply_Error,
                              table_id=msg.table_id, msg_id=msg.msg_id)
                self._send_reply(sock, err)
            else:
                self._deferred.append((sock, msg, deadline))
                self._deferred_socks.add(sock)

    def _gate_for(self, msg: Message) -> Optional[_TableSyncGate]:
        """Sync gate for a table op, or None (async table / control msg).
        Callers guarantee the table is registered (deferral upstream), so
        these are lock-free dict reads (GIL-atomic; entries only added)."""
        if msg.type not in (MsgType.Request_Add, MsgType.Request_Get):
            return None
        return self._sync.get(msg.table_id)

    def _serve(self, sock: socket.socket, msg: Message,
               gate: Optional[_TableSyncGate]) -> None:
        """Apply + reply + (sync mode) tick the worker's clock. Clock ticks
        AFTER application, mirroring the reference's single-threaded server
        actor which applies and clocks atomically. The reply itself is
        handed to the IO thread — the dispatcher never touches a socket."""
        reply = self._dispatch_control(msg)
        if gate is not None and reply is not None:
            gate.tick(msg)
        if reply is None:
            return
        # Remember replies for non-idempotent requests: all Adds, gated
        # Gets (serving one ticks a BSP clock), and STALE gets (take_stale
        # marks rows fresh — a retransmit after a lost reply would get 0
        # rows back and silently lose those values). Byte-bounded — Get
        # replies carry row payloads.
        stale_get = (msg.type == MsgType.Request_Get and msg.data
                     and msg.data[0].size >= 1
                     and int(msg.data[0][0]) in (STALE_GET_KEY,
                                                 STALE_ROWS_GET_KEY))
        if msg.type == MsgType.Request_Add or stale_get or \
                (gate is not None and msg.type == MsgType.Request_Get):
            self._remember_reply(msg, reply)
        self._send_reply(sock, reply)

    def _remember_reply(self, msg: Message, reply: Message) -> None:
        """Exactly-once reply cache insert + byte-bounded eviction. Shared
        by the live serve path and WAL replay (a recovered shard must
        dedup retransmits of adds it applied before the crash)."""
        per = self._applied.setdefault(msg.src,
                                       collections.OrderedDict())
        per[msg.msg_id] = reply
        nbytes = self._applied_bytes.get(msg.src, 0) \
            + _reply_nbytes(reply)
        while len(per) > self.DEDUP_WINDOW or \
                nbytes > self.DEDUP_MAX_BYTES:
            _, old = per.popitem(last=False)
            nbytes -= _reply_nbytes(old)
        self._applied_bytes[msg.src] = nbytes

    def _send_reply(self, sock: socket.socket, reply: Message) -> None:
        from multiverso_tpu.parallel.net import pack_message
        self._to_send.append((sock, pack_message(reply)))
        self._wake_io()

    def _drain_sync_caches(self) -> None:
        """Re-examine cached out-of-clock requests after any clock movement;
        each served message may unlock others, so loop to fixpoint (ref
        SyncServer's drain of its MtQueues, src/server.cpp:141-188)."""
        progress = True
        while progress:
            progress = False
            with self._lock:
                gates = list(self._sync.values())
            for gate in gates:
                for q in list(gate.cached.values()):
                    while q and gate.head_admissible(q[0][1]):
                        sock, msg = q.popleft()
                        self._serve(sock, msg, gate)
                        progress = True

    def _dispatch(self, msg: Message) -> Optional[Message]:
        entry = self._tables.get(msg.table_id)
        if entry is None:   # only reachable via direct tests/misuse:
            log.error("ps_service: unknown table %d", msg.table_id)
            return None     # _dispatch_one defers unregistered table ops
        store, row_offset = entry
        # Raw-wire stores (host KV maps) carry keys/values verbatim: keys
        # are arbitrary int64 hash-routed (never offset), values keep
        # their dtype (int64 word counts must not round-trip float32).
        raw_wire = getattr(store, "wire_raw", False)
        if msg.type == MsgType.Request_Add:
            # payload: [keys(int32, may be empty = whole shard),
            #           opt scalars(float32[6]; older peers send 5 —
            #           staleness reads as unmeasured), marker,
            #           *filtered delta]
            # No delta blobs at all = BSP clock tick (apply nothing).
            if len(msg.data) == 2 and msg.data[0].size == 0:
                return msg.create_reply()
            with monitor("PS_SERVICE_ADD"):   # ref server.cpp:49 monitor
                keys, opt_arr = msg.data[0], msg.data[1]
                wire_opt = _opt_from_array(opt_arr)
                opt = self._maybe_stamp_staleness(store, wire_opt)
                if raw_wire:
                    store.apply_rows(keys, msg.data[2], opt)
                    record_keys(_sketch_surface(msg.table_id, "add"),
                                keys, msg.data[2].nbytes)
                elif keys.size == 0:
                    delta = unpack_payload(msg.data[2:])  # FilterOut analog
                    store.apply_dense(delta, opt)
                    record_keys(_sketch_surface(msg.table_id, "add"),
                                keys, delta.nbytes)
                else:
                    local = keys.astype(np.int64) - row_offset
                    delta = unpack_payload(msg.data[2:])
                    store.apply_rows(local.astype(np.int32), delta, opt)
                    # GLOBAL row ids into the traffic sketch: hot keys
                    # surface in the id space operators route/shard by.
                    record_keys(_sketch_surface(msg.table_id, "add"),
                                keys, delta.nbytes)
                    st = self._sparse.get(msg.table_id)
                    if st is not None:
                        st.on_add(local, opt.worker_id)
            # Durability: the applied delta goes to the WAL in apply
            # order, with the option AS APPLIED (no-op unless attached).
            self._wal_log_add(msg, opt, stamped=opt is not wire_opt)
            # opt.worker_id is always a non-negative global id here (every
            # sender maps through _gid; AddOption defaults to 0).
            self._note_worker_add(opt.worker_id)
            return msg.create_reply()
        if msg.type == MsgType.Request_Get:
            keys = msg.data[0]
            if keys.size == 1 and int(keys[0]) == TICK_GET_KEY:
                reply = msg.create_reply()   # BSP clock tick: no rows
                reply.data = pack_payload(np.empty(0, np.float32), "none")
                return reply
            mode = _wire_mode()
            if keys.size >= 1 and int(keys[0]) == STALE_ROWS_GET_KEY:
                # Keyed incremental Get: only the stale subset of the
                # requested rows crosses the wire (ref keyed
                # UpdateGetState, :244-253). data = [[-4], [wid], keys].
                st = self._sparse.get(msg.table_id)
                wid = int(msg.data[1][0]) if len(msg.data) > 1 \
                    and msg.data[1].size else 0
                check(st is not None,
                      f"table {msg.table_id} is not sparse-tracked")
                req = msg.data[2].astype(np.int64) - row_offset
                with monitor("PS_SERVICE_GET"):
                    rows = st.take_stale_among(wid, req)
                    values = np.asarray(store.read_rows(rows))
                record_keys(_sketch_surface(msg.table_id, "get"),
                            rows + np.int64(row_offset), values.nbytes)
                reply = msg.create_reply()
                reply.data = [rows + np.int32(row_offset),
                              *pack_payload(values, _reply_mode(mode),
                                            clip=0.0)]
                return reply
            if keys.size == 1 and int(keys[0]) == STALE_GET_KEY:
                # Incremental whole-table Get: exactly the rows stale for
                # this worker cross the wire (ref UpdateGetState), tagged
                # with their GLOBAL row ids.
                st = self._sparse.get(msg.table_id)
                wid = int(msg.data[1][0]) if len(msg.data) > 1 \
                    and msg.data[1].size else 0
                check(st is not None,
                      f"table {msg.table_id} is not sparse-tracked")
                with monitor("PS_SERVICE_GET"):
                    rows = st.take_stale(wid)
                    values = np.asarray(store.read_rows(rows))
                record_keys(_sketch_surface(msg.table_id, "get"),
                            rows + np.int64(row_offset), values.nbytes)
                reply = msg.create_reply()
                reply.data = [rows + np.int32(row_offset),
                              *pack_payload(values, _reply_mode(mode),
                                            clip=0.0)]
                return reply
            with monitor("PS_SERVICE_GET"):   # ref server.cpp:37 monitor
                if raw_wire:
                    values = np.asarray(store.read_rows(keys))
                elif keys.size == 0:
                    values = np.asarray(store.read())
                else:
                    values = np.asarray(store.read_rows(
                        keys.astype(np.int32) - row_offset))
            record_keys(_sketch_surface(msg.table_id, "get"), keys,
                        values.nbytes)
            reply = msg.create_reply()
            if raw_wire:
                reply.data = [np.ascontiguousarray(values)]
                return reply
            # FilterIn on the reply leg (ref ProcessGet,
            # sparse_matrix_table.cpp:261-309); onebit never applies to
            # absolute parameter values.
            reply.data = pack_payload(values, _reply_mode(mode),
                                      clip=0.0)
            return reply
        log.error("ps_service: unhandled type %d", msg.type)
        return None

    # -- membership directory (the Controller analog, ref
    # src/controller.cpp:38-80 — extended: registration is re-admittable,
    # not one-shot, so a restarted rank rejoins without peer intervention).
    def enable_directory(self, rank: int, peers: List[Tuple[str, int]]
                         ) -> None:
        """Adopt a rank identity and join the membership directory.
        Idempotent. EVERY service keeps a directory replica (seeded from
        the static peer list); a starting — or RESTARTING — rank
        registers its current address with every live peer, so lookups
        survive any single seat going down, including rank 0 (the
        reference Controller's one uncovered seat)."""
        if getattr(self, "rank", None) is not None:
            return
        self.rank = rank
        with self._lock:
            for r, addr in enumerate(peers):
                self._directory.setdefault(r, tuple(addr))
            self._directory[rank] = tuple(self.address)
        # Fan the registrations out CONCURRENTLY with a short foreground
        # budget: serial 10s connects to not-yet-listening cross-host
        # peers would block table construction for minutes on a cold
        # start. Stragglers keep RETRYING in the background (daemon
        # threads) until acked or the service closes — a RESTARTED seat's
        # registration is the only way peers rediscover it, and one 3s
        # shot dies under load (a busy dispatcher can take >3s to ack,
        # silently stranding every peer's retry loop on the dead
        # address; caught by the BSP fault drill under a loaded box).
        threads = []
        for r, addr in enumerate(peers):
            if r == rank:
                continue

            def reg(r=r, addr=tuple(addr)):
                deadline = time.monotonic() + 600.0
                delay = 1.0
                # Bounded-lifetime retry (600s deadline, event-
                # interruptible backoff), not a service loop: a wedge
                # here self-resolves at the deadline.
                # graftlint: disable=daemon-loop-no-watchdog
                while self._running and time.monotonic() < deadline:
                    # Re-resolve each attempt: the peer may itself have
                    # re-registered at a new address mid-loop.
                    target = self.lookup(r) or addr
                    try:
                        if not self._running:   # close() raced us: a
                            return              # dead seat must not
                        self._register_with(target, timeout=10)   # re-add
                        return                  # its address to peers
                    except OSError as e:
                        log.warning("directory registration with rank %d "
                                    "failed (retrying): %s", r, e)
                    # Event, not sleep: close() interrupts the backoff.
                    if self._reg_stop.wait(delay):
                        return
                    delay = min(delay * 2, 10.0)

            th = threading.Thread(target=reg, daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=3)   # fast path completes inline; rest retry

    def _register_with(self, directory_addr: Tuple[str, int],
                       timeout: float = 10) -> None:
        host, port = self.address
        msg = Message(src=self.rank, type=MsgType.Control_Register,
                      msg_id=0,
                      data=[np.asarray([self.rank, port], dtype=np.int64),
                            np.frombuffer(host.encode(), dtype=np.uint8)])
        with socket.create_connection(directory_addr, timeout=timeout) as s:
            send_message(s, msg)
            if recv_message(s) is None:     # clean EOF = NOT acked
                raise OSError("registration connection closed before ack")

    def lookup(self, rank: int) -> Optional[Tuple[str, int]]:
        with self._lock:
            return self._directory.get(rank)

    def _dispatch_control(self, msg: Message) -> Optional[Message]:
        if msg.type == MsgType.Heartbeat:
            reply = msg.create_reply()
            with self._lock:
                reply.data = [np.asarray(sorted(self._tables),
                                         dtype=np.int64)]
            return reply
        if msg.type == MsgType.Control_Register:
            rank, port = (int(x) for x in msg.data[0])
            host = msg.data[1].tobytes().decode()
            with self._lock:
                self._directory[rank] = (host, port)
            log.info("directory: rank %d re-registered at %s:%d",
                     rank, host, port)
            return msg.create_reply()
        if msg.type == MsgType.Server_Finish_Train:
            # The named worker is done: its clocks go to infinity so
            # laggards can't wait on it (src/server.cpp:190-213; trigger
            # Zoo::FinishTrain, src/zoo.cpp:152-161). Scoped to the
            # message's table when one is named — finishing one table must
            # not retire the worker from other tables' clocks (ADVICE r3);
            # table_id < 0 (mv.finish_train, process-global) retires all.
            w = (int(msg.data[0][0]) if msg.data and msg.data[0].size
                 else max(msg.src, 0))
            self._retire_worker_staleness(w)
            with self._lock:
                if msg.table_id >= 0:
                    # Named table: finish its gate only. Absent gate (async
                    # table, or gate not yet registered) is a no-op — it
                    # must NOT fall back to retiring the worker everywhere.
                    gate = self._sync.get(msg.table_id)
                    gates = [gate] if gate is not None else []
                else:
                    # table_id < 0: retire everywhere. Defensive only —
                    # every current client (DistributedTableBase
                    # .finish_train, which mv.finish_train fans out
                    # through per table) stamps a concrete table_id.
                    gates = list(self._sync.values())
            for gate in gates:
                gate.finish(w)
            return msg.create_reply()
        if msg.type == MsgType.Control_Elastic:
            return self._serve_elastic(msg)
        if msg.type == MsgType.Control_Lookup:
            rank = int(msg.data[0][0])
            addr = self.lookup(rank)
            reply = msg.create_reply()
            if addr is None:
                reply.data = [np.asarray([-1], dtype=np.int64),
                              np.empty(0, dtype=np.uint8)]
            else:
                reply.data = [np.asarray([addr[1]], dtype=np.int64),
                              np.frombuffer(addr[0].encode(),
                                            dtype=np.uint8)]
            return reply
        return self._dispatch(msg)

    def _serve_elastic(self, msg: Message) -> Message:
        """Elastic membership announce (MXNET-MPI, PAPERS.md 1801.03855):
        a worker process joins/leaves this table's server-side BSP clock
        group at runtime. Runs on the dispatcher thread — the only thread
        that touches gates — so membership flips atomically between ops;
        the caller drains unlocked cached ops right after (a leave retires
        clocks to infinity, which may release every gated laggard)."""
        from multiverso_tpu.parallel.net import (pack_json_blob,
                                                 unpack_json_blob)
        reply = msg.create_reply()
        try:
            req = unpack_json_blob(msg.data[0]) if msg.data else {}
        except IOError:
            req = {}
        gate = self._sync.get(msg.table_id)
        op = req.get("op")
        if gate is None:
            # Async table: no clock group to re-form. Loud, not silent —
            # a join that "succeeds" against the wrong mode would strand
            # the worker waiting on gates that don't exist.
            out: Dict[str, object] = {
                "error": f"table {msg.table_id} has no sync gate"}
        elif op == "join":
            worker = req.get("worker")
            out = {"worker": gate.join(None if worker is None
                                       else int(worker))}
            out.update(gate.status())
        elif op == "leave" and req.get("worker") is not None:
            gate.leave(int(req["worker"]))
            out = dict(gate.status())
        elif op == "status":
            out = dict(gate.status())
        else:
            out = {"error": f"bad elastic request {req!r}"}
        reply.data = [pack_json_blob(out)]
        return reply

    def close(self) -> None:
        self._running = False
        self._reg_stop.set()                # interrupt registration retries
        try:
            self._queue.put_nowait(None)    # wake + stop the dispatcher
        except Exception:  # noqa: BLE001 - full queue: dispatcher is live
            pass
        self._wake_io()
        try:
            self._listener.close()
        except OSError:
            pass
        for sock in list(self._decoders):
            self._drop_conn(sock)
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass
        if self._wal is not None:
            # Orderly shutdown seals the log (flush + fsync) — an abrupt
            # kill skips this, which is exactly what recovery handles.
            self._wal.close()


def _reply_nbytes(reply: Message) -> int:
    return sum(int(np.asarray(b).nbytes) for b in reply.data)


def _opt_to_array(opt: AddOption) -> np.ndarray:
    return np.asarray([opt.worker_id, opt.momentum, opt.learning_rate,
                       opt.rho, opt.lambda_, opt.staleness],
                      dtype=np.float32)


def _opt_from_array(arr: np.ndarray) -> AddOption:
    # Older peers ship 5 scalars (no staleness); absent = unmeasured (-1),
    # which keeps the fixed-lambda DC-ASGD math bitwise.
    return AddOption(worker_id=int(arr[0]), momentum=float(arr[1]),
                     learning_rate=float(arr[2]), rho=float(arr[3]),
                     lambda_=float(arr[4]),
                     staleness=float(arr[5]) if arr.size > 5 else -1.0)


# -- wire payload codec (VERDICT r1 #5) -------------------------------------
# Every float payload (add deltas worker->server, get values server->worker)
# passes through a filter with a side-channel marker blob, the reference's
# FilterIn/FilterOut shape (``sparse_matrix_table.cpp:148-153,261-309``;
# marker analog: the size blob with -1 = raw, ``quantization_util.h:34-57``).
# Marker layout: int64 [mode, ndim, *dims]. Modes:
#   0 raw     — payload as-is
#   1 sparse  — (int32 indices, float32 values); chosen only when >50% of
#               entries are within the clip threshold (the reference's rule)
#   2 onebit  — packed sign bits + two scales, with sender-held error
#               feedback; opt-in (dense array add path only: quantizing
#               absolute values or sparse row deltas would be lossy garbage)
#   3 bf16    — round-to-nearest-even bfloat16 truncation (uint16 wire
#               halves), halving bytes on BOTH legs at bf16 delta/param
#               precision; the TPU-native middle ground between raw and
#               onebit (no sender state, works for row deltas and gets)
_WIRE_RAW, _WIRE_SPARSE, _WIRE_ONEBIT, _WIRE_BF16 = 0, 1, 2, 3


def _wire_mode() -> str:
    from multiverso_tpu.utils.configure import get_flag
    return get_flag("wire_compression")


def _wire_clip() -> float:
    from multiverso_tpu.utils.configure import get_flag
    return float(get_flag("wire_compression_clip"))


def _marker(mode: int, shape: Tuple[int, ...]) -> np.ndarray:
    return np.asarray([mode, len(shape), *shape], dtype=np.int64)


def _reply_mode(mode: str) -> str:
    """Reply legs carry ABSOLUTE parameter values: onebit would be lossy
    garbage there, so it degrades to lossless sparsify; bf16 stays bf16 —
    opting into it means bf16 read precision on pulls too (that is where
    half the wire bytes are)."""
    if mode == "bf16":
        return "bf16"
    return "sparse" if mode != "none" else "none"


def pack_payload(arr: np.ndarray, mode: str,
                 onebit: "Optional[OneBitsFilter]" = None,
                 clip: Optional[float] = None) -> List[np.ndarray]:
    """Array -> [marker, *blobs]; picks the cheapest admissible encoding.
    ``clip`` overrides the flag — reply legs carry ABSOLUTE parameter
    values and must pass clip=0.0 (lossless sparsify of exact zeros only);
    the user clip threshold is a delta-compression knob."""
    arr = np.ascontiguousarray(arr, dtype=np.float32)
    if mode == "onebit" and onebit is not None:
        bits, pos_scale, neg_scale = onebit.encode(arr)
        return [_marker(_WIRE_ONEBIT, arr.shape), bits,
                np.asarray([pos_scale, neg_scale], dtype=np.float32)]
    if mode in ("sparse", "onebit") and arr.size < (1 << 31):
        compressed, payload, idx = SparseFilter(
            _wire_clip() if clip is None else clip).filter_in(arr)
        if compressed:
            return [_marker(_WIRE_SPARSE, arr.shape), idx, payload]
    if mode == "bf16":
        from multiverso_tpu.utils.quantization import f32_to_bf16_bits
        return [_marker(_WIRE_BF16, arr.shape), f32_to_bf16_bits(arr)]
    return [_marker(_WIRE_RAW, arr.shape), arr]


def unpack_payload(blobs: List[np.ndarray]) -> np.ndarray:
    marker = blobs[0]
    mode, ndim = int(marker[0]), int(marker[1])
    shape = tuple(int(d) for d in marker[2:2 + ndim])
    size = int(np.prod(shape)) if ndim else 1
    if mode == _WIRE_RAW:
        return blobs[1].reshape(shape)
    if mode == _WIRE_SPARSE:
        out = np.zeros(size, dtype=np.float32)
        out[blobs[1]] = blobs[2]
        return out.reshape(shape)
    if mode == _WIRE_ONEBIT:
        return OneBitsFilter.decode(blobs[1], float(blobs[2][0]),
                                    float(blobs[2][1]), size).reshape(shape)
    if mode == _WIRE_BF16:
        from multiverso_tpu.utils.quantization import bf16_bits_to_f32
        return bf16_bits_to_f32(blobs[1]).reshape(shape)
    raise IOError(f"unknown wire payload mode {mode}")


class PeerClient:
    """Persistent connection to one server process; reply routing by msg_id
    (the Worker-side Communicator + Waiter contract)."""

    def __init__(self, host: str, port: int):
        self._sock = socket.create_connection((host, port), timeout=60)
        # The connect timeout must not become a recv timeout: this is a
        # persistent connection that legitimately sits idle.
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._send_lock = make_lock("ps.peer.send")
        self._waiters: Dict[int, Tuple[threading.Event, List]] = {}
        self._waiters_lock = make_lock("ps.peer.waiters")
        self._dead = False
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def request(self, msg: Message) -> Tuple[threading.Event, List]:
        # A dead reader can never deliver a reply: fail immediately instead
        # of letting the caller ride out its waiter timeout.
        if self._dead:
            raise OSError("connection to peer is closed")
        event = threading.Event()
        slot: List = []
        with self._waiters_lock:
            self._waiters[msg.msg_id] = (event, slot)
        with self._send_lock:
            # _send_lock exists to serialize frame writes on the one
            # shared peer socket — the wire wait IS the serialized step.
            # graftlint: disable=lock-held-across-blocking
            send_message(self._sock, msg)
        return event, slot

    def _read_loop(self) -> None:
        try:
            # Blocks in recv_message() on a deliberately-idle persistent
            # connection; liveness is the peer's to prove (ping()), and
            # socket close breaks the recv on shutdown.
            # graftlint: disable=daemon-loop-no-watchdog
            while True:
                msg = recv_message(self._sock)
                if msg is None:
                    break
                with self._waiters_lock:
                    entry = self._waiters.pop(msg.msg_id, None)
                if entry is not None:
                    event, slot = entry
                    slot.append(msg)
                    event.set()
        except OSError:
            pass
        # Peer went away: mark dead (future requests fail immediately) and
        # release every pending waiter with an empty slot so callers fail
        # fast instead of timing out.
        self._dead = True
        with self._waiters_lock:
            pending = list(self._waiters.values())
            self._waiters.clear()
        for event, _ in pending:
            event.set()

    def ping(self, timeout: float = 10.0) -> Optional[List[int]]:
        """Failure detection: round-trip a heartbeat; returns the peer's
        registered table ids, or None if the peer is unresponsive. (The
        reference had no heartbeats — SURVEY.md §5 'Failure detection:
        minimal' — this closes that gap for the DCN service.)"""
        msg = Message(type=MsgType.Heartbeat,
                      msg_id=DistributedTableBase._next_msg_id())
        try:
            event, slot = self.request(msg)
        except OSError:
            return None
        if not event.wait(timeout) or not slot or not slot[0].data:
            return None
        return slot[0].data[0].tolist()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class _PendingOp:
    """Future over a fan-out of wire requests: completes when every touched
    server replied (the reference's Waiter contract, ``src/table.cpp:41-82``
    — GetAsync/AddAsync return an id immediately; Wait(id) blocks).

    Parts carry ``(server, msg, (event, slot))`` so a lost connection can be
    retried through the membership directory: the retrier rediscovers the
    server's current address and re-sends the SAME message. At-least-once on
    retry (a server that applied an Add but died before replying applies it
    again) — the recovery trade the reference never reaches (its one-shot
    registration simply strands the rank, ``src/controller.cpp:38-72``)."""

    def __init__(self, parts: List[Tuple[int, Message,
                                         Tuple[threading.Event, List]]],
                 assemble: Optional[Callable[[List[Message]], object]] = None,
                 retrier: Optional[Callable[[int, Message],
                                            Tuple[threading.Event, List]]]
                 = None):
        self._parts = parts
        self._assemble = assemble
        self._retrier = retrier
        self._done = False
        self._result: object = None

    def wait(self, timeout: Optional[float] = 60.0):
        """``timeout=None`` waits indefinitely — BSP mode's contract (the
        reference Waiter blocks forever): a clock-gated op legitimately
        sits cached server-side until lagging workers catch up, and worker
        skew (first-call JIT, data stalls) must not become a FatalError.
        Liveness still holds: a lost connection wakes the waiter with an
        empty slot (PeerClient._read_loop) and retries through the
        directory."""
        if self._done:
            return self._result
        replies: List[Message] = []
        for server, msg, (event, slot) in self._parts:
            ok = event.wait(timeout)
            while ok and not slot:
                # Event set with an empty slot is the reader thread's
                # connection-lost release — the ONLY state that may retry
                # (resending is dedup-guarded server-side). A plain timeout
                # on a live connection still fails loudly: the request may
                # be queued server-side behind a slow dispatch.
                check(self._retrier is not None,
                      "peer connection lost during table op")
                event, slot = self._retrier(server, msg)
                ok = event.wait(timeout)
            check(ok, "remote table op timed out")
            check(slot, "peer connection lost during table op")
            check(slot[0].type != MsgType.Reply_Error,
                  f"server rejected table op on table {msg.table_id} "
                  "(unknown table — no registration within the server's "
                  "deadline)")
            replies.append(slot[0])
        self._result = (self._assemble(replies)
                        if self._assemble is not None else None)
        self._done = True
        self._parts = []    # release retained wire messages/payloads
        return self._result


class DistributedTableBase:
    """Shared plumbing: shard ownership, local forward, remote fan-out,
    and the REAL async surface — ``get_async`` fires the wire requests and
    returns before the replies arrive; ``add_async`` stages deltas in the
    native DeltaBuffer (linear updaters) so N pushes merge into ONE wire
    message per server, or fires without waiting (stateful updaters), under
    a bounded in-flight window. Read-your-writes holds because each
    (client, server) pair is one FIFO TCP stream served in order: a Get
    issued after an Add on the same connection is dispatched after it."""

    # Starts at a random 48-bit value so a RESTARTED process (elastic
    # recovery) can never reuse a (src, msg_id) pair still sitting in a
    # server's exactly-once reply cache — a collision there would silently
    # swallow the new incarnation's Adds.
    _msg_counter = int.from_bytes(os.urandom(6), "little")
    _counter_lock = make_lock("ps.client.msgid")

    MAX_PENDING = 256        # tracked-but-unwaited op ids (oldest evicted)
    MAX_INFLIGHT_ADDS = 32   # unwaited fire-and-forget add batches

    RETRY_WINDOW = 15.0      # rediscovery window for a restarting peer

    def __init__(self, table_id: int, service: PSService,
                 peers: List[Tuple[str, int]], rank: int,
                 announce: bool = True):
        self.table_id = table_id
        self.rank = rank
        self.world = len(peers)
        self._service = service
        # BSP across processes (-sync=true, ref src/server.cpp:224-231):
        # every op — including this rank's own — serializes through the
        # clock-gated dispatch of the owning shard's service, so the
        # LocalForward shortcut is disabled and delta staging (which merges
        # N adds into one message, changing the clock count) is off.
        zoo = Zoo.get()
        self._bsp = bool(zoo.sync_mode) and self.world > 1
        # BSP ops wait without deadline (reference Waiter semantics): a
        # clock-gated op is HELD server-side until laggards catch up, and
        # straggler skew >60s is routine (JIT compiles, data stalls).
        # Async-mode ops keep the fail-loud deadline.
        self._op_timeout: Optional[float] = None if self._bsp else 60.0
        self._n_local = max(1, zoo.num_local_workers)
        # Elastic slots: local worker index -> server-ALLOCATED global id
        # (``elastic_join``). Empty for the fixed roster a process was
        # launched with — _gid's arithmetic mapping stays authoritative.
        self._gid_override: Dict[int, int] = {}
        self._clients: Dict[int, PeerClient] = {}
        self._peers = peers
        # Join the REPLICATED membership directory (the Controller analog,
        # replicated on every service): a restarted rank re-registers its
        # new address with every live peer and traffic rediscovers it on
        # the next failed request — no manual reconnect(), any seat may
        # die, rank 0 included. ``announce=False`` defers the
        # registration: a RESTARTING seat must restore its shard
        # checkpoint FIRST and only then announce (call
        # ``service.enable_directory(rank, peers)``) — announcing early
        # lets a peer's retried add land on the fresh shard and be
        # OVERWRITTEN by the restore, silently losing an acked write.
        if announce:
            service.enable_directory(rank, peers)
        self._op_lock = make_rlock("ps.client.op")
        self._pending: "collections.OrderedDict[int, _PendingOp]" = \
            collections.OrderedDict()
        self._inflight_adds: "collections.deque[_PendingOp]" = \
            collections.deque()
        # msg ids handed out for staged (not yet sent) adds; resolved to the
        # flush batch's _PendingOp when the buffer drains.
        self._staged_ids: List[int] = []
        self._stage_buf: Optional[DeltaBuffer] = None
        self._stage_opt: Optional[AddOption] = None
        self._onebit_filters: Dict[int, OneBitsFilter] = {}
        # Telemetry: staged-delta depth (flush queue) + unwaited add
        # batches in flight — the DCN-path async engine gauges, qualified
        # per table so concurrent tables' streams don't conflate
        # (docs/OBSERVABILITY.md).
        self._g_stage_depth = gauge(
            f"async_engine.queue_depth.table_{table_id}")
        self._g_inflight_adds = gauge(
            f"async_engine.inflight_adds.table_{table_id}")

    def _gid(self, worker_id: int) -> int:
        """Global BSP worker id: contiguous per process (rank * local + k;
        accepts either a local index or this process's global id). A slot
        allocated at runtime by ``elastic_join`` overrides the arithmetic
        mapping for its local index."""
        if self._gid_override:
            g = self._gid_override.get(worker_id % self._n_local)
            if g is not None:
                return g
        return self.rank * self._n_local + (worker_id % self._n_local)

    def _sync_workers(self) -> int:
        """Gate size for register_shard: every (process, local worker)."""
        return self.world * self._n_local if self._bsp else 0

    def _init_staging(self, rows: int, cols: int, stageable: bool) -> None:
        if stageable and not self._bsp:
            self._stage_buf = DeltaBuffer(rows, cols)

    def _client(self, server: int) -> PeerClient:
        client = self._clients.get(server)
        if client is None:
            host, port = self._peers[server]
            client = self._clients[server] = PeerClient(host, port)
        return client

    # -- elastic rediscovery -----------------------------------------------
    def _lookup_peer(self, server: int,
                     avoid: Optional[Tuple[str, int]] = None
                     ) -> Optional[Tuple[str, int]]:
        """Current address of ``server``. The directory is REPLICATED:
        this process's own replica answers first (a restarting peer
        registers its new address with every live rank directly), then
        remote replicas are consulted in rank order — so rediscovery
        survives any seat going down, rank 0 included. ``avoid`` is the
        address the caller just failed against: a replica still holding
        it is stale, so the search continues past it (falling back to it
        only when no replica knows better — the retry loop re-polls)."""
        svc = self._service

        def candidates():
            local = svc.lookup(server)
            if local is not None:
                yield tuple(local)
            for r in range(self.world):
                if r in (self.rank, server):
                    continue
                try:
                    msg = Message(src=self.rank,
                                  type=MsgType.Control_Lookup,
                                  msg_id=self._next_msg_id(),
                                  data=[np.asarray([server],
                                                   dtype=np.int64)])
                    # Short timeout: this runs inside the 0.3s retry
                    # poll loop and a partitioned (SYN-dropping) replica
                    # must not eat the whole RETRY_WINDOW per sweep.
                    with socket.create_connection(tuple(self._peers[r]),
                                                  timeout=1.5) as s:
                        send_message(s, msg)
                        reply = recv_message(s)
                    if reply is None:
                        continue
                    port = int(reply.data[0][0])
                    if port < 0:
                        continue
                    yield (reply.data[1].tobytes().decode(), port)
                except OSError:
                    continue

        fallback = None
        for cand in candidates():   # lazy: a fresh local answer returns
            if avoid is None or cand != tuple(avoid):   # without any
                return cand                             # remote queries
            if fallback is None:
                fallback = cand
        return fallback

    def _retry_request(self, server: int, msg: Message
                       ) -> Tuple[threading.Event, List]:
        """Drop the dead connection, rediscover the peer's address, resend.
        Polls the directory for up to RETRY_WINDOW so a peer mid-restart is
        picked up as soon as it re-registers. The poll cadence is the
        standard JITTERED backoff schedule (was a fixed 0.3s): when a
        supervisor kills a shard, every client of it lands here in the
        same instant — identical sleeps would hammer the replacement in
        synchronized waves the moment it announces."""
        from multiverso_tpu.serving.client import backoff_delays
        deadline = time.monotonic() + self.RETRY_WINDOW
        delays = iter(backoff_delays(64, base_delay_s=0.1, cap_s=0.5))
        while True:
            old = self._clients.pop(server, None)
            if old is not None:
                old.close()
            # ``avoid`` is the address that JUST failed — recomputed
            # every sweep, not pinned to the first failure. Pinning let
            # one replica's stale entry (a sibling's bring-up
            # placeholder) outrank everyone's correct answer on every
            # sweep: after a single transient send fault against a
            # HEALTHY peer, the loop parked on the stale (refused) port
            # for the whole window. Chaos drill's net_drop fault found
            # this; with the per-sweep avoid, the next sweep's lookup
            # returns the good address and the request goes through.
            dead_addr = tuple(self._peers[server])
            addr = self._lookup_peer(server, avoid=dead_addr)
            if addr is not None:
                self._peers[server] = addr
            try:
                return self._client(server).request(msg)
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(next(delays, 0.5))

    def _request_or_retry(self, server: int, msg: Message
                          ) -> Tuple[threading.Event, List]:
        try:
            return self._client(server).request(msg)
        except OSError:
            return self._retry_request(server, msg)

    # -- op tracking -------------------------------------------------------
    def _insert_pending(self, msg_id: int, op: _PendingOp) -> None:
        """All tracked-op inserts go through here so the MAX_PENDING
        eviction bound holds on every path (fire-and-forget callers never
        wait, so unevicted entries would pin their delta payloads forever)."""
        self._pending[msg_id] = op
        # Evicted adds still complete via _inflight_adds; eviction only
        # forgets the caller-visible id (same contract as WorkerTable).
        while len(self._pending) > self.MAX_PENDING:
            self._pending.popitem(last=False)

    def _track(self, op: _PendingOp) -> int:
        msg_id = self._next_msg_id()
        with self._op_lock:
            self._insert_pending(msg_id, op)
        return msg_id

    def _track_add(self, op: _PendingOp) -> None:
        """Bound the unwaited-add window: block on the oldest batch once
        MAX_INFLIGHT_ADDS are outstanding (the reference bounds this with
        its one-message-in-flight MPI send queue, ``mpi_net.h:195-216``)."""
        with self._op_lock:
            self._inflight_adds.append(op)
            overflow = (self._inflight_adds.popleft()
                        if len(self._inflight_adds) > self.MAX_INFLIGHT_ADDS
                        else None)
            self._g_inflight_adds.set(len(self._inflight_adds))
        if overflow is not None:
            overflow.wait(self._op_timeout)

    def wait(self, msg_id: int, timeout: Optional[float] = None):
        """Complete an async op. Staged adds flush first (their id resolves
        to the flush batch). ``timeout=None`` uses the table's mode default
        (indefinite in BSP, 60s fail-loud in async)."""
        with self._op_lock:
            if msg_id in self._staged_ids:
                self.flush()
            op = self._pending.pop(msg_id, None)
        check(op is not None, f"unknown or already-waited msg_id {msg_id}")
        return op.wait(self._op_timeout if timeout is None else timeout)

    def flush(self, wait: bool = False) -> None:
        """Drain the staging buffer onto the wire; optionally also wait out
        every in-flight add batch."""
        with self._op_lock:
            if self._stage_buf is not None and self._stage_buf.pending:
                # Distinct from the local engine's ASYNC_FLUSH: ~us device
                # dispatches and ~ms DCN round-trips must not share one
                # histogram (a wire regression would drown in the mix).
                with monitor("DCN_FLUSH"):     # drain + wire fire latency
                    op = self._flush_staged_locked()
                self._g_stage_depth.set(self._stage_buf.pending)
                for sid in self._staged_ids:
                    if sid in self._pending:    # not yet evicted
                        self._insert_pending(sid, op)
                self._staged_ids.clear()
                self._track_add(op)
            drain = list(self._inflight_adds) if wait else []
            if wait:
                self._inflight_adds.clear()
        for op in drain:
            op.wait(self._op_timeout)

    def _flush_staged_locked(self) -> _PendingOp:
        raise NotImplementedError

    @classmethod
    def _next_msg_id(cls) -> int:
        # Explicitly on the BASE class: `cls._msg_counter += 1` from a
        # subclass would shadow the counter per subclass, and two tables
        # of different types would then emit overlapping msg_id streams —
        # colliding in the server's (src, msg_id) exactly-once cache.
        base = DistributedTableBase
        with base._counter_lock:
            base._msg_counter += 1
            return base._msg_counter

    def _bsp_tick_parts(self, msg_type: int, routed,
                        option: Optional[AddOption] = None,
                        get_option: "Optional[GetOption]" = None,
                        key_dtype=np.int32) -> List:
        """BSP invariant: EVERY op ticks EVERY server's clock. Returns one
        tick part (empty Add / sentinel Get) per server absent from
        ``routed``; empty outside sync mode. ``option`` must already carry
        the GLOBAL worker id. Centralized so a routed-table override can't
        forget the fan-out and wedge the gates (ADVICE r3 medium #2)."""
        parts: List = []
        if not self._bsp:
            return parts
        for s in range(self.world):
            if s in routed:
                continue
            if msg_type == MsgType.Request_Add:
                data = [np.empty(0, key_dtype),
                        _opt_to_array(option or AddOption())]
            else:
                data = [np.asarray([TICK_GET_KEY], key_dtype),
                        *self._get_opt_blob(get_option)]
            msg = Message(src=self.rank, type=msg_type,
                          table_id=self.table_id,
                          msg_id=self._next_msg_id(), data=data)
            parts.append((s, msg, self._request_or_retry(s, msg)))
        return parts

    def _get_opt_blob(self, option: "Optional[GetOption]"
                      ) -> List[np.ndarray]:
        """BSP Gets carry the worker's clock identity as an extra blob (the
        reference's GetOption, ``sparse_matrix_table.cpp:36-43``); async
        mode sends the bare keys."""
        if not self._bsp:
            return []
        wid = option.worker_id if option is not None else 0
        return [np.asarray([self._gid(wid)], dtype=np.int32)]

    def finish_train(self, worker_id: Optional[int] = None) -> None:
        """Release this worker from every server's BSP clocks
        (``Zoo::FinishTrain`` -> ``Server_Finish_Train``, ref
        src/zoo.cpp:152-161, src/server.cpp:190-213). No-op in async
        mode. ``worker_id`` is a local index (or this process's global
        id); each local worker retires separately."""
        if not self._bsp:
            return
        gid = self._gid(worker_id if worker_id is not None else 0)
        parts = []
        for s in range(self.world):
            msg = Message(src=self.rank, type=MsgType.Server_Finish_Train,
                          table_id=self.table_id,
                          msg_id=self._next_msg_id(),
                          data=[np.asarray([gid], dtype=np.int32)])
            try:
                parts.append((s, msg, self._request_or_retry(s, msg)))
            except OSError:
                continue    # dead server can't be holding anyone's gate
        _PendingOp(parts, retrier=self._retry_request).wait(
            self._op_timeout)

    # -- elastic membership ------------------------------------------------
    def elastic_join(self, worker_id: int = 0,
                     timeout: Optional[float] = None) -> int:
        """Announce a NEW sync worker to every server's clock group
        (MXNET-MPI elastic membership, PAPERS.md 1801.03855); returns the
        allocated global worker id and binds it to local index
        ``worker_id`` so this table's subsequent ops stamp it. Server 0 is
        the membership LEADER: it allocates the slot, the remaining
        servers adopt that id verbatim — two workers joining concurrently
        can therefore never be assigned the same slot. The join lands at
        the current epoch floor (no gate predicate regresses), and
        because announce + ops share one FIFO connection per server, no
        op stamped with the new slot can outrun the join that creates it.
        No-op (returns the arithmetic gid) in async mode."""
        if not self._bsp:
            return self._gid(worker_id)
        wid: Optional[int] = None
        for s in range(self.world):
            payload: Dict[str, object] = {"op": "join"}
            if wid is not None:
                payload["worker"] = wid
            out = self._elastic_rpc(s, payload, timeout)
            check("error" not in out, f"elastic join rejected by server "
                  f"{s}: {out}")
            wid = int(out["worker"])
        self._gid_override[worker_id % self._n_local] = wid
        return wid

    def elastic_leave(self, worker_id: int = 0,
                      timeout: Optional[float] = None) -> None:
        """Graceful leave: retire this worker from every server's clocks
        (peers' gates stop waiting on it immediately) and free its slot
        for a later :meth:`elastic_join` to reuse. Callers drain their own
        in-flight ops first (:meth:`flush`); anything still gated
        server-side is dropped with the slot. No-op in async mode."""
        if not self._bsp:
            return
        gid = self._gid(worker_id)
        for s in range(self.world):
            try:
                self._elastic_rpc(s, {"op": "leave", "worker": gid},
                                  timeout)
            except OSError:
                continue    # dead server can't be holding anyone's gate
        self._gid_override.pop(worker_id % self._n_local, None)

    def _elastic_rpc(self, server: int, payload: Dict[str, object],
                     timeout: Optional[float] = None) -> Dict[str, object]:
        from multiverso_tpu.parallel.net import (pack_json_blob,
                                                 unpack_json_blob)
        msg = Message(src=self.rank, type=MsgType.Control_Elastic,
                      table_id=self.table_id, msg_id=self._next_msg_id(),
                      data=[pack_json_blob(payload)])
        op = _PendingOp(
            [(server, msg, self._request_or_retry(server, msg))],
            assemble=lambda replies: unpack_json_blob(replies[0].data[0]),
            retrier=self._retry_request)
        return op.wait(self._op_timeout if timeout is None else timeout)

    # -- checkpointing -----------------------------------------------------
    @property
    def checkpoint_suffix(self) -> str:
        """Each rank checkpoints only its own shard; ``save_all`` qualifies
        the filename with this so shards don't collide on a shared
        filesystem (ref ``table_interface.h:61-75``: Store/Load are
        per-server-table there too)."""
        return f"-shard{self.rank}of{self.world}"

    def _shard_offset(self) -> int:
        raise NotImplementedError

    def store_state(self) -> Dict[str, np.ndarray]:
        """Serialize this rank's shard (params + updater state) plus shard
        placement metadata, via the local ServerStore. With a WAL attached
        the snapshot is captured ON the dispatcher (atomic with applies)
        and tagged with the WAL lsn it corresponds to — recovery loads the
        checkpoint and replays only records past that lsn."""
        self.flush(wait=True)     # staged/in-flight adds land first
        if self._service.wal_active:
            payload, lsn = self._service.snapshot_table(self.table_id)
            payload = dict(payload)
            payload["wal_meta"] = np.asarray([lsn], dtype=np.int64)
        else:
            payload = self.local_store.store_state()
        payload["shard_meta"] = np.asarray(
            [self.table_id, self.rank, self.world, self._shard_offset()],
            dtype=np.int64)
        return payload

    def load_state(self, payload: Dict[str, np.ndarray]) -> None:
        payload = dict(payload)
        wal_meta = payload.pop("wal_meta", None)
        if wal_meta is not None:
            # Tell the service which deltas this restore already holds;
            # harmless when no WAL is attached on the restoring side.
            self._service.note_wal_restore(
                self.table_id, int(np.asarray(wal_meta)[0]))
        meta = payload.pop("shard_meta", None)
        if meta is not None:
            _, rank, world, offset = (int(x) for x in np.asarray(meta))
            check(world == self.world and offset == self._shard_offset(),
                  f"checkpoint shard (rank {rank}/{world}, offset {offset}) "
                  f"does not match table shard (rank {self.rank}/"
                  f"{self.world}, offset {self._shard_offset()}) — "
                  "restore requires the same world size")
        self.local_store.load_state(payload)

    def reconnect(self, server: int,
                  address: Optional[Tuple[str, int]] = None) -> None:
        """Elastic re-admission: point this table at a restarted peer
        (optionally at a new address) and drop the dead connection. The
        restarted rank re-registers its shard (restored from checkpoint)
        and traffic resumes — the recovery story the reference leaves to
        'checkpoint/resume' alone (SURVEY.md §5)."""
        if address is not None:
            self._peers[server] = address
        old = self._clients.pop(server, None)
        if old is not None:
            old.close()

    def close(self) -> None:
        try:
            self.flush(wait=True)
        except Exception:  # noqa: BLE001 - peers may already be gone
            pass
        for client in self._clients.values():
            client.close()


class DistributedArrayTable(DistributedTableBase):
    """1-D table contiguously sharded across PROCESSES (the reference's
    server set), each process's shard device-resident via ServerStore."""

    def __init__(self, table_id: int, size: int,
                 service: PSService, peers: List[Tuple[str, int]],
                 rank: int, dtype=np.float32, updater: str = "default",
                 announce: bool = True):
        super().__init__(table_id, service, peers, rank, announce=announce)
        self.name = f"dist_array_{table_id}"
        self.size = size
        self.offsets = reference_server_offsets(size, self.world)
        zoo = Zoo.get()
        local_size = self.offsets[rank + 1] - self.offsets[rank]
        # Per-worker updater state (AdaGrad G^2, ref adagrad_updater.h:17-20)
        # must span the DCN worker universe — every (process, local worker)
        # — not zoo.num_workers(), which is local-only when the ranks run
        # separate JAX runtimes (process_count()==1 per rank).
        self.local_store = ServerStore(
            f"dist_array_{table_id}", (max(local_size, 1),), dtype,
            get_updater(dtype, updater), zoo.local_mesh,
            self.world * self._n_local)
        service.register_shard(table_id, self.local_store,
                               sync_workers=self._sync_workers())
        from multiverso_tpu.parallel.async_engine import _stageable
        self._init_staging(size, 1, _stageable(self.local_store.updater))

    # -- internals ---------------------------------------------------------
    def _send_add(self, delta: np.ndarray, option: AddOption) -> _PendingOp:
        """Partition + LocalForward + fire one wire message per remote
        server. Returns the reply future WITHOUT waiting."""
        # Globalize the worker id unconditionally: it indexes per-worker
        # updater state (AdaGrad) on the serving shard and, in sync mode,
        # the BSP clocks — a rank-local id would alias across ranks.
        option = dataclasses.replace(
            option, worker_id=self._gid(option.worker_id))
        mode = _wire_mode()
        parts = []
        for s in range(self.world):
            lo, hi = self.offsets[s], self.offsets[s + 1]
            if hi <= lo:
                continue
            piece = delta[lo:hi]
            if s == self.rank and not self._bsp:
                self.local_store.apply_dense(piece, option)  # LocalForward
                continue
            onebit = None
            if mode == "onebit":
                # Per-link error feedback state, sized to the peer's shard
                # (1-bit SGD semantics; stateful, so per (table, server)).
                onebit = self._onebit_filters.setdefault(
                    s, OneBitsFilter(hi - lo))
            msg = Message(src=self.rank, type=MsgType.Request_Add,
                          table_id=self.table_id,
                          msg_id=self._next_msg_id(),
                          data=[np.empty(0, np.int32),
                                _opt_to_array(option),
                                *pack_payload(piece, mode, onebit)])
            parts.append((s, msg, self._request_or_retry(s, msg)))
        return _PendingOp(parts, retrier=self._retry_request)

    def _shard_offset(self) -> int:
        return int(self.offsets[self.rank])

    def _flush_staged_locked(self) -> _PendingOp:
        merged, n = self._stage_buf.drain_dense()
        opt, self._stage_opt = self._stage_opt or AddOption(), None
        return self._send_add(merged.reshape(self.size), opt)

    # -- ops ---------------------------------------------------------------
    def add(self, delta: np.ndarray,
            option: Optional[AddOption] = None) -> None:
        delta = np.asarray(delta, dtype=np.float32)
        check(delta.shape == (self.size,), "bad delta shape")
        with self._op_lock:
            self.flush()
            op = self._send_add(delta, option or AddOption())
        op.wait(self._op_timeout)
        self.local_store.block()

    def add_async(self, delta, option: Optional[AddOption] = None) -> int:
        """Fire-and-forget under a bounded window. Linear updaters stage in
        the native DeltaBuffer — N calls become ONE wire message per server
        at the next flush/get (ref ``src/table.cpp:62-82`` returns an id
        immediately; the merge is the TPU-side improvement on it)."""
        delta = np.asarray(delta, dtype=np.float32)
        check(delta.shape == (self.size,), "bad delta shape")
        option = option or AddOption()
        with self._op_lock:
            if self._stage_buf is not None:
                if self._stage_opt is not None and option != self._stage_opt:
                    self.flush()   # option change: can't merge across it
                self._stage_opt = option
                self._stage_buf.add_dense(delta)
                self._g_stage_depth.set(self._stage_buf.pending)
                msg_id = self._next_msg_id()
                self._staged_ids.append(msg_id)
                self._insert_pending(msg_id, _PendingOp([]))  # -> flush op
                return msg_id
            op = self._send_add(delta, option)
            self._track_add(op)
            msg_id = self._track(op)
        return msg_id

    def _get_op(self, option: "Optional[GetOption]" = None) -> _PendingOp:
        self.flush()   # staged adds precede the get on each FIFO stream
        out = np.zeros(self.size, dtype=np.float32)
        parts = []
        for s in range(self.world):
            lo, hi = self.offsets[s], self.offsets[s + 1]
            if hi <= lo:
                continue
            if s == self.rank and not self._bsp:
                out[lo:hi] = np.asarray(self.local_store.read())[:hi - lo]
                continue
            msg = Message(src=self.rank, type=MsgType.Request_Get,
                          table_id=self.table_id,
                          msg_id=self._next_msg_id(),
                          data=[np.empty(0, np.int32),
                                *self._get_opt_blob(option)])
            parts.append((s, msg, self._request_or_retry(s, msg)))
        servers = [s for s, _, _ in parts]

        def assemble(replies: List[Message]) -> np.ndarray:
            for s, reply in zip(servers, replies):
                lo, hi = self.offsets[s], self.offsets[s + 1]
                out[lo:hi] = unpack_payload(reply.data).ravel()[:hi - lo]
            return out

        return _PendingOp(parts, assemble, retrier=self._retry_request)

    def get(self, option: "Optional[GetOption]" = None) -> np.ndarray:
        with self._op_lock:
            op = self._get_op(option)
        return op.wait(self._op_timeout)

    def get_async(self, option: "Optional[GetOption]" = None) -> int:
        """Issues the wire requests and returns immediately; ``wait``
        assembles the replies (ref GetAsync, ``src/table.cpp:41-60``)."""
        with self._op_lock:
            return self._track(self._get_op(option))


class DistributedMatrixTable(DistributedTableBase):
    """2-D table row-sharded across processes; row-granular Get/Add."""

    def __init__(self, table_id: int, num_row: int, num_col: int,
                 service: PSService, peers: List[Tuple[str, int]],
                 rank: int, dtype=np.float32, updater: str = "default",
                 announce: bool = True):
        super().__init__(table_id, service, peers, rank, announce=announce)
        self.name = f"dist_matrix_{table_id}"
        self.num_row = num_row
        self.num_col = num_col
        self.row_offsets = reference_server_offsets(num_row, self.world)
        zoo = Zoo.get()
        local_rows = self.row_offsets[rank + 1] - self.row_offsets[rank]
        self.local_store = ServerStore(
            f"dist_matrix_{table_id}", (max(local_rows, 1), num_col), dtype,
            get_updater(dtype, updater), zoo.local_mesh,
            self.world * self._n_local)   # DCN worker universe (see array)
        # ONE registration carrying the sparse arming too (subclass hook):
        # register-then-overwrite would open a window where peers' STALE
        # gets find the table but not its bitmap and get dropped.
        service.register_shard(table_id, self.local_store,
                               row_offset=self.row_offsets[rank],
                               sync_workers=self._sync_workers(),
                               sparse_workers=self._sparse_slots(),
                               sparse_rows=local_rows)
        from multiverso_tpu.parallel.async_engine import _stageable
        self._init_staging(num_row, num_col,
                           _stageable(self.local_store.updater))

    def _shard_offset(self) -> int:
        return int(self.row_offsets[self.rank])

    def _sparse_slots(self) -> int:
        """Per-worker staleness slots to arm on the serving shard; 0 =
        plain matrix table (DistributedSparseMatrixTable overrides)."""
        return 0

    def _route(self, rows: np.ndarray) -> Dict[int, np.ndarray]:
        out: Dict[int, List[int]] = {}
        bounds = self.row_offsets
        for i, r in enumerate(rows.tolist()):
            s = min(np.searchsorted(bounds, r, side="right") - 1,
                    self.world - 1)
            out.setdefault(int(s), []).append(i)
        return {s: np.asarray(ix, dtype=np.int64) for s, ix in out.items()}

    # -- internals ---------------------------------------------------------
    def _send_add_rows(self, rows: np.ndarray, deltas: np.ndarray,
                       option: AddOption) -> _PendingOp:
        # Globalize the worker id (per-worker updater state + BSP clocks;
        # see _send_add).
        option = dataclasses.replace(
            option, worker_id=self._gid(option.worker_id))
        parts = []
        routed = self._route(rows)
        for s, ix in routed.items():
            keys, piece = rows[ix], deltas[ix]
            if s == self.rank and not self._bsp:
                self.local_store.apply_rows(
                    keys - self.row_offsets[s], piece, option)
                continue
            msg = Message(src=self.rank, type=MsgType.Request_Add,
                          table_id=self.table_id,
                          msg_id=self._next_msg_id(),
                          data=[keys, _opt_to_array(option),
                                *pack_payload(piece, _wire_mode())])
            parts.append((s, msg, self._request_or_retry(s, msg)))
        parts.extend(self._bsp_tick_parts(MsgType.Request_Add, routed,
                                          option=option))
        return _PendingOp(parts, retrier=self._retry_request)

    # Sparse drain cap: bounds the per-flush scratch ([cap, num_col] f32,
    # e.g. 64K x 128 = 32MB) independent of table height; when more rows
    # than this are dirty the dense whole-table path below is cheaper
    # anyway (cf. AsyncTableEngine.sparse_drain_max).
    SPARSE_DRAIN_MAX = 65536

    def _flush_staged_locked(self) -> _PendingOp:
        opt, self._stage_opt = self._stage_opt or AddOption(), None
        sparse = self._stage_buf.drain_rows(
            min(self.num_row, self.SPARSE_DRAIN_MAX))
        if sparse is not None:
            ids, rows = sparse
            if len(ids) == 0:
                return _PendingOp([])
            return self._send_add_rows(np.asarray(ids, dtype=np.int32),
                                       rows, opt)
        merged, n = self._stage_buf.drain_dense()
        all_rows = np.arange(self.num_row, dtype=np.int32)
        return self._send_add_rows(all_rows,
                                   merged.reshape(self.num_row,
                                                  self.num_col), opt)

    # -- ops ---------------------------------------------------------------
    def add_rows(self, row_ids, deltas,
                 option: Optional[AddOption] = None) -> None:
        rows = np.asarray(row_ids, dtype=np.int32)
        deltas = np.asarray(deltas, dtype=np.float32)
        with self._op_lock:
            self.flush()
            op = self._send_add_rows(rows, deltas, option or AddOption())
        op.wait(self._op_timeout)
        self.local_store.block()

    def add_rows_async(self, row_ids, deltas,
                       option: Optional[AddOption] = None) -> int:
        """Stage (linear updaters: merged by the native buffer, one wire
        message per server at flush) or fire without waiting (stateful)."""
        rows = np.asarray(row_ids, dtype=np.int32)
        deltas = np.asarray(deltas, dtype=np.float32)
        option = option or AddOption()
        with self._op_lock:
            if self._stage_buf is not None:
                if self._stage_opt is not None and option != self._stage_opt:
                    self.flush()
                self._stage_opt = option
                self._stage_buf.add_rows(rows, deltas)
                self._g_stage_depth.set(self._stage_buf.pending)
                msg_id = self._next_msg_id()
                self._staged_ids.append(msg_id)
                self._insert_pending(msg_id, _PendingOp([]))  # -> flush op
                return msg_id
            op = self._send_add_rows(rows, deltas, option)
            self._track_add(op)
            msg_id = self._track(op)
        return msg_id

    def _get_rows_op(self, rows: np.ndarray,
                     option: "Optional[GetOption]" = None) -> _PendingOp:
        self.flush()
        out = np.zeros((len(rows), self.num_col), dtype=np.float32)
        parts = []
        indices = []
        routed = self._route(rows)
        for s, ix in routed.items():
            keys = rows[ix]
            if s == self.rank and not self._bsp:
                out[ix] = np.asarray(self.local_store.read_rows(
                    keys - self.row_offsets[s]))
                continue
            msg = Message(src=self.rank, type=MsgType.Request_Get,
                          table_id=self.table_id,
                          msg_id=self._next_msg_id(),
                          data=[keys, *self._get_opt_blob(option)])
            parts.append((s, msg, self._request_or_retry(s, msg)))
            indices.append(ix)
        # Tick parts go AFTER the data parts so assemble's zip skips them.
        parts.extend(self._bsp_tick_parts(MsgType.Request_Get, routed,
                                          get_option=option))

        def assemble(replies: List[Message]) -> np.ndarray:
            for ix, reply in zip(indices, replies):
                out[ix] = unpack_payload(reply.data)
            return out

        return _PendingOp(parts, assemble, retrier=self._retry_request)

    def get_rows(self, row_ids,
                 option: "Optional[GetOption]" = None) -> np.ndarray:
        rows = np.asarray(row_ids, dtype=np.int32)
        with self._op_lock:
            op = self._get_rows_op(rows, option)
        return op.wait(self._op_timeout)

    def get_rows_async(self, row_ids,
                       option: "Optional[GetOption]" = None) -> int:
        """Wire requests fired, id returned before replies arrive — the
        pipelined-pull primitive (ref ``ps_model.cpp:236-271``)."""
        rows = np.asarray(row_ids, dtype=np.int32)
        with self._op_lock:
            return self._track(self._get_rows_op(rows, option))


class KVServerStore:
    """Host-side hash-map shard store for :class:`DistributedKVTable`.

    The reference's KV server map does ``+=`` on Add and returns values on
    Get (``include/multiverso/table/kv_table.h:86-106``). Keys are
    non-negative int64 (negative keys are reserved wire sentinels) and
    values keep their declared dtype on the wire
    (``wire_raw``) — the word-count table needs exact integer accumulation
    (float32 drifts past 2^24 words). Accessed only from the service's
    single dispatcher thread plus checkpoint calls; the lock covers the
    latter."""

    wire_raw = True

    def __init__(self, name: str, dtype=np.int64):
        self.name = name
        self.dtype = np.dtype(dtype)
        self._map: Dict[int, float] = {}
        self._lock = make_lock("ps.sparse.shard")

    def apply_rows(self, keys: np.ndarray, values: np.ndarray,
                   opt: Optional[AddOption] = None) -> None:
        values = np.asarray(values).ravel()
        with self._lock:
            for k, v in zip(np.asarray(keys).ravel().tolist(),
                            values.tolist()):
                self._map[k] = self._map.get(k, 0) + v

    def read_rows(self, keys: np.ndarray) -> np.ndarray:
        with self._lock:
            return np.asarray([self._map.get(k, 0)
                               for k in np.asarray(keys).ravel().tolist()],
                              dtype=self.dtype)

    def read(self) -> np.ndarray:
        """Whole-shard view — (keys, values) stacked; used by checkpoints
        and the sparse-shard row probe, never by the wire protocol."""
        with self._lock:
            ks = np.asarray(sorted(self._map), dtype=np.int64)
            return np.stack([ks.astype(self.dtype),
                             np.asarray([self._map[int(k)] for k in ks],
                                        dtype=self.dtype)]) \
                if ks.size else np.zeros((2, 0), dtype=self.dtype)

    def block(self) -> None:
        pass    # host map: adds are synchronous

    def store_state(self) -> Dict[str, np.ndarray]:
        with self._lock:
            keys = np.asarray(sorted(self._map), dtype=np.int64)
            vals = np.asarray([self._map[int(k)] for k in keys],
                              dtype=self.dtype)
        return {"kv_keys": keys, "kv_values": vals}

    def load_state(self, payload: Dict[str, np.ndarray]) -> None:
        with self._lock:
            self._map = dict(zip(payload["kv_keys"].tolist(),
                                 payload["kv_values"].tolist()))


class DistributedKVTable(DistributedTableBase):
    """Key->value table hash-partitioned across PS shards over DCN.

    The reference partitions by ``key % num_servers``
    (``kv_table.h:48-50``) and merges with ``+=`` server-side
    (``kv_table.h:86-93``); here each shard is a :class:`KVServerStore`
    behind this process's :class:`PSService`, so KV entries live where the
    hash says — across real processes, not a list of dicts in one (the
    round-3 gap). Checkpointing rides the standard per-rank shard path."""

    def __init__(self, table_id: int, service: PSService,
                 peers: List[Tuple[str, int]], rank: int, dtype=np.int64,
                 announce: bool = True):
        super().__init__(table_id, service, peers, rank, announce=announce)
        self.name = f"dist_kv_{table_id}"
        self.value_dtype = np.dtype(dtype)
        self.local_store = KVServerStore(self.name, dtype)
        service.register_shard(table_id, self.local_store,
                               sync_workers=self._sync_workers())

    def _shard_offset(self) -> int:
        return 0    # hash-partitioned: no contiguous offset

    def _route_keys(self, keys: np.ndarray) -> Dict[int, np.ndarray]:
        """``key % num_servers`` (ref kv_table.h:48-50), by index —
        vectorized: bulk KV ops must not pay a Python loop per key."""
        owners = keys % self.world
        return {int(s): np.flatnonzero(owners == s)
                for s in np.unique(owners)}

    def _send_add(self, keys: np.ndarray, values: np.ndarray,
                  option: AddOption) -> _PendingOp:
        option = dataclasses.replace(
            option, worker_id=self._gid(option.worker_id))
        parts = []
        routed = self._route_keys(keys)
        for s, ix in routed.items():
            if s == self.rank and not self._bsp:
                self.local_store.apply_rows(keys[ix], values[ix], option)
                continue
            msg = Message(src=self.rank, type=MsgType.Request_Add,
                          table_id=self.table_id,
                          msg_id=self._next_msg_id(),
                          data=[keys[ix], _opt_to_array(option),
                                np.ascontiguousarray(values[ix])])
            parts.append((s, msg, self._request_or_retry(s, msg)))
        parts.extend(self._bsp_tick_parts(MsgType.Request_Add, routed,
                                          option=option,
                                          key_dtype=np.int64))
        return _PendingOp(parts, retrier=self._retry_request)

    @staticmethod
    def _check_keys(keys: np.ndarray) -> np.ndarray:
        """Keys must be non-negative int64: the wire reserves the negative
        key space for protocol sentinels (TICK_GET_KEY, STALE_GET_KEY)."""
        check(keys.size == 0 or int(keys.min()) >= 0,
              "KV keys must be non-negative (negative keys are reserved "
              "wire sentinels)")
        return keys

    def add(self, keys, values, option: Optional[AddOption] = None) -> None:
        keys = self._check_keys(np.asarray(keys, dtype=np.int64).ravel())
        values = np.asarray(values, dtype=self.value_dtype).ravel()
        check(len(keys) == len(values), "keys/values length mismatch")
        self._send_add(keys, values, option or AddOption()) \
            .wait(self._op_timeout)

    def add_async(self, keys, values,
                  option: Optional[AddOption] = None) -> int:
        keys = self._check_keys(np.asarray(keys, dtype=np.int64).ravel())
        values = np.asarray(values, dtype=self.value_dtype).ravel()
        check(len(keys) == len(values), "keys/values length mismatch")
        op = self._send_add(keys, values, option or AddOption())
        self._track_add(op)
        return self._track(op)

    def _get_op(self, keys: np.ndarray,
                option: "Optional[GetOption]" = None) -> _PendingOp:
        out = np.zeros(len(keys), dtype=self.value_dtype)
        parts, indices = [], []
        routed = self._route_keys(keys)
        for s, ix in routed.items():
            if s == self.rank and not self._bsp:
                out[ix] = self.local_store.read_rows(keys[ix])
                continue
            msg = Message(src=self.rank, type=MsgType.Request_Get,
                          table_id=self.table_id,
                          msg_id=self._next_msg_id(),
                          data=[keys[ix], *self._get_opt_blob(option)])
            parts.append((s, msg, self._request_or_retry(s, msg)))
            indices.append(ix)
        parts.extend(self._bsp_tick_parts(MsgType.Request_Get, routed,
                                          get_option=option,
                                          key_dtype=np.int64))

        def assemble(replies: List[Message]) -> np.ndarray:
            for ix, reply in zip(indices, replies):
                out[ix] = reply.data[0].astype(self.value_dtype)
            return out

        return _PendingOp(parts, assemble, retrier=self._retry_request)

    def get(self, keys, option: "Optional[GetOption]" = None) -> np.ndarray:
        keys = self._check_keys(np.asarray(keys, dtype=np.int64).ravel())
        return self._get_op(keys, option).wait(self._op_timeout)

    def get_async(self, keys,
                  option: "Optional[GetOption]" = None) -> int:
        keys = self._check_keys(np.asarray(keys, dtype=np.int64).ravel())
        return self._track(self._get_op(keys, option))


class DistributedSparseMatrixTable(DistributedMatrixTable):
    """Row-sharded matrix with SERVER-SIDE per-worker staleness over DCN.

    The round-3 gap: the in-process SparseMatrixTable tracked staleness
    client-side only, so every DCN Get shipped every requested row. Here
    each PSService shard owns the reference's ``up_to_date_`` bitmap
    (sparse_matrix_table.cpp:184-258): Adds invalidate touched rows for
    other workers, and the incremental whole-table ``get`` pulls ONLY the
    rows stale for this worker from every shard — wire bytes scale with
    rows touched since the last pull, not with table size."""

    def __init__(self, table_id: int, num_row: int, num_col: int,
                 service: PSService, peers: List[Tuple[str, int]],
                 rank: int, dtype=np.float32, updater: str = "default",
                 announce: bool = True):
        # Bitmap semantics are always the reference's loose UpdateAddState
        # (_SparseShardState docstring). Plain-add clients ADDITIONALLY
        # mirror their own delta into their cache so rows that were fresh
        # stay both fresh and correct; stateful updaters (sgd/ftrl — the
        # client cannot reproduce the server-side step) skip the mirror
        # and see own writes on the next pull of a stale row. Decided
        # from the RESOLVED updater instance after super().__init__ (a
        # typo'd name silently resolves to plain add in get_updater and
        # must still mirror). Placeholders set BEFORE super() because the
        # parent's register_shard path runs during it.
        self._mirror = False
        self._incr_cache: Dict[int, np.ndarray] = {}
        self.last_incremental_rows = 0   # observability (tests/monitor)
        super().__init__(table_id, num_row, num_col, service, peers, rank,
                         dtype=dtype, updater=updater, announce=announce)
        self.name = f"dist_sparse_matrix_{table_id}"
        from multiverso_tpu.core.updater import Updater
        self._mirror = type(self.local_store.updater) is Updater

    def _sparse_slots(self) -> int:
        """Arm the serving shard's staleness bitmap for the DCN worker
        universe (bitmap spans the REAL local rows — 0 on an empty
        shard)."""
        return self.world * self._n_local

    def _cache_for(self, wid: int) -> np.ndarray:
        cache = self._incr_cache.get(wid)
        if cache is None:
            cache = self._incr_cache[wid] = np.zeros(
                (self.num_row, self.num_col), dtype=np.float32)
        return cache

    def _send_add_rows(self, rows: np.ndarray, deltas: np.ndarray,
                       option: AddOption) -> _PendingOp:
        """Adds must reach the staleness bitmap even for this rank's own
        shard, so the LocalForward shortcut is disabled: route EVERYTHING
        through the service dispatch (still in-process for the local
        shard, one loopback hop). The server leaves the writer's own bits
        UNCHANGED (loose UpdateAddState, ref :199-223); plain-add clients
        mirror the delta into their cache here so rows that were fresh
        stay both fresh and correct."""
        option = dataclasses.replace(
            option, worker_id=self._gid(option.worker_id))
        if self._mirror:
            mirror_deltas = np.asarray(deltas, dtype=np.float32)
            if _wire_mode() == "bf16":
                # The freshness contract wants mirror == what the server
                # applied; in bf16 mode that is the ROUNDED delta. Adds
                # then contribute ZERO mirror/server drift — the only
                # residual is the one rounding of the priming pull (bf16
                # reply of a possibly-unrepresentable server value), so
                # total drift is bounded by one bf16 rounding of the
                # primed magnitude, never accumulating per add. That is
                # the precision the operator opted into with bf16 wire.
                from multiverso_tpu.utils.quantization import (
                    bf16_bits_to_f32, f32_to_bf16_bits)
                mirror_deltas = bf16_bits_to_f32(
                    f32_to_bf16_bits(mirror_deltas)).reshape(
                        mirror_deltas.shape)
            np.add.at(self._cache_for(option.worker_id),
                      np.asarray(rows, dtype=np.int64), mirror_deltas)
        parts = []
        routed = self._route(rows)
        for s, ix in routed.items():
            # Mirror mode packs clip=0.0: the freshness contract requires
            # the server to apply EXACTLY the delta the client mirrored —
            # the lossy user clip threshold would diverge them silently.
            msg = Message(src=self.rank, type=MsgType.Request_Add,
                          table_id=self.table_id,
                          msg_id=self._next_msg_id(),
                          data=[rows[ix], _opt_to_array(option),
                                *pack_payload(
                                    deltas[ix], _wire_mode(),
                                    clip=0.0 if self._mirror else None)])
            parts.append((s, msg, self._request_or_retry(s, msg)))
        parts.extend(self._bsp_tick_parts(MsgType.Request_Add, routed,
                                          option=option))
        return _PendingOp(parts, retrier=self._retry_request)

    def _run_incremental(self, option: "Optional[GetOption]",
                         build_parts, result_fn) -> np.ndarray:
        """Shared scaffold for the two incremental-get entry points:
        flush, resolve the worker cache, fire ``build_parts(wid, cache)``
        (returning ``(parts, n_data)`` — data parts FIRST, BSP ticks
        after), scatter the served rows into the cache, and hand the
        cache to ``result_fn``.

        Async mode holds ``_op_lock`` through the wait: a concurrent
        ``add_rows`` mutates the same cache (the plain-add mirror), so a
        stale-get reply applied out of order with it could leave the
        cache holding pre-add values for a row whose fresh bit the mirror
        relies on. BSP waits outside the lock (the clock gates already
        enforce per-worker program order, and a gated wait under the lock
        could deadlock against another local worker's add on the same
        handle)."""
        with self._op_lock:
            self.flush()
            wid = self._gid(option.worker_id if option is not None else 0)
            cache = self._cache_for(wid)
            parts, n_data = build_parts(wid)

            def assemble(replies: List[Message]) -> np.ndarray:
                pulled = 0
                for reply in replies[:n_data]:
                    rows = reply.data[0]
                    if rows.size:
                        cache[rows] = unpack_payload(reply.data[1:])
                    pulled += int(rows.size)
                self.last_incremental_rows = pulled
                return result_fn(cache)

            op = _PendingOp(parts, assemble, retrier=self._retry_request)
            if not self._bsp:
                return op.wait(self._op_timeout)
        return op.wait(self._op_timeout)

    def get(self, option: "Optional[GetOption]" = None) -> np.ndarray:
        """Incremental whole-table get: each shard returns only the rows
        stale for this worker; the rest come from the local cache.

        View semantics per updater (see ``_SparseShardState``): plain-add
        tables mirror, so the view is fully current INCLUDING this
        worker's own writes; stateful updaters (sgd/ftrl) follow the
        reference's loose contract — the view is this worker's LAST PULL
        of each fresh row, and its own writes to fresh rows surface only
        once any worker re-stales them (the reference's exact
        UpdateAddState/UpdateGetState behavior)."""

        def build(wid):
            parts = []
            for s in range(self.world):
                msg = Message(src=self.rank, type=MsgType.Request_Get,
                              table_id=self.table_id,
                              msg_id=self._next_msg_id(),
                              data=[np.asarray([STALE_GET_KEY], np.int32),
                                    np.asarray([wid], np.int32)])
                parts.append((s, msg, self._request_or_retry(s, msg)))
            return parts, len(parts)

        return self._run_incremental(option, build,
                                     lambda cache: cache.copy())

    def get_rows(self, row_ids,
                 option: "Optional[GetOption]" = None) -> np.ndarray:
        """Keyed get. With a GetOption it is INCREMENTAL (the reference's
        keyed UpdateGetState, :244-253): only the stale subset of the
        requested rows crosses the wire; the rest come from this worker's
        cache — the pull shape of the distributed w2v cycle, where row
        sets overlap heavily across blocks. View semantics per updater
        are as :meth:`get` documents (stateful updaters: own writes to
        fresh rows surface on re-stale, the reference's loose contract).
        Without an option it is the plain non-incremental pull
        (staleness untouched, always server truth)."""
        if option is None:
            return super().get_rows(row_ids)
        req = np.asarray(row_ids, dtype=np.int32)
        uniq = np.unique(req)

        def build(wid):
            parts = []
            routed = self._route(uniq)
            for s, ix in routed.items():
                msg = Message(src=self.rank, type=MsgType.Request_Get,
                              table_id=self.table_id,
                              msg_id=self._next_msg_id(),
                              data=[np.asarray([STALE_ROWS_GET_KEY],
                                               np.int32),
                                    np.asarray([wid], np.int32),
                                    uniq[ix]])
                parts.append((s, msg, self._request_or_retry(s, msg)))
            n_data = len(parts)
            parts.extend(self._bsp_tick_parts(MsgType.Request_Get, routed,
                                              get_option=option))
            return parts, n_data

        return self._run_incremental(option, build,
                                     lambda cache: cache[req])

    def load_state(self, payload: Dict[str, np.ndarray]) -> None:
        """Restore this rank's SHARD and mark its whole bitmap stale (the
        reference initializes all-stale), so every worker re-pulls the
        restored rows. The worker-side incremental caches are KEPT: rows
        fresh on REMOTE shards were not restored and their cached values
        remain correct — clearing the cache while only the local bitmap
        resets would serve zeros for them. In a full-cluster restore
        every shard resets its own bitmap, so every row re-ships and
        stale cache contents are overwritten either way."""
        super().load_state(payload)
        st = self._service._sparse.get(self.table_id)
        if st is not None:
            st.stale[:] = True
