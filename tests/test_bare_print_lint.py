"""Lint: no bare ``print(`` inside ``multiverso_tpu/``.

Framework output routes through ``utils/log.py`` (leveled lines, optional
file sink, ``log.raw`` for format-stable CLI results) or the Dashboard's
explicit ``display(echo=True)`` path — a bare print bypasses the file
sink, breaks log-level filtering, and interleaves across the PS service's
threads. ``utils/log.py`` itself is the one sanctioned emitter."""

import io
import os
import tokenize

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_REPO, "multiverso_tpu")
_ALLOWED = {os.path.join("multiverso_tpu", "utils", "log.py")}


def _print_calls(path):
    """(line, col) of every ``print(`` NAME token — tokenizer-based, so
    strings, comments, and attributes like ``pprint.print`` don't trip."""
    with open(path, "rb") as f:
        source = f.read()
    hits = []
    tokens = list(tokenize.tokenize(io.BytesIO(source).readline))
    for i, tok in enumerate(tokens):
        if tok.type != tokenize.NAME or tok.string != "print":
            continue
        # attribute access (x.print) is not the builtin
        prev = next((t for t in reversed(tokens[:i])
                     if t.type not in (tokenize.NL, tokenize.NEWLINE,
                                       tokenize.INDENT, tokenize.DEDENT,
                                       tokenize.COMMENT)), None)
        if prev is not None and prev.type == tokenize.OP \
                and prev.string == ".":
            continue
        nxt = next((t for t in tokens[i + 1:]
                    if t.type not in (tokenize.NL, tokenize.NEWLINE,
                                      tokenize.COMMENT)), None)
        if nxt is not None and nxt.type == tokenize.OP \
                and nxt.string == "(":
            hits.append((tok.start[0], tok.start[1]))
    return hits


def test_no_bare_print_in_framework():
    offenders = []
    for root, _, files in os.walk(_PKG):
        if "__pycache__" in root:
            continue
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            rel = os.path.relpath(path, _REPO)
            if rel in _ALLOWED:
                continue
            for line, col in _print_calls(path):
                offenders.append(f"{rel}:{line}:{col}")
    assert not offenders, (
        "bare print( in framework code (route through utils/log.py or "
        "Dashboard.display(echo=True)): " + ", ".join(offenders))
