"""Continuous-batching decode: bitwise parity + iteration-level joining.

The headline contract (ISSUE 9): a request that JOINS a running decode
batch at a step boundary produces tokens BIT-IDENTICAL to decoding it
through the drain-first path (``AttentionLMRunner.run``) — slot/position
decoupling means pad slots and neighbors are never attended, so which
slots happen to be busy when you arrive cannot change your tokens."""

import threading
import time

import numpy as np
import pytest


def _lm(max_new=6, max_batch=3):
    import jax

    from multiverso_tpu.models.attention_lm import LMConfig, init_params
    from multiverso_tpu.serving import AttentionLMRunner

    cfg = LMConfig(vocab=61, dim=32, heads=4, layers=2, seq=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    runner = AttentionLMRunner({k: np.asarray(v) for k, v in
                                params.items()}, cfg, max_new=max_new,
                               max_batch=max_batch)
    return runner, params, cfg


def _solo_drain_tokens(runner, prompt, bucket):
    """The drain-first reference: this prompt alone through
    AttentionLMRunner.run at the same bucket."""
    mat = np.zeros((runner.max_batch, bucket), np.int32)
    mat[0, :len(prompt)] = prompt
    lens = np.zeros(runner.max_batch, np.int32)
    lens[0] = len(prompt)
    return runner.run(mat, lens)[0].tolist()


def test_late_join_tokens_bitwise_equal_drain_path(mv_env):
    """Submit A; while A decodes, submit B and C (late joiners claiming
    free KV slots). All three must match their solo drain-path tokens
    exactly, and the engine must have had >1 slot active at once."""
    from multiverso_tpu.serving import ContinuousBatcher
    from multiverso_tpu.telemetry import get_registry

    runner, _, _ = _lm(max_new=8, max_batch=3)
    prompts = [[5, 9, 2], [1], [7, 3, 3, 3, 8, 2, 40]]
    solo = {tuple(p): _solo_drain_tokens(runner, p, bucket=8)
            for p in prompts}

    cb = ContinuousBatcher(runner, buckets=(8,), max_batch=3,
                           max_queue=16)
    try:
        f1 = cb.submit(np.asarray(prompts[0], np.int32),
                       deadline_ms=60_000)
        # Wait until A is genuinely mid-decode before the others join.
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            eng = cb._engines.get(8)
            if eng is not None and eng.n_active() and eng.t.max() >= 1:
                break
            time.sleep(0.001)
        f2 = cb.submit(np.asarray(prompts[1], np.int32),
                       deadline_ms=60_000)
        f3 = cb.submit(np.asarray(prompts[2], np.int32),
                       deadline_ms=60_000)
        for p, f in zip(prompts, (f1, f2, f3)):
            assert f.wait(60).tolist() == solo[tuple(p)], p
        snap = get_registry().snapshot(buckets=False)
        assert snap["gauges"]["serve.continuous.active"]["max"] >= 2, \
            "requests never shared the decode batch"
        assert snap["counters"]["serve.continuous.joins"]["value"] == 3
    finally:
        cb.close()


def test_slot_reuse_after_completion_stays_bitwise(mv_env):
    """A slot freed by a finished request is re-prefilled by the next —
    stale K/V in the generated region must never leak into the new
    occupant's tokens (the mask contract). Drive 3x max_batch requests
    through 2 slots worth of churn."""
    from multiverso_tpu.serving import ContinuousBatcher

    runner, _, _ = _lm(max_new=4, max_batch=2)
    rng = np.random.default_rng(11)
    prompts = [rng.integers(1, 60, int(n)).tolist()
               for n in rng.integers(1, 8, 6)]
    solo = [_solo_drain_tokens(runner, p, bucket=8) for p in prompts]

    cb = ContinuousBatcher(runner, buckets=(8,), max_batch=2,
                           max_queue=16)
    try:
        futs = [cb.submit(np.asarray(p, np.int32), deadline_ms=60_000)
                for p in prompts]
        for p, want, f in zip(prompts, solo, futs):
            assert f.wait(60).tolist() == want, p
    finally:
        cb.close()


def test_max_new_one_parity(mv_env):
    """max_new=1: the request completes straight out of prefill. A step
    must never overwrite its only token before delivery (regression:
    the loop once stepped freshly-joined slots before delivering)."""
    from multiverso_tpu.serving import ContinuousBatcher

    runner, _, _ = _lm(max_new=1, max_batch=2)
    cb = ContinuousBatcher(runner, buckets=(8,), max_batch=2,
                           max_queue=8)
    try:
        for p in ([5, 9, 2], [1], [7, 3, 3]):
            want = _solo_drain_tokens(runner, p, bucket=8)
            got = cb.submit(np.asarray(p, np.int32),
                            deadline_ms=60_000).wait(60)
            assert got.tolist() == want, p
    finally:
        cb.close()


def test_same_boundary_completions_batch_into_one_read(mv_env):
    """Requests that join at the same step boundary finish at the same
    boundary and deliver via ONE gathered device sync
    (``serve.continuous.batched_reads``) — with tokens still bitwise
    equal to the solo drain path. The submits happen under the batcher's
    (reentrant) cv so the worker claims all three in one round."""
    from multiverso_tpu.serving import ContinuousBatcher
    from multiverso_tpu.telemetry import get_registry

    runner, _, _ = _lm(max_new=3, max_batch=3)
    prompts = [[5, 9, 2], [1], [7, 3, 3]]
    solo = [_solo_drain_tokens(runner, p, bucket=8) for p in prompts]

    cb = ContinuousBatcher(runner, buckets=(8,), max_batch=3,
                           max_queue=16)
    try:
        with cb._cv:        # hold the worker until all three are queued
            futs = [cb.submit(np.asarray(p, np.int32),
                              deadline_ms=60_000) for p in prompts]
        for p, want, f in zip(prompts, solo, futs):
            assert f.wait(60).tolist() == want, p
        snap = get_registry().snapshot(buckets=False)
        assert snap["counters"]["serve.continuous.batched_reads"][
            "value"] >= 1, "same-boundary completions were read one-by-one"
    finally:
        cb.close()


def test_multi_bucket_engines_and_jit_accounting(mv_env):
    """One prefill + one step executable per exercised bucket (the
    no-retrace witness, continuous flavor)."""
    from multiverso_tpu.serving import ContinuousBatcher

    runner, _, _ = _lm(max_new=3, max_batch=2)
    cb = ContinuousBatcher(runner, buckets=(4, 8), max_batch=2,
                           max_queue=16)
    try:
        s4 = _solo_drain_tokens(runner, [5, 9], bucket=4)
        assert cb.submit(np.asarray([5, 9], np.int32),
                         deadline_ms=60_000).wait(60).tolist() == s4
        assert cb.jit_cache_size() == 1
        s8 = _solo_drain_tokens(runner, [7, 3, 3, 3, 8], bucket=8)
        assert cb.submit(np.asarray([7, 3, 3, 3, 8], np.int32),
                         deadline_ms=60_000).wait(60).tolist() == s8
        assert cb.jit_cache_size() == 2
        # step compiles in lockstep with prefill: same bucket count
        assert int(cb._step._cache_size()) == 2
        # re-serving an old bucket never retraces
        assert cb.submit(np.asarray([5, 9], np.int32),
                         deadline_ms=60_000).wait(60).tolist() == s4
        assert cb.jit_cache_size() == 2
    finally:
        cb.close()


def test_continuous_through_service_with_swap(mv_env):
    """Full plane: register with continuous=True, serve decodes over the
    wire, hot-swap params mid-life (swap lands at a step boundary; the
    NEXT request serves the new weights, tokens again == solo drain)."""
    import jax

    from multiverso_tpu.models.attention_lm import init_params
    from multiverso_tpu.serving import ServingClient, ServingService

    runner, _, cfg = _lm(max_new=5, max_batch=2)
    svc = ServingService()
    svc.register_runner(runner, buckets=(8,), max_batch=2,
                        max_wait_ms=1.0, continuous=True)
    assert svc.warmup() == 2                       # prefill + step
    cli = ServingClient(*svc.address)
    try:
        prompt = [5, 9, 2]
        want = _solo_drain_tokens(runner, prompt, bucket=8)
        got = cli.generate(np.asarray(prompt, np.int32),
                           deadline_ms=60_000, timeout=120)
        assert got.tolist() == want

        new_params = {k: np.asarray(v) for k, v in init_params(
            cfg, jax.random.PRNGKey(9)).items()}
        runner.swap_params(new_params)
        want2 = _solo_drain_tokens(runner, prompt, bucket=8)
        assert want2 != want                       # weights really moved
        got2 = cli.generate(np.asarray(prompt, np.int32),
                            deadline_ms=60_000, timeout=120)
        assert got2.tolist() == want2
    finally:
        cli.close()
        svc.close()


def test_continuous_admission_sheds_and_cancels(mv_env):
    """The DynamicBatcher admission surface carries over: oversize sheds
    immediately, an expired deadline sheds at the claim boundary, and a
    queued cancel never reaches a KV slot."""
    from multiverso_tpu.serving import ContinuousBatcher, ShedError

    runner, _, _ = _lm(max_new=4, max_batch=1)
    cb = ContinuousBatcher(runner, buckets=(4,), max_batch=1,
                           max_queue=8)
    try:
        with pytest.raises(ShedError) as e:
            cb.submit(np.arange(9, dtype=np.int32) + 1,
                      deadline_ms=60_000).wait(30)
        assert e.value.reason == "oversize"

        with pytest.raises(ShedError) as e:
            cb.submit(np.asarray([3], np.int32), deadline_ms=0.0).wait(30)
        assert e.value.reason == "deadline"

        # occupy the single slot, then cancel a queued request
        running = cb.submit(np.asarray([5, 9], np.int32),
                            deadline_ms=60_000)
        done = threading.Event()
        outcome = []

        def on_done(result):
            outcome.append(result)
            done.set()

        token = cb.submit_callback(np.asarray([7], np.int32), 60_000.0,
                                   on_done)
        if token is not None and cb.cancel(token):
            assert done.wait(30)
            assert isinstance(outcome[0], ShedError)
            assert outcome[0].reason == "cancelled"
        running.wait(60)
    finally:
        cb.close()


def test_continuous_quiesce_barrier(mv_env):
    """quiesce() returns only once every slot drained — the checkpoint
    swap barrier, continuous flavor."""
    from multiverso_tpu.serving import ContinuousBatcher

    runner, _, _ = _lm(max_new=12, max_batch=2)
    cb = ContinuousBatcher(runner, buckets=(8,), max_batch=2,
                           max_queue=8)
    try:
        f = cb.submit(np.asarray([5, 9, 2], np.int32), deadline_ms=60_000)
        assert cb.quiesce(timeout_s=60)
        # the request finished before quiesce reported idle
        assert f.event.is_set()
        f.wait(5)
    finally:
        cb.close()
