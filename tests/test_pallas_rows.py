"""Pallas row-op kernels vs numpy references (interpret mode on CPU)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from multiverso_tpu.ops.pallas_rows import (gather_rows, scatter_add_rows,
                                            scatter_add_sorted_rows)


def test_gather_rows():
    rng = np.random.default_rng(0)
    table = rng.normal(size=(64, 128)).astype(np.float32)
    ids = np.array([3, 0, 63, 3, 17], dtype=np.int32)
    out = gather_rows(jnp.asarray(table), jnp.asarray(ids), interpret=True)
    np.testing.assert_allclose(np.asarray(out), table[ids])


def test_scatter_add_sorted_unique():
    table = np.zeros((16, 128), dtype=np.float32)
    ids = np.array([1, 4, 9], dtype=np.int32)
    deltas = np.ones((3, 128), dtype=np.float32)
    out = scatter_add_sorted_rows(jnp.asarray(table), jnp.asarray(ids),
                                  jnp.asarray(deltas), interpret=True)
    expected = table.copy()
    expected[ids] += 1.0
    np.testing.assert_allclose(np.asarray(out), expected)


def test_scatter_add_duplicates_accumulate():
    table = np.ones((8, 128), dtype=np.float32)
    ids = np.array([2, 2, 2, 5], dtype=np.int32)
    deltas = np.stack([np.full(128, float(i + 1), dtype=np.float32)
                       for i in range(4)])
    out = scatter_add_sorted_rows(jnp.asarray(table), jnp.asarray(ids),
                                  jnp.asarray(deltas), interpret=True)
    expected = np.ones((8, 128), dtype=np.float32)
    expected[2] += 1 + 2 + 3
    expected[5] += 4
    np.testing.assert_allclose(np.asarray(out), expected)


def test_scatter_add_run_crossing_group_boundary():
    """Regression: a duplicate-id run longer than GROUP(8) spanning a group
    boundary must not drop the first group's partial sum (advisor round-1
    finding: 16 deltas of 1.0 yielded +8.0; [1]*10+[3]*6 yielded +2.0)."""
    table = np.zeros((8, 128), dtype=np.float32)
    ids = np.full(16, 1, dtype=np.int32)
    deltas = np.ones((16, 128), dtype=np.float32)
    out = scatter_add_sorted_rows(jnp.asarray(table), jnp.asarray(ids),
                                  jnp.asarray(deltas), interpret=True)
    expected = np.zeros((8, 128), dtype=np.float32)
    expected[1] = 16.0
    np.testing.assert_allclose(np.asarray(out), expected)

    ids = np.array([1] * 10 + [3] * 6, dtype=np.int32)
    out = scatter_add_sorted_rows(jnp.zeros((8, 128), dtype=jnp.float32),
                                  jnp.asarray(ids), jnp.asarray(deltas),
                                  interpret=True)
    expected = np.zeros((8, 128), dtype=np.float32)
    expected[1] = 10.0
    expected[3] = 6.0
    np.testing.assert_allclose(np.asarray(out), expected)


def test_scatter_add_long_runs_random():
    """Runs of random lengths (1..20) across several group boundaries."""
    rng = np.random.default_rng(7)
    table = rng.normal(size=(32, 128)).astype(np.float32)
    ids = np.sort(rng.integers(0, 32, size=67)).astype(np.int32)
    deltas = rng.normal(size=(67, 128)).astype(np.float32)
    out = scatter_add_sorted_rows(jnp.asarray(table), jnp.asarray(ids),
                                  jnp.asarray(deltas), interpret=True)
    expected = table.copy()
    np.add.at(expected, ids, deltas)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-4,
                               atol=1e-5)


def test_scatter_add_unsorted_wrapper():
    rng = np.random.default_rng(1)
    table = rng.normal(size=(32, 128)).astype(np.float32)
    ids = np.array([9, 2, 9, 31, 0, 2], dtype=np.int32)
    deltas = rng.normal(size=(6, 128)).astype(np.float32)
    out = scatter_add_rows(jnp.asarray(table), jnp.asarray(ids),
                           jnp.asarray(deltas), interpret=True)
    expected = table.copy()
    np.add.at(expected, ids, deltas)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5)


def test_pallas_table_path(monkeypatch):
    """MatrixTable with use_pallas=True routes row ops through the Mosaic
    kernels (interpret mode on CPU) with identical semantics. Eligibility
    needs a single shard: restrict the mesh to one device."""
    import multiverso_tpu as mv

    mv.init([], devices=jax.devices()[:1])
    try:
        t = mv.create_table(mv.MatrixTableOption(num_row=64, num_col=128,
                                                 use_pallas=True))
        assert t.store._pallas_rows
        rows = [3, 9, 3, 63]
        deltas = np.stack([np.full(128, float(i + 1), dtype=np.float32)
                           for i in range(4)])
        t.add_rows(rows, deltas)
        expected = np.zeros((64, 128), dtype=np.float32)
        np.add.at(expected, rows, deltas)
        np.testing.assert_allclose(t.get_rows([3, 9, 63]),
                                   expected[[3, 9, 63]], rtol=1e-6)
        np.testing.assert_allclose(t.get(), expected, rtol=1e-6)
    finally:
        mv.shutdown()


def test_pallas_flag_ignored_when_ineligible():
    """Sharded tables (8 devices) silently fall back to the XLA path."""
    import multiverso_tpu as mv

    mv.init([])
    try:
        t = mv.create_table(mv.MatrixTableOption(num_row=64, num_col=128,
                                                 use_pallas=True))
        assert not t.store._pallas_rows   # 8 shards -> ineligible
        t.add_rows([5], np.ones((1, 128), dtype=np.float32))
        np.testing.assert_allclose(t.get_row(5), np.ones(128))
    finally:
        mv.shutdown()


# -- round 2: widened eligibility (bf16 tiles, SGD sign) --------------------
def test_scatter_add_sgd_sign():
    """Interpret-mode note: bf16 kernels pass here but are REJECTED by
    Mosaic on real chips (2-byte HBM tiling packs 2 rows/sublane; 1-row DMA
    slices misalign), so table eligibility stays f32-only."""
    import jax.numpy as jnp
    from multiverso_tpu.ops.pallas_rows import (gather_rows,
                                                group_for_dtype,
                                                scatter_add_rows)

    assert group_for_dtype(np.float32) == 8
    assert group_for_dtype(jnp.bfloat16) == 16

    rng = np.random.default_rng(0)
    for dtype in (np.float32,):
        table = jnp.asarray(rng.normal(size=(64, 128)), dtype=dtype)
        ids = jnp.asarray(np.sort(rng.integers(0, 64, size=40))
                          .astype(np.int32))
        deltas = jnp.asarray(rng.normal(size=(40, 128)), dtype=dtype)
        ref = np.array(table, dtype=np.float32)   # writable copy
        np.add.at(ref, np.asarray(ids), np.asarray(deltas,
                                                   dtype=np.float32))
        got = scatter_add_rows(table, ids, deltas, interpret=True)
        np.testing.assert_allclose(np.asarray(got, dtype=np.float32), ref,
                                   rtol=2e-2, atol=2e-2)
        back = gather_rows(got, ids, interpret=True)
        np.testing.assert_allclose(np.asarray(back, dtype=np.float32),
                                   ref[np.asarray(ids)], rtol=2e-2,
                                   atol=2e-2)
    # SGD sign: data -= delta
    table = jnp.zeros((16, 128), jnp.float32)
    ids = jnp.asarray([2, 2, 5], dtype=jnp.int32)
    deltas = jnp.ones((3, 128), jnp.float32)
    got = scatter_add_rows(table, ids, deltas, interpret=True, sign=-1.0)
    assert np.allclose(np.asarray(got)[2], -2.0)
    assert np.allclose(np.asarray(got)[5], -1.0)


def test_table_pallas_eligibility_widened():
    """SGD tables route through the Pallas row path (single shard,
    sign-flipped scatter); bf16 stays on XLA; stateful updaters named by
    the capability registry (adagrad) get the FUSED gather-update-scatter
    kernel; unregistered stateful updaters (dcasgd) stay on XLA."""
    import multiverso_tpu as mv
    from multiverso_tpu.core.options import AddOption
    from multiverso_tpu.core.table import ServerStore
    from multiverso_tpu.core.updater import get_updater
    from multiverso_tpu.core.zoo import Zoo

    mv.init([], devices=jax.devices()[:1])   # single shard for eligibility
    try:
        mesh = Zoo.get().mesh
        st = ServerStore("p1", (32, 128), np.float32,
                         get_updater(np.float32, "sgd"), mesh, 1,
                         use_pallas_rows=True)
        assert st._pallas_rows and st._pallas_cap == "scatter_sub"
        st_bf = ServerStore("p2", (32, 128), jnp.bfloat16,
                            get_updater(np.dtype(jnp.bfloat16), "default"),
                            mesh, 1, use_pallas_rows=True)
        assert not st_bf._pallas_rows   # bf16: Mosaic 1-row DMA misaligned
        st_ada = ServerStore("p3", (32, 128), np.float32,
                             get_updater(np.float32, "adagrad"), mesh, 1,
                             use_pallas_rows=True)
        assert st_ada._pallas_rows and st_ada._pallas_cap == "fused_stateful"
        st_dc = ServerStore("p4", (32, 128), np.float32,
                            get_updater(np.float32, "dcasgd"), mesh, 1,
                            use_pallas_rows=True)
        assert not st_dc._pallas_rows   # not in the capability registry
        # behavior: sgd table applies data -= delta through the kernel
        ids = jnp.asarray([1, 1, 3], dtype=jnp.int32)
        st.apply_rows(ids, jnp.ones((3, 128), jnp.float32), AddOption())
        out = np.asarray(st.read_rows(jnp.asarray([1, 3],
                                                  dtype=jnp.int32)))
        assert np.allclose(out[0], -2.0) and np.allclose(out[1], -1.0)
    finally:
        mv.shutdown()


def test_tiled_scatter_matches_numpy_random():
    """Tiled table-sweep scatter: random duplicated ids vs np.add.at."""
    from multiverso_tpu.ops.pallas_rows import tiled_scatter_add_rows
    rng = np.random.default_rng(0)
    table = rng.normal(size=(1000, 128)).astype(np.float32)
    ids = rng.integers(0, 1000, size=512).astype(np.int32)
    deltas = rng.normal(size=(512, 128)).astype(np.float32)
    want = table.copy()
    np.add.at(want, ids, deltas)
    got = tiled_scatter_add_rows(jnp.asarray(table), jnp.asarray(ids),
                                 jnp.asarray(deltas), interpret=True)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_tiled_scatter_nonmultiple_rows_and_tile_edges():
    """Row count not a multiple of the tile + ids clustered at tile
    boundaries (start/end searchsorted correctness)."""
    from multiverso_tpu.ops.pallas_rows import tiled_scatter_add_rows
    rng = np.random.default_rng(1)
    table = rng.normal(size=(777, 128)).astype(np.float32)
    # hit first/last rows of tiles plus heavy duplication
    ids = np.asarray([0, 255, 255, 256, 511, 512, 512, 512, 776, 776],
                     dtype=np.int32)
    deltas = rng.normal(size=(len(ids), 128)).astype(np.float32)
    want = table.copy()
    np.add.at(want, ids, deltas)
    got = tiled_scatter_add_rows(jnp.asarray(table), jnp.asarray(ids),
                                 jnp.asarray(deltas), interpret=True)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_tiled_scatter_sgd_sign_and_eligibility():
    from multiverso_tpu.ops.pallas_rows import (tiled_scatter_add_rows,
                                                tiled_scatter_eligible)
    rng = np.random.default_rng(2)
    table = np.zeros((300, 8), dtype=np.float32)
    ids = np.asarray([3, 3, 299], dtype=np.int32)
    deltas = np.ones((3, 8), dtype=np.float32)
    got = tiled_scatter_add_rows(jnp.asarray(table), jnp.asarray(ids),
                                 jnp.asarray(deltas), interpret=True,
                                 sign=-1.0)
    want = np.zeros_like(table)
    np.add.at(want, ids, -deltas)
    np.testing.assert_allclose(np.asarray(got), want)
    assert tiled_scatter_eligible(8192, 128, np.float32)
    assert not tiled_scatter_eligible(100_000, 128, np.float32)


# ---------------------------------------------------------------------------
# fused stateful gather-update-scatter (ISSUE 12): interpret-mode BITWISE
# vs the XLA update path — both planes run the updater's shared rows_math,
# so equality here proves the kernel's gather/scatter plumbing.
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("updater", ["momentum_sgd", "adagrad", "ftrl"])
def test_fused_stateful_bitwise_vs_xla(updater):
    import multiverso_tpu as mv

    mv.init([], devices=jax.devices()[:1])
    try:
        t_xla = mv.create_table(mv.MatrixTableOption(33, 16,
                                                     updater=updater,
                                                     name="fx"))
        t_pal = mv.create_table(mv.MatrixTableOption(33, 16,
                                                     updater=updater,
                                                     name="fp",
                                                     use_pallas=True))
        assert t_pal.store._pallas_cap == "fused_stateful"
        rng = np.random.default_rng(3)
        opt = mv.AddOption(worker_id=0, momentum=0.9, learning_rate=0.05,
                           rho=0.1, lambda_=0.01)
        for step in range(5):
            n = int(rng.integers(1, 24))
            ids = rng.integers(0, 33, size=n).astype(np.int32)
            d = rng.normal(size=(n, 16)).astype(np.float32)
            t_xla.add_rows(ids, d, opt)
            t_pal.add_rows(ids, d, opt)
        assert np.array_equal(t_xla.get(), t_pal.get()), updater
        for k in t_xla.store.state:
            assert np.array_equal(np.asarray(t_xla.store.state[k]),
                                  np.asarray(t_pal.store.state[k])), \
                (updater, k)
    finally:
        mv.shutdown()


def test_fused_stateful_duplicates_and_empty():
    """Duplicate ids in one add fold (combine semantics, like the XLA
    path); an empty add is a no-op; heavy duplication across group
    boundaries stays exact."""
    import multiverso_tpu as mv

    mv.init([], devices=jax.devices()[:1])
    try:
        t_xla = mv.create_table(mv.MatrixTableOption(8, 4,
                                                     updater="adagrad",
                                                     name="dx"))
        t_pal = mv.create_table(mv.MatrixTableOption(8, 4,
                                                     updater="adagrad",
                                                     name="dp",
                                                     use_pallas=True))
        opt = mv.AddOption(learning_rate=0.1, rho=0.1)
        # 11 ids over 3 rows: duplicates straddle the 8-lane group
        ids = np.array([2, 2, 2, 6, 6, 1, 1, 1, 1, 2, 6], dtype=np.int32)
        d = np.ones((11, 4), dtype=np.float32)
        t_xla.add_rows(ids, d, opt)
        t_pal.add_rows(ids, d, opt)
        t_pal.add_rows([], np.zeros((0, 4), np.float32), opt)  # no-op
        assert np.array_equal(t_xla.get(), t_pal.get())
        assert np.array_equal(np.asarray(t_xla.store.state["g2"]),
                              np.asarray(t_pal.store.state["g2"]))
    finally:
        mv.shutdown()


def test_fused_stateful_per_worker_state_indexing():
    """AdaGrad's [num_workers, ...] g2: the kernel must address worker w's
    accumulator plane, not worker 0's."""
    import multiverso_tpu as mv

    mv.init([], num_local_workers=2)
    try:
        t_xla = mv.create_table(mv.MatrixTableOption(16, 8,
                                                     updater="adagrad",
                                                     name="wx"))
        t_pal = mv.create_table(mv.MatrixTableOption(16, 8,
                                                     updater="adagrad",
                                                     name="wp",
                                                     use_pallas=True))
        rng = np.random.default_rng(5)
        for step in range(4):
            w = step % 2
            opt = mv.AddOption(worker_id=w, learning_rate=0.1, rho=0.1)
            ids = rng.integers(0, 16, size=6).astype(np.int32)
            d = rng.normal(size=(6, 8)).astype(np.float32)
            t_xla.add_rows(ids, d, opt)
            t_pal.add_rows(ids, d, opt)
        assert np.array_equal(t_xla.get(), t_pal.get())
        g2x = np.asarray(t_xla.store.state["g2"])
        g2p = np.asarray(t_pal.store.state["g2"])
        assert g2x.shape[0] == 2 and np.array_equal(g2x, g2p)
        assert np.abs(g2x[0] - g2x[1]).max() > 0   # both planes really used
    finally:
        mv.shutdown()
