"""Fixture: the idiomatic counterparts — every daemon loop runs under a
watchdog scope and beats once per iteration (inline, or delegating to a
runner helper after entering the scope — the shipped shapes)."""
import threading

from multiverso_tpu.telemetry import watchdog_register, watchdog_scope


class Batcher:
    """Scope-then-beat directly in the loop (canonical shape)."""

    def start(self):
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    def _loop(self):
        with watchdog_scope("serve-batcher", timeout_s=60.0) as wd:
            while self._running:
                wd.beat()
                batch = self._gather()
                if batch:
                    self._runner.run(batch)


class Collector:
    """Scope-then-delegate (the shipped shape for long loops): the
    scope in the target is the evidence; the delegate carries the
    beats, and the rule follows the delegation one level."""

    def start(self):
        threading.Thread(target=self._collect_loop, daemon=True).start()

    def _collect_loop(self):
        with watchdog_scope("serve-collector", timeout_s=60.0) as wd:
            self._run_collect(wd)

    def _run_collect(self, wd):
        while True:
            wd.beat()
            item = self._fifo.popleft()
            item.collect()


def spawn_oneshot(work):
    """A one-shot worker with no loop has nothing to wedge-watch."""
    def run_once():
        work()

    t = threading.Thread(target=run_once, daemon=True)
    t.start()
    return t


def spawn_heartbeat(beat_fn, stop):
    def heartbeat_loop():
        wd = watchdog_register("heartbeat", timeout_s=30.0)
        while not stop.is_set():
            wd.beat()
            beat_fn()
            stop.wait(0.1)

    t = threading.Thread(target=heartbeat_loop, daemon=True)
    t.start()
    return t
