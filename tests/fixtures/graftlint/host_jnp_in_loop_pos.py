"""Fixture: per-iteration device boxing of host scalars/constants."""
import jax.numpy as jnp


def accumulate(losses):
    total = jnp.float32(0)
    for l in losses:
        total = total + jnp.float32(1e-6)  # expect: host-jnp-in-loop
    return total


def pad_all(rows, width):
    out = []
    for r in rows:
        out.append(jnp.zeros((width,)))  # expect: host-jnp-in-loop
    return out
