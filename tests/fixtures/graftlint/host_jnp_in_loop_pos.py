"""Fixture: per-iteration device boxing of host scalars/constants."""
import jax.numpy as jnp


def accumulate(losses):
    total = jnp.float32(0)
    for l in losses:
        total = total + jnp.float32(1e-6)  # expect: host-jnp-in-loop
    return total


def pad_all(rows, width):
    out = []
    for r in rows:
        out.append(jnp.zeros((width,)))  # expect: host-jnp-in-loop
    return out


def train_with_eager_allreduce(step, aggregate, table, blocks):
    """Eager host-side allreduce inside the training loop: the merged
    gradient is re-boxed onto the device EVERY block (the comm-policy
    anti-idiom — build_dense_sync keeps the merge in-graph instead)."""
    w = table.raw()
    for block in blocks:
        w, grad = step(w, block)
        merged = aggregate(grad)                # host-level allreduce
        w = w - jnp.float32(0.05) * merged  # expect: host-jnp-in-loop
    return w
