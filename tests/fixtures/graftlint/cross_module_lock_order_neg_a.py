"""Fixture (negative, half A): both modules agree on one order — the
gate lock always OUTSIDE the note lock. No cycle, no finding."""
import threading

from cross_module_lock_order_neg_b import registry_note

_GATE_LOCK = threading.Lock()


def admit(key):
    with _GATE_LOCK:
        registry_note(key)           # consistent: gate -> note everywhere
