"""Fixture (negative): the three correct spellings — while-predicate
loop, ``wait_for``, and ``while True:`` with a conditional escape."""
import threading


class Mailbox:
    def __init__(self):
        self._cv = threading.Condition()
        self._items = []

    def take(self):
        with self._cv:
            while not self._items:
                self._cv.wait(1.0)
            return self._items.pop(0)

    def take_for(self):
        with self._cv:
            self._cv.wait_for(lambda: self._items, timeout=1.0)
            return self._items.pop(0)

    def take_escape(self):
        with self._cv:
            while True:
                if self._items:
                    return self._items.pop(0)
                self._cv.wait(0.5)
