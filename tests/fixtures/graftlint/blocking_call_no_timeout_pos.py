"""Positive fixture: unbounded blocks in the killable-peer planes."""
import socket
import threading


def connect_no_timeout(addr):
    return socket.create_connection(addr)  # expect: blocking-call-no-timeout


def wait_forever(evt: threading.Event):
    evt.wait()  # expect: blocking-call-no-timeout


def drain_forever(q):
    return q.get()  # expect: blocking-call-no-timeout


def read_no_deadline(sock):
    return sock.recv(4096)  # expect: blocking-call-no-timeout


class Reader:
    def __init__(self, sock):
        self._sock = sock

    def frame(self):
        return self._sock.recv(8)  # expect: blocking-call-no-timeout
