"""Fixture: the idiomatic counterparts — telemetry wraps the CALL SITE
of traced code from the host, never the traced body."""
import jax

from multiverso_tpu.telemetry import histogram, span

_H_STEP = histogram("fixture.step")


@jax.jit
def decorated_step(x):
    return x * 2


def host_driver(batches):
    import time
    for b in batches:
        with span("fixture.dispatch"):      # host side: times every call
            out = decorated_step(b)
        t0 = time.monotonic()
        out.block_until_ready()
        _H_STEP.observe((time.monotonic() - t0) * 1e3)
    return out


def unrelated_observe(sink, value):
    # .observe on a non-telemetry receiver inside traced code is not ours
    def step(x):
        sink.observe(value)
        return x
    return jax.jit(step)
