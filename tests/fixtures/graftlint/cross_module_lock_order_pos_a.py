"""Fixture (cross-module inversion, half A): this module nests B's lock
inside its own — locally consistent, inverted only against half B."""
import threading

from cross_module_lock_order_pos_b import registry_put

_SERVE_LOCK = threading.Lock()
_SLOTS = {}


def admit(key, value):
    with _SERVE_LOCK:
        registry_put(key, value)     # acquires B's _REG_LOCK under ours


def serve_apply(fn):
    with _SERVE_LOCK:
        return fn()
