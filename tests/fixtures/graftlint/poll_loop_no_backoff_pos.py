"""Positive fixture: constant-interval polls inside retry/convergence
waits — the drain-wait shape the rebalancer had to get right."""
import time


def wait_deadline(group, member, deadline):
    while time.monotonic() < deadline:
        if group.drains_completed(member):
            return True
        time.sleep(0.01)  # expect: poll-loop-no-backoff
    return False


def wait_until_ready(service):
    while not service.ready():
        time.sleep(0.1)  # expect: poll-loop-no-backoff


def wait_with_break(table, want):
    while True:
        if table.version() >= want:
            break
        time.sleep(0.05)  # expect: poll-loop-no-backoff


class Drainer:
    def wait_drained(self, member):
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if not self.is_draining(member):
                return True
            time.sleep(0.02)  # expect: poll-loop-no-backoff
        return False

    def is_draining(self, member):
        return False
