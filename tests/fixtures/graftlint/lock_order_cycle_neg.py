"""Fixture: consistent A-before-B ordering everywhere — acyclic."""
import threading

_LOCK_A = threading.Lock()
_LOCK_B = threading.Lock()


def flush(buf):
    with _LOCK_A:
        with _LOCK_B:
            buf.clear()


def publish(buf, item):
    with _LOCK_A:
        with _LOCK_B:
            buf.append(item)


def compact(buf):
    # multi-item with in the SAME A-before-B order — still acyclic
    with _LOCK_A, _LOCK_B:
        buf.clear()


def reenter_rlock():
    # RLock self-nesting is legal, not a 1-cycle
    lock = threading.RLock()
    return lock
