"""Fixture: idiomatic durable writes — atomic publication and fsynced
journals — plus the shapes the rule must not chase (reads, dispatch
layers with variable modes, shadowed open)."""
import json
import os


def save_manifest_atomically(root, meta):
    """The blessed truncating shape: tmp + fsync + os.replace."""
    final = os.path.join(root, "meta.json")
    tmp = final + f".tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(json.dumps(meta))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, final)


class GroupCommitJournal:
    """Append journal whose commit path fsyncs — evidence may live in a
    DIFFERENT method of the same class (open in __init__, fsync in
    flush), the WAL shape."""

    def __init__(self, path):
        self._f = open(path, "ab")
        self._pending = 0

    def append(self, rec):
        self._f.write(rec)
        self._pending += 1

    def flush(self):
        self._f.flush()
        os.fsync(self._f.fileno())
        self._pending = 0


def read_payload(path):
    with open(path, "rb") as f:     # reads are not publications
        return f.read()


def default_mode_read(path):
    with open(path) as f:           # default 'r'
        return f.read()


def dispatch_layer(path, mode):
    # A variable mode is a dispatch layer (utils/stream's factory), not
    # a call site the rule can statically judge.
    return open(path, mode)


def shadowed_open(path):
    def open(p, m):                 # noqa: A001 - deliberate shadow
        return [p, m]
    return open(path, "w")
