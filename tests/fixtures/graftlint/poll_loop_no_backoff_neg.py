"""Negative fixture: waits that back off, block on an Event, or are
constant-cadence tickers (not waiting for anyone)."""
import threading
import time


def wait_with_backoff(group, member, deadline):
    delay = 0.01
    while time.monotonic() < deadline:
        if group.drains_completed(member):
            return True
        time.sleep(delay)           # variable delay: the owner grows it
        delay = min(delay * 2.0, 1.0)
    return False


def wait_on_event(stop: threading.Event, group, member):
    delay = 0.01
    while not stop.wait(delay):     # Event-based: shutdown is immediate
        if group.drains_completed(member):
            return True
        delay = min(delay * 2.0, 1.0)
    return False


class Ticker:
    """A cadence loop doing work every interval — not a wait."""

    def __init__(self):
        self._running = True

    def run(self):
        while self._running:
            self.work()
            time.sleep(1.0)

    def work(self):
        pass
