"""Fixture: bare print in framework code."""


def report(stats):
    print("loss:", stats["loss"])  # expect: bare-print
    for k, v in stats.items():
        print(f"{k}={v}")  # expect: bare-print
