"""Fixture (historical, PR 15): the WAL staging lock held across
``fdatasync`` one call deep — the shape that cost 26% add throughput
before the staging/io lock split. Must keep firing forever."""
import os
import threading


class MiniWal:
    def __init__(self, path):
        self._lock = threading.Lock()
        self._path = path
        self._staged = []

    def append(self, rec):
        with self._lock:
            self._staged.append(rec)
            self._flush()  # expect: lock-held-across-blocking

    def _flush(self):
        with open(self._path, "ab") as f:
            f.write(b"".join(self._staged))
            f.flush()
            os.fdatasync(f.fileno())
