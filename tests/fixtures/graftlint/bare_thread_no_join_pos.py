"""Fixture: non-daemon threads nobody joins wedge interpreter exit."""
import threading


def fire_and_forget(fn):
    t = threading.Thread(target=fn)  # expect: bare-thread-no-join
    t.start()
    return t


class Engine:
    def start(self, loop):
        self._worker = threading.Thread(target=loop)  # expect: bare-thread-no-join
        self._worker.start()


def anonymous(fn):
    threading.Thread(target=fn).start()  # expect: bare-thread-no-join


class FleetAgent:
    """Heartbeat loop on a non-daemon thread with no join on any
    shutdown path: interpreter exit hangs on the last beat."""

    def start_heartbeat(self, beat):
        self._hb = threading.Thread(target=beat)  # expect: bare-thread-no-join
        self._hb.start()


class LeakyPipeline:
    """A dispatch-pipeline collector on a non-daemon thread with no join
    anywhere: a wedged collect() (device hang) blocks interpreter exit
    forever — the pipeline-module hazard the rule scope covers."""

    def start(self, collect_loop):
        self._collector = threading.Thread(target=collect_loop)  # expect: bare-thread-no-join
        self._collector.start()
