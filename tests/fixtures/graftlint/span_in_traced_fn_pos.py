"""Fixture: telemetry calls inside traced bodies — trace-time no-ops
(never imported, only parsed by the lint engine tests)."""
import jax

from multiverso_tpu.telemetry import histogram, span

_H_STEP = histogram("fixture.step")


@jax.jit
def decorated_step(x):
    with span("fixture.decorated"):  # expect: span-in-traced-fn
        y = x * 2
    histogram("fixture.inner").observe(1.0)  # expect: span-in-traced-fn
    return y


def make_step():
    def step(w, g):
        _H_STEP.observe(3.0)  # expect: span-in-traced-fn
        return w - g
    return jax.jit(step)
