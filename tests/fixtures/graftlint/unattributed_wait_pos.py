"""Positive fixture: hot-path waits no phase-ledger span can see."""
import time


def drain_queue(q):
    return q.get()  # expect: unattributed-wait


def drain_queue_timeout(q):
    return q.get(timeout=0.5)  # expect: unattributed-wait


def park_on_event(evt):
    evt.wait(1.0)  # expect: unattributed-wait


def paced_retry():
    time.sleep(0.01)  # expect: unattributed-wait


def read_frame(sock):
    return sock.recv(4096)  # expect: unattributed-wait


class Reader:
    def __init__(self, sock):
        self._sock = sock

    def accept_peer(self):
        return self._sock.accept()  # expect: unattributed-wait
