"""Fixture: metric names formatted from unbounded runtime values —
every one of these leaks a registry entry + a timeseries ring per
distinct value (request ids, row keys), forever."""
from multiverso_tpu.telemetry import counter, gauge, histogram
from multiverso_tpu.telemetry.metrics import get_registry
from multiverso_tpu.utils.dashboard import monitor


def per_request(request_id, key, msg_id, reg):
    counter(f"serve.request.{request_id}").inc()  # expect: unbounded-metric-name
    gauge("row.load.{}".format(key)).set(1.0)  # expect: unbounded-metric-name
    histogram("reply.%d.latency" % msg_id).observe(1.0)  # expect: unbounded-metric-name
    reg.counter(f"cancel.{msg_id}").inc()  # expect: unbounded-metric-name
    get_registry().gauge("conn." + str(msg_id)).set(0)  # expect: unbounded-metric-name
    monitor(f"REQUEST_{request_id}")  # expect: unbounded-metric-name


def family_prefix_not_at_the_hole(worker, key):
    # A family word somewhere in the name does NOT bless a different,
    # unbounded interpolation elsewhere in it.
    counter(f"ps.worker_{worker}.key.{key}").inc()  # expect: unbounded-metric-name
