"""Negative fixture: every block carries a deadline (or owns one)."""
import socket
import threading


def connect_bounded(addr):
    return socket.create_connection(addr, timeout=1.5)


def connect_bounded_positional(addr):
    return socket.create_connection(addr, 1.5)


def wait_bounded(evt: threading.Event):
    return evt.wait(timeout=5.0)


def drain_bounded(q):
    return q.get(timeout=0.5)


def zoo_accessor():
    class Zoo:
        @classmethod
        def get(cls):
            return cls
    return Zoo.get()        # classmethod accessor, not a queue drain


def read_with_deadline(sock):
    sock.settimeout(2.0)
    return sock.recv(4096)


class Reader:
    def __init__(self, sock):
        sock.settimeout(1.0)
        self._sock = sock

    def frame(self):
        return self._sock.recv(8)


def read_from_bounded_connect(addr):
    with socket.create_connection(addr, timeout=1.0) as s:
        return s.recv(16)
