"""Fixture: ``cv.wait()`` guarded by ``if`` (or nothing at all) — one
spurious wakeup, or one notify stolen by a sibling waiter, and the
caller proceeds on a false predicate."""
import threading


class Mailbox:
    def __init__(self):
        self._cv = threading.Condition()
        self._items = []

    def take_if_guarded(self):
        with self._cv:
            if not self._items:
                self._cv.wait()  # expect: condition-wait-no-predicate-loop
            return self._items.pop(0)

    def take_unguarded(self):
        with self._cv:
            self._cv.wait(1.0)  # expect: condition-wait-no-predicate-loop
            return self._items.pop(0)
