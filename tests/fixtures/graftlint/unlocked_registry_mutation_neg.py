"""Fixture: every registry write under the lock; import-time init and
parameter-shadowed names stay exempt."""
import threading

_TABLES = {}
_TABLES_LOCK = threading.Lock()

_TABLES["bootstrap"] = None     # import time: serialized by the import lock


def register(name, table):
    with _TABLES_LOCK:
        _TABLES[name] = table


def drain(_TABLES):
    # parameter shadows the module registry: a local, not the global
    _TABLES.clear()


def snapshot():
    with _TABLES_LOCK:
        return dict(_TABLES)
