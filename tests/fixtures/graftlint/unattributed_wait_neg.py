"""Fixture: idiomatic counterparts — the wait's interval reaches the
ledger (emit_span around it, or a latency histogram observation in the
same scope), dict .get with a positional key, classmethod accessors,
and reasoned suppressions for control-plane idle waits."""
import time

from multiverso_tpu.telemetry import emit_span, histogram


def spanned_queue_drain(q, ctx):
    t0 = time.monotonic()
    item = q.get(timeout=0.5)
    emit_span("serve.admit_wait", ctx, t0,
              (time.monotonic() - t0) * 1e3)
    return item


def observed_wait(evt):
    t0 = time.monotonic()
    evt.wait(1.0)
    histogram("serve.latency.admit").observe(
        (time.monotonic() - t0) * 1e3)


class SpannedReader:
    """Class-scoped evidence: the read loop's arrival path emits the
    deliver span, so the blocking recv in the same class is the
    measured interval's far edge."""

    def __init__(self, sock, ctx):
        self._sock = sock
        self._ctx = ctx

    def frame(self):
        return self._sock.recv(8)

    def deliver(self, t_arrive):
        emit_span("serve.deliver", self._ctx, t_arrive, 0.1)


def dict_lookup(cfg):
    return cfg.get("timeout")       # positional key: a dict, not a queue


def zoo_accessor():
    from multiverso_tpu.utils.zoo import Zoo
    return Zoo.get()                # classmethod accessor, not a drain


def shutdown_tick(stop):
    # daemon ticker: no request ever crosses the control-plane sleep
    # graftlint: disable=unattributed-wait
    stop.wait(5.0)
