"""Fixture: per-iteration syncs that serialize a dispatch pipeline."""
import jax


def train(step, tables, blocks):
    for blk in blocks:
        out = step(*tables, blk)
        tables = out[:4]
        jax.block_until_ready(out)  # expect: block-until-ready-in-loop
    return tables


def drain(queue):
    while queue:
        queue.pop(0).block_until_ready()  # expect: block-until-ready-in-loop
