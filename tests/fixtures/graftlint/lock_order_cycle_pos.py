"""Fixture: the synthetic two-lock cycle — thread 1 runs ``flush``
(A then B), thread 2 runs ``publish`` (B then A)."""
import threading

_LOCK_A = threading.Lock()
_LOCK_B = threading.Lock()


def flush(buf):
    with _LOCK_A:
        with _LOCK_B:  # expect: lock-order-cycle
            buf.clear()


def publish(buf, item):
    with _LOCK_B:
        with _LOCK_A:
            buf.append(item)


_LOCK_C = threading.Lock()
_LOCK_D = threading.Lock()


def compact(buf):
    # the same deadlock spelled as one statement: C-then-D here ...
    with _LOCK_C, _LOCK_D:  # expect: lock-order-cycle
        buf.clear()


def rotate(buf):
    # ... against D-then-C here
    with _LOCK_D:
        with _LOCK_C:
            buf.append(None)
