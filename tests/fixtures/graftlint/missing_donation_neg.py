"""Fixture: donated update steps, and non-step jits that owe nothing."""
import jax


def make_update(raw_update):
    return jax.jit(raw_update, donate_argnums=(0, 1, 2, 3))


def make_predict(predict_fn):
    return jax.jit(predict_fn)      # not a step/update: no donation due
