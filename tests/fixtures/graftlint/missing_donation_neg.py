"""Fixture: donated update steps, and non-step jits that owe nothing."""
import jax


def make_update(raw_update):
    return jax.jit(raw_update, donate_argnums=(0, 1, 2, 3))


def make_predict(predict_fn):
    return jax.jit(predict_fn)      # not a step/update: no donation due


def build_stateful_rows(pallas_rows_update):
    # The shipped fused-stateful shape: the jit donates data (0) and the
    # state pytree (1); inside, pallas_call aliases each buffer onto its
    # output (input_output_aliases), so the whole gather-update-scatter
    # happens in place.
    return jax.jit(pallas_rows_update, donate_argnums=(0, 1))
