"""Fixture: the idiomatic bounded counterparts — construction-time bounds,
length-checked shed paths, drain calls, and batch loops bounded by len()."""
import collections
import queue


class Bounded:
    MAX = 64

    def __init__(self, depth):
        self._pending = collections.deque()
        self._ring = collections.deque(maxlen=128)      # bounded ctor
        self._ring2 = collections.deque([], 256)        # positional maxlen
        self._q = queue.Queue(maxsize=64)               # bounded ctor
        self._sized = queue.Queue(64)                   # positional bound
        self._dyn = queue.Queue(maxsize=depth)          # owner-chosen bound

    def reader(self, sock):
        while True:
            item = sock.recv()
            if item is None:
                break
            if len(self._pending) >= self.MAX:          # shed path
                self._pending.popleft()
            self._pending.append(item)
            self._ring.append(item)
            self._ring2.append(item)
            self._q.put(item)
            self._sized.put_nowait(item)
            self._dyn.put(item)

    def drainer(self, sock):
        while True:
            self._pending.append(sock.recv())
            self.flush()

    def flush(self):
        while self._pending:
            self._pending.popleft()                     # drain evidence


def local_batch(sock):
    out = []
    while len(out) < 16:                                # len-bounded loop
        out.append(sock.recv())
    return out


def unknown_origin(entry, sock):
    # container from a tuple unpack: origin invisible, not flagged
    _, slot = entry
    while True:
        msg = sock.recv()
        if msg is None:
            break
        slot.append(msg)


class HeartbeatDaemonBounded:
    """Fleet heartbeat agent keeping a BOUNDED beat journal (ring)."""

    def __init__(self):
        self._beats = collections.deque(maxlen=256)

    def heartbeat_loop(self, router, stop):
        while not stop.is_set():
            self._beats.append(router.heartbeat())


class DepthBoundedDispatchPipeline:
    """The real dispatch-pipeline shape (serving/pipeline.py): the
    producer blocks behind a len() check against the window depth before
    appending, and the collector popleft()s — both bound AND drain
    evidence in scope."""

    def __init__(self, depth):
        self.depth = depth
        self._fifo = collections.deque()

    def producer_loop(self, batches, cv):
        while True:
            batch = batches.get_next()
            if batch is None:
                break
            with cv:
                while len(self._fifo) >= self.depth:    # backpressure
                    cv.wait(0.2)
                self._fifo.append(batch.dispatch())

    def collector_loop(self, cv):
        while True:
            with cv:
                if self._fifo:
                    self._fifo.popleft()                # drain evidence


class PagePoolBoundedReclaim:
    """The shipped page-pool shape (serving/paged.py): the free list is
    seeded to a FIXED capacity at construction, the reclaim loop sheds
    double-frees behind a capacity check, and the allocator pop()s —
    bound and drain evidence both in scope."""

    CAPACITY = 256

    def __init__(self):
        self._free = list(range(self.CAPACITY))

    def reclaim_loop(self, releases):
        while True:
            page = releases.get_next()
            if page is None:
                break
            if len(self._free) >= self.CAPACITY:        # capacity bound
                continue                                # double-free shed
            self._free.append(page)

    def alloc(self):
        while self._free:
            return self._free.pop()                     # drain evidence
