"""Fixture: every violation here carries a graftlint disable —
same-line, line-above, and file-scoped forms must all hold."""
# graftlint: disable-file=host-jnp-in-loop
import jax
import jax.numpy as jnp


@jax.jit
def step(x):
    return x * float(x.sum())  # graftlint: disable=implicit-host-sync


def drain(markers):
    for m in markers:
        # graftlint: disable=block-until-ready-in-loop
        jax.block_until_ready(m)


def boxed(losses):
    total = jnp.float32(0)
    for l in losses:
        total = total + jnp.float32(l)      # file-scoped disable above
    return total
