"""Fixture (historical, PR 16): roster publication serializing JSON to
disk while holding the membership lock — the encoder convoy that added
260s of tier-1 wall time. Must keep firing forever."""
import json
import threading


class MiniRoster:
    def __init__(self, path):
        self._lock = threading.Lock()
        self._path = path
        self._members = {}

    def admit(self, name, addr):
        with self._lock:
            self._members[name] = addr
            self._publish()  # expect: lock-held-across-blocking

    def _publish(self):
        with open(self._path, "w") as f:
            json.dump(self._members, f)
