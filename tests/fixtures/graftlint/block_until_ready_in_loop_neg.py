"""Fixture: syncing once per block (outside the loop) is the pattern."""
import jax


def train(step, tables, blocks):
    outs = []
    for blk in blocks:
        out = step(*tables, blk)
        tables = out[:4]
        outs.append(out[4])
    jax.block_until_ready(outs)     # one batched wait
    return tables
