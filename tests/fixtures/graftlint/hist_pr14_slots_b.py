"""Fixture (historical, PR 14, half B): the fleet view lock wrapping a
slots readback — B-then-A against half A's A-then-B."""
import threading

from hist_pr14_slots_a import slots_for

_VIEW_LOCK = threading.Lock()
_VIEW = {}


def fleet_view():
    with _VIEW_LOCK:
        return dict(_VIEW)


def rebalance(runner_id):
    with _VIEW_LOCK:
        _VIEW[runner_id] = slots_for(runner_id)
