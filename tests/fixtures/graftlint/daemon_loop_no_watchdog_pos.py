"""Fixture: daemon service loops with no watchdog heartbeat in reach —
a wedge in any of these stalls its plane with no trip, no postmortem."""
import threading


class Batcher:
    """The batcher-worker idiom without a beat: the gather wait and the
    runner call can both wedge, and nothing would ever notice."""

    def start(self):
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    def _loop(self):
        while self._running:  # expect: daemon-loop-no-watchdog
            batch = self._gather()
            if batch:
                self._runner.run(batch)


class Collector:
    """Pipeline-collector shape: the device sync inside collect() is the
    canonical wedge, and this loop is exactly where it hides."""

    def start(self):
        threading.Thread(target=self._collect_loop, daemon=True).start()

    def _collect_loop(self):
        while True:  # expect: daemon-loop-no-watchdog
            item = self._fifo.popleft()
            item.collect()


def spawn_heartbeat(beat_fn, stop):
    def heartbeat_loop():
        while not stop.is_set():  # expect: daemon-loop-no-watchdog
            beat_fn()
            stop.wait(0.1)

    t = threading.Thread(target=heartbeat_loop, daemon=True)
    t.start()
    return t


class DelegatingDispatcher:
    """The loop hides ONE delegation hop down from the Thread target —
    still no watchdog anywhere in reach, still invisible to postmortems
    (the rule follows in-file delegates one level)."""

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        self._run()

    def _run(self):
        while self._running:  # expect: daemon-loop-no-watchdog
            self._dispatch_one()
