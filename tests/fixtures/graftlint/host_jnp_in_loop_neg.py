"""Fixture: numpy on the host path, per-batch uploads stay legal, and
jnp constructors inside traced code are the device path working as
intended."""
import jax
import jax.numpy as jnp
import numpy as np


def accumulate(losses):
    total = np.float32(0)
    for l in losses:
        total = total + np.float32(l)       # numpy: no device round trip
    return total


def upload_batches(step, batches):
    outs = []
    for b in batches:
        outs.append(step(jnp.asarray(b)))   # per-batch upload is the API
    return outs


@jax.jit
def traced(x):
    acc = jnp.float32(0)
    for i in range(4):                      # unrolled AT TRACE TIME
        acc = acc + jnp.float32(i) * x.sum()
    return acc


def train_with_in_graph_allreduce(hybrid_step, sync, blocks):
    """The comm-policy idiom: the allreduce lives INSIDE the jitted step
    (or a prebuilt dense-sync dispatch); host code ships numpy operands
    and never re-boxes per block."""
    losses = []
    for block in blocks:
        losses.append(hybrid_step(block))       # psum is in-graph
        sync(np.asarray([len(block)], np.float32))  # upload, no boxing
    return losses
