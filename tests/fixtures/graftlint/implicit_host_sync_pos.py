"""Fixture: host syncs inside traced functions (never imported, only
parsed by the lint engine tests)."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def decorated_step(x):
    scale = float(x)  # expect: implicit-host-sync
    mean = float(x.sum() / x.shape[0])  # expect: implicit-host-sync
    return x * scale * mean


def make_step():
    def step(w, g):
        lr = w.sum()
        w = w - float(lr) * g  # expect: implicit-host-sync
        host = np.asarray(g)  # expect: implicit-host-sync
        return w + host.sum()
    return jax.jit(step)


def loop_body(i, carry):
    stop = bool(carry[0])  # expect: implicit-host-sync
    val = carry[1].item()  # expect: implicit-host-sync
    return (stop, val)


def run(carry):
    return jax.lax.fori_loop(0, 4, loop_body, carry)
