"""Fixture: host syncs inside traced functions (never imported, only
parsed by the lint engine tests)."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def decorated_step(x):
    scale = float(x)  # expect: implicit-host-sync
    mean = float(x.sum() / x.shape[0])  # expect: implicit-host-sync
    return x * scale * mean


def make_step():
    def step(w, g):
        lr = w.sum()
        w = w - float(lr) * g  # expect: implicit-host-sync
        host = np.asarray(g)  # expect: implicit-host-sync
        return w + host.sum()
    return jax.jit(step)


def loop_body(i, carry):
    stop = bool(carry[0])  # expect: implicit-host-sync
    val = carry[1].item()  # expect: implicit-host-sync
    return (stop, val)


def run(carry):
    return jax.lax.fori_loop(0, 4, loop_body, carry)


def make_hybrid_step(aggregate):
    """Eager host-side allreduce INSIDE the traced step body: the
    np.asarray materializes the traced gradient on the host (TracerError
    or a silent dispatch stall) — the merge belongs in-graph
    (lax.psum / comm_policy.build_dense_sync)."""
    def step(w, grads):
        merged = aggregate(np.asarray(grads))  # expect: implicit-host-sync
        return w - 0.05 * jnp.asarray(merged)
    return jax.jit(step, donate_argnums=0)
