"""Fixture (negative): the idiomatic counterparts — snapshot under the
lock and do the IO after release; pure compute under a lock; a timed
queue get (not the block-forever zero-arg form)."""
import json
import os
import queue
import threading

_LOCK = threading.Lock()
_STATE = {}


def checkpoint(path, fd):
    with _LOCK:
        snap = dict(_STATE)          # snapshot under the lock ...
    with open(path, "w") as f:       # ... publish/IO after release
        json.dump(snap, f)
    os.fsync(fd)


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()

    def bump(self, key):
        with self._lock:
            _STATE[key] = _STATE.get(key, 0) + 1

    def render(self, key):
        with self._lock:
            return self._fmt(key)    # chain to a non-blocking helper

    def _fmt(self, key):
        return "%s=%d" % (key, _STATE.get(key, 0))

    def take(self):
        return self._q.get(timeout=1.0)
