"""Fixture (negative, half B): only ever takes its own lock — never
calls back into half A while holding it."""
import threading

_NOTE_LOCK = threading.Lock()
_NOTES = {}


def registry_note(key):
    with _NOTE_LOCK:
        _NOTES[key] = True


def registry_flush():
    with _NOTE_LOCK:
        _NOTES.clear()
