"""Fixture: lifecycle-owned threads — daemonized, joined locally, joined
on the class shutdown path, or joined through the collecting list."""
import threading


def daemonized(fn):
    t = threading.Thread(target=fn, daemon=True)
    t.start()
    return t


def scoped(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join()


class Engine:
    def start(self, loop):
        self._worker = threading.Thread(target=loop)
        self._worker.start()

    def stop(self):
        self._worker.join()


def fan_out(fns):
    threads = [threading.Thread(target=f, daemon=True) for f in fns]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def pool(fns):
    # non-daemon comprehension pool, joined through the collecting list
    workers = [threading.Thread(target=f) for f in fns]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
