"""Fixture: lifecycle-owned threads — daemonized, joined locally, joined
on the class shutdown path, or joined through the collecting list."""
import threading


def daemonized(fn):
    t = threading.Thread(target=fn, daemon=True)
    t.start()
    return t


def scoped(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join()


class Engine:
    def start(self, loop):
        self._worker = threading.Thread(target=loop)
        self._worker.start()

    def stop(self):
        self._worker.join()


def fan_out(fns):
    threads = [threading.Thread(target=f, daemon=True) for f in fns]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def pool(fns):
    # non-daemon comprehension pool, joined through the collecting list
    workers = [threading.Thread(target=f) for f in fns]
    for w in workers:
        w.start()
    for w in workers:
        w.join()


class FleetAgent:
    """The heartbeat daemon pattern: the loop dies with the process
    (daemon=True) AND close() joins it for orderly shutdown."""

    def start_heartbeat(self, beat):
        self._hb = threading.Thread(target=beat, daemon=True)
        self._hb.start()

    def close(self):
        self._hb.join(timeout=5)


class OwnedPipeline:
    """The DispatchPipeline collector shape: daemonized (a wedged device
    must not block interpreter exit) and joined on the close path."""

    def start(self, collect_loop):
        self._collector = threading.Thread(target=collect_loop,
                                           daemon=True)
        self._collector.start()

    def close(self):
        self._collector.join(timeout=10)
