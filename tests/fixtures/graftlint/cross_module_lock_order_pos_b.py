"""Fixture (cross-module inversion, half B): nests A's lock inside its
own — B-then-A against half A's A-then-B."""
import threading

from cross_module_lock_order_pos_a import serve_apply

_REG_LOCK = threading.Lock()
_REG = {}


def registry_put(key, value):
    with _REG_LOCK:
        _REG[key] = value


def registry_sync():
    with _REG_LOCK:
        serve_apply(lambda: None)    # acquires A's _SERVE_LOCK under ours
