"""Fixture (cross-module cycle, half A): service holds its lock and
calls into the registry, which takes the registry lock."""
import threading

from lock_cycle_xmod_b import registry_put

_SERVICE_LOCK = threading.Lock()


def dispatch(key, value):
    with _SERVICE_LOCK:
        registry_put(key, value)  # acquires lock_cycle_xmod_b._REG_LOCK


def service_apply(fn):
    with _SERVICE_LOCK:
        return fn()
