"""Fixture: durability-critical files published without the tmp + fsync
+ atomic-rename shape — torn bytes at the final path on a crash."""
import json
import os


def save_manifest(root, meta):
    # Truncating write straight at the final path: a crash mid-write
    # leaves a torn meta.json — the durability marker itself.
    with open(os.path.join(root, "meta.json"), "w") as f:  # expect: non-atomic-durable-write
        f.write(json.dumps(meta))


def save_payload_binary(path, blob):
    f = open(path, "wb")  # expect: non-atomic-durable-write
    f.write(blob)
    f.close()


def rename_without_fsync(path, blob):
    # Rename alone is not durable publication: the temp's BYTES may
    # still be in cache when the rename lands — fsync must precede it.
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:  # expect: non-atomic-durable-write
        f.write(blob)
    os.replace(tmp, path)


class Journal:
    """Append-mode journal whose commit path never fsyncs: every
    'durable' record is acked-write loss waiting for a crash."""

    def __init__(self, path):
        self._f = open(path, "ab")  # expect: non-atomic-durable-write

    def append(self, rec):
        self._f.write(rec)
        self._f.flush()     # flush() reaches the page cache, not disk


def keyword_mode_write(path, blob):
    with open(path, mode="wb") as f:  # expect: non-atomic-durable-write
        f.write(blob)
