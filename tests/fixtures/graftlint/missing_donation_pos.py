"""Fixture: update-step jits that copy their table buffers."""
import jax


def make_update(raw_update):
    return jax.jit(raw_update)  # expect: missing-donation


def build(table_step):
    step = jax.jit(table_step, static_argnums=(4,))  # expect: missing-donation
    return step
