"""Fixture: update-step jits that copy their table buffers."""
import jax


def make_update(raw_update):
    return jax.jit(raw_update)  # expect: missing-donation


def build(table_step):
    step = jax.jit(table_step, static_argnums=(4,))  # expect: missing-donation
    return step


def build_stateful_rows(pallas_rows_update):
    # The fused stateful-kernel idiom gone wrong: data AND every updater
    # state leaf ride this dispatch, so an undonated jit holds TWO full
    # copies of the table plus its optimizer state in HBM per step.
    return jax.jit(pallas_rows_update,  # expect: missing-donation
                   static_argnames=("interpret",))
