"""Fixture: locks held across blocking sinks — a direct syscall, a
one-hop chain into another method, a chain through an ASSIGNED-CALLABLE
indirection, and a json.dump serialize+write."""
import json
import os
import threading

_LOCK = threading.Lock()


def flush_direct(fd):
    with _LOCK:
        os.fsync(fd)  # expect: lock-held-across-blocking


class Publisher:
    def __init__(self, sock):
        self._lock = threading.Lock()
        self._sock = sock
        self._emit = self._send_frame        # one level of indirection

    def _send_frame(self, payload):
        self._sock.sendall(payload)

    def publish(self, payload):
        with self._lock:
            self._emit(payload)  # expect: lock-held-across-blocking

    def snapshot_to(self, path, state):
        with self._lock:
            self._write(path, state)  # expect: lock-held-across-blocking

    def _write(self, path, state):
        with open(path, "w") as f:
            json.dump(state, f)
