"""Fixture: module registry written outside its guarding lock."""
import threading

_TABLES = {}
_WAITERS = []
_TABLES_LOCK = threading.Lock()


def register(name, table):
    with _TABLES_LOCK:
        _TABLES[name] = table


def unregister(name):
    _TABLES.pop(name, None)  # expect: unlocked-registry-mutation


def enqueue(waiter):
    _WAITERS.append(waiter)  # expect: unlocked-registry-mutation


def rebind(name, table):
    _TABLES[name] = table  # expect: unlocked-registry-mutation
