"""Fixture: the idiomatic counterparts — static casts and host-side
conversions OUTSIDE traced code carry no finding."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def decorated_step(x):
    d = float(x.shape[-1])          # shape access is trace-time static
    n = int(len(x.shape))           # len() likewise
    scale = 1.0 / np.sqrt(x.shape[-1])
    return x * jnp.float32(scale) * d * n


def host_driver(step, batches):
    total = 0.0
    for b in batches:
        loss = step(jnp.asarray(b))
        total += float(loss)        # host code may sync freely
    return np.asarray(total)


def make_hybrid_step(mesh, shard_map, P):
    """The in-graph counterpart: the gradient merge is a psum inside the
    traced body — no host materialization anywhere in the step."""
    def step(w, grads):
        merged = jax.lax.psum(grads, "data")
        return w - 0.05 * merged
    return jax.jit(shard_map(step, mesh=mesh, in_specs=(P(), P("data")),
                             out_specs=P()), donate_argnums=0)
