"""Fixture: non-reentrant lock re-acquired through a call chain — the
shape of the _CPU_COLLECTIVE_LOCK wedge."""
import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._data = {}

    def put(self, key, value):
        with self._lock:
            self._data[key] = value
            self._notify(key)  # expect: lock-order-cycle

    def _notify(self, key):
        with self._lock:        # called with _lock already held: wedge
            return self._data.get(key)


class ClassLocked:
    _lock = threading.Lock()
    _cache = {}

    @classmethod
    def put(cls, key, value):
        with ClassLocked._lock:
            ClassLocked._cache[key] = value
            ClassLocked.flush()  # expect: lock-order-cycle

    @classmethod
    def flush(cls):
        with ClassLocked._lock:     # re-acquired via the call above
            ClassLocked._cache.clear()
