"""Fixture: idiomatic counterparts — plain literal names, the bounded
index family shapes (worker_<w>, table_<t>, batcher_<i>: populations
fixed at init), and formatted strings that are not metric names."""
from multiverso_tpu.telemetry import counter, gauge, histogram
from multiverso_tpu.utils.dashboard import monitor
from multiverso_tpu.utils.log import log


def literal_names():
    counter("serve.requests").inc()
    gauge("serve.queue_depth").set(3)
    histogram("serve.latency.total").observe(1.0)
    monitor("PS_SERVICE_ADD")


def bounded_families(w, table_id, slot):
    # The deliberate bounded `<family>_<i>` shapes: worker/table/batcher
    # indices are fixed small populations, the documented convention.
    gauge(f"ps_service.staleness.worker_{w}").set(0.0)
    gauge(f"async_engine.queue_depth.table_{table_id}").set(1)
    gauge(f"serve.queue_bound.batcher_{slot}").set(64)
    counter(f"fleet.shard_keys.member_{slot}").inc()


def formatted_but_not_a_metric(request_id):
    # f-strings with runtime values are fine anywhere EXCEPT a metric
    # name — logs and exceptions are per-event, not per-name state.
    log.info(f"serving request {request_id}")
    raise ValueError("bad request %d" % request_id)
