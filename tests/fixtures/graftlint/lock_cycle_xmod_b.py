"""Fixture (cross-module cycle, half B): the registry holds its lock and
calls back into the service — B-then-A against A-then-B in half A."""
import threading

from lock_cycle_xmod_a import service_apply

_REG_LOCK = threading.Lock()
_REG = {}


def registry_put(key, value):
    with _REG_LOCK:
        _REG[key] = value


def registry_sync():
    with _REG_LOCK:
        service_apply(lambda: None)  # acquires lock_cycle_xmod_a._SERVICE_LOCK
