"""Fixture: build the transform once, loop over dispatches."""
import jax


def sweep(fn, lrs, x):
    step = jax.jit(fn)              # hoisted: one trace, many calls
    outs = []
    for lr in lrs:
        outs.append(step(x, lr))
    return outs
