"""Fixture: transform construction inside loops recompiles per
iteration."""
import functools

import jax
from jax.experimental.shard_map import shard_map


def sweep(fn, lrs, x):
    outs = []
    for lr in lrs:
        step = jax.jit(lambda a: fn(a) * lr)  # expect: retrace-hazard
        outs.append(step(x))
    return outs


def sweep_partial(fn, lrs, x):
    outs = []
    for lr in lrs:
        step = functools.partial(jax.jit, static_argnums=0)(fn)  # expect: retrace-hazard
        outs.append(step(lr, x))
    return outs


def shard_sweep(mesh, fn, specs, x):
    outs = []
    while specs:
        spec = specs.pop()
        f = shard_map(fn, mesh=mesh, in_specs=spec, out_specs=spec)  # expect: retrace-hazard
        outs.append(f(x))
    return outs
