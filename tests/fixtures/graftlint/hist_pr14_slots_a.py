"""Fixture (historical, PR 14, half A): the batcher slots lock wrapping
a fleet-view fetch — locally consistent, inverted only against the
fleet side's rebalance path. Must keep firing forever."""
import threading

from hist_pr14_slots_b import fleet_view

_SLOTS_LOCK = threading.Lock()
_SLOTS = {}


def admit(runner_id):
    with _SLOTS_LOCK:
        _SLOTS[runner_id] = fleet_view()


def slots_for(runner_id):
    with _SLOTS_LOCK:
        return _SLOTS.get(runner_id)
