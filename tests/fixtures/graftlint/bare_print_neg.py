"""Fixture: sanctioned output paths — log module, attribute prints,
strings mentioning print, and a local redefinition."""
from multiverso_tpu.utils.log import log


def report(stats, console):
    log.info("loss: %s", stats["loss"])
    log.raw("%s", stats)
    console.print(stats)            # attribute access: not the builtin
    return "do not print(this)"


def shadowed(print):
    print("shadowed builtin is the caller's problem, not a bare print")
