"""Fixture: reader/dispatcher loops growing queues with no bound or shed
path — the slow-consumer OOM the serving admission bound exists to kill."""
import collections
import queue


class Service:
    def __init__(self):
        self._pending = collections.deque()
        self._inbox = queue.Queue()

    def reader_loop(self, sock):
        while True:
            item = sock.recv()
            if item is None:
                break
            self._pending.append(item)  # expect: unbounded-queue-append

    def pump(self, sock):
        while True:
            self._inbox.put(sock.recv())  # expect: unbounded-queue-append


def drain_forever(sock):
    backlog = []
    while True:
        msg = sock.recv()
        if msg is None:
            break
        backlog.append(msg)  # expect: unbounded-queue-append
    return backlog


class Annotated:
    """Typed construction (AnnAssign) must not hide the container."""

    def __init__(self):
        self._typed: "collections.deque" = collections.deque()

    def reader(self, sock):
        while True:
            self._typed.append(sock.recv())  # expect: unbounded-queue-append


class InfiniteBounds:
    """Queue(0)/maxsize=0/maxlen=None mean INFINITE in their own
    semantics — a zero 'bound' is no bound."""

    def __init__(self):
        self._q = queue.Queue(0)
        self._q2 = queue.Queue(maxsize=0)
        self._ring = collections.deque([], None)

    def pump(self, sock):
        while True:
            self._q.put(sock.recv())  # expect: unbounded-queue-append
            self._q2.put(sock.recv())  # expect: unbounded-queue-append
            self._ring.append(sock.recv())  # expect: unbounded-queue-append


class HeartbeatDaemon:
    """Fleet heartbeat agent that journals every beat forever — the
    membership-layer variant of the slow-consumer OOM."""

    def __init__(self):
        self._beats = []

    def heartbeat_loop(self, router, stop):
        while not stop.is_set():
            stats = router.heartbeat()
            self._beats.append(stats)  # expect: unbounded-queue-append


class BrokenDispatchPipeline:
    """The serving dispatch-pipeline idiom gone wrong: a producer that
    enqueues in-flight batches with no depth bound and no drain in scope
    — a stalled collector turns the device queue into an OOM (the exact
    hazard DispatchPipeline's backpressure wait exists to kill)."""

    def __init__(self):
        self._fifo = collections.deque()

    def producer_loop(self, batches):
        while True:
            batch = batches.get_next()
            if batch is None:
                break
            handle = batch.dispatch()
            self._fifo.append(handle)  # expect: unbounded-queue-append


class PagePoolUnboundedGrowth:
    """Decode page pool whose reclaim loop grows its free list straight
    from a network-driven release stream with no bound — a looping or
    hostile peer double-freeing page ids inflates the 'free' list (and
    the release journal) forever."""

    def __init__(self):
        self._free = []
        self._release_log = collections.deque()

    def reclaim_loop(self, sock):
        while True:
            page = sock.recv()
            if page is None:
                break
            self._free.append(page)  # expect: unbounded-queue-append
            self._release_log.append(page)  # expect: unbounded-queue-append
