"""Cross-process BSP on the DCN PS path (VERDICT r2 next-round #2).

Port of the reference sync tests at world > 1: ``Test/unittests/
test_sync.cpp:9-44`` (every worker's i-th Get sees identical parameters)
and ``Test/test_array_table.cpp:14-42`` (the self-checking invariant
``data == delta * (i+1) * num_workers`` after round i under -sync=true).

Tier 1: two PSServices in ONE process, 2 logical ranks x 2 local worker
threads = 4 BSP workers, all ops clock-gated through the services' single
dispatcher threads (LocalForward disabled in sync mode). Tier 2 (slow):
the same invariant with 2 REAL processes x 2 threads.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.core.options import AddOption, GetOption
from multiverso_tpu.parallel.ps_service import (DistributedArrayTable,
                                                PSService)


@pytest.fixture
def sync_two_rank_world():
    """-sync=true world: 2 ranks x 2 local workers in one process."""
    mv.init(["-sync=true"], num_local_workers=2)
    svc0 = PSService()
    svc1 = PSService()
    peers = [svc0.address, svc1.address]
    yield svc0, svc1, peers
    svc0.close()
    svc1.close()
    mv.shutdown()


def _worker_loop(table, local_wid, rounds, size, views, errors):
    delta = np.ones(size, dtype=np.float32)
    try:
        for i in range(rounds):
            table.add(delta, AddOption(worker_id=local_wid))
            got = table.get(GetOption(worker_id=local_wid))
            views.append((i, got.copy()))
    except Exception as e:  # noqa: BLE001 - surfaced by the main thread
        errors.append(e)


def test_sync_identical_views_2rank_2thread(sync_two_rank_world):
    """Every worker's i-th Get is identical — and equal to the closed form
    delta * (i+1) * num_workers (ref test_array_table.cpp:14-42)."""
    svc0, svc1, peers = sync_two_rank_world
    size, rounds = 32, 5
    t0 = DistributedArrayTable(1, size, svc0, peers, rank=0)
    t1 = DistributedArrayTable(1, size, svc1, peers, rank=1)
    assert t0._bsp and t1._bsp

    views = {k: [] for k in range(4)}
    errors = []
    threads = [
        threading.Thread(target=_worker_loop,
                         args=(table, lw, rounds, size,
                               views[r * 2 + lw], errors))
        for r, table in ((0, t0), (1, t1)) for lw in (0, 1)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=120)
        assert not th.is_alive(), "BSP worker wedged"
    assert not errors, errors

    for w, seq in views.items():
        assert len(seq) == rounds
        for i, got in seq:
            np.testing.assert_allclose(
                got, np.full(size, (i + 1) * 4.0),
                err_msg=f"worker {w} round {i}")


def test_sync_finish_train_releases_stragglers(sync_two_rank_world):
    """A worker that stops participating retires via Server_Finish_Train
    (clock -> infinity, ref src/server.cpp:190-213); the others' gates
    then exclude it and training drains deterministically."""
    svc0, svc1, peers = sync_two_rank_world
    size = 16
    t0 = DistributedArrayTable(2, size, svc0, peers, rank=0)
    t1 = DistributedArrayTable(2, size, svc1, peers, rank=1)

    short_rounds, long_rounds = 2, 4
    views = {k: [] for k in range(4)}
    errors = []

    def short_worker():     # rank 0, local worker 0: quits early
        _worker_loop(t0, 0, short_rounds, size, views[0], errors)
        t0.finish_train(0)

    threads = [threading.Thread(target=short_worker)] + [
        threading.Thread(target=_worker_loop,
                         args=(table, lw, long_rounds, size,
                               views[r * 2 + lw], errors))
        for r, table, lw in ((0, t0, 1), (1, t1, 0), (1, t1, 1))]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=120)
        assert not th.is_alive(), "BSP worker wedged after finish_train"
    assert not errors, errors

    # closed form: 3 live workers + the retiree's min(i+1, short) adds
    for w in (1, 2, 3):
        for i, got in views[w]:
            expect = 3.0 * (i + 1) + min(i + 1, short_rounds)
            np.testing.assert_allclose(got, np.full(size, expect),
                                       err_msg=f"worker {w} round {i}")


def test_async_mode_unaffected(mv_env):
    """Without -sync the gate must not exist: LocalForward stays on and no
    clock state is allocated."""
    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    t0 = DistributedArrayTable(3, 10, svc0, peers, rank=0)
    DistributedArrayTable(3, 10, svc1, peers, rank=1)
    assert not t0._bsp
    assert not svc0._sync and not svc1._sync
    t0.add(np.ones(10, dtype=np.float32))
    np.testing.assert_allclose(t0.get(), np.ones(10))
    svc0.close(); svc1.close()


def test_per_worker_updater_state_spans_dcn_world(mv_env):
    """AdaGrad per-worker accumulators must be sized by the DCN worker
    universe (world x local), not zoo.num_workers() — separate JAX runtimes
    report process_count()==1, so remote ranks' stamped worker ids would
    index out of bounds and their G^2 updates silently drop (review r3)."""
    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    t0 = DistributedArrayTable(4, 8, svc0, peers, rank=0, updater="adagrad")
    t1 = DistributedArrayTable(4, 8, svc1, peers, rank=1, updater="adagrad")
    assert t0.local_store.num_workers == 2
    # each rank's adds must land in DISTINCT accumulator slots
    t0.add(np.ones(8, dtype=np.float32), AddOption(worker_id=0))
    t1.add(np.ones(8, dtype=np.float32), AddOption(worker_id=0))
    g2 = np.asarray(t0.local_store.state["g2"])
    assert g2.shape[0] == 2
    shard = t0.offsets[1] - t0.offsets[0]    # real rows; rest is padding
    assert (g2[0][:shard] > 0).all() and (g2[1][:shard] > 0).all()
    svc0.close(); svc1.close()


_SYNC_WORKER = r"""
import os, sys, threading, time
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import multiverso_tpu as mv
from multiverso_tpu.core.options import AddOption, GetOption

rank = int(sys.argv[1]); rendezvous = sys.argv[2]
mv.init(["-sync=true"], num_local_workers=2)
addr = mv.net_bind()
with open(os.path.join(rendezvous, f"addr{rank}"), "w") as f:
    f.write(f"{addr[0]}:{addr[1]}")
other = os.path.join(rendezvous, f"addr{1 - rank}")
for _ in range(600):
    if os.path.exists(other):
        break
    time.sleep(0.05)
host, port = open(other).read().split(":")
peers = [None, None]
peers[rank] = addr
peers[1 - rank] = (host, int(port))
mv.net_connect(peers)
table = mv.create_distributed_array_table(1, 32, rank=rank)
assert table._bsp, "sync flag did not arm BSP"

ROUNDS = 5
delta = np.ones(32, dtype=np.float32)
failures = []

def loop(lw):
    try:
        for i in range(ROUNDS):
            table.add(delta, AddOption(worker_id=lw))
            got = table.get(GetOption(worker_id=lw))
            if not np.allclose(got, (i + 1) * 4.0):
                failures.append((lw, i, got[0]))
                return
    except Exception as e:
        failures.append((lw, "exc", repr(e)))

threads = [threading.Thread(target=loop, args=(lw,)) for lw in (0, 1)]
for t in threads: t.start()
for t in threads: t.join(timeout=120)
assert not failures, failures
print(f"SYNC_RANK{rank}_OK")
with open(os.path.join(rendezvous, f"done{rank}"), "w") as f:
    f.write("ok")
peer_done = os.path.join(rendezvous, f"done{1 - rank}")
for _ in range(600):
    if os.path.exists(peer_done):
        break
    time.sleep(0.05)
mv.shutdown()
"""


@pytest.mark.slow
def test_two_process_two_thread_sync(tmp_path):
    """The VERDICT-prescribed shape: 2 processes x 2 threads, -sync=true,
    every worker's i-th Get equals delta * (i+1) * 4."""
    script = tmp_path / "syncworker.py"
    script.write_text(_SYNC_WORKER)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(r), str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for r in range(2)]
    for r, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            p.kill()
            pytest.fail("sync worker timed out")
        assert p.returncode == 0, f"rank {r} failed:\n{err[-2000:]}"
        assert f"SYNC_RANK{r}_OK" in out


def test_bsp_fuzz_identical_views_with_jitter(sync_two_rank_world):
    """Fuzz the clock-gated dispatch: 2 ranks x 2 local workers with
    random per-round deltas and random timing jitter. The BSP invariant
    must hold regardless of interleaving: every worker's i-th Get is
    IDENTICAL across all four workers, and equals the sum of all
    workers' rounds 0..i of deltas."""
    import random

    svc0, svc1, peers = sync_two_rank_world
    size, rounds = 16, 6
    t0 = DistributedArrayTable(40, size, svc0, peers, rank=0)
    t1 = DistributedArrayTable(40, size, svc1, peers, rank=1)

    # delta[w][i]: deterministic per (worker, round) so the closed form
    # is computable; values differ per worker/round.
    def delta(w, i):
        return np.full(size, float((w + 1) * 100 + i), dtype=np.float32)

    views = {w: [] for w in range(4)}
    errors = []

    def worker(table, lw, gid, seed):
        rng = random.Random(seed)
        try:
            for i in range(rounds):
                time.sleep(rng.random() * 0.02)
                table.add(delta(gid, i), AddOption(worker_id=lw))
                time.sleep(rng.random() * 0.02)
                views[gid].append(table.get(GetOption(worker_id=lw)))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker,
                                args=(tbl, lw, r * 2 + lw, 31 + r * 2 + lw))
               for r, tbl in ((0, t0), (1, t1)) for lw in (0, 1)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=180)
        assert not th.is_alive(), "BSP fuzz worker wedged"
    assert not errors, errors

    for i in range(rounds):
        expect = np.zeros(size, dtype=np.float32)
        for w in range(4):
            for j in range(i + 1):
                expect += delta(w, j)
        for w in range(4):
            np.testing.assert_allclose(
                views[w][i], expect,
                err_msg=f"worker {w} round {i} diverged")
