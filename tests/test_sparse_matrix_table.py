"""SparseMatrixTable staleness semantics
(ref src/table/sparse_matrix_table.cpp:184-258)."""

import numpy as np

import multiverso_tpu as mv
from multiverso_tpu.core.options import (AddOption, GetOption,
                                         MatrixTableOption)


def _make(mv, **kw):
    return mv.create_table(
        mv.MatrixTableOption(num_row=10, num_col=4, is_sparse=True, **kw))


def test_initially_all_rows_stale(mv_env):
    t = _make(mv)
    rows, values = t.get_stale(GetOption(worker_id=0))
    assert len(rows) == 10
    assert np.all(values == 0)
    # second get: nothing stale
    rows2, _ = t.get_stale(GetOption(worker_id=0))
    assert len(rows2) == 0


def test_add_invalidates_other_workers_only(mv_env):
    t = _make(mv)
    # drain initial staleness for worker 0
    t.get_stale(GetOption(worker_id=0))
    # worker 0 adds rows 2,3 — its own view stays fresh
    # (ref sparse_matrix_table.cpp:200-223)
    t.add_rows([2, 3], np.ones((2, 4), dtype=np.float32),
               mv.AddOption(worker_id=0))
    rows, _ = t.get_stale(GetOption(worker_id=0))
    assert len(rows) == 0


def test_incremental_whole_get_with_cache(mv_env):
    t = _make(mv)
    opt = GetOption(worker_id=0)
    first = t.get(opt)
    assert np.all(first == 0)
    t.add_rows([5], np.full((1, 4), 7.0, dtype=np.float32),
               mv.AddOption(worker_id=1))  # another worker's add
    second = t.get(opt)
    expected = np.zeros((10, 4), dtype=np.float32)
    expected[5] = 7.0
    np.testing.assert_allclose(second, expected)
    # only row 5 crossed the wire: staleness was exactly {5}
    t.add_rows([1], np.ones((1, 4), dtype=np.float32),
               mv.AddOption(worker_id=1))
    stale = t.stale_rows(0)
    np.testing.assert_array_equal(stale, [1])


def test_dense_add_invalidates_everything(mv_env):
    t = _make(mv)
    t.get_stale(GetOption(worker_id=0))
    t.add(np.ones((10, 4), dtype=np.float32), mv.AddOption(worker_id=1))
    assert len(t.stale_rows(0)) == 10


def test_pipeline_doubles_slots(mv_env):
    t = _make(mv, is_pipeline=True)
    # ref sparse_matrix_table.cpp:184-197: bitmap doubled when pipelining
    assert t._stale.shape[0] == 2 * mv.num_workers()


def test_restore_marks_all_stale(tmp_path, mv_env):
    """Checkpoint restore resets staleness to all-stale (worker caches are
    not part of the checkpoint, so a fresh bit would lie) and repeated
    incremental gets recover the exact restored values."""
    from multiverso_tpu.core import checkpoint as ckpt

    t = _make(mv)
    t.add_rows([2], np.full((1, 4), 5.0, dtype=np.float32),
               mv.AddOption(worker_id=1))
    full_before = t.get(GetOption(worker_id=0))     # drains staleness
    uri = f"file://{tmp_path}/sparse.npz"
    ckpt.save_table(t, uri)
    ckpt.load_table(t, uri)
    assert len(t.stale_rows(0)) == t.num_row        # everything re-pulls
    np.testing.assert_allclose(t.get(GetOption(worker_id=0)), full_before)


def test_writer_sees_own_unpulled_write_plain_add(mv_env):
    """r4 regression: an add to a never-pulled row must be visible in the
    writer's own incremental get (mirror mode applies the delta to the
    writer's cache; the old code marked the row fresh over a zero
    cache)."""
    t = mv.create_table(MatrixTableOption(8, 2, is_sparse=True,
                                          name="own_write"))
    t.add_rows([3], np.ones((1, 2), dtype=np.float32),
               AddOption(worker_id=0))
    got = t.get(GetOption(worker_id=0))
    np.testing.assert_allclose(got[3], 1.0)
    np.testing.assert_allclose(got[0], 0.0)


def test_stateful_updater_uses_reference_loose_freshness(mv_env):
    """sgd tables can't mirror; writer bits stay untouched on Add (ref
    UpdateAddState :199-223): a never-pulled row stays stale and the next
    get ships server truth; a previously-pulled row keeps the last-pull
    view until another worker re-stales it."""
    t = mv.create_table(MatrixTableOption(8, 2, is_sparse=True,
                                          updater="sgd", name="sgd_loose"))
    assert not t._mirror
    # never pulled: own add leaves the row stale -> get ships the truth
    t.add_rows([2], np.ones((1, 2), dtype=np.float32),
               AddOption(worker_id=0))
    got = t.get(GetOption(worker_id=0))
    np.testing.assert_allclose(got[2], -1.0)     # sgd: data -= delta
    # pulled now: own add is invisible (last-pull view) ...
    t.add_rows([2], np.ones((1, 2), dtype=np.float32),
               AddOption(worker_id=0))
    got = t.get(GetOption(worker_id=0))
    np.testing.assert_allclose(got[2], -1.0)
    # ... until another worker writes the row
    t.add_rows([2], np.ones((1, 2), dtype=np.float32),
               AddOption(worker_id=1))
    got = t.get(GetOption(worker_id=0))
    np.testing.assert_allclose(got[2], -3.0)


def test_random_init_unpulled_write_ships_truth(mv_env):
    """random_init + never-pulled row: loose bits keep the row stale, so
    the incremental get ships SERVER truth (init + delta) — the mirror
    never masks initialization the cache has not seen."""
    t = mv.create_table(MatrixTableOption(6, 2, is_sparse=True,
                                          random_init=True, seed=5,
                                          name="rand_sparse"))
    t.add_rows([3], np.ones((1, 2), dtype=np.float32),
               AddOption(worker_id=0))
    got = t.get(GetOption(worker_id=0))
    truth = np.asarray(t.get_rows([3]))[0]
    np.testing.assert_allclose(got[3], truth)    # init + delta, not delta
