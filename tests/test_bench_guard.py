"""Tier-1 smoke + unit tests for the serving perf-regression gate
(``scripts/bench_guard.py``): the gate function's decisions on synthetic
history, and the CLI's --dry-run self-test end to end."""

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_GUARD = os.path.join(_REPO, "scripts", "bench_guard.py")

sys.path.insert(0, os.path.join(_REPO, "scripts"))
import bench_guard  # noqa: E402


def _rec(qps, cores=4, benchmark="serve_lookup", replicas=0, rows=1000):
    return {"benchmark": benchmark, "achieved_qps": qps,
            "box": {"cores": cores, "machine": "x86_64"},
            "config": {"replicas": replicas, "dry_run": False,
                       "rows": rows}}


def test_gate_passes_within_tolerance():
    records = [_rec(q) for q in (500, 505, 495, 490, 510)] + [_rec(470)]
    out = bench_guard.evaluate(records, tolerance=0.15)
    assert out["status"] == "ok"
    assert out["trailing_median_qps"] == 500.0


def test_gate_fails_same_box_regression():
    records = [_rec(q) for q in (500, 505, 495, 490, 510)] + [_rec(350)]
    out = bench_guard.evaluate(records, tolerance=0.15)
    assert out["status"] == "regression"
    assert out["floor_qps"] == 425.0


def test_gate_warns_not_fails_on_box_mismatch():
    """The 1-core CI box against committed many-core records measures
    the box, not the code — warn-don't-fail (satellite requirement)."""
    records = [_rec(q, cores=16) for q in (500, 505, 495, 490)] \
        + [_rec(350, cores=1)]
    out = bench_guard.evaluate(records, tolerance=0.15)
    assert out["status"] == "warn_box_mismatch"
    # Pre-v7 records without a fingerprint degrade the same way.
    legacy = [dict(_rec(q), box=None) for q in (500, 505, 495)] \
        + [_rec(350)]
    assert bench_guard.evaluate(legacy)["status"] == "warn_box_mismatch"


def test_gate_only_compares_comparable_records():
    """Fleet records never gate a single-process record and vice versa."""
    records = [_rec(1000, replicas=2, benchmark="serve_fleet_lookup")
               for _ in range(5)] + [_rec(300)]
    out = bench_guard.evaluate(records)
    assert out["status"] == "insufficient_history"
    assert out["n_history"] == 0


def test_gate_abstains_below_min_history():
    records = [_rec(500), _rec(505)] + [_rec(10)]
    assert bench_guard.evaluate(records)["status"] == \
        "insufficient_history"


def test_cli_dry_run_self_test():
    proc = subprocess.run([sys.executable, _GUARD, "--dry-run"],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["self_test"] == "bench_guard"
    assert line["failures"] == 0


def test_cli_against_repo_history():
    """The gate must RUN against the real trend file (ok or warn — the
    CI box legitimately differs from committed record boxes; exit 1
    would mean a same-box regression, which tier-1 should surface)."""
    history = os.path.join(_REPO, "BENCH_SERVE_HISTORY.jsonl")
    if not os.path.exists(history):
        return
    proc = subprocess.run([sys.executable, _GUARD,
                           f"--history={history}"],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
