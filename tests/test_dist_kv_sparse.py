"""DistributedKVTable + DistributedSparseMatrixTable over DCN
(VERDICT r3 next-round #3 and #4 — the last two table-family gaps).

Tier 1: two PSServices in one process over loopback TCP. Tier 2 (slow):
two real processes asserting the reference's incremental-Get contract —
the second whole-table Get's wire volume scales with rows touched since
the last pull, not with table size (ref src/table/
sparse_matrix_table.cpp:184-258).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.core.options import AddOption, GetOption
from multiverso_tpu.parallel.ps_service import (DistributedKVTable,
                                                DistributedSparseMatrixTable,
                                                PSService)


@pytest.fixture
def two_rank_world(mv_env):
    svc0 = PSService()
    svc1 = PSService()
    peers = [svc0.address, svc1.address]
    yield svc0, svc1, peers
    svc0.close()
    svc1.close()


# -- KV ----------------------------------------------------------------------
def test_kv_add_get_across_shards(two_rank_world):
    """+= merge server-side; key % num_servers routing (kv_table.h:48-50):
    even keys land on rank 0's shard, odd keys on rank 1's."""
    svc0, svc1, peers = two_rank_world
    t0 = DistributedKVTable(1, svc0, peers, rank=0)
    t1 = DistributedKVTable(1, svc1, peers, rank=1)
    keys = [2, 3, 40, 41]
    t0.add(keys, [10, 20, 30, 40])
    t1.add(keys, [1, 2, 3, 4])
    np.testing.assert_array_equal(t0.get(keys), [11, 22, 33, 44])
    np.testing.assert_array_equal(t1.get(keys), [11, 22, 33, 44])
    # hash placement is real: each shard holds exactly its residue class
    assert set(t0.local_store._map) == {2, 40}
    assert set(t1.local_store._map) == {3, 41}


def test_kv_int64_values_are_exact(two_rank_world):
    """Word counts must accumulate exactly — int64 on the wire, no float32
    round trip (2^40 is unrepresentable in float32)."""
    svc0, svc1, peers = two_rank_world
    t0 = DistributedKVTable(2, svc0, peers, rank=0)
    DistributedKVTable(2, svc1, peers, rank=1)
    big = (1 << 40) + 3
    t0.add([7], [big])
    t0.add([7], [1])
    assert int(t0.get([7])[0]) == big + 1


def test_kv_get_async_pipelines(two_rank_world):
    svc0, svc1, peers = two_rank_world
    t0 = DistributedKVTable(3, svc0, peers, rank=0)
    DistributedKVTable(3, svc1, peers, rank=1)
    t0.add([5], [9])
    op = t0.get_async([5])
    assert int(t0.wait(op)[0]) == 9


def test_kv_checkpoint_roundtrip(two_rank_world):
    svc0, svc1, peers = two_rank_world
    t0 = DistributedKVTable(4, svc0, peers, rank=0)
    DistributedKVTable(4, svc1, peers, rank=1)
    t0.add([2, 3], [10, 20])
    saved = t0.store_state()
    t0.add([2], [100])
    t0.load_state(saved)
    assert int(t0.get([2])[0]) == 10   # rank-0 shard restored


# -- sparse matrix -----------------------------------------------------------
def test_sparse_incremental_get_ships_only_touched_rows(two_rank_world):
    """First whole-table Get pulls everything; an untouched second Get
    pulls ZERO rows; after a peer adds 2 rows, the next Get pulls exactly
    those 2 — wire volume scales with touched rows, not table size."""
    svc0, svc1, peers = two_rank_world
    V = 40
    m0 = DistributedSparseMatrixTable(5, V, 4, svc0, peers, rank=0)
    m1 = DistributedSparseMatrixTable(5, V, 4, svc1, peers, rank=1)
    m0.add_rows(np.arange(V, dtype=np.int32),
                np.ones((V, 4), dtype=np.float32),
                AddOption(worker_id=0))

    got = m1.get(GetOption(worker_id=0))          # worker gid 1 (rank 1)
    np.testing.assert_allclose(got, 1.0)
    assert m1.last_incremental_rows == V          # first pull: all rows

    got = m1.get(GetOption(worker_id=0))
    np.testing.assert_allclose(got, 1.0)
    assert m1.last_incremental_rows == 0          # nothing touched since

    m0.add_rows([3, 25], np.full((2, 4), 5.0, dtype=np.float32),
                AddOption(worker_id=0))
    got = m1.get(GetOption(worker_id=0))
    assert m1.last_incremental_rows == 2          # exactly the touched rows
    np.testing.assert_allclose(got[3], 6.0)
    np.testing.assert_allclose(got[25], 6.0)
    np.testing.assert_allclose(got[4], 1.0)       # cached, not re-shipped


def test_sparse_writer_own_rows_stay_fresh(two_rank_world):
    """The writer's own adds don't invalidate its own view (ref :200-223:
    Add marks rows stale for every OTHER worker) — and its cache still
    reflects them, because adds apply client-side too."""
    svc0, svc1, peers = two_rank_world
    m0 = DistributedSparseMatrixTable(6, 10, 2, svc0, peers, rank=0)
    DistributedSparseMatrixTable(6, 10, 2, svc1, peers, rank=1)
    m0.get(GetOption(worker_id=0))                # prime: all fresh
    m0.add_rows([1], np.ones((1, 2), dtype=np.float32),
                AddOption(worker_id=0))
    got = m0.get(GetOption(worker_id=0))
    assert m0.last_incremental_rows == 0          # own write: still fresh
    np.testing.assert_allclose(got[1], 1.0)       # ...and visible locally


def test_kv_rejects_negative_keys(two_rank_world):
    """Negative keys are reserved wire sentinels (TICK/STALE): using one
    as data must fail loudly, not hit the sentinel paths."""
    from multiverso_tpu.utils.log import FatalError
    svc0, svc1, peers = two_rank_world
    t0 = DistributedKVTable(7, svc0, peers, rank=0)
    DistributedKVTable(7, svc1, peers, rank=1)
    with pytest.raises(FatalError):
        t0.add([-2], [1])
    with pytest.raises(FatalError):
        t0.get([-3])


@pytest.fixture
def sync_two_rank_world():
    mv.init(["-sync=true"], num_local_workers=1)
    svc0 = PSService()
    svc1 = PSService()
    yield svc0, svc1, [svc0.address, svc1.address]
    svc0.close()
    svc1.close()
    mv.shutdown()


def test_bsp_sparse_row_routed_does_not_wedge(sync_two_rank_world):
    """The sparse override of _send_add_rows must keep the parent's BSP
    uniform-tick invariant: workers adding to disjoint shards may not
    wedge the gates."""
    import threading
    svc0, svc1, peers = sync_two_rank_world
    m0 = DistributedSparseMatrixTable(8, 20, 4, svc0, peers, rank=0)
    m1 = DistributedSparseMatrixTable(8, 20, 4, svc1, peers, rank=1)
    assert m0._bsp
    errors = []

    def loop(table, rows):
        try:
            for _ in range(3):
                table.add_rows(rows, np.ones((len(rows), 4),
                                             dtype=np.float32),
                               AddOption(worker_id=0))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=loop, args=(m0, [1, 3])),
               threading.Thread(target=loop, args=(m1, [15, 17]))]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=60)
        assert not th.is_alive(), "BSP sparse row-routed worker wedged"
    assert not errors, errors


def test_sparse_keyed_incremental_get(two_rank_world):
    """Keyed gets are ALSO incremental (ref keyed UpdateGetState,
    :244-253): only the stale subset of the requested rows crosses the
    wire; fresh rows serve from the worker's cache; request order and
    duplicates are honored."""
    svc0, svc1, peers = two_rank_world
    m0 = DistributedSparseMatrixTable(30, 20, 4, svc0, peers, rank=0)
    m1 = DistributedSparseMatrixTable(30, 20, 4, svc1, peers, rank=1)
    m0.add_rows(np.arange(10, dtype=np.int32),
                np.arange(10, dtype=np.float32)[:, None]
                .repeat(4, 1), AddOption(worker_id=0))

    opt = GetOption(worker_id=0)
    got = m1.get_rows([2, 7, 2, 15], opt)       # 15 never written: zeros
    assert m1.last_incremental_rows == 3        # {2, 7, 15} stale
    np.testing.assert_allclose(got[0], 2.0)
    np.testing.assert_allclose(got[1], 7.0)
    np.testing.assert_allclose(got[2], 2.0)     # duplicate honored
    np.testing.assert_allclose(got[3], 0.0)

    got = m1.get_rows([2, 7], opt)              # all fresh: cache only
    assert m1.last_incremental_rows == 0
    np.testing.assert_allclose(got[0], 2.0)

    m0.add_rows([7], np.full((1, 4), 100.0, np.float32),
                AddOption(worker_id=0))
    got = m1.get_rows([2, 7], opt)              # exactly the re-staled row
    assert m1.last_incremental_rows == 1
    np.testing.assert_allclose(got[1], 107.0)
    np.testing.assert_allclose(got[0], 2.0)

    # optionless keyed get stays plain (ships everything, marks nothing)
    got = m1.get_rows([2, 7])
    np.testing.assert_allclose(got[1], 107.0)


def test_sparse_checkpoint_restore_resets_staleness(two_rank_world):
    """Restore marks EVERYTHING stale (the reference initializes
    all-stale): a fresh bit promises the worker cache holds the current
    row, and caches are not part of the checkpoint."""
    svc0, svc1, peers = two_rank_world
    m0 = DistributedSparseMatrixTable(31, 8, 2, svc0, peers, rank=0)
    DistributedSparseMatrixTable(31, 8, 2, svc1, peers, rank=1)
    m0.add_rows([1, 5], np.ones((2, 2), dtype=np.float32),
                AddOption(worker_id=0))
    m0.get(GetOption(worker_id=0))              # prime: all fresh
    assert m0.get(GetOption(worker_id=0)) is not None
    assert m0.last_incremental_rows == 0

    saved = m0.store_state()
    m0.add_rows([1], np.ones((1, 2), dtype=np.float32),
                AddOption(worker_id=0))         # diverge
    m0.load_state(saved)                        # restore rank-0 shard

    got = m0.get(GetOption(worker_id=0))
    # rank 0's shard (rows 0-3) re-shipped from the restored truth; the
    # whole local bitmap went stale, so >= the local shard's rows ship.
    assert m0.last_incremental_rows >= 4
    np.testing.assert_allclose(got[1], 1.0)     # checkpoint value, not 2
    np.testing.assert_allclose(got[5], 1.0)


_SPARSE_WORKER = r"""
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import multiverso_tpu as mv
from multiverso_tpu.core.options import AddOption, GetOption

rank = int(sys.argv[1]); rendezvous = sys.argv[2]
mv.init([])
addr = mv.net_bind()
with open(os.path.join(rendezvous, f"addr{rank}"), "w") as f:
    f.write(f"{addr[0]}:{addr[1]}")
other = os.path.join(rendezvous, f"addr{1 - rank}")
for _ in range(600):
    if os.path.exists(other):
        break
    time.sleep(0.05)
host, port = open(other).read().split(":")
peers = [None, None]
peers[rank] = addr
peers[1 - rank] = (host, int(port))
mv.net_connect(peers)
V = 30
table = mv.create_distributed_sparse_matrix_table(11, V, 4, rank=rank)
kv = mv.create_distributed_kv_table(12, rank=rank)

def phase(tag):
    with open(os.path.join(rendezvous, f"{tag}{rank}"), "w") as f:
        f.write("ok")
    peer = os.path.join(rendezvous, f"{tag}{1 - rank}")
    for _ in range(600):
        if os.path.exists(peer):
            return
        time.sleep(0.05)
    raise SystemExit(f"peer never reached phase {tag}")

if rank == 0:
    table.add_rows(np.arange(V, dtype=np.int32),
                   np.ones((V, 4), dtype=np.float32),
                   AddOption(worker_id=0))
    kv.add([0, 1], [100, 7])
phase("seeded")

got = table.get(GetOption(worker_id=0))
assert np.allclose(got, 1.0), got
first = table.last_incremental_rows
# Loose freshness: never-pulled rows are stale for EVERYONE (a writer's
# own bits are untouched by its adds), so both ranks pull V on first get.
assert first == V, first
got = table.get(GetOption(worker_id=0))
second = table.last_incremental_rows
assert second == 0, f"untouched second get shipped {second} rows"
phase("pulled")

if rank == 1:
    table.add_rows([2, 17], np.full((2, 4), 3.0, dtype=np.float32),
                   AddOption(worker_id=0))
    kv.add([0, 1], [11, 2])
phase("touched")

if rank == 0:
    got = table.get(GetOption(worker_id=0))
    n = table.last_incremental_rows
    assert n == 2, f"expected 2 touched rows over the wire, got {n}"
    assert np.allclose(got[2], 4.0) and np.allclose(got[17], 4.0), got
    assert int(kv.get([0])[0]) == 111 and int(kv.get([1])[0]) == 9
phase("checked")
print(f"SPARSE_RANK{rank}_OK")
mv.shutdown()
"""


@pytest.mark.slow
def test_two_process_sparse_and_kv(tmp_path):
    script = tmp_path / "sparseworker.py"
    script.write_text(_SPARSE_WORKER)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(r), str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for r in range(2)]
    for r, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            p.kill()
            pytest.fail("sparse worker timed out")
        assert p.returncode == 0, f"rank {r} failed:\n{err[-2000:]}"
        assert f"SPARSE_RANK{r}_OK" in out


def test_sparse_sgd_reference_loose_semantics(two_rank_world):
    """Stateful updaters (sgd) use the reference's exact UpdateAddState
    semantics (sparse_matrix_table.cpp:199-223): the writer's own bits
    are untouched on Add — its view is its last pull; other workers see
    the server-side sgd step on their next incremental get."""
    svc0, svc1, peers = two_rank_world
    m0 = DistributedSparseMatrixTable(13, 10, 4, svc0, peers, rank=0,
                                      updater="sgd")
    m1 = DistributedSparseMatrixTable(13, 10, 4, svc1, peers, rank=1,
                                      updater="sgd")
    lr_opt = AddOption(worker_id=0, learning_rate=0.5)

    got0 = m0.get(GetOption(worker_id=0))      # worker 0 pulls (all zero)
    np.testing.assert_allclose(got0, 0.0)

    # worker 0 adds a gradient of +1 on row 2: server does w -= lr*delta
    m0.add_rows([2], np.ones((1, 4), dtype=np.float32), lr_opt)

    # writer's own view: last pull (zeros) — reference loose semantics
    got0 = m0.get(GetOption(worker_id=0))
    assert m0.last_incremental_rows == 0
    np.testing.assert_allclose(got0[2], 0.0)

    # the OTHER worker's incremental get ships the sgd-updated row
    # (sgd: data -= delta; the client pre-scales by lr, sgd_updater.h)
    got1 = m1.get(GetOption(worker_id=0))      # gid 1 (rank 1)
    np.testing.assert_allclose(got1[2], -1.0)

    # worker 1 now writes row 2 -> worker 0's next get refreshes it
    m1.add_rows([2], np.ones((1, 4), dtype=np.float32),
                AddOption(worker_id=0, learning_rate=0.5))
    got0 = m0.get(GetOption(worker_id=0))
    assert m0.last_incremental_rows >= 1
    np.testing.assert_allclose(got0[2], -2.0)


def test_bsp_kv_identical_views(sync_two_rank_world):
    """KV tables under -sync=true: hash-routed adds/gets tick every
    server uniformly (key residues rarely cover all shards), and each
    worker's i-th get sees both workers' first i adds."""
    import threading
    svc0, svc1, peers = sync_two_rank_world
    k0 = DistributedKVTable(33, svc0, peers, rank=0)
    k1 = DistributedKVTable(33, svc1, peers, rank=1)
    assert k0._bsp
    rounds = 4
    views = {0: [], 1: []}
    errors = []

    def worker(table, gid, key):
        try:
            for i in range(rounds):
                table.add([key], [10 ** gid])      # worker g adds 10^g
                views[gid].append(int(table.get([2])[0])
                                  + int(table.get([3])[0]))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    # worker 0 only touches key 2 (shard 0), worker 1 only key 3
    # (shard 1) — the wedge shape without uniform ticks.
    threads = [threading.Thread(target=worker, args=(k0, 0, 2)),
               threading.Thread(target=worker, args=(k1, 1, 3))]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=60)
        assert not th.is_alive(), "BSP KV worker wedged"
    assert not errors, errors
    # i-th view = (i+1) * (1 + 10): both workers' first i+1 adds, and
    # identical across workers.
    for i in range(rounds):
        assert views[0][i] == views[1][i] == (i + 1) * 11, \
            (i, views[0][i], views[1][i])


def test_sparse_mirror_bounded_drift_under_bf16_wire(two_rank_world):
    """-wire_compression=bf16 with a plain-add sparse table: the client
    mirrors the bf16-ROUNDED delta (what the server actually applied), so
    repeated adds contribute ZERO mirror/server drift — the only residual
    is ONE bf16 rounding of the priming pull. Adversarial order: a peer
    first drives the table to a non-bf16-representable value, THEN the
    writer primes and hammers adds; drift must stay bounded by that one
    rounding, not grow with the add count."""
    from multiverso_tpu.utils.configure import set_flag

    svc0, svc1, peers = two_rank_world
    V = 16
    m0 = DistributedSparseMatrixTable(77, V, 4, svc0, peers, rank=0)
    m1 = DistributedSparseMatrixTable(77, V, 4, svc1, peers, rank=1)
    rng = np.random.default_rng(5)
    set_flag("wire_compression", "bf16")
    try:
        # peer makes server truth non-representable in bf16 (1 + 2^-10)
        m1.add_rows(np.arange(V, dtype=np.int32),
                    np.full((V, 4), 1.0, np.float32), AddOption(worker_id=0))
        m1.add_rows(np.arange(V, dtype=np.int32),
                    np.full((V, 4), 2.0 ** -10, np.float32),
                    AddOption(worker_id=0))
        m0.get(GetOption(worker_id=0))     # prime: cache = round(truth)
        deltas = rng.normal(size=(50, V, 4)).astype(np.float32) * 0.01
        for d in deltas:
            m0.add_rows(np.arange(V, dtype=np.int32), d,
                        AddOption(worker_id=0))
        # writer's view: mirror-fresh rows, served from its cache
        mine = np.asarray(m0.get(GetOption(worker_id=0)))
        assert m0.last_incremental_rows == 0   # cache hit, not re-shipped
        # The mechanism, asserted exactly: cache == round(prime) + the sum
        # of ROUNDED deltas (what the server applied). Mirroring the raw
        # f32 deltas (the old bug) diverges from this immediately.
        from multiverso_tpu.utils.quantization import (bf16_bits_to_f32,
                                                       f32_to_bf16_bits)
        rnd = lambda a: bf16_bits_to_f32(  # noqa: E731
            f32_to_bf16_bits(a)).reshape(np.shape(a))
        expect = rnd(np.full((V, 4), 1.0 + 2.0 ** -10, np.float32))
        for d in deltas:
            expect = expect + rnd(d)
        np.testing.assert_array_equal(mine, expect)
        # ...and total drift vs exact f64 server truth is bounded by ~the
        # ONE prime rounding plus per-add rounding noise, not growing
        # 50x: the unrounded-mirror bug shows up as order-of-magnitude
        # larger deviation from this bound on typical draws.
        truth = np.full((V, 4), 1.0 + 2.0 ** -10, np.float64)
        for d in deltas:
            truth = truth + rnd(d).astype(np.float64)
        assert np.abs(mine - truth).max() < 2.0 ** -9, \
            np.abs(mine - truth).max()
    finally:
        set_flag("wire_compression", "sparse")


def test_bf16_bits_nan_inf_preserved():
    from multiverso_tpu.utils.quantization import (bf16_bits_to_f32,
                                                   f32_to_bf16_bits)
    x = np.array([np.nan, -np.nan, np.inf, -np.inf, 0.0], dtype=np.float32)
    y = bf16_bits_to_f32(f32_to_bf16_bits(x))
    assert np.isnan(y[0]) and np.isnan(y[1])
    assert y[2] == np.inf and y[3] == -np.inf and y[4] == 0.0
    # signaling-NaN bit pattern also maps to a quiet NaN, not inf
    s = np.array([0x7F800001, 0xFFFFFFFF], dtype=np.uint32).view(np.float32)
    z = bf16_bits_to_f32(f32_to_bf16_bits(s))
    assert np.isnan(z).all(), z
