"""WordEmbedding (word2vec) tests — dictionary/huffman/sampler units plus
end-to-end training signal on a synthetic two-topic corpus."""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.models.word2vec import (BatchGenerator, Dictionary,
                                            HuffmanEncoder, Sampler,
                                            SkipGramBatch, Word2Vec,
                                            Word2VecConfig)


def _corpus(n_sentences=300, seed=0):
    """Two word 'topics' that never co-occur: a0..a4 vs b0..b4."""
    rng = np.random.default_rng(seed)
    sentences = []
    for i in range(n_sentences):
        topic = "a" if i % 2 == 0 else "b"
        sentences.append([f"{topic}{rng.integers(0, 5)}" for _ in range(12)])
    return sentences



def _assert_topic_separation(w2v, d, margin=0.1):
    emb = w2v.embeddings().astype(np.float32)
    emb = emb / (np.linalg.norm(emb, axis=1, keepdims=True) + 1e-12)
    a_ids = [d.word2id[w] for w in d.words if w.startswith("a")]
    b_ids = [d.word2id[w] for w in d.words if w.startswith("b")]
    intra = np.mean([emb[i] @ emb[j] for i in a_ids for j in a_ids if i != j])
    inter = np.mean([emb[i] @ emb[j] for i in a_ids for j in b_ids])
    assert intra > inter + margin, f"intra={intra:.3f} inter={inter:.3f}"


def test_dictionary_build_and_encode():
    sents = [["x", "y", "x"], ["x", "z"]]
    d = Dictionary.build(sents, min_count=1)
    assert len(d) == 3
    assert d.words[0] == "x"          # most frequent first
    assert d.counts[0] == 3
    assert d.encode(["x", "unknown", "z"]) == [d.word2id["x"],
                                               d.word2id["z"]]
    d2 = Dictionary.build(sents, min_count=2)
    assert len(d2) == 1               # only 'x' survives


def test_huffman_codes_valid():
    counts = [50, 30, 10, 5, 3, 2]
    enc = HuffmanEncoder(counts)
    assert enc.num_inner == len(counts) - 1
    # more frequent words get shorter-or-equal codes
    assert enc.lengths[0] <= enc.lengths[-1]
    # prefix property: full (point-path, code) sequences are unique per word
    paths = set()
    for w in range(len(counts)):
        L = enc.lengths[w]
        key = tuple(enc.points[w, :L]) + tuple(enc.codes[w, :L])
        assert key not in paths
        paths.add(key)
    # all inner-node ids in range
    assert enc.points.max() < enc.num_inner


def test_sampler_follows_unigram_power():
    counts = [1000, 100, 10]
    s = Sampler(counts, table_size=1 << 16, seed=0)
    draws = s.sample(20000)
    freq = np.bincount(draws, minlength=3) / 20000
    assert freq[0] > freq[1] > freq[2]
    expected = np.array(counts, dtype=float) ** 0.75
    expected /= expected.sum()
    np.testing.assert_allclose(freq, expected, atol=0.05)


def test_batch_generator_shapes():
    sents = _corpus(50)
    d = Dictionary.build(sents, min_count=1)
    gen = BatchGenerator(d, batch_size=64, window=3, negative=4, sample=0,
                         sg=True)
    ids = [d.encode(s) for s in sents]
    batches = list(gen.batches(ids))
    assert len(batches) >= 2
    b = batches[0]
    assert isinstance(b, SkipGramBatch)
    assert b.centers.shape == (64,)
    assert b.negatives.shape == (64, 4)
    assert b.mask.sum() == b.n_words == 64
    # last batch padded + masked
    last = batches[-1]
    assert last.mask.sum() == last.n_words <= 64


@pytest.mark.parametrize("sg,hs", [(True, False), (True, True),
                                   (False, False), (False, True)])
def test_all_variants_smoke(mv_env, sg, hs):
    sents = _corpus(40)
    d = Dictionary.build(sents, min_count=1)
    cfg = Word2VecConfig(embedding_size=16, batch_size=128, window=3,
                         negative=3, min_count=1, sample=0, sg=sg, hs=hs,
                         epochs=1, block_words=2000, pipeline=False)
    w2v = Word2Vec(cfg, d)
    stats = w2v.train(sentences=[d.encode(s) for s in sents])
    assert stats["words"] > 0
    assert np.isfinite(stats["loss"])
    emb = w2v.embeddings()
    assert emb.shape == (len(d), 16)
    assert np.isfinite(emb).all()


def test_training_separates_topics(mv_env):
    sents = _corpus(400)
    d = Dictionary.build(sents, min_count=1)
    cfg = Word2VecConfig(embedding_size=32, batch_size=256, window=4,
                         negative=5, min_count=1, sample=0, sg=True,
                         epochs=3, learning_rate=0.1, block_words=5000,
                         pipeline=True, seed=3)
    w2v = Word2Vec(cfg, d)
    w2v.train(sentences=[d.encode(s) for s in sents])
    _assert_topic_separation(w2v, d)
    # most_similar agrees
    sims = w2v.most_similar(d.words[0], topk=3)
    topic = d.words[0][0]
    assert sum(1 for w, _ in sims if w.startswith(topic)) >= 2


def test_word_count_table_updated(mv_env):
    sents = _corpus(40)
    d = Dictionary.build(sents, min_count=1)
    cfg = Word2VecConfig(embedding_size=8, batch_size=64, min_count=1,
                         sample=0, epochs=1, block_words=100,
                         pipeline=False)
    w2v = Word2Vec(cfg, d)
    stats = w2v.train(sentences=[d.encode(s) for s in sents])
    counted = w2v.wordcount_table.get([0])[0]
    assert counted == stats["words"]


def test_save_embeddings(tmp_path, mv_env):
    sents = _corpus(30)
    d = Dictionary.build(sents, min_count=1)
    cfg = Word2VecConfig(embedding_size=8, batch_size=64, min_count=1,
                         sample=0, epochs=1, pipeline=False)
    w2v = Word2Vec(cfg, d)
    w2v.train(sentences=[d.encode(s) for s in sents])
    out = tmp_path / "emb.txt"
    w2v.save(str(out), batch_rows=4)   # force multi-batch export
    lines = out.read_text().strip().split("\n")
    header = lines[0].split()
    assert int(header[0]) == len(d) and int(header[1]) == 8
    assert len(lines) == len(d) + 1
    first = lines[1].split()
    assert first[0] in d.word2id
    assert len(first) == 9


def test_pair_compaction_identity_when_all_valid(mv_env):
    """window=1 + no subsampling leaves every pair slot valid, so the
    compaction scatter is the identity permutation and the compacted
    fori_loop must reproduce the uncompacted scan path bitwise (same key →
    same negatives per chunk slot)."""
    import jax
    import jax.numpy as jnp
    from multiverso_tpu.models.word2vec.model import build_device_block_step

    rng = np.random.default_rng(0)
    V, D, S, L, chunk = 50, 16, 4, 8, 16
    neg_table = jnp.asarray(rng.integers(0, V, size=997).astype(np.int32))
    keep_prob = jnp.ones(V, dtype=np.float32)
    sents = jnp.asarray(rng.integers(0, V, size=(S, L)).astype(np.int32))
    lengths = jnp.full((S,), L, dtype=jnp.int32)
    key = jax.random.PRNGKey(7)

    outs = []
    for compact in (False, True):
        step = build_device_block_step(window=1, negative=3, chunk=chunk,
                                       adagrad=True, compact=compact)
        w_in = jnp.asarray(rng0 := np.random.default_rng(1)
                           .normal(size=(V, D)).astype(np.float32))
        w_out = jnp.zeros((V, D), jnp.float32)
        g_in = jnp.zeros((V, D), jnp.float32)
        g_out = jnp.zeros((V, D), jnp.float32)
        outs.append(step(w_in, w_out, g_in, g_out, neg_table, keep_prob,
                         sents, lengths, key, jnp.float32(0.05)))
    # P = S*(L-1)*2 = 56 -> padded to 64, all 56 valid
    assert int(outs[0][5]) == int(outs[1][5]) == S * (L - 1) * 2
    for a, b in zip(outs[0][:5], outs[1][:5]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pair_compaction_counts_and_loss_with_masking(mv_env):
    """Partial masks (shrunk windows + short sentences): compacted path must
    report the same true-pair count as the scan path and produce a finite
    loss; updates must only touch rows that appear in valid pairs."""
    import jax
    import jax.numpy as jnp
    from multiverso_tpu.models.word2vec.model import build_device_block_step

    rng = np.random.default_rng(3)
    V, D, S, L, chunk = 60, 8, 6, 12, 32
    neg_table = jnp.asarray(rng.integers(0, V, size=499).astype(np.int32))
    keep_prob = jnp.ones(V, dtype=np.float32)
    sents = jnp.asarray(rng.integers(1, V, size=(S, L)).astype(np.int32))
    lengths = jnp.asarray(rng.integers(2, L + 1, size=S).astype(np.int32))
    key = jax.random.PRNGKey(11)
    args = (neg_table, keep_prob, sents, lengths, key, jnp.float32(0.05))

    counts, losses = [], []
    for compact in (False, True):
        step = build_device_block_step(window=4, negative=2, chunk=chunk,
                                       adagrad=False, compact=compact)
        zeros = [jnp.zeros((V, D), jnp.float32) for _ in range(4)]
        out = step(*zeros, *args)
        counts.append(int(out[5]))
        losses.append(float(out[4]))
    assert counts[0] == counts[1] > 0
    assert np.isfinite(losses[1])


def test_device_pipeline_matches_host_semantics(mv_env):
    """Device-side pair-gen path must train to the same topic separation."""
    sents = _corpus(300)
    d = Dictionary.build(sents, min_count=1)
    cfg = Word2VecConfig(embedding_size=32, batch_size=512, window=4,
                         negative=5, min_count=1, sample=0, sg=True,
                         epochs=3, learning_rate=0.1, seed=3,
                         device_pipeline=True, block_sentences=128,
                         pad_sentence_length=16, pipeline=True)
    w2v = Word2Vec(cfg, d)
    stats = w2v.train(sentences=[d.encode(s) for s in sents])
    assert stats["pairs"] > 0
    _assert_topic_separation(w2v, d)


def test_bfloat16_params_train(mv_env):
    """bf16 embedding storage with f32 math still separates topics."""
    sents = _corpus(300)
    d = Dictionary.build(sents, min_count=1)
    cfg = Word2VecConfig(embedding_size=32, batch_size=256, window=4,
                         negative=5, min_count=1, sample=0, sg=True,
                         epochs=3, learning_rate=0.1, block_words=5000,
                         param_dtype="bfloat16", seed=3,
                         device_pipeline=True, block_sentences=128,
                         pad_sentence_length=16)
    w2v = Word2Vec(cfg, d)
    w2v.train(sentences=[d.encode(s) for s in sents])
    assert str(w2v.input_table.store.dtype) == "bfloat16"
    _assert_topic_separation(w2v, d)


def test_bfloat16_loss_delta_bounded(mv_env):
    """bf16 storage (f32 math) must track the f32 loss closely — the
    numerics bound backing the bf16 data path's roofline claim
    (VERDICT r4 #2): identical config/seed, final loss within 3%
    (measured ~0.7% on this config)."""
    sents = _corpus(300)
    d = Dictionary.build(sents, min_count=1)
    ids = [d.encode(s) for s in sents]
    losses = {}
    for dt in ("float32", "bfloat16"):
        cfg = Word2VecConfig(embedding_size=32, batch_size=256, window=4,
                             negative=5, min_count=1, sample=0, sg=True,
                             epochs=3, learning_rate=0.1, block_words=5000,
                             param_dtype=dt, seed=3, device_pipeline=True,
                             block_sentences=128, pad_sentence_length=16)
        w2v = Word2Vec(cfg, d)
        losses[dt] = w2v.train(sentences=ids)["loss"]
    rel = abs(losses["bfloat16"] - losses["float32"]) \
        / abs(losses["float32"])
    assert rel < 0.03, losses


def test_bfloat16_save_and_checkpoint(tmp_path, mv_env):
    """bf16 tables must export text embeddings and round-trip the npz
    checkpoint (regression: bf16 scalars break 'f' formatting; npz stores
    bf16 as raw void)."""
    from multiverso_tpu.core import checkpoint as ckpt

    sents = _corpus(30)
    d = Dictionary.build(sents, min_count=1)
    cfg = Word2VecConfig(embedding_size=8, batch_size=64, min_count=1,
                         sample=0, epochs=1, pipeline=False,
                         param_dtype="bfloat16")
    w2v = Word2Vec(cfg, d)
    w2v.train(sentences=[d.encode(s) for s in sents])
    out = tmp_path / "emb.txt"
    w2v.save(str(out))
    assert len(out.read_text().strip().split("\n")) == len(d) + 1
    uri = f"file://{tmp_path}/bf16_table.npz"
    before = w2v.input_table.get().astype(np.float32)
    ckpt.save_table(w2v.input_table, uri)
    w2v.input_table.add(np.ones((len(d), 8), dtype=np.float32))
    ckpt.load_table(w2v.input_table, uri)
    np.testing.assert_allclose(
        w2v.input_table.get().astype(np.float32), before)
    assert str(w2v.input_table.store.dtype) == "bfloat16"


def test_analogy_query(mv_env):
    sents = _corpus(100)
    d = Dictionary.build(sents, min_count=1)
    cfg = Word2VecConfig(embedding_size=16, batch_size=128, min_count=1,
                         sample=0, epochs=1, pipeline=False)
    w2v = Word2Vec(cfg, d)
    w2v.train(sentences=[d.encode(s) for s in sents])
    out = w2v.analogy("a0", "a1", "b0", topk=3)
    assert len(out) == 3
    assert all(w not in ("a0", "a1", "b0") for w, _ in out)
    assert w2v.analogy("a0", "missing", "b0") == []


def test_chunked_dispatch_matches_block_step_bitwise(mv_env):
    """The host-dispatched chunk pipeline (pair_gen + chunk_step* + tail)
    must reproduce the in-graph compacted block step bitwise: identical key
    -> identical pair stream, negatives, masks, and update order."""
    import jax
    import jax.numpy as jnp
    from multiverso_tpu.models.word2vec.model import (
        build_chunked_pipeline, build_device_block_step,
        expected_live_chunks)

    rng = np.random.default_rng(5)
    V, D, S, L, chunk, W, K = 80, 16, 6, 20, 32, 3, 2
    neg_table = jnp.asarray(rng.integers(0, V, size=1024).astype(np.int32))
    keep_prob_host = np.full(V, 0.8, dtype=np.float32)
    keep_prob = jnp.asarray(keep_prob_host)
    sents = jnp.asarray(rng.integers(0, V, size=(S, L)).astype(np.int32))
    lengths = jnp.asarray(rng.integers(2, L + 1, size=S).astype(np.int32))
    key = jax.random.PRNGKey(13)
    lr = jnp.float32(0.05)

    def init():
        return [jnp.asarray(np.random.default_rng(1).normal(
            size=(V, D)).astype(np.float32))] + \
            [jnp.zeros((V, D), jnp.float32) for _ in range(3)]

    block = build_device_block_step(W, K, chunk, adagrad=True,
                                    compact=True)
    ref = block(*init(), neg_table, keep_prob, sents, lengths, key, lr)

    pair_gen, chunk_step, tail_step = build_chunked_pipeline(
        W, K, chunk, adagrad=True)
    centers2d, contexts2d, negs, n_pairs = pair_gen(
        neg_table, keep_prob, sents, lengths, key)
    n_static = centers2d.shape[0]
    est = expected_live_chunks(keep_prob_host, np.asarray(sents),
                               np.asarray(lengths), W, chunk, n_static)
    tables = init()
    idx = jnp.arange(n_static)
    total_loss = jnp.float32(0)
    for i in range(est):
        out = chunk_step(*tables, centers2d, contexts2d, negs, n_pairs,
                         idx[i], jnp.asarray(lr))
        tables = list(out[:4])
        total_loss = total_loss + out[4]
    out = tail_step(*tables, centers2d, contexts2d, negs, n_pairs,
                    jnp.asarray(lr), start=est)
    tables = out[:4]
    total_loss = total_loss + out[4]

    assert int(n_pairs) == int(ref[5])
    for a, b in zip(tables, ref[:4]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_allclose(float(total_loss), float(ref[4]), rtol=1e-6)


def test_dispatch_modes_three_way_bitwise(mv_env):
    """ISSUE 2 acceptance: all three chunk-loop executions — in-graph
    compacted block step, host-dispatched chunk chain (pipelined_host's
    step functions), and the Pallas grid-resident kernel (interpret on
    CPU) — produce bitwise-identical table state from one key."""
    import jax
    import jax.numpy as jnp
    from multiverso_tpu.models.word2vec.model import (
        build_chunked_pipeline, build_device_block_step,
        expected_live_chunks)
    from multiverso_tpu.ops.pallas_sgns import build_sgns_grid_step

    rng = np.random.default_rng(5)
    V, D, S, L, chunk, W, K = 80, 16, 6, 20, 32, 3, 2
    neg_table = jnp.asarray(rng.integers(0, V, size=1024).astype(np.int32))
    keep_prob_host = np.full(V, 0.8, dtype=np.float32)
    keep_prob = jnp.asarray(keep_prob_host)
    sents = jnp.asarray(rng.integers(0, V, size=(S, L)).astype(np.int32))
    lengths = jnp.asarray(rng.integers(2, L + 1, size=S).astype(np.int32))
    key = jax.random.PRNGKey(13)
    lr = jnp.float32(0.05)

    def init():
        return [jnp.asarray(np.random.default_rng(1).normal(
            size=(V, D)).astype(np.float32))] + \
            [jnp.zeros((V, D), jnp.float32) for _ in range(3)]

    # mode 1: in-graph compacted block step
    block = build_device_block_step(W, K, chunk, adagrad=True, compact=True)
    ref = block(*init(), neg_table, keep_prob, sents, lengths, key, lr)

    # shared pair stream for modes 2 and 3
    pair_gen, chunk_step, tail_step = build_chunked_pipeline(
        W, K, chunk, adagrad=True)
    centers2d, contexts2d, negs, n_pairs = pair_gen(
        neg_table, keep_prob, sents, lengths, key)

    # mode 2: host-dispatched chunk chain + exact tail
    est = expected_live_chunks(keep_prob_host, np.asarray(sents),
                               np.asarray(lengths), W, chunk,
                               centers2d.shape[0])
    tables = init()
    host_loss = jnp.float32(0)
    for i in range(est):
        out = chunk_step(*tables, centers2d, contexts2d, negs, n_pairs,
                         jnp.int32(i), lr)
        tables = list(out[:4])
        host_loss = host_loss + out[4]
    out = tail_step(*tables, centers2d, contexts2d, negs, n_pairs, lr,
                    start=est)
    host_tables, host_loss = out[:4], host_loss + out[4]

    # mode 3: Pallas grid (sequential on-chip loop, one dispatch)
    grid = build_sgns_grid_step(chunk=chunk, negative=K, adagrad=True,
                                interpret=True)
    g_out = grid(*init(), centers2d, contexts2d, negs, n_pairs, lr)

    assert int(n_pairs) == int(ref[5]) > 0
    for a, b, c in zip(ref[:4], host_tables, g_out[:4]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
    np.testing.assert_allclose(float(host_loss), float(ref[4]), rtol=1e-6)
    np.testing.assert_allclose(float(g_out[4]), float(ref[4]), rtol=1e-6)


def test_dispatch_mode_auto_decision_table(monkeypatch, mv_env):
    """resolve_dispatch_mode: latency probe + variant/mesh gates +
    legacy chunk_dispatch mapping + explicit-mode validation."""
    import dataclasses
    from multiverso_tpu.models.word2vec import model as m
    from multiverso_tpu.utils.log import FatalError

    cfg = Word2VecConfig(sg=True, hs=False, device_pipeline=True)
    monkeypatch.setattr(m, "measured_dispatch_latency_ms", lambda: 0.05)
    assert m.resolve_dispatch_mode(cfg, 1000, 1000) == "pipelined_host"
    monkeypatch.setattr(m, "measured_dispatch_latency_ms", lambda: 40.0)
    assert m.resolve_dispatch_mode(cfg, 1000, 1000) == "in_graph"
    # non-sg-ns variants and meshes always use the fused block step
    for variant in (dataclasses.replace(cfg, hs=True),
                    dataclasses.replace(cfg, sg=False),
                    dataclasses.replace(cfg, mesh_data=2)):
        assert m.resolve_dispatch_mode(variant, 1000, 1000) == "in_graph"
    # legacy bool maps onto the new modes
    assert m.resolve_dispatch_mode(
        dataclasses.replace(cfg, chunk_dispatch=True),
        1000, 1000) == "pipelined_host"
    assert m.resolve_dispatch_mode(
        dataclasses.replace(cfg, chunk_dispatch=False),
        1000, 1000) == "in_graph"
    # explicit mode wins over the probe; unknown names are rejected
    assert m.resolve_dispatch_mode(
        dataclasses.replace(cfg, dispatch_mode="pallas_grid"),
        1000, 1000) == "pallas_grid"
    with pytest.raises(FatalError):
        m.resolve_dispatch_mode(
            dataclasses.replace(cfg, dispatch_mode="bogus"), 1000, 1000)


@pytest.mark.parametrize("mode", ["pipelined_host", "pallas_grid"])
def test_device_pipeline_explicit_dispatch_modes_train(mv_env, mode):
    """End-to-end training under each explicit alternative execution
    (Pallas grid runs interpreted on CPU) still separates topics."""
    sents = _corpus(300)
    d = Dictionary.build(sents, min_count=1)
    cfg = Word2VecConfig(embedding_size=32, batch_size=512, window=4,
                         negative=5, min_count=1, sample=0, sg=True,
                         epochs=3, learning_rate=0.1, seed=3,
                         device_pipeline=True, block_sentences=128,
                         pad_sentence_length=16, pipeline=False,
                         dispatch_mode=mode, dispatch_depth=4)
    w2v = Word2Vec(cfg, d)
    stats = w2v.train(sentences=[d.encode(s) for s in sents])
    assert stats["pairs"] > 0
    assert np.isfinite(stats["loss"])
    _assert_topic_separation(w2v, d)


def test_sharded_block_step_bitexact_vs_single(mv_env):
    """The dp4 x tp2 block step is BIT-EXACT against the single-device
    step on identical inputs at a vocab (4096 rows over 2 model shards)
    where pairs certainly cross model shards — a much tighter tripwire
    than the end-to-end rtol test below (any resharding or masking bug in
    the partitioned program flips exact bits)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from multiverso_tpu.models.word2vec.model import (
        build_device_block_step, build_sharded_block_step)

    V, D, K = 4096, 32, 5
    S, L = 32, 64
    rng = np.random.default_rng(0)
    args = (rng.normal(size=(V, D)).astype(np.float32) * 0.1,
            np.zeros((V, D), np.float32), np.zeros((V, D), np.float32),
            np.zeros((V, D), np.float32),
            rng.integers(0, V, size=(1 << 16,)).astype(np.int32),
            np.ones((V,), np.float32),
            rng.integers(0, V, size=(S, L)).astype(np.int32),
            np.full((S,), L, np.int32))
    key, lr = jax.random.PRNGKey(7), jnp.float32(0.05)

    single = build_device_block_step(5, K, 1024, adagrad=True, compact=True)
    ref = single(*[jnp.array(a) for a in args], key, lr)

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(4, 2),
                ("data", "model"))
    shard = build_sharded_block_step(mesh, 5, K, 1024, adagrad=True,
                                     compact=True)
    got = shard(*[jnp.array(a) for a in args], key, lr)

    assert int(ref[5]) == int(got[5]) > 0
    np.testing.assert_array_equal(np.asarray(ref[4]), np.asarray(got[4]))
    for r, g in zip(ref[:4], got[:4]):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g))


def test_sharded_dpxtp_matches_single_device_losses(mv_env):
    """VERDICT r1 #6: the dp x tp sharded block step (sentences over a
    4-way data axis, vocab rows over a 2-way model axis) must produce the
    same losses and embeddings as the unsharded step — same keys -> same
    pairs/negatives/update order; only the layout differs."""
    sents = _corpus(300)
    d = Dictionary.build(sents, min_count=1)
    runs = []
    for mesh_data, mesh_model in ((1, 1), (4, 2)):
        cfg = Word2VecConfig(embedding_size=32, batch_size=256, window=4,
                             negative=5, min_count=1, sample=0, sg=True,
                             epochs=2, learning_rate=0.1, seed=3,
                             device_pipeline=True, block_sentences=128,
                             pad_sentence_length=16, pipeline=False,
                             mesh_data=mesh_data, mesh_model=mesh_model)
        w2v = Word2Vec(cfg, d)
        stats = w2v.train(sentences=[d.encode(s) for s in sents])
        runs.append((stats, w2v.embeddings().astype(np.float32)))
    (s1, e1), (s2, e2) = runs
    assert s1["pairs"] == s2["pairs"] > 0
    np.testing.assert_allclose(s2["loss"], s1["loss"], rtol=1e-4)
    np.testing.assert_allclose(e2, e1, rtol=1e-3, atol=1e-5)


@pytest.mark.parametrize("sg,hs", [(True, True), (False, False),
                                   (False, True)])
def test_device_pipeline_all_variants_train(mv_env, sg, hs):
    """VERDICT r3 #6: the on-device pair-gen path covers ALL FOUR variants
    (sg-ns already tested above) and trains to topic separation."""
    sents = _corpus(300)
    d = Dictionary.build(sents, min_count=1)
    cfg = Word2VecConfig(embedding_size=32, batch_size=512, window=4,
                         negative=5, min_count=1, sample=0, sg=sg, hs=hs,
                         epochs=3, learning_rate=0.1, seed=3,
                         device_pipeline=True, block_sentences=128,
                         pad_sentence_length=16, pipeline=False)
    w2v = Word2Vec(cfg, d)
    stats = w2v.train(sentences=[d.encode(s) for s in sents])
    assert stats["pairs"] > 0
    assert np.isfinite(stats["loss"])
    _assert_topic_separation(w2v, d)


@pytest.mark.parametrize("sg,hs", [(True, True), (False, False),
                                   (False, True)])
def test_device_compaction_bitwise_all_variants(mv_env, sg, hs):
    """Compacted fori_loop path reproduces the uncompacted scan path
    bitwise for every variant when all example slots are valid (window=1,
    no subsampling, full sentences)."""
    import jax
    import jax.numpy as jnp
    from multiverso_tpu.models.word2vec.dictionary import HuffmanEncoder
    from multiverso_tpu.models.word2vec.model import build_device_block_step

    rng = np.random.default_rng(0)
    V, D, S, L = 50, 16, 4, 8
    counts = rng.integers(1, 100, size=V).astype(np.int64)
    huff = HuffmanEncoder(counts, 16) if hs else None
    neg_table = jnp.asarray(rng.integers(0, V, size=997).astype(np.int32))
    keep_prob = jnp.ones(V, dtype=np.float32)
    sents = jnp.asarray(rng.integers(0, V, size=(S, L)).astype(np.int32))
    lengths = jnp.full((S,), L, dtype=jnp.int32)
    key = jax.random.PRNGKey(7)
    out_rows = (V - 1) if hs else V
    chunk = 16 if sg else 8

    outs = []
    for compact in (False, True):
        step = build_device_block_step(window=1, negative=3, chunk=chunk,
                                       adagrad=True, compact=compact,
                                       sg=sg, hs=hs, huffman=huff)
        w_in = jnp.asarray(np.random.default_rng(1)
                           .normal(size=(V, D)).astype(np.float32))
        w_out = jnp.zeros((out_rows, D), jnp.float32)
        g_in = jnp.zeros((V, D), jnp.float32)
        g_out = jnp.zeros((out_rows, D), jnp.float32)
        outs.append(step(w_in, w_out, g_in, g_out, neg_table, keep_prob,
                         sents, lengths, key, jnp.float32(0.05)))
    assert int(outs[0][5]) == int(outs[1][5]) > 0
    for a, b in zip(outs[0][:5], outs[1][:5]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_device_cbow_example_mask_semantics(mv_env):
    """CBOW device examples: pad positions and subsampled tokens drop out
    of both center and context roles; example count matches the number of
    kept positions with at least one kept neighbor."""
    import jax
    import jax.numpy as jnp
    from multiverso_tpu.models.word2vec.model import _cbow_arrays

    sents = jnp.asarray([[1, 2, 3, 0], [4, 5, 0, 0]], dtype=jnp.int32)
    lengths = jnp.asarray([3, 2], dtype=jnp.int32)
    keep = jnp.ones(6, dtype=jnp.float32)
    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    centers, contexts, cmask, ex_mask = _cbow_arrays(
        sents, lengths, keep, k1, k2, window=2)
    assert centers.shape == (8,)
    assert contexts.shape == (8, 4) and cmask.shape == (8, 4)
    ex = np.asarray(ex_mask)
    # positions 3 (pad, row 0) and 6,7 (pads, row 1) are never examples
    assert not ex[3] and not ex[6] and not ex[7]
    # every real position has >=1 in-window neighbor here
    assert ex[[0, 1, 2, 4, 5]].all()
    cm = np.asarray(cmask)
    # No context mask may point at a pad position: recompute each context
    # slot's source position and assert masked slots are all in-range.
    W = 2
    offs = []
    for dd in range(1, W + 1):
        offs += [dd, -dd]
    L = sents.shape[1]
    for p in range(cm.shape[0]):
        row, col = divmod(p, L)
        for j, dd in enumerate(offs):
            if cm[p, j]:
                src = col + dd
                assert 0 <= src < int(lengths[row]), \
                    f"context slot ({p},{j}) points at pad position {src}"
    assert cm[ex].sum() > 0
