"""Ring attention / Ulysses sequence parallelism vs dense reference."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from multiverso_tpu.parallel.sequence import (reference_attention,
                                              ring_attention,
                                              ulysses_attention)


@pytest.fixture
def seq_mesh():
    devices = jax.devices()
    return Mesh(np.asarray(devices), ("seq",))


def _qkv(B=2, H=8, S=64, D=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, H, S, D))
                             .astype(np.float32))
    return mk(), mk(), mk()


def test_ring_attention_matches_dense(seq_mesh):
    q, k, v = _qkv()
    spec = NamedSharding(seq_mesh, P(None, None, "seq", None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = ring_attention(qs, ks, vs, seq_mesh)
    expected = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_is_sequence_sharded(seq_mesh):
    q, k, v = _qkv()
    spec = NamedSharding(seq_mesh, P(None, None, "seq", None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = ring_attention(qs, ks, vs, seq_mesh)
    n = seq_mesh.shape["seq"]
    shard_shapes = {s.data.shape for s in out.addressable_shards}
    assert shard_shapes == {(2, 8, 64 // n, 16)}


def test_ulysses_matches_dense(seq_mesh):
    q, k, v = _qkv(H=8)   # heads divisible by 8 devices
    spec = NamedSharding(seq_mesh, P(None, None, "seq", None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = ulysses_attention(qs, ks, vs, seq_mesh)
    expected = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_jits_under_mesh(seq_mesh):
    """Must compile as one program (the training-step usage)."""
    q, k, v = _qkv(S=32)
    spec = NamedSharding(seq_mesh, P(None, None, "seq", None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    fn = jax.jit(lambda a, b, c: ring_attention(a, b, c, seq_mesh))
    out = fn(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(reference_attention(q, k, v)),
                               rtol=2e-4, atol=2e-5)

def reference_causal_attention(q, k, v):
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    S = q.shape[2]
    mask = jnp.where(jnp.arange(S)[None, :] > jnp.arange(S)[:, None],
                     -1e30, 0.0)
    s = s + mask[None, None]
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)


def test_causal_ring_attention_matches_dense(seq_mesh):
    q, k, v = _qkv()
    spec = NamedSharding(seq_mesh, P(None, None, "seq", None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = ring_attention(qs, ks, vs, seq_mesh, causal=True)
    expected = reference_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)
    assert np.isfinite(np.asarray(out)).all()


def test_ulysses_causal_matches_dense_causal(seq_mesh):
    """VERDICT r2 weak #7: the all-to-all path supports causal masking
    (after the layout swap each device holds the full sequence, so the
    mask is the plain lower triangle)."""
    q, k, v = _qkv(H=8)
    spec = NamedSharding(seq_mesh, P(None, None, "seq", None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = ulysses_attention(qs, ks, vs, seq_mesh, causal=True)
    ring = ring_attention(qs, ks, vs, seq_mesh, causal=True)
    # causal dense reference
    S = q.shape[2]
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    mask = jnp.tril(jnp.ones((S, S), dtype=bool))
    s = jnp.where(mask[None, None], s, jnp.finfo(s.dtype).min)
    expected = jnp.einsum("bhqk,bhkd->bhqd",
                          jax.nn.softmax(s, axis=-1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ring),
                               rtol=2e-4, atol=2e-5)
