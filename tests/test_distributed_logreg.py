"""Distributed logistic regression: two PS-service ranks, each training on
its data shard against process-sharded weights (the reference's multi-node
LR deployment, loopback-scaled)."""

import threading

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.models.logreg import ArrayBatcher, LogReg, LogRegConfig
from multiverso_tpu.models.logreg.model import PSModel
from multiverso_tpu.parallel.ps_service import (DistributedArrayTable,
                                                PSService)


def test_two_rank_distributed_logreg(mv_env):
    rng = np.random.default_rng(0)
    w_true = rng.normal(size=10)
    X = rng.normal(size=(600, 10)).astype(np.float32)
    y = (X @ w_true > 0).astype(np.float32)

    cfg = LogRegConfig(objective="sigmoid", num_feature=10, use_ps=True,
                       learning_rate=0.5, minibatch_size=32,
                       sync_frequency=1)
    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    tables = []
    try:
        tables += [DistributedArrayTable(50, cfg.width, svc, peers, rank=r,
                                        updater="sgd")
                  for r, svc in enumerate((svc0, svc1))]
        models = [PSModel(cfg, table=t) for t in tables]
        regs = []
        for m in models:
            lr = LogReg.__new__(LogReg)
            lr.cfg = cfg
            lr.model = m
            from multiverso_tpu.models.logreg.objective import get_objective
            import jax
            lr._predict = jax.jit(get_objective(cfg.objective)[1])
            regs.append(lr)

        shards = [(X[0::2], y[0::2]), (X[1::2], y[1::2])]

        def train(r):
            regs[r].train(ArrayBatcher(*shards[r], 32), epochs=15)

        threads = [threading.Thread(target=train, args=(r,))
                   for r in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive()

        # both ranks' final models agree and classify well
        for r in range(2):
            regs[r].model.sync()
            acc = regs[r].test(ArrayBatcher(X, y, 64))
            assert acc > 0.9, f"rank {r} acc {acc}"
        np.testing.assert_allclose(tables[0].get(), tables[1].get(),
                                   rtol=1e-5, atol=1e-6)
    finally:
        for t in tables:
            t.close()
        svc0.close()
        svc1.close()
