"""Flag system tests (reference altitude: configure.h / MV_SetFlag paths)."""

import pytest

from multiverso_tpu.utils import configure


def test_core_flags_defined():
    for name in ["sync", "ma", "ps_role", "updater_type", "omp_threads",
                 "backup_worker_ratio", "machine_file", "port"]:
        assert configure._registry.is_defined(name)


def test_defaults():
    assert configure.get_flag("sync") is False
    assert configure.get_flag("updater_type") == "default"
    assert configure.get_flag("omp_threads") == 4


def test_parse_consumes_matched_args():
    remaining = configure.parse_cmd_flags(
        ["prog", "-sync=true", "-updater_type=adagrad", "-not_a_flag=1",
         "positional"])
    assert remaining == ["prog", "-not_a_flag=1", "positional"]
    assert configure.get_flag("sync") is True
    assert configure.get_flag("updater_type") == "adagrad"


def test_double_dash_and_types():
    configure.parse_cmd_flags(["--port=1234", "--backup_worker_ratio=0.5"])
    assert configure.get_flag("port") == 1234
    assert configure.get_flag("backup_worker_ratio") == 0.5


def test_set_flag_coercion():
    configure.set_flag("sync", "1")
    assert configure.get_flag("sync") is True
    configure.set_flag("sync", "off")
    assert configure.get_flag("sync") is False
    configure.set_flag("omp_threads", "8")
    assert configure.get_flag("omp_threads") == 8


def test_unknown_flag_raises():
    with pytest.raises(configure.FlagError):
        configure.get_flag("nonexistent_flag")
    with pytest.raises(configure.FlagError):
        configure.set_flag("nonexistent_flag", 1)


def test_bad_value_raises():
    with pytest.raises(configure.FlagError):
        configure.set_flag("port", "not_an_int")
    with pytest.raises(configure.FlagError):
        configure.set_flag("sync", "maybe")


def test_reset_restores_defaults():
    configure.set_flag("port", 9999)
    configure.reset_flags()
    assert configure.get_flag("port") == 55555
