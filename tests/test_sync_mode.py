"""BSP (SyncServer) semantics tests — port of ``Test/unittests/test_sync.cpp``
invariants plus the vector-clock guarantee of ``src/server.cpp:61-67``: every
worker's i-th Get sees identical parameters."""

import threading

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.core.options import AddOption, GetOption
from multiverso_tpu.core.sync_coordinator import SyncCoordinator, VectorClock


def test_vector_clock_basics():
    vc = VectorClock(3)
    assert vc.min() == 0
    vc.tick(0)
    vc.tick(1)
    assert vc.min() == 0
    vc.tick(2)
    assert vc.min() == 1
    vc.finish(1)
    vc.tick(0)
    assert vc.min() == 1  # finished worker excluded


def test_sync_world_size_1(sync_env):
    """test_sync.cpp:9-44 shape: sync mode, one worker — plain round-trips."""
    mv = sync_env
    table = mv.create_table(mv.ArrayTableOption(size=10))
    delta = np.ones(10, dtype=np.float32)
    for i in range(3):
        table.add(delta)
        np.testing.assert_allclose(table.get(), delta * (i + 1))


def test_bsp_identical_views_across_workers():
    """N threaded workers doing (add, get) rounds: worker w's i-th get must
    equal delta * i * N regardless of interleaving."""
    num_workers = 4
    rounds = 5
    mv.init(["-sync=true"], num_local_workers=num_workers)
    try:
        table = mv.create_table(mv.ArrayTableOption(size=8))
        delta = np.ones(8, dtype=np.float32)
        views = [[] for _ in range(num_workers)]

        def worker(wid):
            for _ in range(rounds):
                table.add(delta, AddOption(worker_id=wid))
                views[wid].append(table.get(GetOption(worker_id=wid)).copy())

        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(num_workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        for i in range(rounds):
            expected = delta * (i + 1) * num_workers
            for w in range(num_workers):
                np.testing.assert_allclose(
                    views[w][i], expected,
                    err_msg=f"worker {w} round {i} saw a non-BSP view")
    finally:
        mv.shutdown()


def test_bsp_get_first_loop_is_live():
    """Regression (advisor round 1): the canonical get-train-add loop must not
    deadlock — the reference serves a worker's Get whenever its own add clock
    is not ahead of the global add clock (src/server.cpp ProcessGet), so the
    FIRST Get is served immediately, before any worker has Added."""
    num_workers = 3
    rounds = 4
    mv.init(["-sync=true"], num_local_workers=num_workers)
    try:
        table = mv.create_table(mv.ArrayTableOption(size=8))
        delta = np.ones(8, dtype=np.float32)
        views = [[] for _ in range(num_workers)]

        def worker(wid):
            for _ in range(rounds):
                views[wid].append(table.get(GetOption(worker_id=wid)).copy())
                table.add(delta, AddOption(worker_id=wid))

        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(num_workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in threads), \
            "get-first BSP loop deadlocked"

        # Worker w's i-th get (0-indexed) sees exactly i adds from everyone.
        for i in range(rounds):
            expected = delta * i * num_workers
            for w in range(num_workers):
                np.testing.assert_allclose(
                    views[w][i], expected,
                    err_msg=f"worker {w} round {i} saw a non-BSP view")
    finally:
        mv.shutdown()


def test_finish_train_releases_stragglers():
    """Server_Finish_Train analog (ref src/server.cpp:190-213): a finished
    worker must not block the others' clocks."""
    num_workers = 2
    mv.init(["-sync=true"], num_local_workers=num_workers)
    try:
        table = mv.create_table(mv.ArrayTableOption(size=4))
        delta = np.ones(4, dtype=np.float32)

        def short_worker():
            table.add(delta, AddOption(worker_id=0))
            table.get(GetOption(worker_id=0))
            table.finish_train(0)

        def long_worker():
            for _ in range(3):
                table.add(delta, AddOption(worker_id=1))
                table.get(GetOption(worker_id=1))

        t0 = threading.Thread(target=short_worker)
        t1 = threading.Thread(target=long_worker)
        t0.start(); t1.start()
        t0.join(timeout=30); t1.join(timeout=30)
        assert not t0.is_alive() and not t1.is_alive(), "BSP deadlock"
        np.testing.assert_allclose(table.get(), delta * 4)
    finally:
        mv.shutdown()
