"""Distributed-tracing unit tests: TraceContext semantics, span
parenting, wire codec, head-based sampling + tail exemplars, cross-
process stitching (including a hedged duplicate-span request), and the
exporter/registry under CONCURRENT mutation — the single-threaded-only
coverage gap called out in ISSUE 7.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from multiverso_tpu.telemetry import (TraceBuffer, activate,
                                      build_chrome_trace, child_of,
                                      current_context, emit_span,
                                      get_registry, get_trace_buffer,
                                      new_root, span, stitch_traces,
                                      trace_index, validate_chrome_trace)
from multiverso_tpu.telemetry.context import (TraceContext, from_wire,
                                              to_wire)


# ---------------------------------------------------------------------------
# Context mechanics
# ---------------------------------------------------------------------------
def test_current_context_is_thread_local():
    root = new_root(sampled=True)
    seen = {}

    def other():
        seen["other"] = current_context()

    with activate(root):
        t = threading.Thread(target=other)
        t.start()
        t.join()
        assert current_context() is root
    assert seen["other"] is None        # contexts never leak across threads
    assert current_context() is None    # ...and the stack pops cleanly


def test_child_of_links_trace_and_parent():
    root = new_root(sampled=True)
    child = child_of(root)
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert child.span_id != root.span_id
    assert child.sampled == root.sampled
    hedged = child_of(root, hedge=2)
    assert hedged.hedge == 2


def test_wire_roundtrip_and_malformed_blob():
    ctx = TraceContext(trace_id=(123 << 64) | 456, span_id=789,
                      parent_id=99, sampled=True, hedge=3)
    back = from_wire(to_wire(ctx))
    assert back == ctx
    unsampled = TraceContext(trace_id=1, span_id=2, sampled=False)
    assert from_wire(to_wire(unsampled)).sampled is False
    # Malformed blobs mean "no context", never an exception.
    assert from_wire(np.asarray([1, 2, 3])) is None
    assert from_wire(np.zeros(5, dtype=np.uint64)) is None  # span id 0
    assert from_wire("garbage") is None


def test_span_parents_under_active_context():
    buf = get_trace_buffer()
    buf.clear()
    root = new_root(sampled=True)
    with activate(root):
        with span("outer"):
            with span("inner"):
                pass
    inner, outer = buf.events()
    assert outer["args"]["trace"] == root.trace_hex
    assert outer["args"]["parent"] == root.span_hex
    assert inner["args"]["parent"] == outer["args"]["span"]
    assert inner["args"]["trace"] == outer["args"]["trace"]


def test_span_without_context_has_no_trace_fields():
    buf = get_trace_buffer()
    buf.clear()
    with span("legacy"):
        pass
    (ev,) = buf.events()
    assert "trace" not in ev["args"]


def test_unsampled_context_skips_buffer_but_times_histogram():
    buf = get_trace_buffer()
    buf.clear()
    root = new_root(sampled=False)
    h = get_registry().histogram("span.quiet")
    before = h.count
    with activate(root):
        with span("quiet"):
            pass
    assert buf.events() == []
    assert h.count == before + 1


def test_emit_span_force_records_tail_exemplar():
    buf = get_trace_buffer()
    buf.clear()
    root = new_root(sampled=False)
    emit_span("not.recorded", root, time.monotonic(), 1.0)
    assert buf.events() == []
    emit_span("tail.recorded", root, time.monotonic() - 0.2, 200.0,
              force=True, shed="deadline")
    (ev,) = buf.events()
    assert ev["args"]["tail"] == 1
    assert ev["args"]["shed"] == "deadline"
    assert ev["dur"] == 200_000      # microseconds


def test_sampling_rate_zero_means_no_root(monkeypatch):
    from multiverso_tpu.telemetry import maybe_new_root
    from multiverso_tpu.utils.configure import set_flag
    old = None
    try:
        from multiverso_tpu.utils.configure import get_flag
        old = float(get_flag("telemetry_sample_rate"))
        set_flag("telemetry_sample_rate", 0.0)
        assert maybe_new_root() is None
        set_flag("telemetry_sample_rate", 1.0)
        root = maybe_new_root()
        assert root is not None and root.sampled
    finally:
        if old is not None:
            set_flag("telemetry_sample_rate", old)


# ---------------------------------------------------------------------------
# Stitching
# ---------------------------------------------------------------------------
def _ev(name, trace, spanid, parent, pid, ts, dur, **extra):
    args = {"trace": trace, "span": spanid, "rank": 0}
    if parent:
        args["parent"] = parent
    args.update(extra)
    return {"name": name, "ph": "X", "ts": ts, "dur": dur, "pid": pid,
            "tid": 1, "cat": "multiverso_tpu", "args": args}


def test_stitch_interleaved_multiprocess_traces_with_hedge(tmp_path):
    """Three per-process trace files, two interleaved requests, one of
    them hedged (duplicate sibling attempts answered by different
    replicas): the stitch must yield one trace per request, correct
    parent links, both hedge tags, and flow events for each hop."""
    t1, t2 = "a" * 32, "b" * 32
    client = [  # pid 100: both roots + three attempts, interleaved
        _ev("fleet.request", t1, "0001", None, 100, 1000, 5000),
        _ev("fleet.request", t2, "0002", None, 100, 1200, 9000),
        _ev("fleet.attempt", t1, "0011", "0001", 100, 1100, 4000),
        _ev("fleet.attempt", t2, "0021", "0002", 100, 1300, 8000,
            hedge=1, attempt=0),
        _ev("fleet.attempt", t2, "0022", "0002", 100, 4000, 5000,
            hedge=1, attempt=1),
    ]
    replica_a = [  # pid 200 answers t1's attempt and t2's primary
        _ev("serve.request", t1, "0111", "0011", 200, 1500, 3000),
        _ev("serve.device", t1, "0112", "0111", 200, 2000, 1000),
        _ev("serve.request", t2, "0121", "0021", 200, 1800, 7000),
    ]
    replica_b = [  # pid 300 answers t2's hedged duplicate
        _ev("serve.request", t2, "0131", "0022", 300, 4500, 4000),
    ]
    for i, events in enumerate((client, replica_a, replica_b)):
        (tmp_path / f"trace-{i}.json").write_text(
            json.dumps({"traceEvents": events}))
    paths = [str(tmp_path / f"trace-{i}.json") for i in range(3)]

    stitched = stitch_traces(paths, out_path=str(tmp_path / "out.json"))
    validate_chrome_trace(stitched)
    spans = [e for e in stitched["traceEvents"] if e["ph"] == "X"]
    idx = trace_index(spans)
    assert set(idx) == {t1, t2}
    assert idx[t1]["n_spans"] == 4 and idx[t1]["parented_ok"]
    assert idx[t1]["pids"] == [100, 200]
    assert idx[t2]["n_spans"] == 5 and idx[t2]["parented_ok"]
    assert idx[t2]["pids"] == [100, 200, 300]
    assert idx[t2]["dur_us"] == 9000        # root duration, not max child
    # Hedged duplicates: sibling attempts under one parent, tagged.
    attempts = [e for e in spans if e["name"] == "fleet.attempt"
                and e["args"]["trace"] == t2]
    assert len(attempts) == 2
    assert {e["args"]["parent"] for e in attempts} == {"0002"}
    assert all(e["args"]["hedge"] == 1 for e in attempts)
    # Flow events: one s/f pair per cross-process parent->child edge
    # (t1: attempt->serve.request; t2: two attempts -> two replicas).
    flows = [e for e in stitched["traceEvents"] if e["ph"] in "sf"]
    assert len(flows) == 2 * 3
    # Filtering to one trace id keeps only that request.
    only_t2 = stitch_traces(paths, trace_id=t2)
    only_spans = [e for e in only_t2["traceEvents"] if e["ph"] == "X"]
    assert {e["args"]["trace"] for e in only_spans} == {t2}


def test_trace_index_flags_orphans(tmp_path):
    t = "c" * 32
    events = [_ev("child", t, "0201", "dead", 100, 1000, 10)]
    (tmp_path / "trace-0.json").write_text(
        json.dumps({"traceEvents": events}))
    stitched = stitch_traces([str(tmp_path / "trace-0.json")])
    idx = trace_index([e for e in stitched["traceEvents"]
                       if e["ph"] == "X"])
    assert idx[t]["parented_ok"] is False
    assert idx[t]["n_orphans"] == 1


# ---------------------------------------------------------------------------
# Registry / exporter under concurrent mutation
# ---------------------------------------------------------------------------
def test_registry_snapshot_under_concurrent_mutation():
    """snapshot() while other threads register NEW metrics and observe
    existing ones: no exception, and every snapshot is internally
    consistent (the single-threaded-only coverage gap)."""
    reg = get_registry()
    stop = threading.Event()
    errors = []

    def mutator(tid):
        i = 0
        try:
            while not stop.is_set():
                reg.histogram(f"conc.h{tid}.{i % 37}").observe(i % 11)
                reg.counter(f"conc.c{tid}.{i % 29}").inc()
                reg.gauge(f"conc.g{tid}.{i % 23}").set(i)
                i += 1
        except Exception as e:  # noqa: BLE001 - reported below
            errors.append(e)

    def snapshotter():
        try:
            while not stop.is_set():
                snap = reg.snapshot()
                for h in snap["histograms"].values():
                    assert h["count"] == sum(h["bucket_counts"])
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=mutator, args=(i,))
               for i in range(3)] + \
        [threading.Thread(target=snapshotter) for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.8)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert not errors, errors[:2]


def test_exporter_write_once_under_concurrent_spans(tmp_path):
    """The exporter writing snapshots + traces while other threads emit
    spans and register metrics: every written file stays valid JSON and
    schema-clean."""
    from multiverso_tpu.telemetry import (TelemetryExporter,
                                          validate_snapshot)
    stop = threading.Event()
    errors = []

    def spanner(tid):
        root = new_root(sampled=True)
        try:
            with activate(root):
                i = 0
                while not stop.is_set():
                    with span(f"conc.span{tid}", i=i):
                        pass
                    i += 1
                    if i % 256 == 0:
                        # Yield the GIL: three unthrottled span loops
                        # convoy the exporter's json.dump into minutes
                        # of wall time on a 1-core box without adding
                        # any concurrency coverage — the races under
                        # test are emit-vs-write interleavings, which
                        # 256-span bursts still produce.
                        time.sleep(0.001)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    exporter = TelemetryExporter(str(tmp_path), interval=0.05)
    threads = [threading.Thread(target=spanner, args=(i,))
               for i in range(3)]
    for t in threads:
        t.start()
    try:
        for _ in range(5):
            exporter.write_once()
            time.sleep(0.05)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        exporter.stop()
    assert not errors, errors[:2]
    snaps = [f for f in os.listdir(tmp_path) if f.startswith("metrics-")]
    traces = [f for f in os.listdir(tmp_path) if f.startswith("trace-")]
    assert snaps and traces
    for f in snaps:
        validate_snapshot(json.load(open(tmp_path / f)))
    for f in traces:
        validate_chrome_trace(json.load(open(tmp_path / f)))


def test_trace_buffer_record_during_events_iteration():
    buf = TraceBuffer(capacity=256)
    stop = threading.Event()
    errors = []

    def writer():
        i = 0
        try:
            while not stop.is_set():
                buf.record({"name": "x", "ph": "X", "ts": i, "dur": 1,
                            "pid": 1, "tid": 1, "args": {}})
                i += 1
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    t = threading.Thread(target=writer)
    t.start()
    try:
        for _ in range(200):
            events = buf.events()
            assert len(events) <= 256
    finally:
        stop.set()
        t.join(timeout=10)
    assert not errors
    assert buf.dropped > 0      # the ring evicted, never grew


def test_build_chrome_trace_validates_with_trace_fields():
    get_trace_buffer().clear()
    root = new_root(sampled=True)
    with activate(root):
        with span("v", runner="x"):
            pass
    validate_chrome_trace(build_chrome_trace())
