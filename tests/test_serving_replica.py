"""Checkpoint-to-replica handoff: load-latest, shard reassembly, and
atomic hot-swap under concurrent readers."""

import os
import threading
import time

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.core.checkpoint import CheckpointManager, save_all
from multiverso_tpu.serving import (CheckpointReplica, DynamicBatcher,
                                    ReplicaLookupRunner)


def _make_table():
    return mv.create_table(mv.MatrixTableOption(num_row=32, num_col=4,
                                                name="served"))


def _train_and_checkpoint(table, tmp_path, steps, seed=0):
    """Advance the table and save a checkpoint per requested step.
    Returns the expected array per step."""
    expected = {}
    rng = np.random.default_rng(seed)
    for step in steps:
        delta = rng.normal(size=(32, 4)).astype(np.float32)
        table.add(delta)
        save_all(str(tmp_path), step=step)
        expected[step] = np.asarray(table.get())
    return expected


def test_replica_loads_latest_checkpoint(mv_env, tmp_path):
    expected = _train_and_checkpoint(_make_table(), tmp_path, [10, 20])
    replica = CheckpointReplica(str(tmp_path))
    try:
        assert replica.step == 20
        np.testing.assert_array_equal(
            replica.snapshot().table("served"), expected[20])
    finally:
        replica.close()


def test_replica_requires_a_checkpoint(tmp_path):
    from multiverso_tpu.utils.log import FatalError
    with pytest.raises(FatalError):
        CheckpointReplica(str(tmp_path / "empty"))


def test_hot_swap_picks_up_new_checkpoint(mv_env, tmp_path):
    table = _make_table()
    expected = _train_and_checkpoint(table, tmp_path, [1])
    replica = CheckpointReplica(str(tmp_path))
    try:
        assert replica.step == 1
        assert not replica.refresh()        # nothing new: no swap
        expected.update(_train_and_checkpoint(table, tmp_path, [2]))
        assert replica.refresh()
        assert replica.step == 2
        np.testing.assert_array_equal(
            replica.snapshot().table("served"), expected[2])
    finally:
        replica.close()


def test_hot_swap_under_concurrent_gets(mv_env, tmp_path):
    """Readers hammer the replica through the batcher while checkpoints
    land and swap underneath. Every read must be one COHERENT step's
    values — a row matching step k's table exactly — never a torn mix."""
    table = _make_table()
    expected = _train_and_checkpoint(table, tmp_path, [1])
    replica = CheckpointReplica(str(tmp_path))
    runner = ReplicaLookupRunner(replica, "served")
    batcher = DynamicBatcher(runner, buckets=(8,), max_batch=4,
                             max_wait_ms=0.5)
    stop = threading.Event()
    errors = []

    by_step = dict(expected)

    def reader():
        rng = np.random.default_rng(os.getpid())
        while not stop.is_set():
            keys = rng.integers(0, 32, 5).astype(np.int32)
            try:
                got = batcher.submit(keys, deadline_ms=10_000).wait(30)
            except Exception as e:  # noqa: BLE001 - collect, don't die
                errors.append(repr(e))
                return
            # Snapshot the dict: the main thread update()s it while we
            # iterate, and a RuntimeError here would kill the reader
            # UNCAUGHT — the torn-read assertion would pass vacuously.
            ok = any(np.array_equal(got, tab[keys])
                     for tab in list(by_step.values()))
            if not ok:
                errors.append(f"torn read for keys {keys.tolist()}")
                return

    threads = [threading.Thread(target=reader) for _ in range(3)]
    try:
        for t in threads:
            t.start()
        for step in (2, 3, 4):
            by_step.update(_train_and_checkpoint(table, tmp_path, [step]))
            assert replica.refresh()
            time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive()
        assert not errors, errors
        assert replica.step == 4
    finally:
        stop.set()
        batcher.close()
        replica.close()


def test_replica_reassembles_rank_shards(tmp_path):
    """A 2-rank checkpoint (one shard file per rank + per-rank manifests)
    loads back as ONE full table, rows at their global offsets."""
    import json

    from multiverso_tpu.core.checkpoint import save_table
    from multiverso_tpu.serving import load_checkpoint_tables

    root = tmp_path / "ckpt_000000000007"
    rows = np.arange(40, dtype=np.float32).reshape(10, 4)

    class FakeShard:
        def __init__(self, offset, data):
            self._payload = {
                "data": data,
                "shard_meta": np.asarray([0, 0, 2, offset], np.int64),
            }

        def store_state(self):
            return self._payload

    for rank, sl in ((0, slice(0, 6)), (1, slice(6, 10))):
        fname = f"dist-shard{rank}of2.npz"
        save_table(FakeShard(sl.start, rows[sl]), str(root / fname))
        meta = {"step": 7, "tables": ["dist"], "files": {"dist": fname}}
        name = "meta.json" if rank == 0 else f"meta.r{rank}.json"
        with open(root / name, "w") as f:
            json.dump(meta, f)

    # A REPLICATED (shard-meta-less) table listed by BOTH ranks' manifests
    # must load as one copy, not be misread as two offset-0 shards.
    class FakeReplica:
        def store_state(self):
            return {"data": np.full((3, 2), 9.0, np.float32)}

    for rank in (0, 1):
        suffix = "" if rank == 0 else f"-r{rank}"
        fname = f"counts{suffix}.npz"
        save_table(FakeReplica(), str(root / fname))
        name = "meta.json" if rank == 0 else f"meta.r{rank}.json"
        meta = json.loads((root / name).read_text())
        meta["tables"].append("counts")
        meta["files"]["counts"] = fname
        (root / name).write_text(json.dumps(meta))

    tables = load_checkpoint_tables(str(root))
    np.testing.assert_array_equal(tables["dist"], rows)
    np.testing.assert_array_equal(tables["counts"],
                                  np.full((3, 2), 9.0, np.float32))

    # a missing shard fails loudly, not silently short
    os.unlink(root / "dist-shard0of2.npz")
    (root / "meta.json").write_text(json.dumps(
        {"step": 7, "tables": [], "files": {}}))
    with pytest.raises(Exception):
        load_checkpoint_tables(str(root))


def test_auto_refresh_follows_training(mv_env, tmp_path):
    table = _make_table()
    expected = _train_and_checkpoint(table, tmp_path, [1])
    replica = CheckpointReplica(str(tmp_path))
    replica.start_auto_refresh(interval_s=0.1)
    try:
        expected.update(_train_and_checkpoint(table, tmp_path, [5]))
        deadline = time.monotonic() + 20
        while replica.step < 5 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert replica.step == 5
        np.testing.assert_array_equal(
            replica.snapshot().table("served"), expected[5])
    finally:
        replica.close()


def test_checkpoint_manager_to_replica_pipeline(mv_env, tmp_path):
    """The real production loop: CheckpointManager triggers periodic
    saves, the replica follows the latest COMPLETE checkpoint."""
    table = mv.create_table(mv.MatrixTableOption(num_row=16, num_col=2,
                                                 name="served"))
    mgr = CheckpointManager(str(tmp_path), save_every_steps=10)
    table.add(np.ones((16, 2), np.float32))
    assert mgr.maybe_save(10) is not None
    replica = CheckpointReplica(str(tmp_path))
    try:
        assert replica.step == 10
        table.add(np.ones((16, 2), np.float32))
        assert mgr.maybe_save(20) is not None
        assert replica.refresh()
        np.testing.assert_array_equal(
            replica.snapshot().table("served"),
            np.full((16, 2), 2.0, np.float32))
    finally:
        replica.close()
