"""Serving-plane integration tests.

The headline acceptance: a 2-rank word2vec world trains over the DCN PS
service, each rank stands up a serving service over its LIVE shard, and
served embedding lookups through the routed client are BITWISE-equal to a
direct ``table.get_rows`` on the same clock — with the batcher having
compiled exactly one executable per bucket it exercised. Plus: wire-level
service/client behavior (concurrent in-flight, shed propagation, bf16
reply payloads) and the KV-cached greedy decode runner parity."""

import threading

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.models.word2vec import Dictionary, Word2VecConfig
from multiverso_tpu.models.word2vec.distributed import DistributedWord2Vec
from multiverso_tpu.parallel.ps_service import PSService
from multiverso_tpu.serving import (RoutedLookupClient, ServingClient,
                                    ServingService, ShedError,
                                    SparseLookupRunner)
from multiverso_tpu.utils.configure import set_flag


def _corpus(n_sentences=200, seed=0):
    rng = np.random.default_rng(seed)
    return [[f"{'a' if i % 2 == 0 else 'b'}{rng.integers(0, 5)}"
             for _ in range(12)] for i in range(n_sentences)]


def test_two_rank_train_then_serve_bitwise_parity(mv_env):
    """Train word2vec across 2 ranks, then serve lookups from each rank's
    LIVE shard through the routed client: bitwise equality with direct
    table.get_rows, one compiled executable per exercised bucket."""
    sents = _corpus()
    d = Dictionary.build(sents, min_count=1)
    ids = [d.encode(s) for s in sents]
    cfg = Word2VecConfig(embedding_size=16, batch_size=128, window=3,
                         negative=3, min_count=1, sample=0, sg=True,
                         epochs=1, learning_rate=0.01, block_words=1000,
                         pipeline=False, seed=3, optimizer="sgd")
    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    serve0 = serve1 = client = None
    try:
        w0 = DistributedWord2Vec(cfg, d, svc0, peers, rank=0)
        w1 = DistributedWord2Vec(cfg, d, svc1, peers, rank=1)
        threads = [threading.Thread(target=w0.train, args=(ids[0::2],)),
                   threading.Thread(target=w1.train, args=(ids[1::2],))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive(), "training hung"

        # Quiesce the add paths so "the same clock" is unambiguous.
        w0.w_in.flush(wait=True)
        w1.w_in.flush(wait=True)

        # One serving service per rank, straight over the live shard.
        buckets = (4, 8)
        runners = []
        serves = []
        for w in (w0, w1):
            runner = SparseLookupRunner(
                w.w_in.local_store,
                row_offset=int(w.w_in.row_offsets[w.rank]))
            svc = ServingService()
            svc.register_runner(runner, buckets=buckets, max_batch=4,
                                max_wait_ms=1.0)
            runners.append(runner)
            serves.append(svc)
        serve0, serve1 = serves
        client = RoutedLookupClient(
            [serve0.address, serve1.address],
            offsets=w0.w_in.row_offsets)

        V = len(d)
        rng = np.random.default_rng(7)
        queries = [rng.integers(0, V, n).astype(np.int64)
                   for n in (3, 4, 2, 7, 8, 1)]
        for q in queries:
            served = client.lookup(q, deadline_ms=10_000)
            direct = w0.w_in.get_rows(q.astype(np.int32))
            assert served.dtype == direct.dtype
            np.testing.assert_array_equal(served, direct)
        # zero-row lookup round-trips with the real column shape
        empty = client.lookup(np.empty(0, np.int64), deadline_ms=10_000)
        assert empty.shape == (0, 16)

        # No-retrace contract: per shard, exactly one executable per
        # bucket it actually served (routing may split a query below the
        # request's own bucket, so derive the expectation from calls).
        for runner in runners:
            assert 1 <= runner.jit_cache_size() <= len(buckets)
        assert sum(r.jit_cache_size() for r in runners) <= 2 * len(buckets)
        # rank 0 saw both buckets: 7- and 8-row queries land rows on both
        # shards, and the 8-row query guarantees a >4 sub-lookup somewhere
        total_cache = sum(r.jit_cache_size() for r in runners)
        assert total_cache >= 2, "batched lookups never exercised a bucket"
    finally:
        for s in (serve0, serve1):
            if s is not None:
                s.close()
        if client is not None:
            client.close()
        svc0.close()
        svc1.close()


def test_single_table_serving_exact_bucket_accounting(mv_env):
    """Direct (unrouted) serving over one live table: the jit cache size
    equals EXACTLY the number of buckets exercised."""
    table = mv.create_table(mv.MatrixTableOption(num_row=64, num_col=8))
    table.add_rows(np.arange(64, dtype=np.int32),
                   np.random.default_rng(0).normal(size=(64, 8))
                   .astype(np.float32))
    runner = table.serving_runner()
    svc = ServingService()
    svc.register_runner(runner, buckets=(4, 8, 16), max_batch=4,
                        max_wait_ms=1.0)
    cli = ServingClient(*svc.address)
    try:
        for n in (2, 3, 4):             # bucket 4 only
            cli.lookup(np.arange(n, dtype=np.int32), deadline_ms=10_000)
        assert runner.jit_cache_size() == 1
        cli.lookup(np.arange(7, dtype=np.int32), deadline_ms=10_000)
        assert runner.jit_cache_size() == 2
        cli.lookup(np.arange(16, dtype=np.int32), deadline_ms=10_000)
        assert runner.jit_cache_size() == 3
        # bitwise parity with the direct read
        q = np.asarray([5, 63, 0, 17], np.int32)
        np.testing.assert_array_equal(
            cli.lookup(q, deadline_ms=10_000), table.get_rows(q))
    finally:
        cli.close()
        svc.close()


def test_concurrent_inflight_requests_one_connection(mv_env):
    """One client socket, many threads: replies route by msg_id even when
    they complete out of order."""
    table = mv.create_table(mv.MatrixTableOption(num_row=128, num_col=4))
    table.add_rows(np.arange(128, dtype=np.int32),
                   np.arange(128 * 4, dtype=np.float32).reshape(128, 4))
    svc = ServingService()
    svc.register_runner(table.serving_runner(), buckets=(8,), max_batch=4,
                        max_wait_ms=2.0)
    cli = ServingClient(*svc.address)
    errors = []

    def hit(seed):
        rng = np.random.default_rng(seed)
        for _ in range(10):
            q = rng.integers(0, 128, 5).astype(np.int32)
            got = cli.lookup(q, deadline_ms=10_000)
            want = np.stack([np.arange(r * 4, r * 4 + 4) for r in q]) \
                .astype(np.float32)
            if not np.array_equal(got, want):
                errors.append((q.tolist(), got.tolist()))
                return

    try:
        threads = [threading.Thread(target=hit, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive()
        assert not errors, errors[:2]
    finally:
        cli.close()
        svc.close()


def test_shed_propagates_to_client_as_error(mv_env):
    table = mv.create_table(mv.MatrixTableOption(num_row=16, num_col=2))
    svc = ServingService()
    svc.register_runner(table.serving_runner(), buckets=(4,), max_batch=2,
                        max_wait_ms=1.0)
    cli = ServingClient(*svc.address)
    try:
        with pytest.raises(ShedError):
            cli.lookup(np.arange(9, dtype=np.int32), deadline_ms=10_000)
        # an already-expired deadline sheds rather than serves
        with pytest.raises(ShedError):
            cli.lookup(np.arange(2, dtype=np.int32), deadline_ms=0.0)
    finally:
        cli.close()
        svc.close()


def test_serve_wire_bf16_flag(mv_env):
    """-serve_wire_dtype=bf16: reply payloads cross as bf16 halves; the
    client sees values equal to the bf16 truncation of the table rows."""
    from multiverso_tpu.utils.quantization import (bf16_bits_to_f32,
                                                   f32_to_bf16_bits)

    table = mv.create_table(mv.MatrixTableOption(num_row=32, num_col=4))
    rng = np.random.default_rng(1)
    table.add_rows(np.arange(32, dtype=np.int32),
                   rng.normal(size=(32, 4)).astype(np.float32))
    svc = ServingService()
    svc.register_runner(table.serving_runner(), buckets=(8,), max_batch=2,
                        max_wait_ms=1.0)
    cli = ServingClient(*svc.address)
    try:
        q = np.asarray([3, 1, 30], np.int32)
        set_flag("serve_wire_dtype", "bf16")
        served = cli.lookup(q, deadline_ms=10_000)
        direct = np.asarray(table.get_rows(q))
        want = bf16_bits_to_f32(f32_to_bf16_bits(direct)).reshape(
            direct.shape)
        np.testing.assert_array_equal(served, want)
        assert not np.array_equal(served, direct) or \
            np.array_equal(want, direct)
        set_flag("serve_wire_dtype", "f32")
        np.testing.assert_array_equal(
            cli.lookup(q, deadline_ms=10_000), direct)
    finally:
        set_flag("serve_wire_dtype", "f32")
        cli.close()
        svc.close()


def test_attention_lm_decode_served_matches_full_forward(mv_env):
    """KV-cached greedy decode through the full serving plane equals the
    naive recompute-everything greedy loop on the flat forward."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from multiverso_tpu.models.attention_lm import (LMConfig, forward,
                                                    init_params)
    from multiverso_tpu.serving import AttentionLMRunner

    cfg = LMConfig(vocab=61, dim=32, heads=4, layers=2, seq=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    runner = AttentionLMRunner(
        {k: np.asarray(v) for k, v in params.items()}, cfg,
        max_new=5, max_batch=3)
    svc = ServingService()
    svc.register_runner(runner, buckets=(8,), max_batch=3, max_wait_ms=1.0)
    cli = ServingClient(*svc.address)
    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1),
                ("data", "seq"))

    def ref_decode(prompt, n):
        toks = list(prompt)
        out = []
        for _ in range(n):
            logits, _ = forward(params, jnp.asarray([toks]), cfg, mesh)
            nxt = int(jnp.argmax(logits[0, -1]))
            out.append(nxt)
            toks.append(nxt)
        return out

    try:
        for prompt in ([5, 9, 2], [1], [7, 3, 3, 3, 8, 2, 40]):
            got = cli.generate(np.asarray(prompt, np.int32),
                               deadline_ms=60_000, timeout=120)
            assert got.tolist() == ref_decode(prompt, 5), prompt
        assert runner.jit_cache_size() == 1     # one bucket exercised
    finally:
        cli.close()
        svc.close()
