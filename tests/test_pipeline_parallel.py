"""GPipe-style pipeline: matches sequential stage application; trains."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from multiverso_tpu.parallel.pipeline import (pipeline_apply,
                                              pipeline_train_1f1b,
                                              stage_sharding)


@pytest.fixture
def stage_mesh():
    devices = jax.devices()[:4]
    return Mesh(np.asarray(devices), ("stage",))


def _stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def _init_stages(S, D, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(S, D, D)).astype(np.float32) * 0.3
    b = rng.normal(size=(S, 1, D)).astype(np.float32) * 0.1
    return w, b


def test_pipeline_matches_sequential(stage_mesh):
    S, M, mb, D = 4, 6, 8, 16
    w, b = _init_stages(S, D)
    x = np.random.default_rng(1).normal(size=(M, mb, D)).astype(np.float32)
    sh = stage_sharding(stage_mesh)
    params = (jax.device_put(w, sh), jax.device_put(b, sh))
    y = pipeline_apply(_stage_fn, params, jnp.asarray(x), stage_mesh)
    # sequential reference
    expected = x.copy()
    for s in range(S):
        expected = np.tanh(expected @ w[s] + b[s])
    np.testing.assert_allclose(np.asarray(y), expected, rtol=2e-4,
                               atol=2e-5)


def test_pipeline_trains_under_grad(stage_mesh):
    """jax.grad through the pipeline updates every stage's weights."""
    S, M, mb, D = 4, 4, 4, 8
    w, b = _init_stages(S, D, seed=2)
    x = np.random.default_rng(3).normal(size=(M, mb, D)).astype(np.float32)
    target = np.random.default_rng(4).normal(size=(M, mb, D)) \
        .astype(np.float32)

    def loss_fn(params):
        y = pipeline_apply(_stage_fn, params, jnp.asarray(x), stage_mesh)
        return ((y - target) ** 2).mean()

    params = (jnp.asarray(w), jnp.asarray(b))
    loss0 = float(loss_fn(params))

    @jax.jit
    def update(params):
        grads = jax.grad(loss_fn)(params)
        return jax.tree.map(lambda p, g: p - 0.3 * g, params, grads)

    for _ in range(30):
        params = update(params)
    loss1 = float(loss_fn(params))
    assert loss1 < loss0 * 0.9, (loss0, loss1)
    # every stage's weights moved (the pipeline really trains all stages)
    for s in range(S):
        assert not np.allclose(np.asarray(params[0][s]), w[s])


def test_pipeline_rejects_mismatched_stage_count(stage_mesh):
    """8 stage rows on a 4-stage mesh must error loudly, not drop stages."""
    from multiverso_tpu.utils.log import FatalError
    w, b = _init_stages(8, 8)
    x = np.zeros((2, 4, 8), dtype=np.float32)
    with pytest.raises(FatalError):
        pipeline_apply(_stage_fn, (jnp.asarray(w), jnp.asarray(b)),
                       jnp.asarray(x), stage_mesh)


def _loss_fn(y, target):
    return ((y - target) ** 2).sum()


def _sequential_loss(params, x, target):
    """Reference: sum of per-microbatch losses through the stage chain."""
    w, b = params
    S = w.shape[0]
    total = 0.0
    for m in range(x.shape[0]):
        h = x[m]
        for s in range(S):
            h = _stage_fn((w[s], b[s]), h)
        total = total + _loss_fn(h, target[m])
    return total


def test_1f1b_matches_sequential_grads(stage_mesh):
    """1F1B loss and per-stage grads == jax.grad of the sequential chain."""
    S, M, mb, D = 4, 7, 4, 8          # M deliberately not a multiple of S
    w, b = _init_stages(S, D, seed=5)
    rng = np.random.default_rng(6)
    x = rng.normal(size=(M, mb, D)).astype(np.float32)
    tgt = rng.normal(size=(M, mb, D)).astype(np.float32)

    params = (jnp.asarray(w), jnp.asarray(b))
    loss, grads = pipeline_train_1f1b(_stage_fn, _loss_fn, params,
                                      jnp.asarray(x), jnp.asarray(tgt),
                                      stage_mesh)
    ref_loss, ref_grads = jax.value_and_grad(_sequential_loss)(
        params, jnp.asarray(x), jnp.asarray(tgt))
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    for g, rg in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                                   rtol=5e-3, atol=5e-5)


def test_1f1b_trains(stage_mesh):
    """SGD on 1F1B grads reduces the loss and moves every stage."""
    S, M, mb, D = 4, 8, 4, 8
    w, b = _init_stages(S, D, seed=7)
    rng = np.random.default_rng(8)
    x = rng.normal(size=(M, mb, D)).astype(np.float32)
    tgt = rng.normal(size=(M, mb, D)).astype(np.float32)
    params = (jnp.asarray(w), jnp.asarray(b))

    @jax.jit
    def update(params):
        loss, grads = pipeline_train_1f1b(
            _stage_fn, _loss_fn, params, jnp.asarray(x), jnp.asarray(tgt),
            stage_mesh)
        return loss, jax.tree.map(lambda p, g: p - 1e-3 * g, params, grads)

    loss0, params1 = update(params)
    for _ in range(20):
        loss1, params1 = update(params1)
    assert float(loss1) < float(loss0) * 0.9, (float(loss0), float(loss1))
    for s in range(S):
        assert not np.allclose(np.asarray(params1[0][s]), w[s])


def test_1f1b_saved_ring_is_O_S_not_O_M():
    """The saved-input ring must be 2*(S-1) slots regardless of M — the
    1F1B memory contract (GPipe-under-grad retains all M residuals)."""
    S, mb, D = 4, 2, 4
    devices = jax.devices()[:S]
    mesh = Mesh(np.asarray(devices), ("stage",))
    w, b = _init_stages(S, D, seed=9)
    params = (jnp.asarray(w), jnp.asarray(b))
    temps = {}
    for M in (8, 32):
        x = jnp.zeros((M, mb, D), jnp.float32)
        t = jnp.zeros((M, mb, D), jnp.float32)
        jitted = jax.jit(lambda p, x, t: pipeline_train_1f1b(
            _stage_fn, _loss_fn, p, x, t, mesh))
        compiled = jitted.lower(params, x, t).compile()
        # the ring appears as a [R, mb, D] buffer in the while-loop carry
        assert compiled.as_text().count(
            f"f32[{2 * (S - 1)},{mb},{D}]") > 0
        temps[M] = compiled.memory_analysis().temp_size_in_bytes
    # TEMP allocation (scan carries: ring + hop buffers + grads) must not
    # scale with M — a regression that retains per-microbatch residuals
    # would add at least one [M, mb, D] stack (M=32: 1024 floats = 4KB).
    assert temps[32] - temps[8] < 2048, temps


def test_pipeline_stream_stays_sharded_no_allgather(stage_mesh):
    """Round-2 efficiency pass (VERDICT r1 weak #4): with the microbatch
    stream sharded over the stage axis, the compiled program must contain
    NO all-gather — the stream feeds stage 0 via the chunk conveyor
    (collective-permute hops), never by replicating [M, mb, D] to every
    device. The old in_specs P() feed would force exactly that all-gather
    when handed a sharded stream."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    S, M, mb, D = 4, 8, 4, 8
    w, b = _init_stages(S, D)
    x = np.random.default_rng(5).normal(size=(M, mb, D)).astype(np.float32)
    sh = stage_sharding(stage_mesh)
    xsh = NamedSharding(stage_mesh, P("stage"))

    jitted = jax.jit(
        lambda params, xs: pipeline_apply(_stage_fn, params, xs,
                                          stage_mesh),
        in_shardings=((sh, sh), xsh))
    params = (jax.device_put(w, sh), jax.device_put(b, sh))
    xs = jax.device_put(jnp.asarray(x), xsh)
    hlo = jitted.lower(params, xs).compile().as_text()
    assert "all-gather" not in hlo, "stream was replicated, not streamed"
    assert "collective-permute" in hlo          # the hop + conveyor
    y = np.asarray(jitted(params, xs))
    expected = x.copy()
    for s in range(S):
        expected = np.tanh(expected @ w[s] + b[s])
    np.testing.assert_allclose(y, expected, rtol=2e-4, atol=2e-5)
