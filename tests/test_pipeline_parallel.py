"""GPipe-style pipeline: matches sequential stage application; trains."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from multiverso_tpu.parallel.pipeline import (pipeline_apply,
                                              stage_sharding)


@pytest.fixture
def stage_mesh():
    devices = jax.devices()[:4]
    return Mesh(np.asarray(devices), ("stage",))


def _stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def _init_stages(S, D, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(S, D, D)).astype(np.float32) * 0.3
    b = rng.normal(size=(S, 1, D)).astype(np.float32) * 0.1
    return w, b


def test_pipeline_matches_sequential(stage_mesh):
    S, M, mb, D = 4, 6, 8, 16
    w, b = _init_stages(S, D)
    x = np.random.default_rng(1).normal(size=(M, mb, D)).astype(np.float32)
    sh = stage_sharding(stage_mesh)
    params = (jax.device_put(w, sh), jax.device_put(b, sh))
    y = pipeline_apply(_stage_fn, params, jnp.asarray(x), stage_mesh)
    # sequential reference
    expected = x.copy()
    for s in range(S):
        expected = np.tanh(expected @ w[s] + b[s])
    np.testing.assert_allclose(np.asarray(y), expected, rtol=2e-4,
                               atol=2e-5)


def test_pipeline_trains_under_grad(stage_mesh):
    """jax.grad through the pipeline updates every stage's weights."""
    S, M, mb, D = 4, 4, 4, 8
    w, b = _init_stages(S, D, seed=2)
    x = np.random.default_rng(3).normal(size=(M, mb, D)).astype(np.float32)
    target = np.random.default_rng(4).normal(size=(M, mb, D)) \
        .astype(np.float32)

    def loss_fn(params):
        y = pipeline_apply(_stage_fn, params, jnp.asarray(x), stage_mesh)
        return ((y - target) ** 2).mean()

    params = (jnp.asarray(w), jnp.asarray(b))
    loss0 = float(loss_fn(params))

    @jax.jit
    def update(params):
        grads = jax.grad(loss_fn)(params)
        return jax.tree.map(lambda p, g: p - 0.3 * g, params, grads)

    for _ in range(30):
        params = update(params)
    loss1 = float(loss_fn(params))
    assert loss1 < loss0 * 0.9, (loss0, loss1)
    # every stage's weights moved (the pipeline really trains all stages)
    for s in range(S):
        assert not np.allclose(np.asarray(params[0][s]), w[s])


def test_pipeline_rejects_mismatched_stage_count(stage_mesh):
    """8 stage rows on a 4-stage mesh must error loudly, not drop stages."""
    from multiverso_tpu.utils.log import FatalError
    w, b = _init_stages(8, 8)
    x = np.zeros((2, 4, 8), dtype=np.float32)
    with pytest.raises(FatalError):
        pipeline_apply(_stage_fn, (jnp.asarray(w), jnp.asarray(b)),
                       jnp.asarray(x), stage_mesh)


def test_pipeline_stream_stays_sharded_no_allgather(stage_mesh):
    """Round-2 efficiency pass (VERDICT r1 weak #4): with the microbatch
    stream sharded over the stage axis, the compiled program must contain
    NO all-gather — the stream feeds stage 0 via the chunk conveyor
    (collective-permute hops), never by replicating [M, mb, D] to every
    device. The old in_specs P() feed would force exactly that all-gather
    when handed a sharded stream."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    S, M, mb, D = 4, 8, 4, 8
    w, b = _init_stages(S, D)
    x = np.random.default_rng(5).normal(size=(M, mb, D)).astype(np.float32)
    sh = stage_sharding(stage_mesh)
    xsh = NamedSharding(stage_mesh, P("stage"))

    jitted = jax.jit(
        lambda params, xs: pipeline_apply(_stage_fn, params, xs,
                                          stage_mesh),
        in_shardings=((sh, sh), xsh))
    params = (jax.device_put(w, sh), jax.device_put(b, sh))
    xs = jax.device_put(jnp.asarray(x), xsh)
    hlo = jitted.lower(params, xs).compile().as_text()
    assert "all-gather" not in hlo, "stream was replicated, not streamed"
    assert "collective-permute" in hlo          # the hop + conveyor
    y = np.asarray(jitted(params, xs))
    expected = x.copy()
    for s in range(S):
        expected = np.tanh(expected @ w[s] + b[s])
    np.testing.assert_allclose(y, expected, rtol=2e-4, atol=2e-5)
