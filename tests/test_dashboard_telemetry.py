"""Telemetry layer tests: histogram math, concurrent monitors, trace
schema, exporter files, multi-worker merge, and the end-to-end CPU
word2vec smoke (ISSUE 3 acceptance: a ``-telemetry_dir`` run emits a
loadable Chrome trace and snapshots with PS latency percentiles,
async-engine queue-depth samples, and per-worker staleness gauges)."""

import json
import re
import threading
import time

import numpy as np
import pytest

from multiverso_tpu.telemetry import (Histogram, build_chrome_trace,
                                      export_chrome_trace, gauge,
                                      get_registry, get_trace_buffer,
                                      merge_traces, metrics_snapshot,
                                      span, start_exporter, stop_exporter,
                                      validate_chrome_trace,
                                      validate_snapshot)
from multiverso_tpu.utils.dashboard import Dashboard, monitor


# -- histogram math ---------------------------------------------------------
def test_histogram_bucket_boundaries():
    h = Histogram("b")
    # Exact bucket edges are INCLUSIVE upper bounds: (lo*2^(i-1), lo*2^i].
    assert Histogram.bucket_index(0.0) == 0
    assert Histogram.bucket_index(0.0005) == 0
    assert Histogram.bucket_index(Histogram.LO_MS) == 0
    assert Histogram.bucket_index(Histogram.BOUNDS[1]) == 1
    assert Histogram.bucket_index(Histogram.BOUNDS[1] * 1.01) == 2
    for i, edge in enumerate(Histogram.BOUNDS):
        assert Histogram.bucket_index(edge) == i, edge
    # Beyond the last bound: the overflow bucket, never an IndexError.
    assert Histogram.bucket_index(Histogram.BOUNDS[-1] * 100) == \
        Histogram.N_BOUNDS
    for v in (0.0004, 0.003, 1.7, 900.0, 1e9):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 5
    assert sum(snap["bucket_counts"]) == 5
    assert snap["bucket_counts"][-1] == 1          # the 1e9 overflow
    assert snap["max_ms"] == 1e9


def test_histogram_percentiles_against_numpy():
    rng = np.random.default_rng(42)
    samples = rng.lognormal(mean=1.0, sigma=1.2, size=5000)   # ms
    h = Histogram("p")
    for v in samples:
        h.observe(float(v))
    for q in (0.50, 0.95, 0.99):
        ours = h.percentile(q)
        ref = float(np.quantile(samples, q))
        # Log-2 buckets with geometric interpolation: within one bucket
        # ratio of the exact quantile.
        assert ref / 2 <= ours <= ref * 2, (q, ours, ref)
    assert h.percentile(1.0) == pytest.approx(float(samples.max()))
    snap = h.snapshot()
    assert snap["p50"] <= snap["p95"] <= snap["p99"] <= snap["max_ms"]


def test_histogram_empty_and_single():
    h = Histogram("e")
    assert h.percentile(0.99) == 0.0
    assert h.snapshot()["count"] == 0
    h.observe(3.5)
    # One sample: every percentile is that sample (min/max clamping).
    assert h.percentile(0.5) == pytest.approx(3.5)
    assert h.percentile(0.99) == pytest.approx(3.5)


# -- monitors under concurrency --------------------------------------------
def test_concurrent_monitor_stress():
    n_threads, n_iter = 8, 300
    errors = []

    def worker():
        try:
            for _ in range(n_iter):
                with monitor("stress_op"):
                    pass
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    m = Dashboard.get("stress_op")
    assert m.count == n_threads * n_iter
    snap = m.snapshot()
    assert snap["count"] == n_threads * n_iter
    assert snap["min_ms"] >= 0.0
    assert snap["p50"] <= snap["max_ms"]


def test_monitor_begin_not_clobbered_across_threads():
    """Two threads in the same monitored region: each end() must pair with
    ITS OWN begin (the old shared ``_begin`` was clobbered, yielding one
    tiny duration and one dropped)."""
    m = Dashboard.get("clobber_op")
    a_begun = threading.Event()
    b_done = threading.Event()

    def slow():
        m.begin()
        a_begun.set()
        b_done.wait(5)
        time.sleep(0.02)
        m.end()

    def fast():
        a_begun.wait(5)
        m.begin()
        time.sleep(0.01)
        m.end()
        b_done.set()

    ta, tb = threading.Thread(target=slow), threading.Thread(target=fast)
    ta.start()
    tb.start()
    ta.join()
    tb.join()
    snap = m.snapshot()
    assert snap["count"] == 2
    # The slow thread's span covers the fast thread's whole window (>=30ms);
    # under the clobbered shared-begin it would measure ~20ms from B's begin.
    assert snap["max_ms"] >= 25.0, snap


def test_monitor_nested_same_thread():
    m = Dashboard.get("nested_op")
    m.begin()
    m.begin()
    time.sleep(0.005)
    m.end()            # inner
    time.sleep(0.005)
    m.end()            # outer: must use the OUTER begin (stack, not slot)
    snap = m.snapshot()
    assert snap["count"] == 2
    assert snap["max_ms"] >= 9.0, snap          # outer ~10ms
    assert snap["min_ms"] >= 4.0, snap          # inner ~5ms


def test_dashboard_display_returns_without_echo(capsys):
    Dashboard.get("quiet_op").add(1.0)
    report = Dashboard.display()
    assert "quiet_op" in report and "p95" in report
    assert capsys.readouterr().out == ""        # echo=False: no stdout
    Dashboard.display(echo=True)
    assert "quiet_op" in capsys.readouterr().out


# -- spans + chrome trace ---------------------------------------------------
def test_span_records_trace_event_and_histogram():
    with span("unit.test_span", mode="x", idx=3):
        time.sleep(0.002)
    events = [e for e in get_trace_buffer().events()
              if e["name"] == "unit.test_span"]
    assert events, "span did not reach the trace buffer"
    ev = events[-1]
    assert ev["ph"] == "X" and ev["dur"] >= 1000      # us
    assert ev["args"]["mode"] == "x" and ev["args"]["idx"] == 3
    assert "rank" in ev["args"]
    h = get_registry().histogram("span.unit.test_span")
    assert h.count >= 1


def test_chrome_trace_schema(tmp_path):
    for i in range(3):
        with span("unit.trace_schema", i=i):
            pass
    trace = build_chrome_trace()
    validate_chrome_trace(trace)
    # JSON round-trip (what chrome://tracing actually loads)
    path = tmp_path / "trace.json"
    export_chrome_trace(str(path))
    loaded = json.loads(path.read_text())
    validate_chrome_trace(loaded)
    xs = [e for e in loaded["traceEvents"] if e["ph"] == "X"]
    assert len(xs) >= 3
    assert any(e["ph"] == "M" for e in loaded["traceEvents"])


def test_validate_chrome_trace_rejects_garbage():
    with pytest.raises(ValueError):
        validate_chrome_trace([])
    with pytest.raises(ValueError):
        validate_chrome_trace({"traceEvents": [{"ph": "X", "pid": 1}]})
    with pytest.raises(ValueError):
        validate_chrome_trace({"traceEvents": [
            {"ph": "X", "pid": 1, "tid": 1, "name": "a",
             "ts": -5, "dur": 1}]})


def test_merge_traces_multi_worker(tmp_path):
    """Two processes' trace files merge into one multi-track trace."""
    def fake_trace(pid, t0):
        return {"traceEvents": [
            {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
             "args": {"name": f"rank {pid}"}},
            {"ph": "X", "name": "op", "pid": pid, "tid": 1,
             "ts": t0, "dur": 10, "args": {}}],
            "displayTimeUnit": "ms"}

    p1, p2 = tmp_path / "trace-100.json", tmp_path / "trace-200.json"
    p1.write_text(json.dumps(fake_trace(100, 2000)))
    p2.write_text(json.dumps(fake_trace(200, 1000)))
    out = tmp_path / "merged.json"
    merged = merge_traces([str(p1), str(p2)], out_path=str(out))
    validate_chrome_trace(merged)
    validate_chrome_trace(json.loads(out.read_text()))
    xs = [e for e in merged["traceEvents"] if e["ph"] == "X"]
    assert [e["ts"] for e in xs] == [1000, 2000]      # time-sorted
    metas = [e for e in merged["traceEvents"] if e["ph"] == "M"]
    assert {e["pid"] for e in metas} == {100, 200}


# -- snapshots + exporter ---------------------------------------------------
def test_snapshot_schema_and_contents():
    gauge("unit.depth").set(7)
    get_registry().counter("unit.events").inc(3)
    with monitor("unit.snap_op"):
        pass
    snap = metrics_snapshot()
    validate_snapshot(snap)
    assert snap["gauges"]["unit.depth"]["last"] == 7
    assert snap["counters"]["unit.events"]["value"] == 3
    hist = snap["histograms"]["unit.snap_op"]
    assert hist["count"] == 1
    for q in ("p50", "p95", "p99"):
        assert hist[q] >= 0.0
    # compact form for bench embeds
    compact = metrics_snapshot(buckets=False)
    assert "bucket_counts" not in compact["histograms"]["unit.snap_op"]


def test_exporter_writes_snapshots_and_trace(tmp_path):
    gauge("unit.exp").set(1)
    with span("unit.exporter_span"):
        pass
    start_exporter(str(tmp_path), interval=0.05)
    time.sleep(0.25)
    stop_exporter()
    snaps = sorted(tmp_path.glob("metrics-*.json"))
    assert len(snaps) >= 2          # periodic + final
    seqs = []
    for path in snaps:
        snap = json.loads(path.read_text())
        validate_snapshot(snap)
        seqs.append(snap["seq"])
    assert seqs == sorted(seqs)
    traces = list(tmp_path.glob("trace-*.json"))
    assert len(traces) == 1
    validate_chrome_trace(json.loads(traces[0].read_text()))


def test_sync_coordinator_emits_staleness_and_gate_wait():
    from multiverso_tpu.core.sync_coordinator import SyncCoordinator

    sc = SyncCoordinator(2)
    sc.acquire_add(0)
    sc.commit_add(0)
    # worker 0 is one committed add ahead: the STRAGGLER (worker 1) reads
    # positive, the leader reads 0 (ps_service.staleness polarity).
    g0 = get_registry().gauge("sync.staleness.add.worker_0")
    g1 = get_registry().gauge("sync.staleness.add.worker_1")
    assert g0.last == 0.0 and g0.samples >= 1
    assert g1.last == 1.0
    assert get_registry().histogram("sync.gate_wait.add").count >= 1
    # the get clock has its OWN gauge family: worker 1 (the add straggler)
    # may still get — and its get-commit must not overwrite (mask) the
    # add-side straggler signal
    sc.acquire_get(1)
    sc.commit_get(1)
    assert get_registry().gauge("sync.staleness.add.worker_1").last == 1.0
    assert get_registry().gauge("sync.staleness.get.worker_1").last == 0.0
    assert get_registry().gauge("sync.staleness.get.worker_0").last == 1.0
    # a retired worker must not poison the gauges with INF
    sc.finish_train(1)
    sc.acquire_add(0)
    sc.commit_add(0)
    snap = metrics_snapshot()
    assert snap["gauges"]["sync.staleness.add.worker_0"]["last"] == 0.0
    assert snap["gauges"]["sync.staleness.add.worker_1"]["last"] == 1.0


# -- end-to-end: CPU word2vec run with -telemetry_dir -----------------------
def _write_corpus(path, n=120, seed=0):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for i in range(n):
            topic = "a" if i % 2 == 0 else "b"
            words = [f"{topic}{rng.integers(0, 5)}" for _ in range(15)]
            f.write(" ".join(words) + "\n")


def test_word2vec_cli_telemetry_e2e(tmp_path):
    """ISSUE 3 acceptance: a 2-rank CPU word2vec run with -telemetry_dir
    emits (a) Chrome traces that pass the schema validator + merge and
    (b) snapshots with PS_SERVICE_ADD/GET p50/p95/p99, async-engine
    queue-depth gauge samples, and per-worker staleness gauges."""
    import subprocess
    import sys

    corpus = tmp_path / "corpus.txt"
    tdir = tmp_path / "telemetry"
    _write_corpus(str(corpus))
    rc = subprocess.run(
        [sys.executable, "-m", "multiverso_tpu.apps.word2vec_main",
         f"-train_file={corpus}", f"-output_file={tmp_path / 'vec.txt'}",
         "-world_size=2", "-size=16", "-window=3", "-negative=3",
         "-min_count=1", "-epoch=1", "-batch_size=256", "-sample=0",
         f"-rendezvous_dir={tmp_path}",
         f"-telemetry_dir={tdir}", "-telemetry_interval=0.5"],
        timeout=420).returncode
    assert rc == 0

    # (a) one trace per rank, schema-valid, mergeable, with real spans
    traces = sorted(tdir.glob("trace-*.json"))
    assert len(traces) == 2, list(tdir.iterdir())
    for path in traces:
        validate_chrome_trace(json.loads(path.read_text()))
    merged = merge_traces([str(p) for p in traces])
    validate_chrome_trace(merged)
    xs = [e for e in merged["traceEvents"] if e["ph"] == "X"]
    assert len(xs) >= 2
    assert {e["pid"] for e in xs} == \
        {e["pid"] for e in merged["traceEvents"] if e["ph"] == "M"}
    assert any(e["name"] == "w2v.dist_block" for e in xs)

    # (b) snapshots: merge the final snapshot of each rank
    snaps = sorted(tdir.glob("metrics-*.json"))
    assert snaps, list(tdir.iterdir())
    hists, gauges_all = {}, {}
    for path in snaps:
        snap = json.loads(path.read_text())
        validate_snapshot(snap)
        hists.update({k: v for k, v in snap["histograms"].items()
                      if v["count"]})
        gauges_all.update({k: v for k, v in snap["gauges"].items()
                           if v["samples"]})
    for name in ("PS_SERVICE_ADD", "PS_SERVICE_GET"):
        assert name in hists, sorted(hists)
        for q in ("p50", "p95", "p99"):
            assert hists[name][q] >= 0.0
        assert hists[name]["count"] >= 1
    q_depth = [n for n in gauges_all
               if n.startswith("async_engine.queue_depth")]
    assert q_depth, sorted(gauges_all)
    staleness = [n for n in gauges_all
                 if re.match(r".*staleness\.worker_\d+$", n)]
    assert len(staleness) >= 2, sorted(gauges_all)
