"""Flash block-attention kernel vs the XLA formulation (interpret mode on
CPU — the same two-tier protocol as the scatter kernels: exact math here,
on-chip timing decides adoption)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from multiverso_tpu.ops.pallas_attention import flash_block_attn, supported
from multiverso_tpu.parallel.sequence import (_block_attn, ring_attention,
                                              ring_attention_block)


def _qkv(rng, B=2, H=3, Sq=256, Sk=384, D=64, dtype=np.float32):
    q = jnp.asarray(rng.normal(size=(B, H, Sq, D)).astype(dtype))
    k = jnp.asarray(rng.normal(size=(B, H, Sk, D)).astype(dtype))
    v = jnp.asarray(rng.normal(size=(B, H, Sk, D)).astype(dtype))
    return q, k, v


@pytest.mark.parametrize("with_bias", [False, True])
def test_flash_matches_xla_block_attn(with_bias):
    rng = np.random.default_rng(0)
    q, k, v = _qkv(rng)
    scale = 1.0 / np.sqrt(q.shape[-1])
    Sq, Sk = q.shape[2], k.shape[2]
    bias = None
    if with_bias:
        bias = jnp.where(jnp.arange(Sk)[None, :] >
                         jnp.arange(Sq)[:, None] + 100,
                         -1e30, 0.0).astype(jnp.float32)
    o1, m1, l1 = _block_attn(q, k, v, scale, bias)
    o2, m2, l2 = flash_block_attn(q, k, v, bias, scale=float(scale),
                                  interpret=True)
    # tile-order-dependent rounding only; normalized outputs agree tightly
    np.testing.assert_allclose(np.asarray(o2 / jnp.maximum(l2, 1e-20)),
                               np.asarray(o1 / jnp.maximum(l1, 1e-20)),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_array_equal(np.asarray(m2), np.asarray(m1))
    np.testing.assert_allclose(np.asarray(l2), np.asarray(l1), rtol=2e-5)


def test_flash_in_kernel_causal_offsets_match_materialized_mask():
    """causal=True + offsets must equal the XLA path with the equivalent
    materialized k_pos > q_pos mask — the ring-step contract, with the
    mask never leaving the kernel."""
    rng = np.random.default_rng(5)
    q, k, v = _qkv(rng, Sq=128, Sk=256)
    scale = 1.0 / np.sqrt(q.shape[-1])
    Sq, Sk = q.shape[2], k.shape[2]
    for q_off, k_off in ((0, 0), (384, 128), (128, 384)):
        mask = jnp.where((k_off + jnp.arange(Sk))[None, :] >
                         (q_off + jnp.arange(Sq))[:, None],
                         -1e30, 0.0)
        o1, m1, l1 = _block_attn(q, k, v, scale, mask)
        o2, m2, l2 = flash_block_attn(
            q, k, v, scale=float(scale), causal=True,
            offsets=jnp.asarray([q_off, k_off], jnp.int32),
            interpret=True)
        np.testing.assert_array_equal(np.asarray(m2), np.asarray(m1))
        np.testing.assert_allclose(
            np.asarray(o2 / jnp.maximum(l2, 1e-20)),
            np.asarray(o1 / jnp.maximum(l1, 1e-20)),
            rtol=2e-5, atol=2e-6, err_msg=f"offsets {q_off},{k_off}")


def test_flash_fully_masked_rows_match_xla_convention():
    """A ring step whose K/V block is entirely future (causal) must mirror
    _block_attn's -1e30 convention exactly: finite o/m/l with m ~= -1e30,
    so the streaming merge's beta factor zeroes the block's contribution."""
    rng = np.random.default_rng(1)
    q, k, v = _qkv(rng, Sq=128, Sk=128)
    scale = 0.125
    bias = jnp.full((128, 128), -1e30, dtype=jnp.float32)
    o1, m1, l1 = _block_attn(q, k, v, scale, bias)
    o2, m2, l2 = flash_block_attn(q, k, v, bias, scale=scale,
                                  interpret=True)
    for a in (o2, m2, l2):
        assert np.isfinite(np.asarray(a)).all()
    np.testing.assert_array_equal(np.asarray(m2), np.asarray(m1))
    np.testing.assert_allclose(np.asarray(l2), np.asarray(l1), rtol=1e-6)
    # merge-zeroable: beta = exp(m - m_merged) underflows for any real m
    assert float(np.asarray(m2).max()) <= -1e29


def test_flash_bf16_inputs_compute_in_f32():
    """bf16 q/k/v (the TPU training dtype): kernel math runs f32 and must
    match the XLA path computed on the same bf16 inputs."""
    rng = np.random.default_rng(6)
    q, k, v = _qkv(rng, Sq=128, Sk=256, D=64)
    q = q.astype(jnp.bfloat16)
    k = k.astype(jnp.bfloat16)
    v = v.astype(jnp.bfloat16)
    scale = 0.125
    o1, m1, l1 = _block_attn(q.astype(jnp.float32),
                             k.astype(jnp.float32),
                             v.astype(jnp.float32), scale, None)
    o2, m2, l2 = flash_block_attn(q, k, v, scale=scale, interpret=True)
    np.testing.assert_allclose(np.asarray(o2 / jnp.maximum(l2, 1e-20)),
                               np.asarray(o1 / jnp.maximum(l1, 1e-20)),
                               rtol=2e-5, atol=2e-6)
    assert o2.dtype == jnp.float32    # stats/output stay full precision


def test_supported_gate():
    rng = np.random.default_rng(2)
    q, k, _ = _qkv(rng)
    assert supported(q, k)
    q_bad, k_bad, _ = _qkv(rng, Sq=100, Sk=128)
    assert not supported(q_bad, k_bad)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_flash_flag_matches_xla_path(mv_env, causal):
    """Ulysses with -flash_attention=true equals its dense-softmax path."""
    import multiverso_tpu as mv
    from jax.sharding import Mesh

    from multiverso_tpu.parallel.sequence import ulysses_attention

    rng = np.random.default_rng(4)
    B, H, S, D = 1, 8, 512, 32
    q = jnp.asarray(rng.normal(size=(B, H, S, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, H, S, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, H, S, D)).astype(np.float32))
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("seq",))

    ref = ulysses_attention(q, k, v, mesh, causal=causal)
    mv.set_flag("flash_attention", True)
    try:
        got = ulysses_attention(q, k, v, mesh, causal=causal)
    finally:
        mv.set_flag("flash_attention", False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_flash_flag_matches_xla_path(mv_env, causal):
    """End to end on the 8-device mesh: ring attention with
    -flash_attention=true equals the XLA path (both exact softmax)."""
    import multiverso_tpu as mv
    from jax.sharding import Mesh

    rng = np.random.default_rng(3)
    B, H, S, D = 1, 2, 1024, 32
    q = jnp.asarray(rng.normal(size=(B, H, S, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, H, S, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, H, S, D)).astype(np.float32))
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("seq",))

    ref = ring_attention(q, k, v, mesh, causal=causal)
    mv.set_flag("flash_attention", True)
    try:
        got = ring_attention(q, k, v, mesh, causal=causal)
    finally:
        mv.set_flag("flash_attention", False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_paged_decode_attn_matches_gather_formulation():
    """The paged decode kernel (scalar-prefetched page table, online
    softmax across pages) equals the serving step's gather-then-attend
    formulation — including the slot/position mask and the masked
    alignment tail past bucket+max_new."""
    from multiverso_tpu.ops.pallas_attention import paged_decode_attn

    rng = np.random.default_rng(0)
    B, H, dh, P, G = 3, 4, 8, 4, 4
    bucket = 8
    n_phys = 16
    q = jnp.asarray(rng.normal(size=(B, H, dh)).astype(np.float32))
    kp = jnp.asarray(rng.normal(size=(n_phys, H, P, dh))
                     .astype(np.float32))
    vp = jnp.asarray(rng.normal(size=(n_phys, H, P, dh))
                     .astype(np.float32))
    ptab = jnp.asarray(rng.integers(0, n_phys, (B, G)).astype(np.int32))
    lengths = jnp.asarray([3, 1, 7], jnp.int32)
    t = jnp.asarray([0, 2, 5], jnp.int32)
    scale = 1.0 / np.sqrt(dh)

    kf = jnp.take(kp, ptab, axis=0).transpose(0, 2, 1, 3, 4) \
        .reshape(B, H, G * P, dh)
    vf = jnp.take(vp, ptab, axis=0).transpose(0, 2, 1, 3, 4) \
        .reshape(B, H, G * P, dh)
    key_slot = jnp.arange(G * P)[None, :]
    mask = (key_slot < lengths[:, None]) | \
        ((key_slot >= bucket) & (key_slot <= (bucket + t)[:, None]))
    s = jnp.einsum("bhd,bhkd->bhk", q, kf) * scale
    probs = jax.nn.softmax(jnp.where(mask[:, None], s, -jnp.inf),
                           axis=-1)
    want = np.asarray(jnp.einsum("bhk,bhkd->bhd", probs, vf))

    got = np.asarray(paged_decode_attn(
        q, kp, vp, ptab, lengths, t, bucket=bucket, page=P,
        scale=float(scale), interpret=True))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)
