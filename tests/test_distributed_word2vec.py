"""Distributed word2vec over the PS service: two ranks in one process
(loopback wire path), interleaved worker threads, topic-separation signal —
plus the app-level fault drills (real processes, SIGKILL mid-epoch)."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.models.word2vec import Dictionary, Word2VecConfig
from multiverso_tpu.models.word2vec.distributed import DistributedWord2Vec
from multiverso_tpu.parallel.ps_service import PSService


def _corpus(n_sentences=400, seed=0):
    rng = np.random.default_rng(seed)
    sentences = []
    for i in range(n_sentences):
        topic = "a" if i % 2 == 0 else "b"
        sentences.append([f"{topic}{rng.integers(0, 5)}" for _ in range(12)])
    return sentences


def test_two_rank_distributed_training(mv_env):
    sents = _corpus()
    d = Dictionary.build(sents, min_count=1)
    ids = [d.encode(s) for s in sents]
    # SGD path: with a 10-word toy vocab each word recurs ~30x per batch,
    # so the summed per-batch gradient needs a small lr (adagrad
    # self-normalizes this away; see the adagrad test below).
    cfg = Word2VecConfig(embedding_size=32, batch_size=256, window=4,
                         negative=5, min_count=1, sample=0, sg=True,
                         epochs=4, learning_rate=0.005, block_words=2000,
                         pipeline=False, seed=3, optimizer="sgd")

    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    try:
        w0 = DistributedWord2Vec(cfg, d, svc0, peers, rank=0)
        w1 = DistributedWord2Vec(cfg, d, svc1, peers, rank=1)

        # Each worker trains on half the corpus, concurrently (ASGD).
        threads = [
            threading.Thread(target=w0.train, args=(ids[0::2],)),
            threading.Thread(target=w1.train, args=(ids[1::2],)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive(), "distributed training hung"

        emb = w0.embeddings()
        assert emb.shape == (len(d), 32)
        emb = emb / (np.linalg.norm(emb, axis=1, keepdims=True) + 1e-12)
        a_ids = [d.word2id[w] for w in d.words if w.startswith("a")]
        b_ids = [d.word2id[w] for w in d.words if w.startswith("b")]
        intra = np.mean([emb[i] @ emb[j]
                         for i in a_ids for j in a_ids if i != j])
        inter = np.mean([emb[i] @ emb[j] for i in a_ids for j in b_ids])
        assert intra > inter + 0.1, f"intra={intra:.3f} inter={inter:.3f}"
        # Both ranks see the same global table.
        np.testing.assert_allclose(w1.embeddings(), w0.embeddings(),
                                   rtol=1e-5, atol=1e-6)
    finally:
        svc0.close()
        svc1.close()


def test_two_rank_distributed_adagrad(mv_env):
    """AdaGrad mode: accumulators live in their own PS tables (the
    reference's two adagrad matrices) and workers push unscaled squared
    gradients."""
    sents = _corpus(300)
    d = Dictionary.build(sents, min_count=1)
    ids = [d.encode(s) for s in sents]
    cfg = Word2VecConfig(embedding_size=32, batch_size=256, window=4,
                         negative=5, min_count=1, sample=0, sg=True,
                         epochs=3, learning_rate=0.1, block_words=2000,
                         pipeline=False, seed=3, optimizer="adagrad")
    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    try:
        w0 = DistributedWord2Vec(cfg, d, svc0, peers, rank=0)
        w1 = DistributedWord2Vec(cfg, d, svc1, peers, rank=1)
        threads = [
            threading.Thread(target=w0.train, args=(ids[0::2],)),
            threading.Thread(target=w1.train, args=(ids[1::2],)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive()
        emb = w0.embeddings()
        emb = emb / (np.linalg.norm(emb, axis=1, keepdims=True) + 1e-12)
        a_ids = [d.word2id[w] for w in d.words if w.startswith("a")]
        b_ids = [d.word2id[w] for w in d.words if w.startswith("b")]
        intra = np.mean([emb[i] @ emb[j]
                         for i in a_ids for j in a_ids if i != j])
        inter = np.mean([emb[i] @ emb[j] for i in a_ids for j in b_ids])
        assert intra > inter + 0.1, f"intra={intra:.3f} inter={inter:.3f}"
        # accumulators actually accumulated on the PS
        g = w0.g_in.get_rows(np.arange(len(d), dtype=np.int32))
        assert g.sum() > 0
    finally:
        svc0.close()
        svc1.close()


@pytest.mark.parametrize("sg,hs", [(True, True), (False, False),
                                   (False, True)])
def test_distributed_variants_smoke(mv_env, sg, hs):
    """HS and CBOW distributed modes train without NaNs and update both
    tables (sg+ns is covered by the convergence tests above)."""
    sents = _corpus(80)
    d = Dictionary.build(sents, min_count=1)
    ids = [d.encode(s) for s in sents]
    cfg = Word2VecConfig(embedding_size=16, batch_size=128, window=3,
                         negative=3, min_count=1, sample=0, sg=sg, hs=hs,
                         epochs=1, learning_rate=0.05, block_words=500,
                         pipeline=False, seed=1, optimizer="adagrad")
    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    try:
        w0 = DistributedWord2Vec(cfg, d, svc0, peers, rank=0)
        w1 = DistributedWord2Vec(cfg, d, svc1, peers, rank=1)
        w0.train(ids[0::2])
        w1.train(ids[1::2])
        emb = w0.embeddings()
        assert np.isfinite(emb).all()
        out_rows = (len(d) - 1) if hs else len(d)
        out = w0.w_out.get_rows(np.arange(out_rows, dtype=np.int32))
        assert np.abs(out).sum() > 0      # output table actually trained
        np.testing.assert_allclose(w1.embeddings(), emb, rtol=1e-5,
                                   atol=1e-6)
    finally:
        svc0.close()
        svc1.close()


def test_global_lr_schedule_matches_single_rank(mv_env):
    """VERDICT r2 #4: SGD lr decays on the GLOBAL word count pulled from
    the word-count table (distributed_wordembedding.cpp:92-134). Two ranks
    each training half the corpus must drive the schedule to its END — the
    rank-local bug left lr at (1 - 1/N) of the schedule."""
    sents = _corpus(200)
    d = Dictionary.build(sents, min_count=1)
    ids = [d.encode(s) for s in sents]
    cfg = Word2VecConfig(embedding_size=8, batch_size=128, window=3,
                         negative=3, min_count=1, sample=0, sg=True,
                         epochs=1, learning_rate=0.05, block_words=300,
                         pipeline=False, seed=1, optimizer="sgd")

    # single-rank run over the FULL corpus: the trajectory to match
    svc = PSService()
    w_single = DistributedWord2Vec(cfg, d, svc, [svc.address], rank=0)
    w_single.train(ids)
    lr_single_final = w_single._current_lr()
    svc.close()

    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    try:
        w0 = DistributedWord2Vec(cfg, d, svc0, peers, rank=0)
        w1 = DistributedWord2Vec(cfg, d, svc1, peers, rank=1)
        threads = [threading.Thread(target=w0.train, args=(ids[0::2],)),
                   threading.Thread(target=w1.train, args=(ids[1::2],))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive()
        # both ranks pulled the drained global count
        w0._sync_word_count(); w1._sync_word_count()
        total = sum(len(s) for s in ids)
        assert w0.global_trained_words == pytest.approx(total)
        assert w1.global_trained_words == pytest.approx(total)
        # each rank's final lr matches the single-rank schedule end,
        # NOT the (1 - 1/2) point the rank-local count produced
        lr_half = cfg.learning_rate * 0.5
        for w in (w0, w1):
            assert w._current_lr() == pytest.approx(lr_single_final,
                                                    rel=0.05)
            assert w._current_lr() < lr_half * 0.5
    finally:
        svc0.close()
        svc1.close()


def test_two_rank_sparse_tables_train_and_save_wire(mv_env):
    """sparse_tables=True: pulls become incremental (keyed
    UpdateGetState) — training still separates topics, both ranks agree,
    and the wire ships fewer rows than the request volume (frequent words
    serve from the worker cache when unwritten since the last pull)."""
    sents = _corpus()
    d = Dictionary.build(sents, min_count=1)
    ids = [d.encode(s) for s in sents]
    cfg = Word2VecConfig(embedding_size=32, batch_size=256, window=4,
                         negative=5, min_count=1, sample=0, sg=True,
                         epochs=4, learning_rate=0.005, block_words=500,
                         pipeline=False, seed=3, optimizer="sgd")

    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    try:
        w0 = DistributedWord2Vec(cfg, d, svc0, peers, rank=0,
                                 sparse_tables=True)
        w1 = DistributedWord2Vec(cfg, d, svc1, peers, rank=1,
                                 sparse_tables=True)
        requested = [0]
        shipped = [0]
        orig = w0.w_in.get_rows

        def spy(rows, option=None):
            out = orig(rows, option)
            if option is not None:
                requested[0] += len(np.unique(np.asarray(rows)))
                shipped[0] += w0.w_in.last_incremental_rows
            return out

        w0.w_in.get_rows = spy
        threads = [
            threading.Thread(target=w0.train, args=(ids[0::2],)),
            threading.Thread(target=w1.train, args=(ids[1::2],)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive(), "sparse distributed training hung"

        assert requested[0] > 0
        # Incremental pulls must beat re-shipping every requested row.
        assert shipped[0] < requested[0], (shipped, requested)

        emb = w0.embeddings()
        emb = emb / (np.linalg.norm(emb, axis=1, keepdims=True) + 1e-12)
        a_ids = [d.word2id[w] for w in d.words if w.startswith("a")]
        b_ids = [d.word2id[w] for w in d.words if w.startswith("b")]
        intra = np.mean([emb[i] @ emb[j]
                         for i in a_ids for j in a_ids if i != j])
        inter = np.mean([emb[i] @ emb[j] for i in a_ids for j in b_ids])
        assert intra > inter + 0.1, f"intra={intra:.3f} inter={inter:.3f}"
        np.testing.assert_allclose(w1.embeddings(), w0.embeddings(),
                                   rtol=1e-5, atol=1e-6)
    finally:
        svc0.close()
        svc1.close()


# ---------------------------------------------------------------------------
# App-level fault drills (VERDICT r4 #4): kill a worker PROCESS mid-epoch.
# The reference's only straggler handling is Server_Finish_Train clock
# retirement (src/server.cpp:190-213); these drills prove the end-to-end
# story — re-admission in async mode, finish_train drain in BSP — at the
# application level, not just the table level (tests/test_ps_robustness.py).
# ---------------------------------------------------------------------------

_RANK_SCRIPT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "_w2v_fault_rank.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(_RANK_SCRIPT)))


def _drill_corpus(path, n_sentences=360, seed=0):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for i in range(n_sentences):
            topic = "a" if i % 2 == 0 else "b"
            f.write(" ".join(f"{topic}{rng.integers(0, 5)}"
                             for _ in range(12)) + "\n")


def _spawn(args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.Popen(
        [sys.executable, _RANK_SCRIPT, json.dumps(args)],
        cwd=_REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)


_HOST_SCALE = None


def _host_speed_scale():
    """Deadline multiplier measured from THIS host's current speed.

    The fault drills run three real Python processes (jax import + CPU
    training each); their fixed 600/1200 s deadlines were tuned on an
    unloaded box and are load-sensitive on shared CI hosts — a loaded or
    slow machine turns a passing drill into a hang-flake (VERDICT weak
    #6). A quick numpy probe (median of 5 after one warm-up, clamped to
    [1, 8]x) measures how much slower this host is than the ~0.02 s
    reference and scales every drill deadline by it, so the drills keep
    one fixed *logical* budget while the wall budget tracks load."""
    global _HOST_SCALE
    if _HOST_SCALE is None:
        def probe():
            t0 = time.perf_counter()
            a = np.random.default_rng(0).normal(size=(256, 256))
            for _ in range(8):
                a = a @ a.T / 256.0
            return time.perf_counter() - t0
        probe()                      # warm-up (allocator, BLAS threads)
        t = float(np.median([probe() for _ in range(5)]))
        _HOST_SCALE = float(np.clip(t / 0.02, 1.0, 8.0))
    return _HOST_SCALE


def _wait_progress(rdv, rank, min_blocks, timeout, procs):
    """Block until rank's progress mark reaches min_blocks; fail fast if
    any drill process already died. ``timeout`` is the unloaded-host
    budget; the wall deadline scales with the measured host speed."""
    path = os.path.join(rdv, f"progress{rank}")
    deadline = time.time() + timeout * _host_speed_scale()
    while time.time() < deadline:
        for p in procs:
            if p.poll() not in (None, 0):
                out = p.communicate()[0]
                raise AssertionError(f"drill rank died early rc={p.returncode}:"
                                     f"\n{out[-3000:]}")
        if os.path.exists(path):
            try:
                blocks = int(open(path).read().split()[0])
            except (ValueError, IndexError):
                blocks = 0
            if blocks >= min_blocks:
                return
        time.sleep(0.1)
    raise AssertionError(f"rank {rank} never reached {min_blocks} blocks")


def _drain(procs, timeout=1200):
    """Collect drill outputs; the drain budget scales with measured host
    speed (see _host_speed_scale) instead of hanging a fixed 1200 s wall
    on loaded shared hosts."""
    outs = []
    deadline = time.time() + timeout * _host_speed_scale()
    for p in procs:
        try:
            out, _ = p.communicate(timeout=max(deadline - time.time(), 1))
        except subprocess.TimeoutExpired:
            p.kill()
            out = p.communicate()[0]
            raise AssertionError(f"drill rank hung:\n{(out or '')[-3000:]}")
        outs.append(out or "")
    return outs


@pytest.mark.slow
def test_fault_drill_async_worker_killed_and_readmitted(tmp_path):
    """ASGD: SIGKILL rank 2 (worker + its table shard) mid-epoch, restart
    it; survivors retry through the replicated directory and re-admit the
    new seat; ALL ranks finish and the saved model is sane."""
    corpus = str(tmp_path / "corpus.txt")
    _drill_corpus(corpus)
    rdv = str(tmp_path / "rdv")
    os.makedirs(rdv)
    cfg = dict(embedding_size=16, batch_size=128, window=3, negative=3,
               min_count=1, sample=0, sg=True, epochs=4, learning_rate=0.1,
               block_words=400, pipeline=False, seed=3, optimizer="adagrad")
    base = dict(repo=_REPO, corpus=corpus, rdv=rdv, world=3, cfg=cfg,
                mode="train", sync=False, retry_window=600.0)

    procs = [_spawn({**base, "rank": r}) for r in range(3)]
    victim = procs[2]
    try:
        # mid-epoch: the victim has trained >= 2 blocks but nobody is done
        _wait_progress(rdv, 2, 2, timeout=600, procs=procs)
        assert not os.path.exists(os.path.join(rdv, "done0"))
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=30)
        # restart the SAME rank at a new address (fresh, zeroed shard)
        procs[2] = _spawn({**base, "rank": 2})
        outs = _drain(procs)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"rc={p.returncode}\n{out[-3000:]}"
    for r in range(3):
        stats = json.load(open(os.path.join(rdv, f"stats{r}.json")))
        assert stats["words"] > 0
    emb = np.load(os.path.join(rdv, "embeddings.npy"))
    assert np.isfinite(emb).all()
    assert np.abs(emb).sum() > 0


@pytest.mark.slow
def test_fault_drill_bsp_finish_train_unblocks_survivors(tmp_path):
    """BSP (-sync=true): SIGKILL rank 2 mid-epoch. Survivors' clock-gated
    ops wedge on the dead worker by design; restarting the SEAT (service +
    shards, no training) and retiring the victim's clocks via
    Server_Finish_Train lets both survivors drain, finish, and save —
    the reference's straggler path proven end to end.

    Marked ``slow`` (kept out of tier-1) deliberately: the drill spawns
    four real processes whose BSP drain is wall-clock-bounded, and on a
    loaded shared host even a generous fixed deadline can either hang the
    fast suite for minutes or flake. The deadline itself scales with the
    measured host speed (``_host_speed_scale``), so the nightly/slow lane
    stays deterministic under load."""
    corpus = str(tmp_path / "corpus.txt")
    _drill_corpus(corpus)
    rdv = str(tmp_path / "rdv")
    os.makedirs(rdv)
    cfg = dict(embedding_size=16, batch_size=128, window=3, negative=3,
               min_count=1, sample=0, sg=True, epochs=3, learning_rate=0.05,
               block_words=400, pipeline=False, seed=3, optimizer="sgd")
    base = dict(repo=_REPO, corpus=corpus, rdv=rdv, world=3, cfg=cfg,
                sync=True, retry_window=600.0)

    procs = [_spawn({**base, "rank": r, "mode": "train",
                     "barrier_ranks": [0, 1]}) for r in range(3)]
    victim = procs[2]
    seat = None
    try:
        _wait_progress(rdv, 2, 1, timeout=600, procs=procs)
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=30)
        # seat restart: shards re-served at a new address + finish_train
        seat = _spawn({**base, "rank": 2, "mode": "seat_restart"})
        outs = _drain([procs[0], procs[1], seat])
    finally:
        # includes the original rank 2: a failure BEFORE the SIGKILL step
        # must not leave it serving for its whole serve_timeout
        for p in procs + ([seat] if seat else []):
            if p and p.poll() is None:
                p.kill()
    for p, out in zip([procs[0], procs[1], seat], outs):
        assert p.returncode == 0, f"rc={p.returncode}\n{out[-3000:]}"
    assert victim.returncode != 0          # really killed
    emb = np.load(os.path.join(rdv, "embeddings.npy"))
    assert np.isfinite(emb).all()
    assert np.abs(emb).sum() > 0
    for r in (0, 1):
        stats = json.load(open(os.path.join(rdv, f"stats{r}.json")))
        assert stats["words"] > 0


def test_two_rank_param_prefetch_pipeline(mv_env):
    """param_prefetch=True: block N+1's pulls are in flight while block N
    computes (the reference's is_pipeline double buffer). Views are one
    block stale by design — training must still separate topics and both
    ranks converge to the same table."""
    sents = _corpus(300)
    d = Dictionary.build(sents, min_count=1)
    ids = [d.encode(s) for s in sents]
    cfg = Word2VecConfig(embedding_size=32, batch_size=256, window=4,
                         negative=5, min_count=1, sample=0, sg=True,
                         epochs=3, learning_rate=0.1, block_words=500,
                         pipeline=False, seed=3, optimizer="adagrad",
                         param_prefetch=True)
    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    try:
        w0 = DistributedWord2Vec(cfg, d, svc0, peers, rank=0)
        w1 = DistributedWord2Vec(cfg, d, svc1, peers, rank=1)
        threads = [
            threading.Thread(target=w0.train, args=(ids[0::2],)),
            threading.Thread(target=w1.train, args=(ids[1::2],)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive(), "prefetch training hung"
        # small blocks -> the double buffer actually cycled many times
        assert w0.trained_words == sum(len(s) for s in ids[0::2]) * 3
        emb = w0.embeddings()
        emb = emb / (np.linalg.norm(emb, axis=1, keepdims=True) + 1e-12)
        a_ids = [d.word2id[w] for w in d.words if w.startswith("a")]
        b_ids = [d.word2id[w] for w in d.words if w.startswith("b")]
        intra = np.mean([emb[i] @ emb[j]
                         for i in a_ids for j in a_ids if i != j])
        inter = np.mean([emb[i] @ emb[j] for i in a_ids for j in b_ids])
        assert intra > inter + 0.1, f"intra={intra:.3f} inter={inter:.3f}"
        np.testing.assert_allclose(w1.embeddings(), w0.embeddings(),
                                   rtol=1e-5, atol=1e-6)
    finally:
        svc0.close()
        svc1.close()


def test_two_rank_distributed_bf16_wire(mv_env):
    """-wire_compression=bf16: every pull/push crosses the wire as bf16
    halves (half the DCN bytes of f32) and training still separates
    topics — the distributed leg of the bf16 data-path story."""
    from multiverso_tpu.utils.configure import set_flag

    sents = _corpus(300)
    d = Dictionary.build(sents, min_count=1)
    ids = [d.encode(s) for s in sents]
    cfg = Word2VecConfig(embedding_size=32, batch_size=256, window=4,
                         negative=5, min_count=1, sample=0, sg=True,
                         epochs=3, learning_rate=0.1, block_words=2000,
                         pipeline=False, seed=3, optimizer="adagrad")
    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    set_flag("wire_compression", "bf16")
    try:
        w0 = DistributedWord2Vec(cfg, d, svc0, peers, rank=0)
        w1 = DistributedWord2Vec(cfg, d, svc1, peers, rank=1)
        threads = [
            threading.Thread(target=w0.train, args=(ids[0::2],)),
            threading.Thread(target=w1.train, args=(ids[1::2],)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive()
        emb = w0.embeddings()
        assert np.isfinite(emb).all()
        emb = emb / (np.linalg.norm(emb, axis=1, keepdims=True) + 1e-12)
        a_ids = [d.word2id[w] for w in d.words if w.startswith("a")]
        b_ids = [d.word2id[w] for w in d.words if w.startswith("b")]
        intra = np.mean([emb[i] @ emb[j]
                         for i in a_ids for j in a_ids if i != j])
        inter = np.mean([emb[i] @ emb[j] for i in a_ids for j in b_ids])
        assert intra > inter + 0.1, f"intra={intra:.3f} inter={inter:.3f}"
    finally:
        set_flag("wire_compression", "sparse")
        svc0.close()
        svc1.close()
