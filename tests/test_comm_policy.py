"""Per-table CommPolicy: AUTO decision table + policy parity (ISSUE 10).

Covers the resolver's decision table (sparse -> ps, HBM-scale -> ps,
explicit override wins, small dense -> the measured probe's pick), the
routed table telemetry, and the policy-parity contracts: logreg
``allreduce`` params BITWISE-identical to the PS path, ``model_average``
loss-trajectory parity, and word2vec hybrid/model_average table bytes
bitwise-identical to the fused plane (the policies change the
communication, never the math).
"""

import numpy as np
import pytest


# ---------------------------------------------------------------------------
# resolver units
# ---------------------------------------------------------------------------
def test_resolve_sparse_and_hbm_scale_pick_ps(mv_env):
    from multiverso_tpu.parallel import comm_policy as cp

    assert cp.resolve_comm_policy((50_000, 128), np.float32,
                                  sparse=True) == cp.PS
    assert cp.resolve_comm_policy((1_000_000, 128), np.float32,
                                  sparse=False, probe=False) == cp.PS


def test_resolve_explicit_override_wins(mv_env):
    from multiverso_tpu.parallel import comm_policy as cp
    from multiverso_tpu.utils.log import FatalError

    # Explicit wins even where the table would say otherwise.
    assert cp.resolve_comm_policy((50_000, 128), np.float32, sparse=True,
                                  explicit=cp.MODEL_AVERAGE) \
        == cp.MODEL_AVERAGE
    assert cp.resolve_comm_policy((8,), np.float32,
                                  explicit=cp.PS) == cp.PS
    with pytest.raises(FatalError):
        cp.resolve_comm_policy((8,), np.float32, explicit="bogus")


def test_resolve_small_dense_follows_probe_and_caches(mv_env):
    """The probe path: AUTO's small-dense pick must equal the argmin of
    its own measurement, and the measurement is one-shot (cached)."""
    from multiverso_tpu.core.zoo import Zoo
    from multiverso_tpu.parallel import comm_policy as cp

    mesh = Zoo.get().mesh
    lat = cp.measured_policy_latency_ms(256, mesh, world=1)
    want = cp.PS if lat[cp.PS] < lat[cp.ALLREDUCE] else cp.ALLREDUCE
    got = cp.resolve_comm_policy((64,), np.float32, sparse=False,
                                 mesh=mesh, world=1, table="probe_case")
    assert got == want
    # One-shot: the second call returns the cached measurement.
    assert cp.measured_policy_latency_ms(256, mesh, world=1) is lat


def test_decision_evidence_records_reasons(mv_env):
    from multiverso_tpu.parallel import comm_policy as cp

    cp.reset_decisions()
    cp.resolve_comm_policy((9, 9), np.float32, sparse=True, table="t_sp")
    ev = cp.decision_evidence()
    mine = [d for d in ev["decisions"] if d["table"] == "t_sp"]
    assert mine and mine[0]["policy"] == cp.PS
    assert "sparse" in mine[0]["reason"]


def test_record_ticks_per_plane_counters(mv_env):
    from multiverso_tpu.parallel import comm_policy as cp
    from multiverso_tpu.telemetry import get_registry

    cp.record(cp.ALLREDUCE, 1234, 0.5)
    snap = get_registry().snapshot(buckets=False)
    assert snap["counters"]["comm.allreduce.bytes"]["value"] >= 1234
    assert snap["counters"]["comm.allreduce.ops"]["value"] >= 1
    assert "comm.allreduce.latency_ms" in snap["histograms"]


def test_dense_sync_preserves_value_on_mesh(mv_env):
    """build_dense_sync over the 8-device test mesh: psum of a
    replicated operand normalized by the (power-of-two) axis size is
    value-preserving BITWISE — the hybrid step's merge is a barrier,
    not a perturbation."""
    from multiverso_tpu.core.zoo import Zoo
    from multiverso_tpu.parallel import comm_policy as cp

    sync = cp.build_dense_sync(Zoo.get().mesh)
    x = np.asarray([3.0, 0.125, 17.5, 1e-3], np.float32)
    out = np.asarray(sync(x))
    assert np.array_equal(out, x)


# ---------------------------------------------------------------------------
# routed tables
# ---------------------------------------------------------------------------
def test_table_policy_attribute_and_publish(mv_env):
    import multiverso_tpu as mv
    from multiverso_tpu.parallel import comm_policy as cp
    from multiverso_tpu.telemetry import get_registry

    t = mv.create_table(mv.MatrixTableOption(32, 4, name="cpol_default"))
    assert t.comm_policy == cp.PS       # None -> ps, no probe
    t2 = mv.create_table(mv.MatrixTableOption(
        32, 4, name="cpol_explicit", comm_policy="model_average"))
    assert t2.comm_policy == cp.MODEL_AVERAGE
    # Client row ops are the ps plane and count there; on a non-ps table
    # they also tick the fallback counter.
    t2.add_rows([0, 1], np.ones((2, 4), np.float32))
    got = t2.get_rows([0, 1])
    assert np.array_equal(got, np.ones((2, 4), np.float32))
    snap = get_registry().snapshot(buckets=False)
    assert snap["counters"]["comm.ps.bytes"]["value"] > 0
    assert snap["counters"]["comm.policy.ps_fallback"]["value"] >= 2
    # publish = whole-replica write, counted under the table's own plane.
    vals = np.full((32, 4), 7.0, np.float32)
    t2.publish(vals)
    assert np.array_equal(t2.get(), vals)
    snap = get_registry().snapshot(buckets=False)
    assert snap["counters"]["comm.model_average.bytes"]["value"] \
        >= vals.nbytes


# ---------------------------------------------------------------------------
# logreg policy parity
# ---------------------------------------------------------------------------
def _lr_data(F=24, B=16, N=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(N * B, F + 1)).astype(np.float32)
    X[:, -1] = 1.0
    w_true = rng.normal(size=(F + 1, 1)).astype(np.float32)
    y = (X @ w_true > 0).astype(np.float32).ravel()
    return [(X[i * B:(i + 1) * B], y[i * B:(i + 1) * B])
            for i in range(N)], F, B


def test_logreg_allreduce_bitwise_equals_ps(mv_env):
    """The headline parity contract: same batches, same epochs — the
    allreduce-policy model's params are BITWISE identical to PSModel's
    (the policy moves bytes differently; it must not move values)."""
    from multiverso_tpu.models.logreg.logreg import LogReg
    from multiverso_tpu.models.logreg.model import (AllreduceModel,
                                                    LogRegConfig, PSModel,
                                                    make_model)

    batches, F, B = _lr_data()
    cfg = LogRegConfig(objective="sigmoid", num_feature=F,
                       learning_rate=0.1, minibatch_size=B, epochs=3)
    m_ps = PSModel(cfg)
    LogReg(cfg, model=m_ps).train(batches)
    cfg_ar = LogRegConfig(objective="sigmoid", num_feature=F,
                          learning_rate=0.1, minibatch_size=B, epochs=3,
                          comm_policy="allreduce")
    m_ar = make_model(cfg_ar)
    assert isinstance(m_ar, AllreduceModel)
    LogReg(cfg_ar, model=m_ar).train(batches)
    assert np.array_equal(m_ps.get_weights(), m_ar.get_weights())
    # The table surface reconciles at sync: published replica == weights.
    assert np.array_equal(
        m_ar.table.get().reshape(m_ar.get_weights().shape),
        m_ar.get_weights())


def test_logreg_allreduce_dp_psum_matches_single(mv_env):
    """The in-graph psum path proper: the step shard_mapped over the
    8-device test mesh (per-shard grads psum-merged in-graph) tracks the
    single-contributor step to float tolerance."""
    from multiverso_tpu.core.zoo import Zoo
    from multiverso_tpu.models.logreg.model import (AllreduceModel,
                                                    LogRegConfig)

    batches, F, B = _lr_data(B=16)      # 16 % 8 == 0 shards evenly
    cfg = LogRegConfig(objective="sigmoid", num_feature=F,
                       learning_rate=0.1, minibatch_size=B,
                       comm_policy="allreduce")
    m_dp = AllreduceModel(cfg, dp_mesh=Zoo.get().mesh, dp_axis="server")
    m_s = AllreduceModel(cfg)
    for Xb, yb in batches:
        l_dp = float(m_dp.update(Xb, yb))
        l_s = float(m_s.update(Xb, yb))
        assert l_dp == pytest.approx(l_s, rel=1e-5)
    assert np.allclose(m_dp.get_weights(), m_s.get_weights(), atol=1e-6)


def test_logreg_model_average_loss_trajectory_parity(mv_env):
    """model_average changes merge cadence, not per-step math: in a
    one-process world its loss trajectory tracks the PS path to float
    tolerance (not bitwise — the fused local step rounds differently)."""
    from multiverso_tpu.models.logreg.logreg import LogReg
    from multiverso_tpu.models.logreg.model import (LogRegConfig,
                                                    ModelAverageModel,
                                                    PSModel, make_model)

    batches, F, B = _lr_data()
    cfg = LogRegConfig(objective="sigmoid", num_feature=F,
                       learning_rate=0.1, minibatch_size=B, epochs=3)
    losses_ps = LogReg(cfg, model=PSModel(cfg)).train(batches)
    cfg_ma = LogRegConfig(objective="sigmoid", num_feature=F,
                          learning_rate=0.1, minibatch_size=B, epochs=3,
                          comm_policy="model_average")
    m_ma = make_model(cfg_ma)
    assert isinstance(m_ma, ModelAverageModel)
    losses_ma = LogReg(cfg_ma, model=m_ma).train(batches)
    assert np.allclose(losses_ps, losses_ma, rtol=1e-4)


def test_logreg_ftrl_pins_ps(mv_env):
    from multiverso_tpu.models.logreg.model import (LogRegConfig,
                                                    resolve_logreg_comm_policy)
    from multiverso_tpu.utils.log import FatalError

    cfg = LogRegConfig(objective="ftrl", num_feature=4,
                       comm_policy="auto")
    assert resolve_logreg_comm_policy(cfg) == "ps"
    cfg_bad = LogRegConfig(objective="ftrl", num_feature=4,
                           comm_policy="allreduce")
    with pytest.raises(FatalError):
        resolve_logreg_comm_policy(cfg_bad)


# ---------------------------------------------------------------------------
# word2vec policy parity
# ---------------------------------------------------------------------------
def _w2v_corpus(V=120, n_sent=24, sent_len=24, seed=0):
    from multiverso_tpu.models.word2vec import Dictionary

    rng = np.random.default_rng(seed)
    d, zipf = Dictionary.synthetic_zipf(V, n_sent * sent_len)
    sents = [rng.choice(V, size=sent_len, p=zipf).astype(np.int32)
             for _ in range(n_sent)]
    return d, sents


def _w2v_cfg(**kw):
    from multiverso_tpu.models.word2vec import Word2VecConfig

    base = dict(embedding_size=8, window=3, negative=3, batch_size=64,
                sample=1e-3, sg=True, hs=False, optimizer="adagrad",
                epochs=1, pipeline=False, device_pipeline=True,
                block_sentences=8, pad_sentence_length=32, seed=0)
    base.update(kw)
    return Word2VecConfig(**base)


def test_w2v_hybrid_tables_bitwise_equal_fused(mv_env):
    """Hybrid = fused sparse plane + a value-preserving dense-plane
    merge: the trained embeddings must be BITWISE identical to the
    legacy fused run, with both planes' counters ticking."""
    from multiverso_tpu.models.word2vec import Word2Vec
    from multiverso_tpu.telemetry import get_registry

    d, sents = _w2v_corpus()
    w_f = Word2Vec(_w2v_cfg(), d)
    assert w_f.comm_mode == "fused"
    w_f.train(sentences=sents)
    emb_f = w_f.embeddings().copy()

    w_h = Word2Vec(_w2v_cfg(comm_policy="auto"), d)
    assert w_h.comm_mode == "hybrid"
    assert w_h.comm_policies["w2v_input"] == "ps"
    assert w_h.input_table.comm_policy == "ps"
    stats = w_h.train(sentences=sents)
    assert np.array_equal(emb_f, w_h.embeddings())
    # The dense plane carries a real value: the device-side merged word
    # count equals the host count exactly (power-of-two test mesh).
    assert stats["synced_words"] == stats["words"]
    snap = get_registry().snapshot(buckets=False)
    assert snap["counters"]["comm.ps.bytes"]["value"] > 0
    assert snap["counters"]["comm.allreduce.bytes"]["value"] > 0


def test_w2v_hybrid_override_pins_wordcount_to_ps(mv_env):
    from multiverso_tpu.models.word2vec import Word2Vec

    d, _ = _w2v_corpus()
    w = Word2Vec(_w2v_cfg(comm_policy="auto",
                          comm_policy_overrides={"w2v_wordcount": "ps"}),
                 d)
    assert w.comm_policies["w2v_wordcount"] == "ps"
    assert w._dense_sync is None        # no collective leg configured


def test_w2v_allreduce_mode_rejected(mv_env):
    from multiverso_tpu.models.word2vec import Word2Vec
    from multiverso_tpu.utils.log import FatalError

    d, _ = _w2v_corpus()
    with pytest.raises(FatalError):
        Word2Vec(_w2v_cfg(comm_policy="allreduce"), d)


def test_w2v_ps_plane_trains_and_counts(mv_env):
    """comm_policy=ps: pull-train-push through the table clients — the
    model still learns (finite loss, words counted) and every parameter
    byte shows up on the ps plane."""
    from multiverso_tpu.models.word2vec import Word2Vec
    from multiverso_tpu.telemetry import get_registry

    d, sents = _w2v_corpus()
    w = Word2Vec(_w2v_cfg(comm_policy="ps", device_pipeline=False), d)
    assert w.comm_mode == "ps"
    stats = w.train(sentences=sents)
    assert stats["comm_mode"] == "ps"
    assert stats["words"] == sum(len(s) for s in sents)
    assert np.isfinite(stats["loss"]) and stats["pairs"] > 0
    emb = w.embeddings()
    assert np.isfinite(emb).all() and np.abs(emb).sum() > 0
    snap = get_registry().snapshot(buckets=False)
    # 4 tables x (pull + push) per block, plus wordcount adds.
    assert snap["counters"]["comm.ps.bytes"]["value"] > emb.nbytes
    assert "comm.ps.latency_ms" in snap["histograms"]


def test_w2v_model_average_bitwise_equal_fused_one_process(mv_env):
    """In one process the "ma" epoch merge is the identity (mean of one
    replica), so model_average must reproduce the fused tables exactly
    while still exercising (and counting) the collective plane."""
    from multiverso_tpu.models.word2vec import Word2Vec
    from multiverso_tpu.telemetry import get_registry

    d, sents = _w2v_corpus()
    w_f = Word2Vec(_w2v_cfg(), d)
    w_f.train(sentences=sents)
    emb_f = w_f.embeddings().copy()

    w_m = Word2Vec(_w2v_cfg(comm_policy="model_average"), d)
    assert w_m.comm_mode == "model_average"
    w_m.train(sentences=sents)
    assert np.array_equal(emb_f, w_m.embeddings())
    snap = get_registry().snapshot(buckets=False)
    assert snap["counters"]["comm.model_average.bytes"]["value"] > 0
