"""ReplicaSupervisor decision core: deterministic tick-driven tests.

The supervisor's loop is a thin poller; every decision lives in
``tick(stats, now)``, so these tests drive synthetic ``Fleet_Stats``
payloads and fake process handles through it and assert the action log —
replacement triggers, hysteresis, cooldown, floors/ceilings — with no
real processes, sockets, or sleeps.
"""

import threading
import time

import numpy as np
import pytest

from multiverso_tpu.fleet.supervisor import ReplicaSupervisor


class FakeHandle:
    def __init__(self):
        self.alive = True
        self.terminated = 0

    def poll(self):
        return None if self.alive else 1

    def terminate(self):
        self.terminated += 1
        self.alive = False


class FakeView:
    def __init__(self):
        self.drained = []

    def stats(self):        # the loop path is not used in these tests
        return None

    def drain(self, member_id, timeout_s=30.0):
        self.drained.append(member_id)
        return True


def stats_for(member_ids, replica_alerts=(), router_alerts=()):
    """Minimal Fleet_Stats-shaped payload."""
    return {
        "replicas": {mid: {"alerts": [{"name": a} for a in replica_alerts]}
                     for mid in member_ids},
        "router_alerts": [{"name": a} for a in router_alerts],
    }


def make_supervisor(spawned, view=None, **kw):
    def spawn(slot):
        h = FakeHandle()
        spawned.append((slot, h))
        return h

    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("cooldown_s", 10.0)
    kw.setdefault("scale_up_windows", 3)
    kw.setdefault("scale_quiet_s", 30.0)
    kw.setdefault("join_grace_s", 20.0)
    return ReplicaSupervisor(view or FakeView(), spawn, **kw)


def test_dead_process_respawned_once_with_backoff():
    spawned = []
    sup = make_supervisor(spawned)
    h0 = FakeHandle()
    sup.adopt(0, h0)
    sup.tick(stats_for(["replica-0"]), now=100.0)
    assert not spawned                      # healthy: nothing happens
    h0.alive = False
    sup.tick(stats_for([]), now=101.0)      # dead + out of the ring
    assert [s for s, _ in spawned] == [0]
    assert sup.events()[-1]["trigger"] == "process_exit"
    # The fresh spawn is pending-join: repeated ticks inside the grace
    # window must NOT respawn again.
    sup.tick(stats_for([]), now=101.5)
    sup.tick(stats_for([]), now=110.0)
    assert len(spawned) == 1
    # It joins: pending clears, slot healthy again.
    sup.tick(stats_for(["replica-0"]), now=111.0)
    assert sup.status()["respawns"] == 1


def test_crash_loop_backs_off_exponentially():
    spawned = []
    sup = make_supervisor(spawned)
    h = FakeHandle()
    h.alive = False
    sup.adopt(0, h)
    t, respawn_times = 100.0, []
    for _ in range(200):
        sup.tick(stats_for([]), now=t)
        if spawned and not spawned[-1][1].alive is False:
            pass
        if spawned:
            spawned[-1][1].alive = False    # every incarnation dies
        if len(spawned) > len(respawn_times):
            respawn_times.append(t)
        t += 0.5
    gaps = np.diff(respawn_times)
    assert len(respawn_times) >= 3
    # Gaps grow (exponential backoff), and are capped.
    assert gaps[1] >= gaps[0]
    assert max(gaps) <= sup.max_respawn_backoff_s + 0.5


def test_heartbeat_loss_alert_triggers_replacement_of_live_process():
    """A member missing from the ring while its process LOOKS alive (a
    wedged replica) is replaced once the router's heartbeat-loss alert
    confirms the death — and the zombie is reaped first."""
    spawned = []
    sup = make_supervisor(spawned)
    h0 = FakeHandle()
    sup.adopt(0, h0)
    # Missing but no alert: the supervisor defers to the detector.
    sup.tick(stats_for([]), now=100.0)
    assert not spawned
    sup.tick(stats_for([], router_alerts=["fleet.heartbeat_loss"]),
             now=101.5)
    assert [s for s, _ in spawned] == [0]
    assert h0.terminated == 1               # zombie reaped
    assert sup.events()[-1]["trigger"] == "heartbeat_loss"


def test_scale_up_needs_sustained_alert_and_respects_ceiling():
    spawned = []
    sup = make_supervisor(spawned, max_replicas=2)
    sup.adopt(0, FakeHandle())
    base = stats_for(["replica-0"])
    burn = stats_for(["replica-0"], replica_alerts=["serve.slo_burn"])
    # A 2-window spike that recovers never scales (hysteresis).
    sup.tick(burn, now=100.0)
    sup.tick(burn, now=101.0)
    sup.tick(base, now=102.0)
    sup.tick(burn, now=103.0)
    sup.tick(burn, now=104.0)
    assert not spawned
    # Third consecutive bad window scales up exactly one slot.
    sup.tick(burn, now=105.0)
    sup.tick(burn, now=106.0)
    sup.tick(burn, now=107.0)
    assert [s for s, _ in spawned] == [1]
    # Ceiling: sustained burn at max_replicas never spawns more.
    burn2 = stats_for(["replica-0", "replica-1"],
                      replica_alerts=["serve.queue_saturation"])
    for i in range(10):
        sup.tick(burn2, now=120.0 + i)
    assert len(spawned) == 1
    assert sup.status()["scale_ups"] == 1


def test_cooldown_bounds_action_rate():
    spawned = []
    sup = make_supervisor(spawned, max_replicas=8, cooldown_s=50.0)
    sup.adopt(0, FakeHandle())
    burn = ["serve.slo_burn"]

    def members():
        return ["replica-0"] + [f"replica-{s}" for s, _ in spawned]

    t = 100.0
    for _ in range(30):                     # 30s of continuous burn
        sup.tick(stats_for(members(), replica_alerts=burn), now=t)
        t += 1.0
    # One scale-up at the 3rd window; everything after sat in cooldown.
    assert len(spawned) == 1
    from multiverso_tpu.telemetry import get_registry
    assert get_registry().counter(
        "fleet.supervisor.skipped_cooldown").value > 0
    # Past the cooldown, the NEXT sustained streak may act again.
    for _ in range(30):
        sup.tick(stats_for(members(), replica_alerts=burn), now=t)
        t += 1.0
    assert len(spawned) == 2


def test_scale_down_after_quiet_only_scaled_up_slots(monkeypatch):
    spawned = []
    view = FakeView()
    sup = make_supervisor(spawned, view=view, min_replicas=1,
                          max_replicas=4, cooldown_s=5.0,
                          scale_quiet_s=20.0)
    sup.adopt(0, FakeHandle())              # baseline: never drained
    burn = stats_for(["replica-0"], replica_alerts=["serve.slo_burn"])
    for i in range(3):
        sup.tick(burn, now=100.0 + i)
    assert [s for s, _ in spawned] == [1]   # scaled up
    joined = stats_for(["replica-0", "replica-1"])
    # Quiet, but not long enough.
    sup.tick(joined, now=110.0)
    sup.tick(joined, now=120.0)
    assert sup.status()["scale_downs"] == 0
    # Long quiet: the SCALED-UP slot drains + stops; baseline survives.
    sup.tick(joined, now=131.0)
    deadline = time.monotonic() + 5
    while not view.drained and time.monotonic() < deadline:
        time.sleep(0.01)
    assert view.drained == ["replica-1"]
    deadline = time.monotonic() + 5
    while not spawned[0][1].terminated and time.monotonic() < deadline:
        time.sleep(0.01)
    assert spawned[0][1].terminated == 1
    assert sup.status()["slots"] == [0]
    # Further quiet never goes below the baseline/min floor.
    for i in range(100):
        sup.tick(stats_for(["replica-0"]), now=140.0 + i)
    assert sup.status()["slots"] == [0]
    assert sup.status()["scale_downs"] == 1
    # A LATER scale-up must take a FRESH index, never reuse the drained
    # slot's (two live processes behind one member id otherwise —
    # review finding).
    burn2 = stats_for(["replica-0"], replica_alerts=["serve.slo_burn"])
    for i in range(3):
        sup.tick(burn2, now=300.0 + i)
    assert [s for s, _ in spawned[1:]] == [2]


def test_retiring_slot_stays_reachable_until_stopped():
    """A scale-down victim mid-drain must remain in slots() — the
    owner's teardown stops every handle it can see, and a handle that
    vanished at drain START would outlive the owner as an orphan
    (review finding)."""
    spawned = []

    class SlowView(FakeView):
        def __init__(self):
            super().__init__()
            self.release = threading.Event()

        def drain(self, member_id, timeout_s=30.0):
            self.drained.append(member_id)
            self.release.wait(10)
            return True

    view = SlowView()
    sup = make_supervisor(spawned, view=view, cooldown_s=1.0,
                          scale_quiet_s=5.0)
    sup.adopt(0, FakeHandle())
    burn = stats_for(["replica-0"], replica_alerts=["serve.slo_burn"])
    for i in range(3):
        sup.tick(burn, now=100.0 + i)
    victim = spawned[0][1]
    joined = stats_for(["replica-0", "replica-1"])
    sup.tick(joined, now=110.0)
    sup.tick(joined, now=116.0)        # quiet long enough: scale-down
    deadline = time.monotonic() + 5
    while not view.drained and time.monotonic() < deadline:
        time.sleep(0.01)
    # Mid-drain: the victim is out of the MANAGED set but still in
    # slots(), un-terminated.
    assert 1 in sup.slots() and not victim.terminated
    view.release.set()
    deadline = time.monotonic() + 5
    while 1 in sup.slots() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert victim.terminated == 1
    assert 1 not in sup.slots()


def test_unreachable_view_holds_position():
    spawned = []
    sup = make_supervisor(spawned)
    h = FakeHandle()
    h.alive = False
    sup.adopt(0, h)
    sup.tick(None, now=100.0)       # view returned None (router down)
    assert not spawned              # no stats -> no action


def test_loop_runs_and_stops():
    spawned = []

    class LiveView(FakeView):
        def __init__(self):
            super().__init__()
            self.calls = 0

        def stats(self):
            self.calls += 1
            return stats_for(["replica-0"])

    view = LiveView()
    sup = make_supervisor(spawned, view=view, poll_s=0.05)
    sup.adopt(0, FakeHandle())
    sup.start()
    deadline = time.monotonic() + 5
    while view.calls < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    sup.stop()
    assert view.calls >= 3
    assert not spawned
