"""Device-resident open-addressing directory (ops/device_hash).

Covers the advisor round-2 findings: slab overflow must trip as soon as
allocations exceed the caller-sized value slab (not only when the 2x
directory fills), duplicate-key batches, multi-round contention, and the
unsigned fmix32 avalanche for high-bit keys.
"""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.core.options import KVTableOption
from multiverso_tpu.ops import device_hash as dh
from multiverso_tpu.tables.device_kv_table import DeviceKVTable


def _split(keys):
    return dh.split_keys(np.asarray(keys, dtype=np.int64))


def test_lookup_miss_on_empty():
    st = dh.make_state(8)
    hi, lo = _split([1, 2, 1 << 40])
    slots = np.asarray(dh.lookup(st, hi, lo))
    assert (slots == -1).all()


def test_insert_then_lookup_roundtrip():
    st = dh.make_state(16)
    keys = [5, 17, -3, 1 << 35, 0]
    hi, lo = _split(keys)
    st, slots, overflow = dh.insert(st, hi, lo)
    assert not bool(overflow)
    slots = np.asarray(slots)
    # distinct keys -> distinct slots in [0, n)
    assert sorted(slots.tolist()) == list(range(len(keys)))
    found = np.asarray(dh.lookup(st, hi, lo))
    np.testing.assert_array_equal(found, slots)
    # unrelated keys still miss
    hi2, lo2 = _split([1234, 9999])
    assert (np.asarray(dh.lookup(st, hi2, lo2)) == -1).all()


def test_duplicate_keys_within_batch_converge():
    st = dh.make_state(8)
    keys = [42, 7, 42, 42, 7]
    hi, lo = _split(keys)
    st, slots, overflow = dh.insert(st, hi, lo)
    assert not bool(overflow)
    slots = np.asarray(slots)
    assert slots[0] == slots[2] == slots[3]
    assert slots[1] == slots[4]
    assert slots[0] != slots[1]
    assert int(st.next_slot) == 2          # only two distinct keys allocated


def test_multi_round_contention_dense_batch():
    """A batch filling the slab exactly: heavy bucket contention, several
    claim rounds, every key must still land on a unique slot."""
    cap = 64
    st = dh.make_state(cap)
    keys = np.arange(cap, dtype=np.int64) * 7919 + 1  # arbitrary spread
    hi, lo = _split(keys)
    st, slots, overflow = dh.insert(st, hi, lo)
    assert not bool(overflow)
    slots = np.asarray(slots)
    assert sorted(slots.tolist()) == list(range(cap))
    np.testing.assert_array_equal(np.asarray(dh.lookup(st, hi, lo)), slots)


def test_slab_overflow_detected_before_directory_full():
    """ADVICE r2 (medium): 12 distinct keys into make_state(8) previously
    returned slot ids up to 11 with overflow=False — out-of-bounds into an
    8-row value slab. Now overflow trips and no slot id >= capacity leaks."""
    st = dh.make_state(8)
    hi, lo = _split(np.arange(12, dtype=np.int64) + 100)
    st, slots, overflow = dh.insert(st, hi, lo)
    assert bool(overflow)
    slots = np.asarray(slots)
    assert slots.max() < 8
    assert int(st.next_slot) <= 8
    # directory never stores an out-of-slab slot id
    assert np.asarray(st.slot).max() < 8


def test_incremental_fill_then_overflow():
    st = dh.make_state(4)
    hi, lo = _split([1, 2])
    st, s1, ov = dh.insert(st, hi, lo)
    assert not bool(ov)
    hi, lo = _split([3, 4])
    st, s2, ov = dh.insert(st, hi, lo)
    assert not bool(ov)
    hi, lo = _split([5])
    st, s3, ov = dh.insert(st, hi, lo)
    assert bool(ov)
    # existing entries undisturbed
    hi, lo = _split([1, 2, 3, 4])
    np.testing.assert_array_equal(
        np.asarray(dh.lookup(st, hi, lo)),
        np.concatenate([np.asarray(s1), np.asarray(s2)]))


def test_reinsert_existing_allocates_nothing():
    st = dh.make_state(8)
    hi, lo = _split([11, 22])
    st, first, _ = dh.insert(st, hi, lo)
    st, again, overflow = dh.insert(st, hi, lo)
    assert not bool(overflow)
    np.testing.assert_array_equal(np.asarray(first), np.asarray(again))
    assert int(st.next_slot) == 2


def test_high_bit_keys_mix_unsigned():
    """Keys with the int32 high bit set probe fine (logical-shift mix)."""
    st = dh.make_state(32)
    keys = [-1, -2, -(1 << 40), (1 << 63) - 1, -(1 << 62)]
    hi, lo = _split(keys)
    st, slots, overflow = dh.insert(st, hi, lo)
    assert not bool(overflow)
    assert sorted(np.asarray(slots).tolist()) == list(range(len(keys)))
    np.testing.assert_array_equal(np.asarray(dh.lookup(st, hi, lo)),
                                  np.asarray(slots))


def test_insert_preassigned_reproduces_mapping():
    """Checkpoint-restore: saved (key, slot) pairs rebuild verbatim."""
    st = dh.make_state(16)
    keys = np.arange(10, dtype=np.int64) * 1_000_003
    hi, lo = _split(keys)
    st, slots, _ = dh.insert(st, hi, lo)
    slots = np.asarray(slots)
    # rebuild into a fresh directory in scrambled order
    perm = np.random.RandomState(0).permutation(10)
    st2 = dh.make_state(16)
    st2, overflow = dh.insert_preassigned(
        st2, hi[perm], lo[perm], slots[perm].astype(np.int32))
    assert not bool(overflow)
    np.testing.assert_array_equal(np.asarray(dh.lookup(st2, hi, lo)), slots)
    assert int(st2.next_slot) == 10


def test_insert_preassigned_overflow_on_bad_slot():
    st = dh.make_state(4)
    hi, lo = _split([1])
    st, overflow = dh.insert_preassigned(st, hi, lo,
                                         np.asarray([7], dtype=np.int32))
    assert bool(overflow)


def test_insert_preassigned_conflict_reported():
    """A key already present with a different slot id must not be silently
    kept — restore requires a fresh directory."""
    st = dh.make_state(8)
    hi, lo = _split([42])
    st, slots, _ = dh.insert(st, hi, lo)
    assert int(np.asarray(slots)[0]) == 0
    st2, overflow = dh.insert_preassigned(st, hi, lo,
                                          np.asarray([5], dtype=np.int32))
    assert bool(overflow)


def test_device_directory_requires_device_flag():
    with pytest.raises(ValueError):
        KVTableOption(device_directory=True, capacity=8)


# -- DeviceKVTable wiring ---------------------------------------------------

def _dir_table(**kw):
    return DeviceKVTable(KVTableOption(device=True, device_directory=True,
                                       **kw))


def test_kv_device_directory_semantics(mv_env):
    t = _dir_table(capacity=64)
    t.add([10, 99, 10**12], [1.0, 2.0, 3.0])
    np.testing.assert_allclose(t.get([10, 99, 10**12]), [1.0, 2.0, 3.0])
    t.add([99], [10.0])
    np.testing.assert_allclose(t.get([99]), [12.0])
    np.testing.assert_allclose(t.get([555]), [0.0])   # miss reads zero
    assert len(t) == 3                                 # gets don't allocate


def test_kv_device_directory_capacity_fatal(mv_env):
    from multiverso_tpu.utils.log import FatalError
    t = _dir_table(capacity=2)
    t.add([1, 2], [1.0, 1.0])
    with pytest.raises(FatalError):
        t.add([3], [1.0])


def test_kv_device_directory_checkpoint_roundtrip(mv_env):
    import os
    import tempfile

    from multiverso_tpu.core import checkpoint as ckpt

    t = _dir_table(capacity=32, name="dkvdir")
    t.add([100, 200, 300], [1.0, 2.0, 3.0])
    uri = f"file://{os.path.join(tempfile.mkdtemp(), 'dkvdir.npz')}"
    ckpt.save_table(t, uri)
    t.add([100, 400], [50.0, 7.0])
    ckpt.load_table(t, uri)
    np.testing.assert_allclose(t.get([100, 200, 300, 400]),
                               [1.0, 2.0, 3.0, 0.0])
    assert len(t) == 3


def test_factory_routes_device_directory(mv_env):
    t = mv.create_table(KVTableOption(device=True, device_directory=True,
                                      capacity=16, value_dim=4))
    assert isinstance(t, DeviceKVTable)
    assert t._device_dir
    t.add([3], np.ones((1, 4), dtype=np.float32))
    np.testing.assert_allclose(t.get([3]), np.ones((1, 4)))
