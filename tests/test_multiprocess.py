"""Real multi-process distributed tests: two jax processes on one host over
the coordination service (the reference tier-2 ladder: ``mpirun -np N`` on
one box, SURVEY.md §4).

Each subprocess runs ``mv.init`` with -coordinator/-world_size/-rank flags
(the RegisterNode analog), checks rank/size/barrier, and validates that
``mv.aggregate`` sums contributions across processes — the
``Test/test_allreduce.cpp:11-20`` invariant at world size 2.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import multiverso_tpu as mv

coordinator, rank = sys.argv[1], int(sys.argv[2])
mv.init([f"-coordinator={coordinator}", "-world_size=2", f"-rank={rank}"])
assert mv.rank() == rank, (mv.rank(), rank)
assert mv.size() == 2
mv.barrier()
out = mv.aggregate(np.full(8, float(rank + 1), dtype=np.float32))
# 1.0 + 2.0 from the two ranks
np.testing.assert_allclose(out, np.full(8, 3.0))
# 2-D model-average shape through the same psum path
mat = mv.aggregate(np.full((4, 3), float(rank + 1), dtype=np.float32))
np.testing.assert_allclose(mat, np.full((4, 3), 3.0))
mv.barrier()
mv.shutdown()
print(f"RANK{rank}_OK")
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_aggregate(tmp_path):
    port = _free_port()
    coordinator = f"127.0.0.1:{port}"
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)   # single CPU device per process
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(script), coordinator, str(r)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for r in range(2)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            p.kill()
            pytest.fail("multiprocess worker timed out")
        outs.append((p.returncode, out, err))
    for r, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"rank {r} failed:\n{err[-2000:]}"
        assert f"RANK{r}_OK" in out
