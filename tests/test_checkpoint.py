"""Checkpoint/resume + stream IO tests (ref Store/Load surface,
table_interface.h:61-75; streams io.h:24-132)."""

import os

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.core import checkpoint as ckpt
from multiverso_tpu.utils.stream import (StreamError, TextReader, exists,
                                         open_stream, register_scheme)


def test_stream_roundtrip(tmp_path):
    uri = f"file://{tmp_path}/sub/dir/data.bin"
    with open_stream(uri, "w") as s:
        s.write(b"hello multiverso")
    assert exists(uri)
    with open_stream(uri, "r") as s:
        assert s.read() == b"hello multiverso"


def test_plain_path_is_file_scheme(tmp_path):
    p = str(tmp_path / "x.bin")
    with open_stream(p, "w") as s:
        s.write(b"1")
    assert exists(p)


def test_unknown_and_gated_schemes(tmp_path):
    with pytest.raises(StreamError):
        open_stream("weird://x", "r")
    with pytest.raises(StreamError):
        open_stream("gs://bucket/obj", "r")


def test_register_scheme(tmp_path):
    calls = []

    def opener(path, mode):
        calls.append(path)
        return open(str(tmp_path / "custom.bin"), mode + "b")

    register_scheme("mem", opener)
    with open_stream("mem://anything", "w") as s:
        s.write(b"x")
    assert calls == ["anything"]


def test_text_reader(tmp_path):
    p = tmp_path / "lines.txt"
    p.write_text("alpha\nbeta\r\ngamma")
    with TextReader(f"file://{p}") as r:
        assert list(r) == ["alpha", "beta", "gamma"]
        assert r.get_line() is None


def test_array_table_store_load(tmp_path, mv_env):
    t = mv.create_table(mv.ArrayTableOption(size=100, updater="adagrad"))
    t.add(np.ones(100, dtype=np.float32), mv.AddOption(rho=0.1,
                                                       learning_rate=0.1))
    before = t.get()
    uri = f"file://{tmp_path}/array.npz"
    ckpt.save_table(t, uri)
    t.add(np.ones(100, dtype=np.float32), mv.AddOption(rho=0.1,
                                                       learning_rate=0.1))
    assert not np.allclose(t.get(), before)
    ckpt.load_table(t, uri)
    np.testing.assert_allclose(t.get(), before)
    # adagrad accumulator state restored too: next add matches a replay
    t.add(np.ones(100, dtype=np.float32), mv.AddOption(rho=0.1,
                                                       learning_rate=0.1))
    replay = t.get()
    ckpt.load_table(t, uri)
    t.add(np.ones(100, dtype=np.float32), mv.AddOption(rho=0.1,
                                                       learning_rate=0.1))
    np.testing.assert_allclose(t.get(), replay)


def test_save_all_load_all(tmp_path, mv_env):
    a = mv.create_table(mv.ArrayTableOption(size=10, name="weights"))
    m = mv.create_table(mv.MatrixTableOption(num_row=4, num_col=4,
                                             name="embed"))
    kv = mv.create_table(mv.KVTableOption(name="counts"))
    a.add(np.ones(10, dtype=np.float32))
    m.add(np.full((4, 4), 2.0, dtype=np.float32))
    kv.add([7], [3.0])
    path = ckpt.save_all(str(tmp_path), step=42)
    assert os.path.exists(os.path.join(path, "meta.json"))
    a.add(np.ones(10, dtype=np.float32))
    kv.add([7], [10.0])
    step = ckpt.load_all(path)
    assert step == 42
    np.testing.assert_allclose(a.get(), np.ones(10))
    np.testing.assert_allclose(m.get(), np.full((4, 4), 2.0))
    np.testing.assert_allclose(kv.get([7]), [3.0])


def test_checkpoint_manager_periodic_and_resume(tmp_path, mv_env):
    t = mv.create_table(mv.ArrayTableOption(size=4, name="w"))
    mgr = ckpt.CheckpointManager(str(tmp_path), save_every_steps=10,
                                 keep_last=2)
    for step in range(1, 41):
        t.add(np.ones(4, dtype=np.float32))
        mgr.maybe_save(step)
    # retention: only 2 newest kept
    kept = sorted(d for d in os.listdir(tmp_path) if d.startswith("ckpt_"))
    assert kept == ["ckpt_000000000030", "ckpt_000000000040"]
    # resume restores the newest
    t.add(np.full(4, 100.0, dtype=np.float32))
    step = mgr.restore_latest()
    assert step == 40
    np.testing.assert_allclose(t.get(), np.full(4, 40.0))


def test_restore_latest_empty_dir(tmp_path, mv_env):
    mgr = ckpt.CheckpointManager(str(tmp_path / "nope"))
    assert mgr.restore_latest() is None


def test_orbax_backend_roundtrip(tmp_path, mv_env):
    from multiverso_tpu.core import checkpoint_orbax as co

    a = mv.create_table(mv.ArrayTableOption(size=64, updater="adagrad",
                                            name="ow"))
    m = mv.create_table(mv.MatrixTableOption(num_row=16, num_col=4,
                                             name="om"))
    kv = mv.create_table(mv.KVTableOption(name="okv"))
    a.add(np.ones(64, dtype=np.float32), mv.AddOption(rho=0.1,
                                                      learning_rate=0.1))
    m.add(np.full((16, 4), 2.0, dtype=np.float32))
    kv.add([5], [1.5])
    before_a, before_m = a.get(), m.get()
    path = co.save_all(str(tmp_path), step=7)
    a.add(np.ones(64, dtype=np.float32), mv.AddOption(rho=0.1,
                                                      learning_rate=0.1))
    m.add(np.ones((16, 4), dtype=np.float32))
    kv.add([5], [10.0])
    co.load_all(path)
    np.testing.assert_allclose(a.get(), before_a)
    np.testing.assert_allclose(m.get(), before_m)
    np.testing.assert_allclose(kv.get([5]), [1.5])
    # shardings restored intact
    import jax
    assert len(a.store.data.sharding.device_set) == mv.num_servers()


def test_checkpoint_manager_orbax_async_backend(tmp_path, mv_env):
    """CheckpointManager(backend='orbax'): periodic triggers stage + return
    immediately (training continues while the write lands), at most one
    save in flight, retention prunes orbax trees, restore_latest recovers
    the last step's snapshot."""
    from multiverso_tpu.core.checkpoint import CheckpointManager

    m = mv.create_table(mv.MatrixTableOption(num_row=128, num_col=16,
                                             name="mgr_orbax"))
    mgr = CheckpointManager(str(tmp_path), save_every_steps=2, keep_last=2,
                            backend="orbax")
    snapshots = {}
    for step in range(1, 9):
        m.add(np.ones((128, 16), dtype=np.float32))
        if mgr.maybe_save(step):
            snapshots[step] = np.asarray(m.get())
    mgr.finalize()
    # retention: only the last keep_last orbax trees survive
    import re as _re
    kept = sorted(d for d in os.listdir(tmp_path)
                  if _re.fullmatch(r"orbax_\d{12}", d))
    assert len(kept) == 2, kept
    m.add(np.ones((128, 16), dtype=np.float32))      # post-save drift
    # An interrupted save (root exists, manifest.json never written —
    # the crash-before-join case) must be INVISIBLE to restore.
    os.makedirs(tmp_path / "orbax_000000000099" / "mgr_orbax")
    step = mgr.restore_latest()
    assert step == max(snapshots)
    np.testing.assert_allclose(m.get(), snapshots[step])


def test_orbax_crash_recovery_resave_and_retention(tmp_path, mv_env):
    """The two crash-path regressions: (1) resuming after an interrupted
    save must be able to RE-SAVE the same step (the leftover manifest-less
    root is cleared, orbax would otherwise refuse the existing
    destination); (2) retention must count only COMPLETE checkpoints
    toward keep_last — a newer manifest-less leftover must neither
    displace a manifested checkpoint nor be selected by restore."""
    from multiverso_tpu.core.checkpoint import CheckpointManager

    a = mv.create_table(mv.ArrayTableOption(size=32, name="crash_a"))
    mgr = CheckpointManager(str(tmp_path), save_every_steps=2, keep_last=1,
                            backend="orbax")
    a.add(np.ones(32, dtype=np.float32))
    assert mgr.maybe_save(2)
    mgr.finalize()
    snap = np.asarray(a.get())

    # crash-interrupted save at step 4: root exists, no manifest
    os.makedirs(tmp_path / "orbax_000000000004" / "crash_a")
    # prune (via a later join) must keep manifested step 2, not count 4
    mgr._prune()
    assert (tmp_path / "orbax_000000000002" / "manifest.json").exists()
    # restore ignores the leftover and recovers step 2
    a.add(np.ones(32, dtype=np.float32))
    assert mgr.restore_latest() == 2
    np.testing.assert_allclose(a.get(), snap)
    # ...and re-saving step 4 after resume succeeds (leftover cleared)
    mgr._last_saved_step = -1
    a.add(np.ones(32, dtype=np.float32))
    assert mgr.maybe_save(4)
    mgr.finalize()
    assert (tmp_path / "orbax_000000000004" / "manifest.json").exists()
    # older incomplete garbage is pruned once a newer complete one exists
    os.makedirs(tmp_path / "orbax_000000000003")
    mgr._prune()
    assert not (tmp_path / "orbax_000000000003").exists()


def test_orbax_manifested_staging_is_restorable(tmp_path, mv_env):
    """Crash between 'manifest written' and 'rename landed': the complete
    checkpoint sits under its staging name. Restore must select it (the
    manifest, not the name, is the durability marker), and prune must
    keep it until a committed root supersedes it."""
    import shutil

    from multiverso_tpu.core.checkpoint import CheckpointManager

    a = mv.create_table(mv.ArrayTableOption(size=16, name="stage_a"))
    mgr = CheckpointManager(str(tmp_path), save_every_steps=1, keep_last=2,
                            backend="orbax")
    a.add(np.ones(16, dtype=np.float32))
    mgr.maybe_save(1)
    mgr.finalize()
    a.add(np.ones(16, dtype=np.float32))          # state for "step 3"
    mgr._last_saved_step = -1
    mgr.maybe_save(3)
    mgr.finalize()
    # simulate the crash window: step-3 commit exists only as manifested
    # staging (rename never landed)
    shutil.move(str(tmp_path / "orbax_000000000003"),
                str(tmp_path / "orbax_000000000003.tmp-99999"))
    mgr._prune()                                  # must NOT delete it
    assert (tmp_path / "orbax_000000000003.tmp-99999").exists()
    a.add(np.ones(16, dtype=np.float32))          # drift
    assert mgr.restore_latest() == 3
    np.testing.assert_allclose(a.get(), 2.0)
    # a committed root at the same-or-newer step supersedes the staging
    mgr._last_saved_step = -1
    mgr.maybe_save(4)
    mgr.finalize()
    mgr._prune()
    assert not (tmp_path / "orbax_000000000003.tmp-99999").exists()


def test_orbax_async_save_overlaps_training(tmp_path, mv_env):
    """``save_all_async`` returns after device→host staging; training adds
    issued while the write is in flight must NOT leak into the checkpoint
    (snapshot consistency), and the handle joins the background writers."""
    from multiverso_tpu.core import checkpoint_orbax as co

    m = mv.create_table(mv.MatrixTableOption(num_row=512, num_col=64,
                                             name="async_m"))
    m.add(np.ones((512, 64), dtype=np.float32))
    snap = m.get()

    handle = co.save_all_async(str(tmp_path), step=3)
    # "training" continues while the storage write is (possibly) in flight
    for _ in range(3):
        m.add(np.ones((512, 64), dtype=np.float32))
    path = handle.wait_until_finished()
    assert path == handle.root
    np.testing.assert_allclose(m.get(), 4.0 * np.ones((512, 64)))

    co.load_all(path)
    np.testing.assert_allclose(m.get(), snap)   # pre-save snapshot, exactly
    # idempotent second wait
    assert handle.wait_until_finished() == path


def test_bf16_momentum_state_dtype_roundtrip(tmp_path, mv_env):
    """Regression: widened-to-f32 updater state must restore to the live
    leaf dtype (momentum 'smooth' is bf16 for bf16 tables)."""
    t = mv.create_table(mv.MatrixTableOption(
        num_row=8, num_col=4, dtype=np.dtype("bfloat16"),
        updater="momentum_sgd"))
    t.add(np.ones((8, 4), dtype=np.float32), mv.AddOption(momentum=0.5))
    uri = f"file://{tmp_path}/bf16m.npz"
    ckpt.save_table(t, uri)
    ckpt.load_table(t, uri)
    assert str(t.store.state["smooth"].dtype) == "bfloat16"
    assert str(t.store.data.dtype) == "bfloat16"
    # next update must not retrace to f32 nor change table dtype
    t.add(np.ones((8, 4), dtype=np.float32), mv.AddOption(momentum=0.5))
    assert str(t.store.data.dtype) == "bfloat16"


# -- gs:// (round 2: VERDICT #9) --------------------------------------------
class _FakeGCS:
    """In-memory GCS emulator speaking the slice of the JSON API the stream
    uses: media GET, metadata GET, media upload POST."""

    def __init__(self):
        import http.server
        import threading
        import urllib.parse

        store = self.store = {}

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: N802 - silence
                pass

            def _object_key(self):
                # /storage/v1/b/<bucket>/o/<object>[?alt=media]
                path, _, query = self.path.partition("?")
                parts = path.split("/")
                bucket, obj = parts[4], urllib.parse.unquote(parts[6])
                return f"{bucket}/{obj}", "alt=media" in query

            def do_GET(self):  # noqa: N802
                key, media = self._object_key()
                if key not in store:
                    self.send_response(404); self.end_headers(); return
                body = store[key] if media else b"{}"
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):  # noqa: N802
                # /upload/storage/v1/b/<bucket>/o?uploadType=media&name=X
                import urllib.parse as up
                path, _, query = self.path.partition("?")
                bucket = path.split("/")[5]
                name = up.unquote(dict(up.parse_qsl(query))["name"])
                n = int(self.headers["Content-Length"])
                store[f"{bucket}/{name}"] = self.rfile.read(n)
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")

        self.server = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                                      Handler)
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)
        self.thread.start()
        self.address = f"http://127.0.0.1:{self.server.server_address[1]}"

    def close(self):
        self.server.shutdown()


@pytest.fixture
def fake_gcs(monkeypatch):
    gcs = _FakeGCS()
    monkeypatch.setenv("STORAGE_EMULATOR_HOST", gcs.address)
    yield gcs
    gcs.close()


def test_gcs_stream_roundtrip_and_exists(fake_gcs):
    with open_stream("gs://bucket/dir/blob.bin", "w") as s:
        s.write(b"payload-123")
    assert fake_gcs.store["bucket/dir/blob.bin"] == b"payload-123"
    with open_stream("gs://bucket/dir/blob.bin", "r") as s:
        assert s.read() == b"payload-123"
    assert exists("gs://bucket/dir/blob.bin")
    assert not exists("gs://bucket/missing")
    with pytest.raises(StreamError):
        open_stream("gs://bucket/missing", "r")


def test_gcs_gate_without_emulator_or_token(monkeypatch):
    monkeypatch.delenv("STORAGE_EMULATOR_HOST", raising=False)
    monkeypatch.delenv("GCS_OAUTH_TOKEN", raising=False)
    with pytest.raises(StreamError, match="STORAGE_EMULATOR_HOST"):
        open_stream("gs://bucket/obj", "r")


def test_checkpoint_through_gcs_scheme(fake_gcs, mv_env):
    """A table checkpoint written through gs:// must restore bit-exact —
    the reference's HDFS Store/Load path (src/io/hdfs_stream.cpp) at GCS."""
    from multiverso_tpu.core import checkpoint as ckpt

    table = mv_env.create_table(mv_env.ArrayTableOption(
        size=64, name="gcs_ckpt"))
    table.add(np.arange(64, dtype=np.float32))
    ckpt.save_table(table, "gs://ckpts/run1/table.npz")

    table.add(np.ones(64, dtype=np.float32))   # diverge
    ckpt.load_table(table, "gs://ckpts/run1/table.npz")
    np.testing.assert_allclose(table.get(), np.arange(64))


def test_gcs_aborted_write_preserves_old_object(fake_gcs):
    """An exception inside the with-body must NOT replace the object with a
    truncated buffer (regression: review r2 finding)."""
    with open_stream("gs://bucket/ckpt.bin", "w") as s:
        s.write(b"good-checkpoint")
    with pytest.raises(RuntimeError):
        with open_stream("gs://bucket/ckpt.bin", "w") as s:
            s.write(b"half-")
            raise RuntimeError("died mid-write")
    with open_stream("gs://bucket/ckpt.bin", "r") as s:
        assert s.read() == b"good-checkpoint"
