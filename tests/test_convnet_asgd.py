"""CNN + ASGD param-manager sync — the binding benchmark workload shape
(ResNet/CIFAR ASGD in the reference's BENCHMARK.md, miniaturized)."""

import numpy as np
import pytest

import jax

import multiverso_tpu as mv
from multiverso_tpu.binding.param_manager import PyTreeParamManager
from multiverso_tpu.models.convnet import (ASGDConvNetWorker, ConvNetConfig,
                                           init_params)
from multiverso_tpu.parallel.async_engine import WorkerPool


def _striped_images(n, size=16, seed=0):
    """Class 0: horizontal stripes; class 1: vertical stripes (+noise)."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 2, size=n)
    x = np.zeros((n, size, size, 1), dtype=np.float32)
    phase = rng.integers(0, 4, size=n)
    for i in range(n):
        stripes = ((np.arange(size) + phase[i]) // 2) % 2
        img = np.tile(stripes[:, None], (1, size))   # horizontal stripes
        if y[i] == 1:
            img = img.T                              # vertical stripes
        x[i, :, :, 0] = img + rng.normal(0, 0.3, size=(size, size))
    return x, y.astype(np.int64)


def test_single_worker_learns(mv_env):
    cfg = ConvNetConfig(seed=1)
    params = init_params(cfg, jax.random.PRNGKey(1))
    manager = PyTreeParamManager(params, name="cnn1")
    worker = ASGDConvNetWorker(cfg, manager, sync_freq=4)
    x, y = _striped_images(512)
    batches = [(x[i:i + 64], y[i:i + 64]) for i in range(0, 512, 64)]
    for _ in range(6):
        worker.train(batches)
    xt, yt = _striped_images(256, seed=9)
    acc = worker.accuracy(xt, yt)
    assert acc > 0.9, acc


def test_multi_worker_asgd_converges(mv_env):
    """Four ASGD workers on disjoint shards, syncing through one table,
    converge to one good shared model (the 8-proc x 1-GPU benchmark shape)."""
    cfg = ConvNetConfig(seed=2, learning_rate=0.03)
    params = init_params(cfg, jax.random.PRNGKey(2))
    manager = PyTreeParamManager(params, name="cnn4")
    n_workers = 4
    x, y = _striped_images(1024, seed=3)
    shards = [(x[w::n_workers], y[w::n_workers]) for w in range(n_workers)]
    workers = [ASGDConvNetWorker(cfg, manager, sync_freq=2)
               for _ in range(n_workers)]

    def run(wid):
        xs, ys = shards[wid]
        batches = [(xs[i:i + 32], ys[i:i + 32])
                   for i in range(0, len(xs), 32)]
        for _ in range(6):
            workers[wid].train(batches)

    # the GLOBAL model (fresh pull) must be good — not just a local
    # replica. ASGD convergence is race-dependent (gradient staleness
    # varies with thread scheduling); on a loaded host one 6-epoch round
    # can fall just short, so train up to 3 rounds before judging —
    # what's asserted is convergence, not a fixed-budget race.
    xt, yt = _striped_images(256, seed=11)
    acc = 0.0
    for _ in range(3):
        WorkerPool(n_workers).run(run)
        probe = ASGDConvNetWorker(cfg, manager)
        acc = probe.accuracy(xt, yt)
        if acc > 0.9:
            break
    assert acc > 0.9, acc
