"""Unit tests for the data-plane traffic microscope (ISSUE 14):
Count-Min / Space-Saving sketch math, cross-thread/cross-process merge,
the hub's bounded memory, the cache-headroom advisor's CDF, and the
shard-imbalance rule's fire/resolve hysteresis — all deterministic
(seeded streams, manual ticks), no sleeping against live engines."""

import collections
import json
import threading

import numpy as np

from multiverso_tpu.telemetry import get_registry
from multiverso_tpu.telemetry.alerts import AlertManager, ImbalanceRule
from multiverso_tpu.telemetry.sketch import (CountMinSketch, SketchHub,
                                             SpaceSaving, TrafficSketch,
                                             coverage_at, load_ratio)
from multiverso_tpu.telemetry.timeseries import TimeseriesStore


def _zipf_stream(n, rows=100_000, alpha=1.3, seed=0):
    r = np.random.default_rng(seed)
    return ((r.zipf(alpha, n) - 1) % rows).astype(np.int64)


# ---------------------------------------------------------------------------
# Count-Min
# ---------------------------------------------------------------------------
def test_cms_error_bound_on_zipf_stream(mv_env):
    """Estimates never under-count, and the over-count respects the
    Count-Min guarantee (<= 2N/width per key, modulo the 2^-depth
    failure probability — asserted with headroom on a fixed seed)."""
    n, width = 200_000, 2048
    keys = _zipf_stream(n)
    cms = CountMinSketch(width=width, depth=4)
    cms.update(keys)
    assert cms.total == n
    true = collections.Counter(keys.tolist())
    probe = np.asarray(sorted(true, key=true.get, reverse=True)[:200]
                       + list(true)[:200], dtype=np.int64)
    est = cms.estimate(probe)
    truth = np.asarray([true[int(k)] for k in probe])
    assert (est >= truth).all(), "Count-Min must never under-count"
    assert (est - truth).max() <= 2 * n / width, \
        f"over-count {int((est - truth).max())} beyond the CMS bound"


def test_cms_update_with_explicit_counts(mv_env):
    cms = CountMinSketch(width=64, depth=3)
    cms.update(np.asarray([5, 9]), np.asarray([10, 3]))
    est = cms.estimate(np.asarray([5, 9, 7]))
    assert est[0] >= 10 and est[1] >= 3
    assert cms.total == 13


# ---------------------------------------------------------------------------
# Space-Saving
# ---------------------------------------------------------------------------
def test_spacesaving_topk_recovery_and_error_bounds(mv_env):
    """Every true top-10 key of a Zipf stream is recovered by a 128-slot
    sketch, and each tracked count brackets the truth:
    count - error <= true <= count."""
    keys = _zipf_stream(100_000, alpha=1.5, seed=1)
    ss = SpaceSaving(128)
    ss.update(keys)
    assert len(ss) <= 128
    true = collections.Counter(keys.tolist())
    true_top10 = {k for k, _ in true.most_common(10)}
    sketched = {k for k, _, _ in ss.topk(20)}
    assert true_top10 <= sketched, \
        f"missed hot keys: {true_top10 - sketched}"
    for k, count, err in ss.topk():
        assert count - err <= true[k] <= count, (k, count, err, true[k])


def test_spacesaving_guarantee_threshold(mv_env):
    """Any key above total/capacity frequency is guaranteed tracked."""
    keys = np.concatenate([np.full(500, 7), np.arange(1000) + 100])
    ss = SpaceSaving(64)
    ss.update(keys)
    assert 7 in {k for k, _, _ in ss.topk()}


# ---------------------------------------------------------------------------
# Merge: across threads and (serialized) across processes
# ---------------------------------------------------------------------------
def test_merge_associative_across_thread_shards(mv_env):
    """Three thread-local sketches over disjoint stream slices merge to
    the same answer regardless of merge order: Count-Min EXACTLY (adds
    commute), Space-Saving's recovered heavy hitters and totals."""
    keys = _zipf_stream(60_000, alpha=1.4, seed=2)
    shards = np.array_split(keys, 3)
    sketches = [TrafficSketch(width=512, depth=4, topk=128)
                for _ in shards]
    threads = [threading.Thread(target=sk.update, args=(part,))
               for sk, part in zip(sketches, shards)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    def fresh(i):
        sk = TrafficSketch(width=512, depth=4, topk=128)
        sk.merge(sketches[i])
        return sk

    ab_c = fresh(0)
    ab_c.merge(sketches[1])
    ab_c.merge(sketches[2])
    bc = fresh(1)
    bc.merge(sketches[2])
    a_bc = fresh(0)
    a_bc.merge(bc)
    assert (ab_c.cms.rows == a_bc.cms.rows).all()
    assert ab_c.keys == a_bc.keys == len(keys)
    top = lambda sk: {k for k, _, _ in sk.heavy.topk(10)}  # noqa: E731
    true = collections.Counter(keys.tolist())
    true_top = {k for k, _ in true.most_common(10)}
    assert true_top <= top(ab_c) and true_top <= top(a_bc)
    # ...and both merge orders equal one sketch over the whole stream.
    single = TrafficSketch(width=512, depth=4, topk=128)
    single.update(keys)
    assert (single.cms.rows == ab_c.cms.rows).all()


def test_merge_across_processes_via_state_roundtrip(mv_env):
    """Cross-process merge = JSON state out of one process, merged in
    another; the round trip is lossless for both sketches."""
    a, b = TrafficSketch(), TrafficSketch()
    keys = _zipf_stream(20_000, alpha=1.5, seed=3)
    a.update(keys[:10_000], nbytes=111)
    b.update(keys[10_000:], nbytes=222)
    wire = json.dumps(b.to_state())                 # the "other process"
    b2 = TrafficSketch.from_state(json.loads(wire))
    assert (b2.cms.rows == b.cms.rows).all()
    assert b2.heavy.topk() == b.heavy.topk()
    a.merge(b2)
    assert a.keys == len(keys) and a.bytes == 333
    single = TrafficSketch()
    single.update(keys)
    assert (a.cms.rows == single.cms.rows).all()


# ---------------------------------------------------------------------------
# Bounded memory
# ---------------------------------------------------------------------------
def test_bounded_memory_under_1m_distinct_keys(mv_env):
    """1M distinct keys through one sketch: memory stays at the fixed
    geometry (CMS rows + capped heavy-hitter table), not O(keys)."""
    sk = TrafficSketch(width=1024, depth=4, topk=128)
    for lo in range(0, 1_000_000, 100_000):
        sk.update(np.arange(lo, lo + 100_000, dtype=np.int64))
    assert sk.keys == 1_000_000
    assert len(sk.heavy) <= 128
    fixed = 1024 * 4 * 8 + 128 * 96
    assert sk.nbytes <= fixed, (sk.nbytes, fixed)


def test_hub_memory_bound_and_surface_cap(mv_env):
    hub = SketchHub(width=256, depth=4, topk=32)
    for i in range(hub.MAX_SURFACES + 8):
        hub.record(f"s{i}", np.arange(4))
    hub.flush()
    assert len(hub.surfaces()) == hub.MAX_SURFACES
    assert hub.memory_bytes() <= hub.memory_bound()


# ---------------------------------------------------------------------------
# Hub: record -> tick -> registry metrics
# ---------------------------------------------------------------------------
def test_hub_flush_publishes_metrics_from_many_threads(mv_env):
    hub = SketchHub(width=512, depth=4, topk=64)
    keys = _zipf_stream(30_000, alpha=1.5, seed=4)
    shards = np.array_split(keys, 4)

    def worker(part):
        for chunk in np.array_split(part, 10):
            hub.record("serve.lookup", chunk, int(chunk.size) * 256)

    threads = [threading.Thread(target=worker, args=(p,))
               for p in shards]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    hub.flush()
    s = hub.summary("serve.lookup")
    assert s["keys"] == len(keys)
    assert s["bytes"] == len(keys) * 256
    assert s["top1_share"] > 0.2        # zipf 1.5: rank-1 ~38%
    reg = get_registry()
    assert reg.counter("sketch.serve.lookup.keys").value == len(keys)
    assert reg.counter("sketch.serve.lookup.bytes").value \
        == len(keys) * 256
    assert reg.gauge("sketch.serve.lookup.top1_share").last > 0.2


def test_tick_drives_flush_into_rate_series(mv_env):
    from multiverso_tpu.telemetry.sketch import record_keys
    store = TimeseriesStore()
    store.tick(now=0.0)
    record_keys("ps.table_0.get", np.arange(50), 800)
    store.tick(now=1.0)
    record_keys("ps.table_0.get", np.arange(100), 1600)
    store.tick(now=2.0)
    # rows/sec and bytes/sec per surface land as timeseries rates.
    assert store.latest("rate.sketch.ps.table_0.get.keys") == 100.0
    assert store.latest("rate.sketch.ps.table_0.get.bytes") == 1600.0


def test_record_disabled_is_a_noop(mv_env):
    hub = SketchHub()
    hub.enabled = False
    hub.record("s", np.arange(10))
    hub.flush()
    assert hub.surfaces() == []


# ---------------------------------------------------------------------------
# Cache-headroom advisor math
# ---------------------------------------------------------------------------
def test_coverage_cdf_predicts_zipf_hit_share(mv_env):
    """The fitted-tail CDF prediction for a cache of C rows tracks the
    empirical share of traffic the true top-C keys carry — within a few
    points, which is what sizing a cache needs."""
    keys = _zipf_stream(300_000, rows=50_000, alpha=1.3, seed=5)
    ss = SpaceSaving(128)
    ss.update(keys)
    counts = ss.reliable_counts()
    true = collections.Counter(keys.tolist())
    for capacity in (64, 1024, 8192):
        predicted = coverage_at(counts, len(keys), capacity)
        empirical = sum(c for _, c in true.most_common(capacity)) \
            / len(keys)
        assert abs(predicted - empirical) < 0.08, \
            (capacity, predicted, empirical)
    # Within the tracked K the read is direct, not extrapolated.
    direct = coverage_at(counts, len(keys), 10)
    emp10 = sum(c for _, c in true.most_common(10)) / len(keys)
    assert abs(direct - emp10) < 0.02


def test_coverage_edge_cases(mv_env):
    assert coverage_at([], 0, 100) == 0.0
    assert coverage_at([10], 10, 1) == 1.0
    assert coverage_at([5, 3], 8, 100) <= 1.0


def test_advisor_gauges_published_for_registered_cache(mv_env):
    """A HotRowCache registers itself; the flush after traffic publishes
    predicted-vs-measured hit-rate gauges."""
    from multiverso_tpu.serving import HotRowCache
    from multiverso_tpu.telemetry.sketch import get_sketch_hub
    cache = HotRowCache(capacity=32)
    hot = np.arange(4)
    cache.put_rows(hot, np.ones((4, 8), np.float32), clock=0.0)
    hub = get_sketch_hub()
    for _ in range(20):
        got = cache.get_rows(hot, now_clock=0.0)        # hits -> sketch
        assert got is not None
    cache.get_rows(np.asarray([99]), now_clock=0.0)     # one miss
    hub.flush()
    reg = get_registry()
    predicted = reg.gauge(
        "serve.cache.advisor.predicted_hit_rate").snapshot()
    measured = reg.gauge(
        "serve.cache.advisor.measured_hit_rate").snapshot()
    assert predicted["samples"] >= 1 and measured["samples"] >= 1
    # 4 distinct keys, capacity 32: the CDF says ~everything could hit.
    assert predicted["last"] > 0.9
    assert 0.9 < measured["last"] < 1.0     # 20 hits / 21 lookups


# ---------------------------------------------------------------------------
# Shard-imbalance rule: fire/resolve hysteresis (satellite 4)
# ---------------------------------------------------------------------------
def _drive(store, mgr, ratio, volume, now):
    from multiverso_tpu.telemetry import gauge
    gauge("fleet.shard_load_ratio").set(ratio)
    gauge("fleet.shard_keys_rate").set(volume)
    store.tick(now=now)
    mgr.evaluate()


def test_shard_imbalance_fire_resolve_hysteresis(mv_env):
    store = TimeseriesStore()
    rule = ImbalanceRule("fleet.shard_imbalance",
                         "gauge.fleet.shard_load_ratio",
                         "gauge.fleet.shard_keys_rate",
                         ratio=1.7, min_volume=100.0,
                         for_windows=3, clear_windows=2)
    mgr = AlertManager(store, [rule], shared_telemetry=False)
    now = [0.0]

    def window(ratio, volume):
        now[0] += 1.0
        _drive(store, mgr, ratio, volume, now[0])

    for _ in range(5):
        window(1.05, 5000.0)                    # balanced baseline
    assert not mgr.active()
    window(2.0, 5000.0)                         # one skewed window:
    window(1.0, 5000.0)                         # a blip, then recovery
    assert not mgr.active(), "a single spike must never fire"
    window(2.0, 5000.0)
    window(2.0, 5000.0)
    assert not mgr.active(), "needs for_windows consecutive bad"
    window(2.0, 5000.0)                         # 3rd consecutive: fires
    assert [a["name"] for a in mgr.active()] == ["fleet.shard_imbalance"]
    window(1.1, 5000.0)                         # one good window is not
    assert mgr.active(), "resolve hysteresis: clear_windows needed"
    window(1.1, 5000.0)                         # 2nd good: resolves
    assert not mgr.active()


def test_shard_imbalance_volume_guard(mv_env):
    store = TimeseriesStore()
    rule = ImbalanceRule("fleet.shard_imbalance",
                         "gauge.fleet.shard_load_ratio",
                         "gauge.fleet.shard_keys_rate",
                         ratio=1.7, min_volume=100.0,
                         for_windows=2, clear_windows=2)
    mgr = AlertManager(store, [rule], shared_telemetry=False)
    for i in range(6):
        _drive(store, mgr, 3.0, 10.0, float(i + 1))     # skewed, idle
    assert not mgr.active(), "an idle fleet's skew must not page"
    # ...but a FIRING alert resolves through a trough (guard gates only
    # the firing direction).
    for i in range(3):
        _drive(store, mgr, 3.0, 5000.0, float(10 + i))
    assert mgr.active()
    for i in range(2):
        _drive(store, mgr, 1.0, 10.0, float(20 + i))
    assert not mgr.active()


def test_load_ratio_shapes(mv_env):
    assert load_ratio([]) == 1.0
    assert load_ratio([100.0, 100.0]) == 1.0
    assert load_ratio([0.0, 200.0]) == 2.0
    assert abs(load_ratio([1.0] * 99 + [101.0]) - 50.5) < 1.0
