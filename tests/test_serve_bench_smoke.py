"""Tier-1 smokes for the serving tooling surface.

``scripts/serve_bench.py --dry-run`` must stay runnable on CPU (the full
QPS numbers only mean something on a quiet box, but the harness itself —
service bring-up, pacing loop, percentile record — must not bit-rot), and
the ``serve_main`` CLI must stand up a replica-backed service end to end
from a checkpoint directory."""

import json
import os
import subprocess
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BENCH = os.path.join(_REPO, "scripts", "serve_bench.py")


def test_serve_bench_dry_run_cpu(tmp_path):
    out = tmp_path / "BENCH_SERVE.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, _BENCH, "--dry-run", f"--out={out}"],
        cwd=_REPO, env=env, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["benchmark"] == "serve_lookup"
    record = json.loads(out.read_text())
    # v9: + chaos block (--chaos-drill seeded kill-any-subset rounds);
    # config grows chaos_seed/chaos_rounds/rpc_timeout_ms
    assert record["schema"] == "multiverso_tpu.bench_serve/v12"
    assert record["box"]["cores"] >= 1
    lat = record["latency_ms"]
    assert set(lat) >= {"p50", "p95", "p99", "mean", "max"}
    assert lat["p50"] <= lat["p95"] <= lat["p99"] <= lat["max"]
    assert record["n_ok"] > 0
    assert 0.0 <= record["shed_rate"] <= 1.0
    assert record["achieved_qps"] > 0
    # tracing block: both QPS numbers + a trace-derived stage breakdown
    tracing = record["tracing"]
    assert tracing["qps_untraced"] > 0 and tracing["qps_traced"] > 0
    breakdown = tracing["stage_breakdown"]
    for stage in ("admit_wait", "batch_form", "device", "reply"):
        assert breakdown[stage]["count"] > 0, stage
        assert breakdown[stage]["p50"] <= breakdown[stage]["p95"] \
            <= breakdown[stage]["p99"]
    assert tracing["slowest"], "no slow-request timelines recorded"
    slow = tracing["slowest"][0]
    assert slow["n_spans"] >= 3 and slow["stages"]
    # the serve.* metric family rides along with the record
    assert any(k.startswith("serve.latency.")
               for k in record["serve_metrics"]["histograms"])
    assert "serve.queue_depth" in record["serve_metrics"]["gauges"]
    # PR-9 acceptance witnesses: the dispatch pipeline genuinely
    # OVERLAPPED (window occupancy reached >= 2 — not the serialized
    # path) and the hot-row cache recorded a hit. Either silently
    # regressing to the old path fails tier-1 here.
    pipe = record["pipeline"]
    assert pipe["depth"] >= 2, pipe
    assert pipe["max_inflight"] >= 2, pipe
    assert pipe["overlap_ok"] is True, pipe
    assert pipe["cache_hits"] >= 1, pipe
    assert pipe["cache_hit_ok"] is True, pipe
    assert "serve.pipeline.inflight" in record["serve_metrics"]["gauges"]
    # ISSUE-11 acceptance witnesses: the dry run forces a prefix-heavy
    # decode workload (shared-prompt burst) — the prefix cache must
    # record hits, paged f32 decode must be bitwise-equal to the drain
    # path, and peak pages resident must stay BELOW max-shape backing
    # for every slot (the decode memory hierarchy cannot silently
    # regress to preallocation).
    # ISSUE-13 acceptance witnesses: the observability plane measured
    # its own cost (A/B legs recorded — the number is box-noisy on 1
    # core, so the smoke bounds it loosely; full runs gate at 1%), the
    # synthetic SLO breach drove the shipped burn-rate state machine
    # through quiet -> tolerated spike -> fired-within-fast-window ->
    # resolved, and a stuck-free steady state tripped NO watchdog.
    obs = record["observability"]
    assert obs["ab"]["qps_plain"] > 0 and obs["ab"]["qps_observed"] > 0
    assert obs["ab"]["overhead_pct"] < 15.0, obs["ab"]
    slo = obs["slo_breach"]
    assert slo["baseline_quiet"] is True
    assert slo["spike_tolerated"] is True
    assert slo["fired"] is True
    assert slo["fired_within_fast_window"] is True, slo
    assert slo["resolved"] is True
    assert obs["watchdog"]["trips"] == 0, obs["watchdog"]
    # ISSUE-14 acceptance witnesses: the hot-key sketch recovered the
    # planted Zipf hot keys through the LIVE serving path (admission ->
    # cache -> device), its memory stayed under the configured bound,
    # and the cache-headroom advisor reported predicted-vs-measured hit
    # rates. The A/B above now also brackets the sketch's record()
    # appends (the plain leg disables them), so the <=1% full-run
    # acceptance covers this plane too.
    hk = record["hotkeys"]
    assert hk["recovered_count"] >= 9, hk
    assert hk["memory_ok"] is True, hk
    assert hk["memory_bytes"] <= hk["memory_bound"]
    assert hk["keys_observed"] > 0
    adv = hk["advisor"]
    assert 0.0 < adv["predicted_hit_rate"] <= 1.0, adv
    assert adv["predicted_hit_rate_2x"] >= adv["predicted_hit_rate"]
    assert "measured_hit_rate" in adv
    dm = record["decode_memory"]
    wit = dm["witness"]
    assert wit["paged_f32_bitwise_vs_drain"] is True, dm
    assert wit["prefix_hits_ok"] is True, dm
    assert wit["paged_held_ok"] is True, dm
    f32 = dm["runs"]["f32"]              # pure-paging witness run
    pref = dm["runs"]["f32+prefix"]      # shared-prompt burst run
    assert pref["prefix"]["hits"] >= 1
    assert pref["prefix"]["prefill_skipped"] >= 1
    assert f32["pages_used_max"] \
        < dm["max_batch"] * f32["pages_per_slot_max"]
    assert f32["users_per_chip_paged"] > f32["users_per_chip_prealloc"]
    assert pref["users_per_chip_prefix_shared"] \
        >= f32["users_per_chip_paged"]
    # ISSUE-18 acceptance witnesses: the attribution layer's phase
    # ledgers conserve on the paced probe (phases sum within 10% of
    # measured e2e, residual published into latency.unattributed), the
    # slowest-request exemplars carry trace ids resolvable against the
    # stitched trace file, every serving plane got a roofline verdict,
    # and the ledger+profiler A/B recorded its own overhead (box-noisy
    # on 1 core, so the smoke bounds it loosely; full runs gate at 1%).
    cp = record["tracing"]["critical_path"]
    probe = cp["probe"]
    assert probe["n_decomposed"] >= 10, probe
    assert probe["unattributed"]["mean_frac"] <= 0.10, probe
    assert probe["conserved_frac"] >= 0.5, probe
    assert cp["published_residual"]["count"] > 0, cp["published_residual"]
    assert cp["phases"].get("device", {}).get("total_ms", 0) > 0, cp
    ex = record["exemplars"]
    assert len(ex) > 0
    stitched = json.load(open(record["tracing"]["stitched_path"]))
    ids = {e.get("args", {}).get("trace")
           for e in stitched["traceEvents"] if e.get("ph") == "X"}
    assert any(e.get("trace") in ids for e in ex), ex
    assert all("phases" in e and e["total_ms"] > 0 for e in ex), ex
    rl = record["roofline"]
    for plane in ("serve", "client"):
        assert rl[plane]["bound"] in (
            "dispatch", "host", "wire", "device", "idle"), rl
    ab = obs["attribution_ab"]
    assert ab["qps_plain"] > 0 and ab["qps_attributed"] > 0
    assert ab["overhead_pct"] < 15.0, ab
    prof = record["profile"]
    assert prof["samples"] > 0 and prof["n_stacks"] > 0, prof
    # graftsan acceptance witnesses: the dry run's witness leg first
    # proves the OFF path hands out bare threading primitives (zero
    # overhead by construction — there is no instrumented code to pay
    # for), then drives a WAL commit + a nested lock pair under the
    # witness and records hold-time histograms with ZERO observed
    # inversions.
    lw = record["lockwitness"]
    assert lw["ab_off_is_bare_lock"] is True, lw
    assert lw["inversions"] == 0, lw
    assert lw["cycles"] == [], lw
    assert lw["edges"], lw
    held = lw["held_ms"]
    assert any(k.startswith("lock.wal.") for k in held), held
    for name, h in held.items():
        assert h["count"] > 0, (name, h)


def test_serve_main_cli_end_to_end(tmp_path):
    """serve_main: checkpoint dir in, bound address out, lookups served
    from the frozen replica — the full handoff through the real CLI."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    ckpt_dir = tmp_path / "ckpts"
    # Write a checkpoint with a driver process (the CLI reads, not shares,
    # the runtime).
    prep = subprocess.run(
        [sys.executable, "-c", f"""
import numpy as np
import multiverso_tpu as mv
from multiverso_tpu.core.checkpoint import save_all
mv.init([])
t = mv.create_table(mv.MatrixTableOption(num_row=32, num_col=4,
                                         name="served"))
t.add_rows(np.arange(32, dtype=np.int32),
           np.arange(128, dtype=np.float32).reshape(32, 4))
save_all({str(ckpt_dir)!r}, step=3)
mv.shutdown()
"""],
        cwd=_REPO, env=env, capture_output=True, text=True, timeout=240)
    assert prep.returncode == 0, prep.stdout + prep.stderr

    addr_file = tmp_path / "addr"
    proc = subprocess.Popen(
        [sys.executable, "-m", "multiverso_tpu.apps.serve_main",
         f"-checkpoint_dir={ckpt_dir}", "-serve_table=served",
         "-serve_buckets=4,8", "-serve_max_wait_ms=1",
         f"-serve_addr_file={addr_file}", "-serve_duration=45",
         "-serve_device=cpu"],
        cwd=_REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 120
        while not addr_file.exists():
            assert proc.poll() is None, proc.communicate()[0][-3000:]
            assert time.time() < deadline, "serve_main never bound"
            time.sleep(0.1)
        host, port = addr_file.read_text().split(":")

        from multiverso_tpu.serving import ServingClient
        cli = ServingClient(host, int(port))
        try:
            q = np.asarray([0, 7, 31], np.int32)
            got = cli.lookup(q, deadline_ms=10_000, timeout=60)
            want = np.stack([np.arange(r * 4, r * 4 + 4) for r in q]) \
                .astype(np.float32)
            np.testing.assert_array_equal(got, want)
        finally:
            cli.close()
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
