"""End-to-end CLI tests for the two reference applications."""

import numpy as np
import pytest


def _write_corpus(path, n=200, seed=0):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for i in range(n):
            topic = "a" if i % 2 == 0 else "b"
            words = [f"{topic}{rng.integers(0, 5)}" for _ in range(15)]
            f.write(" ".join(words) + "\n")


def test_word2vec_cli(tmp_path):
    from multiverso_tpu.apps.word2vec_main import main

    corpus = tmp_path / "corpus.txt"
    out = tmp_path / "vectors.txt"
    _write_corpus(str(corpus))
    rc = main([f"-train_file={corpus}", f"-output_file={out}",
               "-size=16", "-window=3", "-negative=3", "-min_count=1",
               "-epoch=1", "-batch_size=256",
               "-use_device_pipeline=false"])
    assert rc == 0
    lines = out.read_text().strip().split("\n")
    v, d = lines[0].split()
    assert int(v) == 10 and int(d) == 16
    assert len(lines) == 11


def test_word2vec_cli_device_pipeline(tmp_path):
    from multiverso_tpu.apps.word2vec_main import main

    corpus = tmp_path / "corpus.txt"
    out = tmp_path / "vectors.txt"
    _write_corpus(str(corpus))
    rc = main([f"-train_file={corpus}", f"-output_file={out}",
               "-size=16", "-min_count=1", "-epoch=1", "-batch_size=256",
               "-use_device_pipeline=true", "-block_sentences=64",
               "-pad_sentence_length=16"])
    assert rc == 0
    assert out.exists()


def test_word2vec_cli_missing_file():
    from multiverso_tpu.apps.word2vec_main import main

    assert main([]) == 1


def test_logreg_cli(tmp_path):
    from multiverso_tpu.apps.logreg_main import main

    rng = np.random.default_rng(0)
    w = rng.normal(size=8)
    train = tmp_path / "train.libsvm"
    test = tmp_path / "test.libsvm"
    for path, n in ((train, 300), (test, 100)):
        with open(path, "w") as f:
            for _ in range(n):
                x = rng.normal(size=8)
                y = int(x @ w > 0)
                feats = " ".join(f"{i}:{x[i]:.4f}" for i in range(8))
                f.write(f"{y} {feats}\n")
    conf = tmp_path / "lr.conf"
    conf.write_text("objective=sigmoid\nnum_feature=8\nlearning_rate=1.0\n"
                    "minibatch_size=32\nepochs=10\n")
    preds = tmp_path / "preds.txt"
    rc = main([f"-config_file={conf}", f"-lr_train_file={train}",
               f"-lr_test_file={test}", f"-output_file={preds}"])
    assert rc == 0
    assert len(preds.read_text().strip().split("\n")) == 100


def test_lda_cli(tmp_path, capsys):
    from multiverso_tpu.apps.lda_main import main

    rng = np.random.default_rng(0)
    docs = tmp_path / "docs.txt"
    with open(docs, "w") as f:
        for i in range(60):
            lo = 0 if i % 2 == 0 else 10
            words = [f"w{rng.integers(lo, lo + 10)}" for _ in range(40)]
            f.write(" ".join(words) + "\n")
    rc = main([f"-docs_file={docs}", "-num_topics=2",
               "-lda_iterations=20", "-topn=5"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "topic   0:" in out and "topic   1:" in out
