"""End-to-end CLI tests for the two reference applications."""

import numpy as np
import pytest


def _write_corpus(path, n=200, seed=0):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for i in range(n):
            topic = "a" if i % 2 == 0 else "b"
            words = [f"{topic}{rng.integers(0, 5)}" for _ in range(15)]
            f.write(" ".join(words) + "\n")


def test_word2vec_cli(tmp_path):
    from multiverso_tpu.apps.word2vec_main import main

    corpus = tmp_path / "corpus.txt"
    out = tmp_path / "vectors.txt"
    _write_corpus(str(corpus))
    rc = main([f"-train_file={corpus}", f"-output_file={out}",
               "-size=16", "-window=3", "-negative=3", "-min_count=1",
               "-epoch=1", "-batch_size=256",
               "-use_device_pipeline=false"])
    assert rc == 0
    lines = out.read_text().strip().split("\n")
    v, d = lines[0].split()
    assert int(v) == 10 and int(d) == 16
    assert len(lines) == 11


def test_word2vec_cli_device_pipeline(tmp_path):
    from multiverso_tpu.apps.word2vec_main import main

    corpus = tmp_path / "corpus.txt"
    out = tmp_path / "vectors.txt"
    _write_corpus(str(corpus))
    rc = main([f"-train_file={corpus}", f"-output_file={out}",
               "-size=16", "-min_count=1", "-epoch=1", "-batch_size=256",
               "-use_device_pipeline=true", "-block_sentences=64",
               "-pad_sentence_length=16"])
    assert rc == 0
    assert out.exists()


@pytest.mark.parametrize("mode", ["in_graph", "pipelined_host",
                                  "pallas_grid"])
def test_word2vec_cli_dispatch_modes(tmp_path, mode):
    """-dispatch_mode reaches the model (Round 6 selector): every explicit
    mode trains end to end through the CLI (pallas_grid interpreted on
    CPU)."""
    from multiverso_tpu.apps.word2vec_main import main

    corpus = tmp_path / "corpus.txt"
    out = tmp_path / "vectors.txt"
    _write_corpus(str(corpus), n=60)
    rc = main([f"-train_file={corpus}", f"-output_file={out}",
               "-size=16", "-min_count=1", "-epoch=1", "-batch_size=128",
               "-use_device_pipeline=true", "-block_sentences=64",
               "-pad_sentence_length=16", f"-dispatch_mode={mode}",
               "-dispatch_depth=2"])
    assert rc == 0
    assert out.exists()


def test_word2vec_cli_missing_file():
    from multiverso_tpu.apps.word2vec_main import main

    assert main([]) == 1


def test_word2vec_cli_distributed(tmp_path):
    """-world_size=2: the launcher spawns two real worker processes that
    shard the tables over the PS service (the `mpirun -np 2` analog);
    rank 0 exports the merged embeddings."""
    import subprocess
    import sys

    corpus = tmp_path / "corpus.txt"
    out = tmp_path / "vectors.txt"
    _write_corpus(str(corpus))
    # launch through a real process so the spawned ranks' platform pinning
    # (not the test conftest) is what's exercised
    rc = subprocess.run(
        [sys.executable, "-m", "multiverso_tpu.apps.word2vec_main",
         f"-train_file={corpus}", f"-output_file={out}", "-world_size=2",
         "-size=16", "-window=3", "-negative=3", "-min_count=1",
         "-epoch=2", "-batch_size=256", "-sample=0",
         f"-rendezvous_dir={tmp_path}"],
        timeout=420).returncode
    assert rc == 0
    lines = out.read_text().strip().split("\n")
    v, d = lines[0].split()
    assert int(v) == 10 and int(d) == 16
    assert len(lines) == 11
    # the trained vectors separate the two corpus topics
    vecs = {}
    for line in lines[1:]:
        parts = line.split()
        vecs[parts[0]] = np.asarray([float(x) for x in parts[1:]])
    def cos(a, b):
        return float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))
    intra = np.mean([cos(vecs[f"a{i}"], vecs[f"a{j}"])
                     for i in range(5) for j in range(i + 1, 5)])
    cross = np.mean([cos(vecs[f"a{i}"], vecs[f"b{j}"])
                     for i in range(5) for j in range(5)])
    assert intra > cross, (intra, cross)


def test_logreg_cli(tmp_path):
    from multiverso_tpu.apps.logreg_main import main

    rng = np.random.default_rng(0)
    w = rng.normal(size=8)
    train = tmp_path / "train.libsvm"
    test = tmp_path / "test.libsvm"
    for path, n in ((train, 300), (test, 100)):
        with open(path, "w") as f:
            for _ in range(n):
                x = rng.normal(size=8)
                y = int(x @ w > 0)
                feats = " ".join(f"{i}:{x[i]:.4f}" for i in range(8))
                f.write(f"{y} {feats}\n")
    conf = tmp_path / "lr.conf"
    conf.write_text("objective=sigmoid\nnum_feature=8\nlearning_rate=1.0\n"
                    "minibatch_size=32\nepochs=10\n")
    preds = tmp_path / "preds.txt"
    rc = main([f"-config_file={conf}", f"-lr_train_file={train}",
               f"-lr_test_file={test}", f"-output_file={preds}"])
    assert rc == 0
    assert len(preds.read_text().strip().split("\n")) == 100


def test_logreg_cli_distributed(tmp_path):
    """-world_size=2: two real PS ranks share the sharded weight table and
    each trains on half the samples; rank 0 tests and writes predictions."""
    import subprocess
    import sys

    rng = np.random.default_rng(1)
    w = rng.normal(size=8)
    train = tmp_path / "train.libsvm"
    test = tmp_path / "test.libsvm"
    for path, n in ((train, 400), (test, 100)):
        with open(path, "w") as f:
            for _ in range(n):
                x = rng.normal(size=8)
                y = int(x @ w > 0)
                feats = " ".join(f"{i}:{x[i]:.4f}" for i in range(8))
                f.write(f"{y} {feats}\n")
    conf = tmp_path / "lr.conf"
    conf.write_text("objective=sigmoid\nnum_feature=8\nlearning_rate=0.5\n"
                    "minibatch_size=32\nepochs=10\nsync_frequency=1\n")
    preds = tmp_path / "preds.txt"
    proc = subprocess.run(
        [sys.executable, "-m", "multiverso_tpu.apps.logreg_main",
         f"-config_file={conf}", f"-lr_train_file={train}",
         f"-lr_test_file={test}", f"-output_file={preds}", "-world_size=2",
         f"-rendezvous_dir={tmp_path}"],
        timeout=420, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert len(preds.read_text().strip().split("\n")) == 100
    # rank 0 logs a test accuracy line; the task is separable-ish
    import re
    m = re.search(r"test accuracy: (0\.\d+|1\.0+)",
                  proc.stderr + proc.stdout)
    assert m, (proc.stderr[-1500:], proc.stdout[-1500:])
    assert float(m.group(1)) > 0.85, m.group(1)


def test_lda_cli(tmp_path, capsys):
    from multiverso_tpu.apps.lda_main import main

    rng = np.random.default_rng(0)
    docs = tmp_path / "docs.txt"
    with open(docs, "w") as f:
        for i in range(60):
            lo = 0 if i % 2 == 0 else 10
            words = [f"w{rng.integers(lo, lo + 10)}" for _ in range(40)]
            f.write(" ".join(words) + "\n")
    rc = main([f"-docs_file={docs}", "-num_topics=2",
               "-lda_iterations=20", "-topn=5"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "topic   0:" in out and "topic   1:" in out
