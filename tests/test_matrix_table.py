"""MatrixTable tests — port of ``Test/test_matrix_table.cpp:38-95`` invariants:
dense + row updates across two tables with exact expected counts, plus row
routing (Partition) checks."""

import numpy as np
import pytest

import multiverso_tpu as mv


def test_dense_add_get(mv_env):
    table = mv.create_table(mv.MatrixTableOption(num_row=20, num_col=10))
    delta = np.full((20, 10), 2.0, dtype=np.float32)
    table.add(delta)
    np.testing.assert_allclose(table.get(), delta)
    table.add(delta)
    np.testing.assert_allclose(table.get(), 2 * delta)


def test_row_get_add(mv_env):
    table = mv.create_table(mv.MatrixTableOption(num_row=100, num_col=8))
    rows = [3, 17, 50, 99]
    deltas = np.arange(4 * 8, dtype=np.float32).reshape(4, 8)
    table.add_rows(rows, deltas)
    got = table.get_rows(rows)
    np.testing.assert_allclose(got, deltas)
    # untouched rows remain zero
    assert np.all(table.get_rows([0, 1, 2]) == 0)
    # whole-table view consistent with row view
    whole = table.get()
    np.testing.assert_allclose(whole[rows], deltas)


def test_duplicate_row_ids_accumulate(mv_env):
    """Scatter-add must accumulate duplicate row ids in one call (the
    reference server adds each per-row message independently)."""
    table = mv.create_table(mv.MatrixTableOption(num_row=10, num_col=4))
    rows = [5, 5, 5]
    deltas = np.ones((3, 4), dtype=np.float32)
    table.add_rows(rows, deltas)
    np.testing.assert_allclose(table.get_row(5), np.full(4, 3.0))


def test_two_tables_exact_counts(mv_env):
    """Two tables, mixed dense/row updates, exact expected values
    (Test/test_matrix_table.cpp:38-95 shape)."""
    workers = mv.num_workers()
    t1 = mv.create_table(mv.MatrixTableOption(num_row=16, num_col=4))
    t2 = mv.create_table(mv.MatrixTableOption(num_row=16, num_col=4))
    ones = np.ones((16, 4), dtype=np.float32)
    for _ in range(workers):
        t1.add(ones)
    rows = [1, 7]
    for _ in range(workers):
        t2.add_rows(rows, np.ones((2, 4), dtype=np.float32))
    np.testing.assert_allclose(t1.get(), ones * workers)
    expected = np.zeros((16, 4), dtype=np.float32)
    expected[rows] = workers
    np.testing.assert_allclose(t2.get(), expected)


def test_random_init_reproducible(mv_env):
    opt = mv.MatrixTableOption(num_row=8, num_col=8, random_init=True, seed=7)
    t = mv.create_table(opt)
    vals = t.get()
    assert vals.min() >= -0.5 and vals.max() < 0.5
    assert vals.std() > 0.1  # actually random


def test_row_partition_routing(mv_env):
    """Row r routes to server min(r // num_row_each, n-1)
    (ref matrix_table.cpp:235-313)."""
    table = mv.create_table(mv.MatrixTableOption(num_row=100, num_col=2))
    n = mv.num_servers()
    parts = table.partition(range(100))
    assert sum(len(v) for v in parts.values()) == 100
    each = max(1, 100 // n)
    for sid, rows in parts.items():
        for r in rows:
            assert min(int(r) // each, n - 1) == sid


def test_degenerate_fewer_rows_than_servers(mv_env):
    """num_row < num_servers (ref matrix_table.cpp:347-369 degenerate case)."""
    table = mv.create_table(mv.MatrixTableOption(num_row=3, num_col=5))
    delta = np.ones((3, 5), dtype=np.float32)
    table.add(delta)
    np.testing.assert_allclose(table.get(), delta)
    parts = table.partition([0, 1, 2])
    assert sum(len(v) for v in parts.values()) == 3
