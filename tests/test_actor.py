"""Actor/Message runtime tests (ports of unittests/test_message.cpp and the
actor dispatch altitude)."""

import threading
import time

import pytest

from multiverso_tpu.core.actor import (Actor, Message, MsgType,
                                       stop_all_actors)


@pytest.fixture(autouse=True)
def _cleanup():
    yield
    stop_all_actors()


def test_message_reply_inversion():
    """ref test_message.cpp:9-41: reply negates type, swaps src/dst."""
    msg = Message(src=3, dst=7, type=MsgType.Request_Get, table_id=2,
                  msg_id=11)
    reply = msg.create_reply()
    assert reply.src == 7 and reply.dst == 3
    assert reply.type == MsgType.Reply_Get
    assert reply.table_id == 2 and reply.msg_id == 11


def test_msgtype_routing():
    """ref communicator.cpp:15-27 sign/range routing."""
    assert Message(type=MsgType.Request_Add).to_server()
    assert Message(type=MsgType.Reply_Get).to_worker()
    assert Message(type=MsgType.Control_Barrier).to_controller()
    assert not Message(type=MsgType.Request_Add).to_worker()


def test_actor_dispatch():
    got = []
    done = threading.Event()
    a = Actor("echo")
    a.register_handler(MsgType.Request_Get,
                       lambda m: (got.append(m.data[0]), done.set()))
    a.start()
    a.receive(Message(type=MsgType.Request_Get, data=["hello"]))
    assert done.wait(5)
    assert got == ["hello"]


def test_actor_send_to_and_reply():
    reply_done = threading.Event()
    replies = []

    server = Actor("server")
    client = Actor("client")

    def on_get(msg):
        reply = msg.create_reply()
        reply.data = [sum(msg.data)]
        server.send_to("client", reply)

    def on_reply(msg):
        replies.append(msg.data[0])
        reply_done.set()

    server.register_handler(MsgType.Request_Get, on_get)
    client.register_handler(MsgType.Reply_Get, on_reply)
    server.start()
    client.start()
    client.send_to("server", Message(src=0, dst=1,
                                     type=MsgType.Request_Get,
                                     data=[1, 2, 3]))
    assert reply_done.wait(5)
    assert replies == [6]


def test_actor_survives_handler_error():
    done = threading.Event()
    a = Actor("flaky")
    calls = []

    def handler(msg):
        calls.append(msg.msg_id)
        if msg.msg_id == 1:
            raise ValueError("boom")
        done.set()

    a.register_handler(MsgType.Request_Add, handler)
    a.start()
    a.receive(Message(type=MsgType.Request_Add, msg_id=1))
    a.receive(Message(type=MsgType.Request_Add, msg_id=2))
    assert done.wait(5)
    assert calls == [1, 2]


def test_actor_stop_drains():
    a = Actor("stopper")
    a.register_handler(MsgType.Request_Add, lambda m: time.sleep(0.01))
    a.start()
    for i in range(5):
        a.receive(Message(type=MsgType.Request_Add, msg_id=i))
    a.stop()
    assert a._thread is None
