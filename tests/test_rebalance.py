"""Tier-1 tests for the skew actuators (docs/DESIGN.md "Skew actuation").

Three legs, each with its correctness witness:

* **Vnode ownership transfer** (hashring overrides): minimal disruption
  (only the migrated arcs' keys move, and all of them land on the
  target), determinism (router and clients rebuild the identical
  effective ring from ``(members, vnodes, overrides)``), and the
  mid-migration retry property — through the flip a key resolves to
  exactly one of {old owner, new owner}, never a third member.
* **Hot-key replication** (HotKeyReplicator + RoutingTable freshness):
  windowed-share promotion, demotion hysteresis, counter-reset resync,
  and the staleness gate — a member serves a replicated key iff
  ``fleet_max_step - member_step <= hot_staleness``, filtered at table
  build time.
* **Drain-and-handoff** (FleetRebalancer): deterministic hysteresis
  under a fake clock, hottest-arc targeting from merged sketch data,
  one-migration-in-flight, and the WAL parity witness — every write
  sync-acked before/during/after the handoff window replays bitwise.

Plus the CacheAutosizer's grow/shrink/clamp discipline (leg 3).
"""

import threading

import numpy as np
import pytest

from multiverso_tpu.core.wal import WriteAheadLog, replay
from multiverso_tpu.fleet.client import FleetClient, RoutingTable
from multiverso_tpu.fleet.hashring import HashRing
from multiverso_tpu.fleet.membership import ReplicaGroup
from multiverso_tpu.fleet.rebalance import FleetRebalancer, HotKeyReplicator
from multiverso_tpu.serving.cache import CacheAutosizer, HotRowCache

KEYS = np.arange(20_000, dtype=np.int64)


# ---------------------------------------------------------------------------
# Leg 2 actuation surface: vnode ownership transfer on the hash ring.
# ---------------------------------------------------------------------------

class TestOwnershipTransfer:
    def test_transfer_moves_only_the_migrated_arcs(self):
        ring = HashRing(["a", "b", "c"], vnodes=16)
        before = ring.owner_indices(KEYS)
        arcs = [("a", 0), ("a", 1), ("a", 2)]
        ring.set_overrides([(m, v, "b") for m, v in arcs])
        after = ring.owner_indices(KEYS)
        names = ring.members
        moved = np.flatnonzero(before != after)
        assert moved.size > 0
        # Every moved key left the donor for the target — nobody else.
        assert all(names[before[i]] == "a" for i in moved.tolist())
        assert all(names[after[i]] == "b" for i in moved.tolist())
        # Every moved key sits on a migrated arc; keys on any other arc
        # (including the donor's other arcs) did not move at all.
        assert set(ring.arc_ids(KEYS[moved])) <= set(arcs)
        untouched = [i for i, arc in enumerate(ring.arc_ids(KEYS))
                     if arc not in set(arcs)]
        assert (before[untouched] == after[untouched]).all()

    def test_ring_is_deterministic_in_members_vnodes_overrides(self):
        ov = [("a", 3, "c"), ("b", 7, "a")]
        r1 = HashRing(["a", "b", "c"], vnodes=16, overrides=ov)
        r2 = HashRing(["c", "b", "a"], vnodes=16,
                      overrides=list(reversed(ov)))
        assert r1.members == r2.members
        assert (r1.owner_indices(KEYS) == r2.owner_indices(KEYS)).all()
        assert r1.overrides == r2.overrides == tuple(sorted(ov))
        # assign_vnode(member, v, member) clears; the ring reverts to
        # the pure hash placement bit-for-bit.
        r1.assign_vnode("a", 3, "a")
        r1.assign_vnode("b", 7, "b")
        base = HashRing(["a", "b", "c"], vnodes=16)
        assert r1.overrides == ()
        assert (r1.owner_indices(KEYS) == base.owner_indices(KEYS)).all()

    def test_retry_through_the_flip_lands_on_old_xor_new_owner(self):
        """A client retrying through the announce sees either the
        pre-flip or the post-flip table; in both, a migrated key's owner
        is one of {donor, target} — the park-and-retry loop can never be
        routed to a member that was never responsible for the key."""
        old = HashRing(["a", "b", "c"], vnodes=16)
        new = HashRing(["a", "b", "c"], vnodes=16,
                       overrides=[("a", 0, "c")])
        names = old.members
        ob, nb = old.owner_indices(KEYS), new.owner_indices(KEYS)
        flipped = np.flatnonzero(ob != nb)
        assert flipped.size > 0
        for i in flipped.tolist():
            assert (names[ob[i]], names[nb[i]]) == ("a", "c")
        # The un-migrated majority resolves identically on both tables.
        same = np.flatnonzero(ob == nb)
        assert same.size + flipped.size == KEYS.size

    def test_dangling_override_reverts_to_hash_owner(self):
        base = HashRing(["a", "b"], vnodes=16)
        gone = HashRing(["a", "b"], vnodes=16, overrides=[("a", 0, "zz")])
        assert (base.owner_indices(KEYS) == gone.owner_indices(KEYS)).all()
        # Removing a live override's target reverts those arcs too — the
        # fail-safe a swept member needs, with no bookkeeping.
        ring = HashRing(["a", "b", "c"], vnodes=16,
                        overrides=[("a", 0, "c")])
        ring.remove("c")
        two = HashRing(["a", "b"], vnodes=16)
        assert (ring.owner_indices(KEYS) == two.owner_indices(KEYS)).all()


# ---------------------------------------------------------------------------
# Leg 1: hot-key replication — promotion/demotion hysteresis.
# ---------------------------------------------------------------------------

def _group(n=3, vnodes=16):
    g = ReplicaGroup(vnodes=vnodes, heartbeat_ms=1000.0)
    names = [f"r{i}" for i in range(n)] if n > 3 else ["a", "b", "c"][:n]
    for i, mid in enumerate(names):
        g.join(mid, "127.0.0.1", 1000 + i)
    return g


def _beat(group, mid, keys_total, hot):
    """One metrics-bearing heartbeat: cumulative served-keys total plus
    the member's heavy-hitter list [[key, cumulative_count], ...]."""
    group.heartbeat(mid, {}, {"keys": keys_total, "hot_keys": hot})


class TestHotKeyReplicator:
    def test_promotion_publishes_replica_set_home_owner_first(self):
        g = _group()
        rep = HotKeyReplicator(g, replicas=1, promote_share=0.02,
                               min_window_keys=100)
        assert rep.tick() == {}          # zero-traffic baseline window
        _beat(g, "a", 1000, [[7, 500]])
        mapping = rep.tick()
        assert list(mapping) == [7]
        assert mapping[7] == g.ring.replica_set(7, 2)
        assert mapping[7][0] == g.ring.owner(7)
        assert len(set(mapping[7])) == 2
        assert g.hot_keys() == mapping   # installed for the next payload

    def test_demotion_needs_consecutive_cold_windows(self):
        g = _group()
        rep = HotKeyReplicator(g, promote_share=0.02, demote_windows=2,
                               min_window_keys=100)
        rep.tick()
        _beat(g, "a", 1000, [[7, 500]])
        assert 7 in rep.tick()           # promoted
        _beat(g, "a", 2000, [[7, 500]])
        assert 7 in rep.tick()           # one cold window: still hot
        _beat(g, "a", 3000, [[7, 1000]])
        assert 7 in rep.tick()           # hot again: streak resets
        _beat(g, "a", 4000, [[7, 1000]])
        assert 7 in rep.tick()           # cold window 1 of 2
        _beat(g, "a", 5000, [[7, 1000]])
        assert 7 not in rep.tick()       # cold window 2: demoted

    def test_tiny_window_is_not_judged(self):
        """A trickle window (fewer than min_window_keys served fleet-wide)
        neither promotes nor advances demotion — quiet periods must not
        flap the confident set."""
        g = _group()
        rep = HotKeyReplicator(g, promote_share=0.02, demote_windows=1,
                               min_window_keys=200)
        rep.tick()
        _beat(g, "a", 1000, [[7, 500]])
        assert 7 in rep.tick()
        for total in (1050, 1100, 1150):     # 50-key windows, key 7 cold
            _beat(g, "a", total, [[7, 500]])
            assert 7 in rep.tick()
        _beat(g, "a", 2000, [[7, 500]])      # a real window, still cold
        assert 7 not in rep.tick()           # demote_windows=1: out

    def test_counter_reset_resyncs_baseline(self):
        """A member restart drops the cumulative totals; the replicator
        must resynchronize instead of judging a negative window."""
        g = _group()
        rep = HotKeyReplicator(g, promote_share=0.02, demote_windows=3,
                               min_window_keys=100)
        rep.tick()
        _beat(g, "a", 1000, [[7, 500]])
        assert 7 in rep.tick()
        _beat(g, "a", 100, [[7, 10]])        # restarted: counters reset
        assert 7 in rep.tick()               # resync window: no judgment
        _beat(g, "a", 1100, [[9, 900], [7, 10]])
        mapping = rep.tick()                 # next window judges normally
        assert 9 in mapping                  # 900/1000 promotes
        assert 7 in mapping                  # 1 cold window of 3: kept

    def test_topk_caps_the_confident_set_by_share(self):
        g = _group()
        rep = HotKeyReplicator(g, promote_share=0.01, topk=2,
                               min_window_keys=100)
        rep.tick()
        _beat(g, "a", 1000, [[1, 400], [2, 300], [3, 200], [4, 100]])
        assert set(rep.tick()) == {1, 2}

    def test_counts_merge_across_members_and_version_bumps_on_delta(self):
        g = _group()
        rep = HotKeyReplicator(g, promote_share=0.02, min_window_keys=100)
        rep.tick()
        # 300 + 300 out of 1000: neither member alone crosses 2%-of-
        # window confidently enough to matter — the MERGED share does.
        _beat(g, "a", 500, [[7, 300]])
        _beat(g, "b", 500, [[7, 300]])
        v0 = g.version
        assert 7 in rep.tick()
        assert g.version == v0 + 1           # real delta: announce
        assert 7 in rep.tick()               # steady set: no churn
        assert g.version == v0 + 1


# ---------------------------------------------------------------------------
# Leg 1 routing: build-time freshness filter + all-or-nothing hot routing.
# ---------------------------------------------------------------------------

def _payload(steps, hot, overrides=(), draining=()):
    return {
        "version": 1, "vnodes": 16,
        "hot_keys": {str(k): list(v) for k, v in hot.items()},
        "overrides": [list(o) for o in overrides],
        "members": [{"id": mid, "host": "127.0.0.1", "port": 1000 + i,
                     "health": 1.0, "draining": mid in draining,
                     "step": step, "drains_completed": 0}
                    for i, (mid, step) in enumerate(sorted(steps.items()))],
    }


class _Cnt:
    def __init__(self):
        self.n = 0

    def inc(self, k=1):
        self.n += k


def _cli_stub():
    """The two attrs _affinity_pref touches, without dialing a router."""
    class _S:
        pass
    s = _S()
    s._hot_rr = 0
    s._c_hot_routed = _Cnt()
    return s


class TestReplicatedReadFreshness:
    def test_stale_replica_filtered_at_build_time(self):
        pay = _payload({"a": 10.0, "b": 8.0, "c": 10.0},
                       {5: ["a", "b", "c"]})
        assert RoutingTable(pay, hot_staleness=0.0).hot_replicas \
            == {5: ["a", "c"]}
        assert RoutingTable(pay, hot_staleness=1.0).hot_replicas \
            == {5: ["a", "c"]}
        assert RoutingTable(pay, hot_staleness=2.0).hot_replicas \
            == {5: ["a", "b", "c"]}

    def test_unversioned_fleet_is_always_fresh(self):
        pay = _payload({"a": -1.0, "b": -1.0}, {5: ["a", "b"]})
        assert RoutingTable(pay, hot_staleness=0.0).hot_replicas \
            == {5: ["a", "b"]}

    def test_stepless_member_in_versioned_fleet_never_serves_hot(self):
        pay = _payload({"a": 10.0, "b": -1.0}, {5: ["a", "b"]})
        assert RoutingTable(pay, hot_staleness=1e9).hot_replicas \
            == {5: ["a"]}

    def test_key_with_no_fresh_replica_falls_back_to_affinity(self):
        pay = _payload({"a": 10.0, "b": 0.0}, {5: ["b"]})
        table = RoutingTable(pay, hot_staleness=0.0)
        assert table.hot_replicas == {}
        cli = _cli_stub()
        pref = FleetClient._affinity_pref(
            cli, np.array([5], dtype=np.int64), table)
        assert sorted(pref) == sorted(table.ring.members)
        assert cli._c_hot_routed.n == 0      # classic route, not hot

    def test_draining_member_is_not_a_hot_replica(self):
        pay = _payload({"a": -1.0, "b": -1.0}, {5: ["a", "b"]},
                       draining=("b",))
        assert RoutingTable(pay, hot_staleness=0.0).hot_replicas \
            == {5: ["a"]}

    def test_hot_routing_round_robins_over_fresh_union(self):
        ring = HashRing(["a", "b", "c"], vnodes=16)
        hot = {1: ring.replica_set(1, 2), 2: ring.replica_set(2, 2)}
        pay = _payload({"a": -1.0, "b": -1.0, "c": -1.0}, hot)
        table = RoutingTable(pay, hot_staleness=0.0)
        cli = _cli_stub()
        rows = np.array([1, 2], dtype=np.int64)
        cand = []
        for r in rows:
            for m in hot[int(r)]:
                if m not in cand:
                    cand.append(m)
        picks = []
        for _ in range(3 * len(cand)):
            pref = FleetClient._affinity_pref(cli, rows, table)
            picks.append(pref[0])
            # Every preference list covers the whole fleet exactly once.
            assert sorted(pref) == sorted(table.ring.members)
        assert set(picks) == set(cand)       # round-robin visits them all
        assert cli._c_hot_routed.n == len(picks)

    def test_partial_hot_set_routes_classic(self):
        """All-or-nothing: one un-replicated row in the request disables
        hot routing for the whole request (mirrors the cache's
        all-or-nothing admission)."""
        ring = HashRing(["a", "b", "c"], vnodes=16)
        pay = _payload({"a": -1.0, "b": -1.0, "c": -1.0},
                       {1: ring.replica_set(1, 2)})
        table = RoutingTable(pay, hot_staleness=0.0)
        cli = _cli_stub()
        prefs = {tuple(FleetClient._affinity_pref(
            cli, np.array([1, 3], dtype=np.int64), table))
            for _ in range(6)}
        assert len(prefs) == 1               # sticky, not round-robin
        assert cli._c_hot_routed.n == 0


# ---------------------------------------------------------------------------
# Leg 2: drain-and-handoff rebalancer (deterministic via fake clock +
# injected drain).
# ---------------------------------------------------------------------------

SKEWED = {"r0": 100.0, "r1": 1.0, "r2": 50.0}
BALANCED = {"r0": 50.0, "r1": 50.0, "r2": 50.0}


def _rgroup(n=3):
    g = ReplicaGroup(vnodes=8, heartbeat_ms=1000.0)
    for i in range(n):
        g.join(f"r{i}", "127.0.0.1", 1000 + i)
    return g


class TestFleetRebalancer:
    def test_arms_after_windows_and_migrates_hot_to_cold(self):
        g = _rgroup()
        drained = []
        reb = FleetRebalancer(g, ratio=1.5, windows=2, cooldown_s=10.0,
                              move_vnodes=2,
                              drain_fn=lambda m: bool(drained.append(m)))
        assert reb.tick(SKEWED, now=0.0) is None         # streak 1 of 2
        assert reb.tick(SKEWED, now=1.0) == ("r0", "r1")
        assert reb.join()
        assert drained == ["r0"]
        ov = g.vnode_overrides()
        assert len(ov) == 2
        assert all(m == "r0" and t == "r1" for m, _v, t in ov)
        assert g.ring.overrides == tuple(ov)             # announced
        assert reb.migrations_started == 1
        # Display state (fleet_top REBAL) cleared once the handoff
        # settles.
        sp = g.stats_payload()
        assert sp["replicas"]["r0"]["migrations"] == 0
        assert sp["fleet"]["rebalance"] == {"overrides": 2,
                                            "migrations": 0}

    def test_balanced_window_resets_the_streak(self):
        g = _rgroup()
        reb = FleetRebalancer(g, ratio=1.5, windows=2, cooldown_s=0.0,
                              drain_fn=lambda m: True)
        assert reb.tick(SKEWED, now=0.0) is None
        assert reb.tick(BALANCED, now=1.0) is None       # streak reset
        assert reb.tick(SKEWED, now=2.0) is None         # back to 1 of 2
        assert reb.tick(SKEWED, now=3.0) is not None
        assert reb.join()

    def test_cooldown_gates_back_to_back_migrations(self):
        g = _rgroup()
        reb = FleetRebalancer(g, ratio=1.5, windows=1, cooldown_s=10.0,
                              move_vnodes=1, drain_fn=lambda m: True)
        assert reb.tick(SKEWED, now=0.0) is not None
        assert reb.join()
        assert reb.tick(SKEWED, now=5.0) is None         # cooling down
        assert reb.tick(SKEWED, now=10.5) is not None
        assert reb.join()
        assert reb.migrations_started == 2

    def test_one_migration_in_flight_at_a_time(self):
        g = _rgroup()
        gate = threading.Event()
        reb = FleetRebalancer(g, ratio=1.5, windows=1, cooldown_s=0.0,
                              drain_fn=lambda m: gate.wait(5.0))
        assert reb.tick(SKEWED, now=0.0) is not None
        assert reb.migrating
        assert reb.tick(SKEWED, now=100.0) is None       # handoff busy
        gate.set()
        assert reb.join()
        assert reb.migrations_started == 1

    def test_picks_the_arcs_the_sketch_says_are_hot(self):
        g = _rgroup(2)
        # One key the sketch blames, on a donor-owned arc; a second
        # donor-owned key on a DIFFERENT arc must stay home.
        hot_key = next(int(k) for k in range(5000)
                       if g.ring.owner(int(k)) == "r0")
        hot_arc = g.ring.arc_ids(np.array([hot_key]))[0]
        cold_key = next(
            int(k) for k in range(5000)
            if g.ring.owner(int(k)) == "r0"
            and g.ring.arc_ids(np.array([int(k)]))[0] != hot_arc)
        _beat(g, "r0", 1000, [[hot_key, 900]])
        reb = FleetRebalancer(g, ratio=1.5, windows=1, cooldown_s=0.0,
                              move_vnodes=1, drain_fn=lambda m: True)
        assert reb.tick({"r0": 100.0, "r1": 1.0}, now=0.0) == ("r0", "r1")
        assert reb.join()
        assert g.ring.owner(hot_key) == "r1"             # heat moved
        assert g.ring.owner(cold_key) == "r0"            # cold stayed

    def test_wal_parity_through_the_handoff_window(self, tmp_path):
        """The durability witness: every write sync-acked before, DURING
        (mid-drain, while ownership flips), and after the handoff
        replays bitwise and in order — extending the PR-15 WAL parity
        guarantee to the migration path."""
        g = _rgroup(2)
        wal = WriteAheadLog(str(tmp_path))
        acked = []

        def ack(payload):
            acked.append((wal.append(payload, sync=True), payload))

        for i in range(4):
            ack(b"pre-%d" % i)

        def drain_fn(donor):
            assert donor == "r0"
            for i in range(4):
                ack(b"mid-%d" % i)       # acks keep landing mid-drain
            return True

        reb = FleetRebalancer(g, ratio=1.5, windows=1, cooldown_s=0.0,
                              move_vnodes=2, drain_fn=drain_fn)
        assert reb.tick({"r0": 100.0, "r1": 1.0}, now=0.0) == ("r0", "r1")
        assert reb.join()
        assert g.vnode_overrides()       # ownership really flipped
        for i in range(4):
            ack(b"post-%d" % i)
        wal.close()
        assert list(replay(str(tmp_path))) == acked

    def test_membership_ships_actuation_state_to_clients(self):
        g = _rgroup()
        v0 = g.version
        g.set_hot_keys({5: ["r0", "r1"]})
        g.apply_vnode_overrides([("r0", 1, "r2")])
        assert g.version == v0 + 2
        # Idempotent re-installs must NOT churn client tables.
        g.set_hot_keys({5: ["r0", "r1"]})
        g.apply_vnode_overrides([("r0", 1, "r2")])
        assert g.version == v0 + 2
        pay = g.routing_payload()
        assert pay["hot_keys"] == {"5": ["r0", "r1"]}
        assert pay["overrides"] == [["r0", 1, "r2"]]
        table = RoutingTable(pay)
        assert table.ring.overrides == g.ring.overrides
        sample = np.arange(2000, dtype=np.int64)
        assert (table.ring.owner_indices(sample)
                == g.ring.owner_indices(sample)).all()
        sp = g.stats_payload()
        assert sp["fleet"]["hotkey_replicated"] == 1
        assert sp["fleet"]["rebalance"]["overrides"] == 1
        assert sp["replicas"]["r0"]["hot_replicated"] == 1
        assert sp["replicas"]["r1"]["hot_replicated"] == 1
        assert sp["replicas"]["r2"]["hot_replicated"] == 0


# ---------------------------------------------------------------------------
# Leg 3: advisor-sized hot-row cache.
# ---------------------------------------------------------------------------

GROWS = {"predicted_hit_rate": 0.50, "predicted_hit_rate_2x": 0.60}
FLAT = {"predicted_hit_rate": 0.50, "predicted_hit_rate_2x": 0.50}


def _sized_cache(capacity=64):
    cache = HotRowCache(capacity, staleness=0)
    cache.put_rows(np.array([1]), np.ones((1, 16), np.float32), clock=1.0)
    return cache


class TestCacheAutosizer:
    def test_no_resize_until_row_bytes_are_learned(self):
        cache = HotRowCache(64, staleness=0)
        auto = CacheAutosizer(cache, mem_budget=1 << 20, windows=1,
                              cooldown_s=0.0)
        assert auto.budget_rows() is None
        assert auto.on_advice(GROWS, now=0.0) is None
        assert cache.capacity == 64

    def test_grow_needs_streak_and_cooldown_and_budget_caps_it(self):
        cache = _sized_cache(64)
        auto = CacheAutosizer(cache, mem_budget=cache.row_nbytes * 200,
                              windows=2, cooldown_s=10.0, min_rows=16)
        assert auto.budget_rows() == 200
        assert auto.on_advice(GROWS, now=0.0) is None    # streak 1 of 2
        assert auto.on_advice(GROWS, now=1.0) == "grow"
        assert cache.capacity == 128
        assert auto.on_advice(GROWS, now=2.0) is None    # streak rebuilt
        assert auto.on_advice(GROWS, now=3.0) is None    # cooling down
        assert auto.on_advice(GROWS, now=11.0) == "grow"  # cooldown over
        assert cache.capacity == 200                     # budget clamp
        # Keep occupancy above half so only the grow arm is in play:
        # at the bound, more grow-worthy advice must be a no-op.
        cache.put_rows(np.arange(2, 152),
                       np.ones((150, 16), np.float32), clock=1.0)
        assert auto.on_advice(GROWS, now=30.0) is None   # at the bound
        assert auto.on_advice(GROWS, now=31.0) is None
        assert cache.capacity == 200

    def test_flat_advice_resets_the_grow_streak(self):
        cache = _sized_cache(64)
        # Keep occupancy above half so the shrink arm stays quiet.
        cache.put_rows(np.arange(2, 40),
                       np.ones((38, 16), np.float32), clock=1.0)
        auto = CacheAutosizer(cache, mem_budget=cache.row_nbytes * 200,
                              windows=2, cooldown_s=0.0)
        assert auto.on_advice(GROWS, now=0.0) is None
        assert auto.on_advice(FLAT, now=1.0) is None     # streak reset
        assert auto.on_advice(GROWS, now=2.0) is None    # back to 1 of 2
        assert auto.on_advice(GROWS, now=3.0) == "grow"

    def test_idle_cache_shrinks_to_the_floor(self):
        cache = _sized_cache(256)                        # occupancy 1
        auto = CacheAutosizer(cache, mem_budget=cache.row_nbytes * 1024,
                              windows=2, cooldown_s=0.0, min_rows=64)
        assert auto.on_advice(FLAT, now=0.0) is None
        assert auto.on_advice(FLAT, now=1.0) == "shrink"
        assert cache.capacity == 128
        assert auto.on_advice(FLAT, now=2.0) is None
        assert auto.on_advice(FLAT, now=3.0) == "shrink"
        assert cache.capacity == 64                      # min_rows floor
        assert auto.on_advice(FLAT, now=4.0) is None
        assert auto.on_advice(FLAT, now=5.0) is None
        assert cache.capacity == 64

    def test_budget_breach_clamps_immediately(self):
        """The budget is a ceiling, not advice: when learned row bytes
        put capacity over it, the clamp skips streak AND cooldown."""
        cache = _sized_cache(1024)
        auto = CacheAutosizer(cache, mem_budget=cache.row_nbytes * 100,
                              windows=3, cooldown_s=1e9, min_rows=16)
        assert auto.on_advice(FLAT, now=0.0) == "shrink"
        assert cache.capacity == 100
        # Evictions happen at clamp time, not lazily at the next insert.
        cache2 = _sized_cache(8)
        cache2.put_rows(np.arange(2, 10),
                        np.ones((8, 16), np.float32), clock=1.0)
        assert len(cache2) == 8
        cache2.resize(4)
        assert len(cache2) == 4
