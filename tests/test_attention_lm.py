"""Long-context LM over causal ring attention: learns a deterministic
sequence on the (data x seq) mesh."""

import numpy as np
import pytest

import jax

from multiverso_tpu.models.attention_lm import AttentionLM, LMConfig


def _cyclic_batches(n_batches, B=4, S=64, K=17, seed=0):
    """Deterministic cyclic sequences: token[t+1] = (token[t]+1) mod K."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        starts = rng.integers(0, K, size=(B, 1))
        out.append((starts + np.arange(S)[None, :]) % K)
    return out


def test_lm_learns_cyclic_sequence():
    cfg = LMConfig(vocab=32, dim=32, heads=4, layers=2, seq=64,
                   learning_rate=3e-3, seq_parallel=4, data_parallel=2)
    lm = AttentionLM(cfg)
    assert dict(zip(lm.mesh.axis_names, lm.mesh.devices.shape)) == \
        {"data": 2, "seq": 4}
    batches = _cyclic_batches(60)
    initial = lm.loss(batches[0])
    losses = lm.fit(batches)
    final = lm.loss(batches[0])
    assert np.isfinite(losses).all()
    # the transition rule is deterministic: loss should collapse well below
    # the uniform baseline (log 32 ~ 3.47) and far below the initial loss
    assert final < initial * 0.5
    assert final < 1.0, f"final loss {final:.3f} (initial {initial:.3f})"


def test_lm_full_seq_parallel():
    """All 8 devices on the seq axis (pure context parallelism)."""
    cfg = LMConfig(vocab=16, dim=32, heads=4, layers=1, seq=64,
                   seq_parallel=8, data_parallel=1, learning_rate=3e-3)
    lm = AttentionLM(cfg)
    losses = lm.fit(_cyclic_batches(20, B=2, K=11))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_moe_lm_trains():
    """MoE MLP (expert parallelism inside the LM) still learns the cycle."""
    cfg = LMConfig(vocab=16, dim=32, heads=4, layers=1, seq=32,
                   seq_parallel=2, data_parallel=2, moe_experts=4,
                   learning_rate=3e-3)
    lm = AttentionLM(cfg)
    batches = _cyclic_batches(40, B=4, S=32, K=11)
    initial = lm.loss(batches[0])
    lm.fit(batches)
    final = lm.loss(batches[0])
    assert np.isfinite(final)
    assert final < initial * 0.6, (initial, final)


def test_pipelined_lm_loss_matches_flat_forward():
    """1F1B PP x SP: the pipelined step's loss equals the flat (unstacked)
    forward's loss on the same params/tokens — same math, different
    schedule and sharding."""
    from multiverso_tpu.models.attention_lm import pipeline_params_to_flat

    cfg = LMConfig(vocab=16, dim=32, heads=4, layers=4, seq=32,
                   pipeline_stages=2, pipeline_microbatches=4,
                   seq_parallel=2, learning_rate=1e-3, seed=3)
    lm = AttentionLM(cfg)
    assert dict(zip(lm.mesh.axis_names, lm.mesh.devices.shape)) == \
        {"stage": 2, "seq": 2}
    batch = _cyclic_batches(1, B=8, S=32, K=11)[0]
    flat_loss = lm.loss(batch)          # flat forward on converted params
    (pipe_loss,) = lm.fit([batch])      # 1F1B step reports pre-update loss
    np.testing.assert_allclose(pipe_loss, flat_loss, rtol=1e-4)


def test_pipelined_lm_learns_cyclic_sequence():
    cfg = LMConfig(vocab=16, dim=32, heads=4, layers=2, seq=32,
                   pipeline_stages=2, pipeline_microbatches=2,
                   seq_parallel=2, learning_rate=3e-3, seed=4)
    lm = AttentionLM(cfg)
    batches = _cyclic_batches(40, B=4, S=32, K=11)
    initial = lm.loss(batches[0])
    losses = lm.fit(batches)
    final = lm.loss(batches[0])
    assert np.isfinite(losses).all()
    assert final < initial * 0.6, (initial, final)


def test_remat_matches_baseline_loss():
    """jax.checkpoint on the layer blocks changes memory, not math."""
    cfg_a = LMConfig(vocab=16, dim=32, heads=4, layers=2, seq=32,
                     seq_parallel=2, data_parallel=2, seed=5)
    cfg_b = LMConfig(vocab=16, dim=32, heads=4, layers=2, seq=32,
                     seq_parallel=2, data_parallel=2, seed=5, remat=True)
    lm_a, lm_b = AttentionLM(cfg_a), AttentionLM(cfg_b)
    batch = _cyclic_batches(1, B=4, S=32, K=11)[0]
    np.testing.assert_allclose(lm_a.loss(batch), lm_b.loss(batch),
                               rtol=1e-5)
    (la,) = lm_a.fit([batch])
    (lb,) = lm_b.fit([batch])
    np.testing.assert_allclose(la, lb, rtol=1e-5)


def test_lm_ulysses_mode_matches_ring_loss():
    """sp_mode='ulysses' is a first-class training path: same loss as the
    ring program on identical params/batch (heads must divide the seq
    axis: 8 heads over the 8-device mesh)."""
    from multiverso_tpu.models.attention_lm import AttentionLM, LMConfig

    batch = np.tile(np.arange(16, dtype=np.int32), (2, 9))[:, :128]
    ring = AttentionLM(LMConfig(vocab=32, dim=64, heads=8, layers=2,
                                seq=128, seed=5, sp_mode="ring"))
    uly = AttentionLM(LMConfig(vocab=32, dim=64, heads=8, layers=2,
                               seq=128, seed=5, sp_mode="ulysses"))
    l_ring = ring.fit([batch])
    l_uly = uly.fit([batch])
    np.testing.assert_allclose(l_uly, l_ring, rtol=1e-4, atol=1e-5)
