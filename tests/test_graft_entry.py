"""The driver hooks (__graft_entry__) must keep compiling and running —
guard them in-suite so a refactor can't silently break the out-of-band
checks."""

import importlib.util
import os

import numpy as np
import pytest

import jax


def _load_module():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "__graft_entry__.py")
    spec = importlib.util.spec_from_file_location("graft_entry", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_entry_compiles_and_runs():
    mod = _load_module()
    fn, args = mod.entry()
    loss = jax.jit(fn)(*args)
    assert np.isfinite(float(loss))


def test_dryrun_multichip_8():
    mod = _load_module()
    mod.dryrun_multichip(8)   # asserts internally


def test_dryrun_multichip_4():
    """Non-8 device counts must also factor into a valid mesh."""
    mod = _load_module()
    mod.dryrun_multichip(4)
