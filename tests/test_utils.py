"""Log / CHECK / Timer / Dashboard tests (reference unittest altitude)."""

import time

import pytest

from multiverso_tpu.utils.dashboard import Dashboard, monitor, monitored
from multiverso_tpu.utils.log import FatalError, check, check_notnull, log
from multiverso_tpu.utils.timer import Timer


def test_check_passes_and_fails():
    check(True)
    with pytest.raises(FatalError):
        check(False, "boom")


def test_check_notnull():
    assert check_notnull(5) == 5
    with pytest.raises(FatalError):
        check_notnull(None, "thing")


def test_timer_elapses():
    t = Timer()
    time.sleep(0.01)
    assert t.elapse() >= 5.0  # ms
    t.start()
    assert t.elapse() < 5.0


def test_monitor_counts():
    with monitor("unit_test_op"):
        time.sleep(0.005)
    with monitor("unit_test_op"):
        time.sleep(0.005)
    m = Dashboard.get("unit_test_op")
    assert m.count == 2
    assert m.total_ms >= 5.0
    assert m.average_ms > 0
    assert "unit_test_op" in Dashboard.watch("unit_test_op")


def test_monitored_decorator():
    @monitored("deco_op")
    def f(x):
        return x * 2

    assert f(21) == 42
    assert Dashboard.get("deco_op").count == 1


def test_display_contains_all():
    Dashboard.get("a").add(1.0)
    Dashboard.get("b").add(2.0)
    report = Dashboard.display()
    assert "[a]" in report and "[b]" in report
