"""Log / CHECK / Timer / Dashboard tests (reference unittest altitude)."""

import time

import pytest

from multiverso_tpu.utils.dashboard import Dashboard, monitor, monitored
from multiverso_tpu.utils.log import FatalError, check, check_notnull, log
from multiverso_tpu.utils.timer import Timer


def test_check_passes_and_fails():
    check(True)
    with pytest.raises(FatalError):
        check(False, "boom")


def test_check_notnull():
    assert check_notnull(5) == 5
    with pytest.raises(FatalError):
        check_notnull(None, "thing")


def test_timer_elapses():
    t = Timer()
    time.sleep(0.01)
    assert t.elapse() >= 5.0  # ms
    t.start()
    assert t.elapse() < 5.0


def test_monitor_counts():
    with monitor("unit_test_op"):
        time.sleep(0.005)
    with monitor("unit_test_op"):
        time.sleep(0.005)
    m = Dashboard.get("unit_test_op")
    assert m.count == 2
    assert m.total_ms >= 5.0
    assert m.average_ms > 0
    assert "unit_test_op" in Dashboard.watch("unit_test_op")


def test_monitored_decorator():
    @monitored("deco_op")
    def f(x):
        return x * 2

    assert f(21) == 42
    assert Dashboard.get("deco_op").count == 1


def test_display_contains_all():
    Dashboard.get("a").add(1.0)
    Dashboard.get("b").add(2.0)
    report = Dashboard.display()
    assert "[a]" in report and "[b]" in report


def test_log_file_sink(tmp_path):
    path = str(tmp_path / "mv.log")
    log.set_log_file(path)
    try:
        log.info("sink check %d", 42)
    finally:
        log.set_log_file(None)
    content = open(path).read()
    assert "sink check 42" in content and "[INFO]" in content


def test_log_levels_filter(capsys):
    from multiverso_tpu.utils.log import LogLevel
    log.set_level(LogLevel.ERROR)
    try:
        log.info("hidden message")
        log.error("shown message")
    finally:
        log.set_level(LogLevel.INFO)
    out = capsys.readouterr()
    assert "hidden message" not in out.out
    assert "shown message" in out.err


def test_profiler_annotate_smoke():
    from multiverso_tpu.utils.profiler import annotate
    with annotate("annotated_region"):
        pass
    assert Dashboard.get("annotated_region").count == 1
