"""One rank of the app-level fault drill — run as a REAL process.

Modes (argv[1] is a JSON dict):
* ``train``        — a full DistributedWord2Vec worker+shard rank: rendezvous,
                     train its corpus shard, write per-block progress marks,
                     rank 0 saves embeddings, write ``done<rank>``.
* ``seat_restart`` — restart rank R's SEAT only (service + registered table
                     shards, no training loop) after the original process was
                     SIGKILLed, and retire R's BSP clocks via finish_train —
                     the Server_Finish_Train straggler path
                     (ref src/server.cpp:190-213) driven end to end. Serves
                     until every surviving rank's done-file appears.
"""

import json
import os
import sys
import time


def _pin_cpu(repo):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, repo)
    from multiverso_tpu.apps._runner import _pin_jax_cpu
    _pin_jax_cpu()


def _build(args):
    import numpy as np  # noqa: F401

    from multiverso_tpu.models.word2vec import Dictionary, Word2VecConfig

    sents = [ln.split() for ln in open(args["corpus"])]
    d = Dictionary.build(sents, min_count=1)
    ids = [d.encode(s) for s in sents]
    cfg = Word2VecConfig(**args["cfg"])
    return d, ids, cfg


def main():
    args = json.loads(sys.argv[1])
    _pin_cpu(args["repo"])

    import numpy as np

    import multiverso_tpu as mv
    from multiverso_tpu.apps._runner import rendezvous
    from multiverso_tpu.parallel.ps_service import (DistributedKVTable,
                                                    DistributedMatrixTable,
                                                    DistributedTableBase,
                                                    PSService)

    # Slow-box drill: a killed rank needs time to re-import jax before the
    # survivors' rediscovery window closes.
    DistributedTableBase.RETRY_WINDOW = float(args.get("retry_window", 60.0))

    mode = args["mode"]
    rank, world, rdv = args["rank"], args["world"], args["rdv"]
    mv.init(["-sync=true"] if args.get("sync") else [])
    d, ids, cfg = _build(args)

    if mode == "seat_restart":
        from multiverso_tpu.models.word2vec.distributed import \
            DistributedWord2Vec as W
        svc = PSService()
        # Original addresses from the rendezvous dir, ours replaced.
        peers = []
        for r in range(world):
            host, port = open(os.path.join(rdv, f"addr{r}")).read().split(":")
            peers.append((host, int(port)))
        peers[rank] = svc.address
        V, D = len(d), cfg.embedding_size
        out_rows = max((V - 1) if cfg.hs else V, 1)
        tables = [DistributedMatrixTable(W.TABLE_IN, V, D, svc, peers, rank),
                  DistributedMatrixTable(W.TABLE_OUT, out_rows, D, svc,
                                         peers, rank),
                  DistributedKVTable(W.TABLE_WORD_COUNT, svc, peers, rank,
                                     dtype=np.int64)]
        if cfg.optimizer == "adagrad":
            tables.append(DistributedMatrixTable(W.TABLE_G_IN, V, D, svc,
                                                 peers, rank))
            tables.append(DistributedMatrixTable(W.TABLE_G_OUT, out_rows, D,
                                                 svc, peers, rank))
        for t in tables:
            t.finish_train()
        open(os.path.join(rdv, f"seat{rank}"), "w").write("up")
        # Serve the (fresh) shard until the survivors all finish.
        deadline = time.time() + args.get("serve_timeout", 600)
        waiting = [r for r in range(world) if r != rank]
        while waiting and time.time() < deadline:
            waiting = [r for r in waiting
                       if not os.path.exists(os.path.join(rdv, f"done{r}"))]
            time.sleep(0.2)
        svc.close()
        mv.shutdown()
        sys.exit(0 if not waiting else 3)

    # -- mode == "train" ---------------------------------------------------
    from multiverso_tpu.models.word2vec.distributed import DistributedWord2Vec

    svc = PSService()
    peers = rendezvous(rdv, rank, world, svc.address)
    w2v = DistributedWord2Vec(cfg, d, svc, peers, rank=rank)
    progress = os.path.join(rdv, f"progress{rank}")

    def mark(block_i, words):
        with open(progress, "w") as f:
            f.write(f"{block_i} {words}")

    stats = w2v.train(ids[rank::world], on_block=mark)
    if rank == 0:
        emb = w2v.embeddings()
        np.save(os.path.join(rdv, "embeddings.npy"), emb)
    with open(os.path.join(rdv, f"stats{rank}.json"), "w") as f:
        json.dump({"words": int(stats["words"]),
                   "words_per_sec": stats["words_per_sec"]}, f)
    open(os.path.join(rdv, f"done{rank}"), "w").write("ok")
    # Hold the shard up until every peer is done (wait_all_done analog,
    # ref distributed_wordembedding.cpp:232) — but tolerate a DEAD peer:
    # the drill's async variant has no seat_restart holding the barrier.
    deadline = time.time() + args.get("serve_timeout", 600)
    expected = set(args.get("barrier_ranks", range(world)))
    while time.time() < deadline:
        if all(os.path.exists(os.path.join(rdv, f"done{r}"))
               for r in expected):
            break
        time.sleep(0.2)
    svc.close()
    mv.shutdown()
    sys.exit(0)


if __name__ == "__main__":
    main()
