"""Cross-replica-sharded optimizer state (docs/DESIGN.md "Sharded updater
state"; arXiv 2004.13336) — the parity tests are the contract:

* params AND state bitwise-equal to the unsharded layout over multi-epoch
  runs (pow-2 replica axes, every stateful updater);
* per-store state bytes drop (k-1)/k on a k-replica mesh, gauge-backed;
* checkpoints round-trip across replica counts (reshard on load), legacy
  padded payloads still load, genuinely incompatible shapes fail loudly;
* SSP staleness-adaptive DC-ASGD: measured clock lag scales the
  variance-control term (lambda_eff = lambda * lag) only when armed.
"""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.core.checkpoint import load_table, save_table

MESH_2x4 = "-mesh_shape=server:2,worker:4"
MESH_2x2 = "-mesh_shape=server:2,worker:2"

STATEFUL = ("momentum_sgd", "adagrad", "ftrl", "dcasgd", "dcasgda")


def _train_table(updater, epochs=3, rows=64, cols=8, name="t"):
    """A multi-epoch mixed row/dense add schedule; returns the table."""
    t = mv.create_table(mv.MatrixTableOption(rows, cols, updater=updater,
                                             name=name))
    rng = np.random.default_rng(7)
    opt = mv.AddOption(worker_id=0, momentum=0.5, learning_rate=0.1,
                       rho=0.1, lambda_=0.01)
    for _ in range(epochs):
        for _ in range(3):
            ids = rng.integers(0, rows, size=16).astype(np.int32)
            t.add_rows(ids, rng.normal(size=(16, cols)).astype(np.float32),
                       opt)
        t.add(rng.normal(size=(rows, cols)).astype(np.float32), opt)
    return t


def _run(mode, updater, mesh=MESH_2x4, epochs=3):
    mv.init([mesh, f"-state_sharding={mode}"])
    try:
        t = _train_table(updater, epochs=epochs)
        params = t.get().copy()
        state = {k: np.asarray(v).copy() for k, v in t.store.state.items()}
        state_bytes = t.store.state_bytes()
        data_bytes = t.store.data_bytes()
        sharded = t.store.state_sharded
        return params, state, state_bytes, data_bytes, sharded
    finally:
        mv.shutdown()


@pytest.mark.parametrize("updater", STATEFUL)
def test_sharded_state_bitwise_params_and_state(updater):
    """THE acceptance contract: pow-2 replica axis, multi-epoch run,
    params and every state leaf bitwise-equal to the unsharded layout."""
    p_off, s_off, b_off, _, sh_off = _run("off", updater)
    p_on, s_on, b_on, _, sh_on = _run("on", updater)
    assert not sh_off and sh_on
    assert np.array_equal(p_off, p_on), updater
    for key in s_off:
        assert np.array_equal(s_off[key], s_on[key]), (updater, key)
    # 4 replicas: sharded state holds 1/4 of the unsharded bytes.
    assert b_on * 4 == b_off, (updater, b_off, b_on)


def test_sharded_state_bitwise_second_mesh():
    """Same contract on a second pow-2 axis size (k=2)."""
    p_off, _, b_off, _, _ = _run("off", "adagrad", mesh=MESH_2x2, epochs=2)
    p_on, _, b_on, _, sh = _run("on", "adagrad", mesh=MESH_2x2, epochs=2)
    assert sh and np.array_equal(p_off, p_on)
    # the >= 40% acceptance floor at world 2 is exactly 50% here
    assert b_on * 2 == b_off


def test_state_bytes_gauges_published():
    """ps.data_bytes / ps.state_bytes are host-computed gauges, set at
    init (and load), so the HBM claim is a measured number."""
    from multiverso_tpu.telemetry import metrics_snapshot
    mv.init([MESH_2x4, "-state_sharding=on"])
    try:
        t = mv.create_table(mv.MatrixTableOption(64, 8, updater="adagrad",
                                                 name="gt"))
        snap = metrics_snapshot(buckets=False)
        gauges = snap.get("gauges", {})
        assert gauges["ps.state_bytes.gt"]["last"] == t.store.state_bytes()
        assert gauges["ps.data_bytes.gt"]["last"] == t.store.data_bytes()
        # data is replicated across the worker axis (lookups stay local),
        # state is not — that asymmetry IS the memory win.
        assert t.store.state_bytes() < t.store.data_bytes() * 4
    finally:
        mv.shutdown()


def test_state_leaf_sharding_spec_includes_worker_axis():
    mv.init([MESH_2x4, "-state_sharding=on"])
    try:
        t = mv.create_table(mv.MatrixTableOption(64, 8,
                                                 updater="momentum_sgd",
                                                 name="sp"))
        spec = t.store.state["smooth"].sharding.spec
        flat = [ax for entry in spec if entry
                for ax in (entry if isinstance(entry, tuple) else (entry,))]
        assert "worker" in flat and "server" in flat
        # params stay replicated over worker
        dspec = t.store.data.sharding.spec
        dflat = [ax for entry in dspec if entry
                 for ax in (entry if isinstance(entry, tuple)
                            else (entry,))]
        assert "worker" not in dflat
    finally:
        mv.shutdown()


def test_state_sharding_on_rejects_indivisible():
    """-state_sharding=on fails loudly when a leaf cannot split evenly;
    auto silently keeps that leaf unsharded."""
    mv.init([MESH_2x4, "-state_sharding=on"])
    try:
        with pytest.raises(Exception, match="state_sharding=on"):
            mv.create_table(mv.MatrixTableOption(9, 3,
                                                 updater="momentum_sgd",
                                                 name="bad"))
    finally:
        mv.shutdown()
    mv.init([MESH_2x4, "-state_sharding=auto"])
    try:
        t = mv.create_table(mv.MatrixTableOption(9, 3,
                                                 updater="momentum_sgd",
                                                 name="ok"))
        assert not t.store.state_sharded   # 10 padded rows !% 8
    finally:
        mv.shutdown()


# ---------------------------------------------------------------------------
# checkpoint round-trips across replica counts
# ---------------------------------------------------------------------------
def _ckpt_train_and_save(tmp_path, mesh, mode, updater="adagrad"):
    # mesh "" must RESET the flag (it persists across init cycles within
    # one test), restoring the default 1-axis all-server mesh.
    mv.init([f"-mesh_shape={mesh.split('=', 1)[1] if mesh else ''}",
             f"-state_sharding={mode}"])
    try:
        t = _train_table(updater, epochs=2, name="ck")
        uri = str(tmp_path / "ck.npz")
        save_table(t, uri)
        return (uri, t.get().copy(),
                {k: np.asarray(v).copy() for k, v in t.store.state.items()})
    finally:
        mv.shutdown()


def _ckpt_load(uri, mesh, mode, updater="adagrad"):
    mv.init([f"-mesh_shape={mesh.split('=', 1)[1] if mesh else ''}",
             f"-state_sharding={mode}"])
    try:
        t = mv.create_table(mv.MatrixTableOption(64, 8, updater=updater,
                                                 name="ck"))
        load_table(t, uri)
        return (t.get().copy(),
                {k: np.asarray(v).copy() for k, v in t.store.state.items()},
                t.store.state_sharded)
    finally:
        mv.shutdown()


def test_checkpoint_reshard_on_replica_count_change(tmp_path):
    """Sharded save (k=4) loads into k=2, k=1 (unsharded world), and back
    — params and state bitwise through every reshard."""
    uri, params, state = _ckpt_train_and_save(tmp_path, MESH_2x4, "on")
    for mesh, mode, want_sharded in ((MESH_2x2, "on", True),
                                     ("", "auto", False),
                                     (MESH_2x4, "off", False)):
        got_p, got_s, sharded = _ckpt_load(uri, mesh, mode)
        assert sharded == want_sharded, (mesh, mode)
        assert np.array_equal(got_p, params), (mesh, mode)
        for k in state:
            assert np.array_equal(got_s[k], state[k]), (mesh, mode, k)


def test_checkpoint_legacy_unsharded_into_sharded(tmp_path):
    """A checkpoint written with unsharded state (and legacy PADDED state
    leaves) loads into a sharded store bitwise."""
    uri, params, state = _ckpt_train_and_save(tmp_path, "", "off")
    got_p, got_s, sharded = _ckpt_load(uri, MESH_2x4, "on")
    assert sharded
    assert np.array_equal(got_p, params)
    for k in state:
        assert np.array_equal(got_s[k], state[k]), k

    # Legacy format: state leaves saved at the PADDED extent. Build one by
    # hand and load it — the pad region is zeros by construction.
    mv.init([MESH_2x4, "-state_sharding=on"])
    try:
        t = mv.create_table(mv.MatrixTableOption(64, 8, updater="adagrad",
                                                 name="ck"))
        padded_rows = t.store.padded_shape[0]
        legacy = {"data": params,
                  "state/g2": np.zeros((1, padded_rows + 8, 8),
                                       np.float32)}
        legacy["state/g2"][:, :64] = state["g2"][:, :64]
        t.store.load_state(legacy)
        assert np.array_equal(t.get(), params)
        assert np.array_equal(np.asarray(t.store.state["g2"])[:, :64],
                              state["g2"][:, :64])
    finally:
        mv.shutdown()


def test_checkpoint_incompatible_shapes_fail_loud(tmp_path):
    """Wrong table shape / worker extent / column width must raise, not
    silently truncate."""
    uri, params, state = _ckpt_train_and_save(tmp_path, "", "off")
    mv.init([])
    try:
        t = mv.create_table(mv.MatrixTableOption(64, 8, updater="adagrad",
                                                 name="ck"))
        with pytest.raises(Exception, match="incompatible"):
            t.store.load_state({"data": params[:32]})
        with pytest.raises(Exception, match="incompatible"):
            t.store.load_state({"data": params,
                                "state/g2": state["g2"][..., :4]})
        with pytest.raises(Exception, match="incompatible"):
            t.store.load_state({"data": params,
                                "state/g2": np.concatenate(
                                    [state["g2"], state["g2"]], axis=0)})
    finally:
        mv.shutdown()


# ---------------------------------------------------------------------------
# SSP staleness-adaptive DC-ASGD
# ---------------------------------------------------------------------------
def test_dcasgd_staleness_scales_lambda():
    """Updater math: staleness tau >= 0 makes lambda_eff = lambda * tau —
    update(staleness=tau, lambda) == update(unmeasured, lambda*tau);
    unmeasured (negative) keeps the fixed lambda bitwise."""
    import jax.numpy as jnp

    from multiverso_tpu.core.options import AddOption
    from multiverso_tpu.core.updater import get_updater

    upd = get_updater(np.float32, "dcasgd")
    data = jnp.asarray(np.random.default_rng(0)
                       .normal(size=(6, 4)).astype(np.float32))
    state = upd.init_state((6, 4), np.float32, 2)
    state = {"backup": state["backup"] + 0.3}   # nonzero (data - backup)
    delta = jnp.asarray(np.random.default_rng(1)
                        .normal(size=(6, 4)).astype(np.float32))

    def run(lam, stale):
        opt = AddOption(worker_id=1, learning_rate=0.1, lambda_=lam,
                        staleness=stale).scalars()
        d, s = upd.update_dense(data, dict(state), delta, opt)
        return np.asarray(d)

    assert np.array_equal(run(0.5, 3.0), run(1.5, -1.0))      # 0.5*3
    assert np.array_equal(run(0.5, 1.0), run(0.5, -1.0))      # tau=1 = fixed
    assert np.array_equal(run(0.5, 0.0), run(0.0, -1.0))      # fresh: off


def test_sync_coordinator_lag_measured():
    from multiverso_tpu.core.sync_coordinator import SyncCoordinator

    sc = SyncCoordinator(3, name="lagt")
    for _ in range(2):                      # worker 0 commits 2 adds
        sc.acquire_add(0)
        sc.commit_add(0)
    sc.acquire_add(1)
    sc.commit_add(1)                        # worker 1 commits 1
    assert sc.lag(0) == 0.0
    assert sc.lag(1) == 1.0
    assert sc.lag(2) == 2.0
    sc.finish_train(2)
    assert sc.lag(2) == 0.0                 # retired: nothing to be stale


def test_bsp_add_stamps_measured_staleness():
    """End to end: -sync + -staleness_adaptive, two workers, a dcasgd
    table — the straggler's add is dispatched with its measured lag, so
    its params differ from the unarmed run exactly as lambda*lag
    predicts."""
    results = {}
    for armed in (False, True):
        argv = ["-sync=true"]
        if armed:
            argv.append("-staleness_adaptive=true")
        mv.init(argv, num_local_workers=2)
        try:
            t = mv.create_table(mv.ArrayTableOption(size=4,
                                                    updater="dcasgd"))
            g = np.array([1.0, -2.0, 0.5, 3.0], dtype=np.float32)
            # Homogeneous BSP loop: each round both workers add then get.
            for _ in range(3):
                for w in (0, 1):
                    t.add(g * (1 + w),
                          mv.AddOption(worker_id=w, learning_rate=0.1,
                                       lambda_=0.5))
                for w in (0, 1):
                    t.get(mv.GetOption(worker_id=w))
            results[armed] = t.get().copy()
        finally:
            mv.shutdown()
    # Worker 1 always adds at lag 1 (worker 0 committed first): armed run
    # keeps lambda_eff = lambda * 1 == lambda for it, but worker 0 adds at
    # lag 0 -> compensation OFF for it, so trajectories must diverge.
    assert not np.array_equal(results[False], results[True])
    assert np.all(np.isfinite(results[True]))


def test_ps_service_wire_option_staleness_roundtrip():
    """DCN leg: the 6th wire scalar round-trips; legacy 5-scalar blobs
    read as unmeasured; the service-side stamp arms only for
    staleness-aware updaters under the flag."""
    import types

    from multiverso_tpu.core.options import AddOption
    from multiverso_tpu.core.updater import get_updater
    from multiverso_tpu.parallel.ps_service import (PSService,
                                                    _opt_from_array,
                                                    _opt_to_array)

    # exactly-representable f32 values so the wire round-trip compares ==
    opt = AddOption(worker_id=3, momentum=0.5, learning_rate=0.25,
                    rho=0.125, lambda_=0.75, staleness=2.0)
    arr = _opt_to_array(opt)
    assert arr.shape == (6,)
    back = _opt_from_array(arr)
    assert back == opt
    legacy = _opt_from_array(arr[:5])           # older peer
    assert legacy.staleness == -1.0

    # service-side stamping off the dispatcher's add-lag counts
    svc = object.__new__(PSService)             # no sockets needed
    svc._top_add_count = 7
    svc._worker_add_counts = {3: 4}
    store = types.SimpleNamespace(updater=get_updater(np.float32,
                                                      "dcasgd"))
    plain = AddOption(worker_id=3)
    assert svc._maybe_stamp_staleness(store, plain).staleness == -1.0
    mv.set_flag("staleness_adaptive", True)
    stamped = svc._maybe_stamp_staleness(store, plain)
    assert stamped.staleness == 3.0             # 7 - 4
    # already-stamped options pass through; non-aware updaters too
    assert svc._maybe_stamp_staleness(store, stamped).staleness == 3.0
    sgd_store = types.SimpleNamespace(updater=get_updater(np.float32,
                                                          "sgd"))
    assert svc._maybe_stamp_staleness(sgd_store, plain).staleness == -1.0
