"""LDA (lightLDA-style PS workload) tests."""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.models.lda import LDA, LDAConfig


def _planted_corpus(n_docs=60, doc_len=50, seed=0):
    """Two planted topics: words 0-9 vs words 10-19; each doc draws from
    one topic only."""
    rng = np.random.default_rng(seed)
    words, docs = [], []
    for d in range(n_docs):
        lo = 0 if d % 2 == 0 else 10
        w = rng.integers(lo, lo + 10, size=doc_len)
        words.extend(w.tolist())
        docs.extend([d] * doc_len)
    return np.asarray(words), np.asarray(docs), n_docs, 20


def test_lda_recovers_planted_topics(mv_env):
    words, docs, D, V = _planted_corpus()
    cfg = LDAConfig(num_topics=2, iterations=30, alpha=0.5, beta=0.1,
                    block_tokens=1 << 12, seed=1)
    lda = LDA(cfg, num_docs=D, vocab_size=V)
    lda.train(words, docs)
    dist = lda.topic_word()        # [2, 20]
    # Each topic should concentrate on one of the two word groups.
    mass_low = dist[:, :10].sum(axis=1)    # P(words 0-9 | topic)
    # one topic mostly low words, the other mostly high words
    lo_topic = int(np.argmax(mass_low))
    hi_topic = 1 - lo_topic
    assert mass_low[lo_topic] > 0.85
    assert mass_low[hi_topic] < 0.15
    # top words agree
    top_lo = set(lda.top_words(lo_topic, 10))
    assert len(top_lo & set(range(10))) >= 8


def test_lda_count_conservation(mv_env):
    """Total counts in the tables must equal the number of tokens after any
    number of sweeps (deltas conserve mass)."""
    words, docs, D, V = _planted_corpus(n_docs=20, doc_len=30)
    cfg = LDAConfig(num_topics=4, iterations=5, block_tokens=256, seed=2)
    lda = LDA(cfg, num_docs=D, vocab_size=V)
    lda.train(words, docs)
    n_tokens = len(words)
    assert lda.word_topic.get().sum() == pytest.approx(n_tokens)
    assert lda.topic.get().sum() == pytest.approx(n_tokens)
    assert lda.doc_topic.sum() == pytest.approx(n_tokens)


def test_lda_pushes_scale_with_touched_rows_not_vocab(mv_env):
    """lightLDA scale (VERDICT r3 #7): per-block word-topic pushes carry
    O(unique words in block) rows, never the dense [V, K] table — at
    V=100K a dense push would be 100K rows per block."""
    import multiverso_tpu as mv_mod  # noqa: F401 - fixture resets state
    from multiverso_tpu.models.lda import LDA, LDAConfig

    V, K = 100_000, 8
    rng = np.random.default_rng(0)
    # 512 tokens drawn from a 50-word active vocabulary inside V=100K
    active = rng.choice(V, size=50, replace=False)
    words = rng.choice(active, size=512)
    docs = rng.integers(0, 4, size=512)

    cfg = LDAConfig(num_topics=K, iterations=2, block_tokens=256, seed=0)
    lda = LDA(cfg, num_docs=4, vocab_size=V)

    pushed = []
    orig = lda.word_topic.add_rows

    def spy(rows, deltas, *a, **k):
        pushed.append(np.asarray(deltas).shape)
        return orig(rows, deltas, *a, **k)

    lda.word_topic.add_rows = spy
    lda.train(words, docs)

    assert pushed, "no row pushes recorded"
    for shape in pushed:
        assert shape[0] <= 50, \
            f"push carried {shape[0]} rows for a 50-word block (V={V})"
        assert shape[1] == K
