"""LDA (lightLDA-style PS workload) tests."""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.models.lda import LDA, LDAConfig


def _planted_corpus(n_docs=60, doc_len=50, seed=0):
    """Two planted topics: words 0-9 vs words 10-19; each doc draws from
    one topic only."""
    rng = np.random.default_rng(seed)
    words, docs = [], []
    for d in range(n_docs):
        lo = 0 if d % 2 == 0 else 10
        w = rng.integers(lo, lo + 10, size=doc_len)
        words.extend(w.tolist())
        docs.extend([d] * doc_len)
    return np.asarray(words), np.asarray(docs), n_docs, 20


def test_lda_recovers_planted_topics(mv_env):
    words, docs, D, V = _planted_corpus()
    cfg = LDAConfig(num_topics=2, iterations=30, alpha=0.5, beta=0.1,
                    block_tokens=1 << 12, seed=1)
    lda = LDA(cfg, num_docs=D, vocab_size=V)
    lda.train(words, docs)
    dist = lda.topic_word()        # [2, 20]
    # Each topic should concentrate on one of the two word groups.
    mass_low = dist[:, :10].sum(axis=1)    # P(words 0-9 | topic)
    # one topic mostly low words, the other mostly high words
    lo_topic = int(np.argmax(mass_low))
    hi_topic = 1 - lo_topic
    assert mass_low[lo_topic] > 0.85
    assert mass_low[hi_topic] < 0.15
    # top words agree
    top_lo = set(lda.top_words(lo_topic, 10))
    assert len(top_lo & set(range(10))) >= 8


def test_lda_count_conservation(mv_env):
    """Total counts in the tables must equal the number of tokens after any
    number of sweeps (deltas conserve mass)."""
    words, docs, D, V = _planted_corpus(n_docs=20, doc_len=30)
    cfg = LDAConfig(num_topics=4, iterations=5, block_tokens=256, seed=2)
    lda = LDA(cfg, num_docs=D, vocab_size=V)
    lda.train(words, docs)
    n_tokens = len(words)
    assert lda.word_topic.get().sum() == pytest.approx(n_tokens)
    assert lda.topic.get().sum() == pytest.approx(n_tokens)
    assert lda.doc_topic.sum() == pytest.approx(n_tokens)
