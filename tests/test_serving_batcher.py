"""Batcher unit tests: bucket selection, max-wait flush, deadline-aware
shed ordering, admission bound, and the no-retrace contract (jit cache
size == buckets exercised)."""

import threading
import time

import numpy as np
import pytest

from multiverso_tpu.serving import BucketLadder, DynamicBatcher, ShedError


class RecordingRunner:
    """Runner double: records every (batch shape, lengths) it was handed
    and parrots the payload back; optional per-batch delay to force
    queueing."""

    name = "recording"
    payload_dtype = np.int32
    pad_id = 0

    def __init__(self, delay_s: float = 0.0):
        self.calls = []
        self.delay_s = delay_s
        self._shapes = set()

    def run(self, batch, lengths):
        self.calls.append((batch.shape, lengths.copy()))
        self._shapes.add(batch.shape)
        if self.delay_s:
            time.sleep(self.delay_s)
        return batch.copy()

    def slice_result(self, out, i, length):
        return out[i, :length]

    def jit_cache_size(self):
        # the double's "compiled executable" count: distinct shapes seen
        return len(self._shapes)


def test_bucket_ladder_selection():
    ladder = BucketLadder([32, 8, 16, 8])
    assert ladder.buckets == (8, 16, 32)
    assert ladder.pick(1) == 8
    assert ladder.pick(8) == 8
    assert ladder.pick(9) == 16
    assert ladder.pick(32) == 32
    assert ladder.pick(33) is None
    assert ladder.max == 32


def test_coalescing_and_padding():
    runner = RecordingRunner()
    b = DynamicBatcher(runner, buckets=(4, 8), max_batch=4,
                       max_wait_ms=50.0)
    try:
        futs = [b.submit(np.arange(n, dtype=np.int32) + 1,
                         deadline_ms=5000) for n in (2, 3, 4, 5)]
        results = [f.wait(10) for f in futs]
        for n, r in zip((2, 3, 4, 5), results):
            np.testing.assert_array_equal(r, np.arange(n) + 1)
        # all four coalesced into ONE batch, padded to (max_batch, bucket)
        assert len(runner.calls) == 1
        shape, lengths = runner.calls[0]
        assert shape == (4, 8)          # max payload 5 -> bucket 8
        assert sorted(lengths.tolist()) == [2, 3, 4, 5]
    finally:
        b.close()


def test_max_wait_flushes_partial_batch():
    runner = RecordingRunner()
    b = DynamicBatcher(runner, buckets=(8,), max_batch=64,
                       max_wait_ms=20.0)
    try:
        t0 = time.monotonic()
        out = b.submit(np.asarray([7], np.int32), deadline_ms=5000).wait(10)
        dt = time.monotonic() - t0
        np.testing.assert_array_equal(out, [7])
        # flushed on the max-wait timer, not a full batch (and well before
        # any deadline)
        assert dt < 2.0
        assert len(runner.calls) == 1
        assert runner.calls[0][0] == (64, 8)
    finally:
        b.close()


def test_no_retrace_one_executable_per_bucket():
    runner = RecordingRunner()
    b = DynamicBatcher(runner, buckets=(4, 8, 16), max_batch=4,
                       max_wait_ms=1.0)
    try:
        for n in (2, 4, 2, 3):          # all land in bucket 4
            b.submit(np.arange(n, dtype=np.int32), 5000).wait(10)
        assert runner.jit_cache_size() == 1
        b.submit(np.arange(7, dtype=np.int32), 5000).wait(10)   # bucket 8
        assert runner.jit_cache_size() == 2
        for n in (1, 5, 16):
            b.submit(np.arange(n, dtype=np.int32), 5000).wait(10)
        # buckets exercised: 4, 8, 16 -> exactly three compiled shapes
        assert runner.jit_cache_size() == 3
    finally:
        b.close()


def test_oversize_payload_sheds_immediately():
    runner = RecordingRunner()
    b = DynamicBatcher(runner, buckets=(4,), max_batch=2, max_wait_ms=1.0)
    try:
        with pytest.raises(ShedError) as e:
            b.submit(np.arange(9, dtype=np.int32), 5000).wait(10)
        assert e.value.reason == "oversize"
        assert not runner.calls
    finally:
        b.close()


def test_admission_bound_sheds_nearest_deadline_first():
    """Overfill a stalled queue: the requests shed are exactly the ones
    with the nearest deadlines — the deadline-aware ordering — and the
    queue never exceeds the admission bound."""
    runner = RecordingRunner(delay_s=0.25)
    b = DynamicBatcher(runner, buckets=(4,), max_batch=2, max_wait_ms=0.0,
                       max_queue=4)
    try:
        # Plug the worker with one slow batch so later submits queue up.
        plug = [b.submit(np.asarray([0], np.int32), deadline_ms=30_000)
                for _ in range(2)]
        time.sleep(0.05)                # worker picked up the plug batch
        # 8 requests into a 4-slot queue. Deadlines descend: the LAST
        # submits have the tightest deadlines and must be the shed ones.
        futs = []
        for i in range(8):
            deadline_ms = 30_000 - 3000 * i
            futs.append((i, b.submit(np.asarray([i], np.int32),
                                     deadline_ms=deadline_ms)))
        outcomes = {}
        for i, f in futs:
            try:
                f.wait(20)
                outcomes[i] = "served"
            except ShedError as e:
                outcomes[i] = e.reason
        for f in plug:
            f.wait(20)
        shed = sorted(i for i, o in outcomes.items() if o != "served")
        served = sorted(i for i, o in outcomes.items() if o == "served")
        assert len(shed) == 4, outcomes
        # nearest-deadline (latest-submitted here) requests were shed
        assert shed == [4, 5, 6, 7], outcomes
        assert served == [0, 1, 2, 3], outcomes
        assert all(outcomes[i] == "queue_full" for i in shed)
    finally:
        b.close()


def test_expired_requests_shed_not_served():
    """A request whose deadline passes while queued is shed at batch
    formation instead of burning device time."""
    runner = RecordingRunner(delay_s=0.3)
    b = DynamicBatcher(runner, buckets=(4,), max_batch=1, max_wait_ms=0.0)
    try:
        plug = b.submit(np.asarray([0], np.int32), deadline_ms=30_000)
        time.sleep(0.05)
        doomed = b.submit(np.asarray([1], np.int32), deadline_ms=1.0)
        with pytest.raises(ShedError) as e:
            doomed.wait(20)
        assert e.value.reason == "deadline"
        plug.wait(20)
        # the expired request never reached the runner
        assert all(0 in lengths or lengths[0] == 1
                   for shape, lengths in runner.calls)
        served_payloads = [l.tolist() for _, l in runner.calls]
        assert all(l != [1] or True for l in served_payloads)
        assert len(runner.calls) == 1   # only the plug batch ran
    finally:
        b.close()


def test_queue_stays_bounded_under_sustained_overload():
    """Acceptance: QPS above the admission bound sheds instead of growing
    the queue without bound."""
    from multiverso_tpu.telemetry import get_registry

    runner = RecordingRunner(delay_s=0.02)
    bound = 8
    b = DynamicBatcher(runner, buckets=(4,), max_batch=2, max_wait_ms=0.0,
                       max_queue=bound)
    served = []
    shed = []
    lock = threading.Lock()

    def on_done(result):
        with lock:
            (shed if isinstance(result, ShedError) else served).append(1)

    try:
        for _ in range(300):
            b.submit_callback(np.asarray([1], np.int32), 10_000.0, on_done)
            with b._cv:
                assert len(b._queue) <= bound
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with lock:
                if len(served) + len(shed) == 300:
                    break
            time.sleep(0.01)
        with lock:
            assert len(served) + len(shed) == 300
            assert shed, "overload never shed"
            assert served, "overload served nothing"
        snap = get_registry().snapshot(buckets=False)
        assert snap["counters"]["serve.shed.queue_full"]["value"] > 0
        assert snap["gauges"]["serve.queue_depth"]["max"] <= bound
    finally:
        b.close()


def test_close_releases_queued_requests():
    runner = RecordingRunner(delay_s=0.2)
    b = DynamicBatcher(runner, buckets=(4,), max_batch=1, max_wait_ms=0.0)
    b.submit(np.asarray([0], np.int32), 30_000)
    time.sleep(0.05)
    tail = b.submit(np.asarray([1], np.int32), 30_000)
    b.close()
    with pytest.raises(ShedError):
        tail.wait(10)


def test_queue_bound_sums_across_batchers_and_shrinks_on_close():
    """ISSUE-14 satellite: `serve.queue_bound`/`serve.queue_depth` used
    to be last-writer-wins — with two batchers the saturation alert
    compared one batcher's depth against the OTHER's bound. The
    unlabeled gauges are now sums over live batchers (each also exports
    a slot-labeled `.batcher_<i>` pair), and a closed batcher leaves
    the aggregate coherent."""
    from multiverso_tpu.telemetry import get_registry
    reg = get_registry()
    a = DynamicBatcher(RecordingRunner(), buckets=(4,), max_queue=64)
    b = DynamicBatcher(RecordingRunner(), buckets=(4,), max_queue=16)
    try:
        assert reg.gauge("serve.queue_bound").last == 64 + 16
        labels = {a._slot, b._slot}
        assert len(labels) == 2, "each batcher owns a distinct slot"
        assert reg.gauge(
            f"serve.queue_bound.batcher_{a._slot}").last == 64
        assert reg.gauge(
            f"serve.queue_bound.batcher_{b._slot}").last == 16
    finally:
        b.close()
    assert reg.gauge("serve.queue_bound").last == 64, \
        "closing a batcher must shrink the summed bound"
    slot_b = [s for s in labels if s != a._slot][0]
    assert reg.gauge(f"serve.queue_bound.batcher_{slot_b}").last == 0
    # The freed slot is REUSED: labeled-gauge cardinality is bounded by
    # peak concurrency, not by batcher churn.
    c = DynamicBatcher(RecordingRunner(), buckets=(4,), max_queue=8)
    try:
        assert c._slot == slot_b
        assert reg.gauge("serve.queue_bound").last == 64 + 8
    finally:
        c.close()
        a.close()
    assert reg.gauge("serve.queue_bound").last == 0


def test_double_close_keeps_queue_totals_and_slots_coherent():
    """close() is idempotent: an explicit close followed by a service
    close (a normal shutdown sequence) must not subtract the batcher's
    bound from the shared totals twice, nor re-free a slot a NEWER
    batcher has since reused."""
    from multiverso_tpu.telemetry import get_registry
    reg = get_registry()
    a = DynamicBatcher(RecordingRunner(), buckets=(4,), max_queue=64)
    b = DynamicBatcher(RecordingRunner(), buckets=(4,), max_queue=16)
    a.close()
    c = DynamicBatcher(RecordingRunner(), buckets=(4,), max_queue=8)
    assert c._slot == a._slot, "c reuses a's freed slot"
    a.close()   # second close: must be a no-op
    try:
        assert reg.gauge("serve.queue_bound").last == 16 + 8, \
            "double close must not subtract a's bound twice"
        # a second acquisition must NOT be handed c's still-live slot
        d = DynamicBatcher(RecordingRunner(), buckets=(4,), max_queue=4)
        try:
            assert d._slot != c._slot
        finally:
            d.close()
    finally:
        b.close()
        c.close()
    assert reg.gauge("serve.queue_bound").last == 0
