"""Expert-parallel MoE vs per-token reference; sharded over the expert
axis."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from multiverso_tpu.parallel.expert import (MoEParams, init_moe,
                                            reference_top1_moe, top1_moe)


def test_moe_matches_per_token_reference():
    key = jax.random.PRNGKey(0)
    params = init_moe(key, dim=16, hidden=32, num_experts=4)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
    y, aux = top1_moe(params, x, capacity_factor=2.0)
    expected = reference_top1_moe(params, x, capacity_factor=2.0)
    np.testing.assert_allclose(np.asarray(y), expected, rtol=2e-3,
                               atol=2e-4)
    assert float(aux) > 0


def test_moe_capacity_drops_overflow():
    """With tiny capacity, overflow tokens produce zero output (standard
    top-1 drop semantics)."""
    key = jax.random.PRNGKey(0)
    params = init_moe(key, dim=8, hidden=16, num_experts=2)
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 16, 8))
    y, _ = top1_moe(params, x, capacity_factor=0.25)   # capacity 2/expert
    expected = reference_top1_moe(params, x, capacity_factor=0.25)
    np.testing.assert_allclose(np.asarray(y), expected, rtol=2e-3,
                               atol=2e-4)
    # some token rows are exactly zero (dropped)
    flat = np.asarray(y).reshape(-1, 8)
    assert (np.abs(flat).sum(axis=1) == 0).any()


def test_moe_expert_sharded_under_jit():
    """Expert weights sharded over an 8-way 'expert' axis; jitted forward
    and gradient both execute."""
    devices = jax.devices()
    mesh = Mesh(np.asarray(devices), ("expert",))
    params = init_moe(jax.random.PRNGKey(0), dim=16, hidden=32,
                      num_experts=8, mesh=mesh)
    assert len(params.w1.sharding.device_set) == 8
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16))

    @jax.jit
    def loss_fn(w1, w2, router, x):
        y, aux = top1_moe(MoEParams(router, w1, w2), x)
        return (y ** 2).mean() + 0.01 * aux

    loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
        params.w1, params.w2, params.router, x)
    assert np.isfinite(float(loss))
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()
