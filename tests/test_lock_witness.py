"""graftsan runtime side: the lock witness, and the static<->runtime
cross-check that is the subsystem's whole point.

Mechanics first (zero overhead off, edges, hold histograms, reentrancy,
condition integration, inversion detection, cross-process ledger merge),
then the two regression tests for the real bugs the static triage found
(serving registry lock held across batcher build; the router-feed lock
monopolized by an in-flight fetch), a concurrent stress of the
HotRowCache/CacheAutosizer under the witness, and finally the tier-1
scenario: a train+serve+fleet workload under the witness must observe
ZERO lock-order inversions, and every statically-claimed cross-module
edge (``analysis.interproc.cross_module_witness_claims``) must either be
observed live or carry a reasoned suppression below.
"""

import os
import socket
import threading
import time

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def witness():
    """Witness ON for locks constructed inside the test; always restored
    (the autouse telemetry reset clears the ledger afterwards)."""
    from multiverso_tpu.telemetry.lockwitness import reset_lockwitness
    from multiverso_tpu.utils.locks import set_witness_enabled
    set_witness_enabled(True)
    reset_lockwitness()
    yield
    set_witness_enabled(None)


# ---------------------------------------------------------------------------
# Zero overhead when off — by construction, not by measurement
# ---------------------------------------------------------------------------
def test_witness_off_returns_bare_primitives():
    from multiverso_tpu.utils.locks import (make_condition, make_lock,
                                            make_rlock,
                                            set_witness_enabled,
                                            witness_enabled)
    set_witness_enabled(None)
    assert not witness_enabled()
    # The factory returns the exact threading type: no wrapper frame,
    # no extra attribute, nothing for the hot path to pay for.
    assert type(make_lock("off.x")) is type(threading.Lock())
    assert type(make_rlock("off.x")) is type(threading.RLock())
    cv = make_condition("off.x")
    assert type(cv) is threading.Condition
    # and nothing was registered in the ledger
    from multiverso_tpu.telemetry.lockwitness import observed_locks
    assert "off.x" not in observed_locks()


def test_witness_forced_on_returns_instrumented_locks(witness):
    from multiverso_tpu.telemetry.lockwitness import (WitnessCondition,
                                                      WitnessLock,
                                                      WitnessRLock)
    from multiverso_tpu.utils.locks import (make_condition, make_lock,
                                            make_rlock)
    assert isinstance(make_lock("on.x"), WitnessLock)
    assert isinstance(make_rlock("on.x"), WitnessRLock)
    assert isinstance(make_condition("on.x"), WitnessCondition)


# ---------------------------------------------------------------------------
# Ledger mechanics
# ---------------------------------------------------------------------------
def test_edges_and_hold_histograms_recorded(witness):
    from multiverso_tpu.telemetry import get_registry
    from multiverso_tpu.telemetry.lockwitness import observed_edges
    from multiverso_tpu.utils.locks import make_lock
    a, b = make_lock("t.a"), make_lock("t.b")
    with a:
        with b:
            pass
    with a:         # second solo acquisition: hold time only, no edge
        pass
    edges = observed_edges()
    assert edges[("t.a", "t.b")] == 1
    assert ("t.b", "t.a") not in edges
    hists = get_registry().snapshot()["histograms"]
    assert hists["lock.t.a.held_ms"]["count"] == 2
    assert hists["lock.t.b.held_ms"]["count"] == 1


def test_rlock_owner_reacquire_records_no_self_edge(witness):
    from multiverso_tpu.telemetry.lockwitness import observed_edges
    from multiverso_tpu.utils.locks import make_rlock
    r = make_rlock("t.r")
    with r:
        with r:     # owner re-acquire cannot deadlock: no edge
            pass
    assert ("t.r", "t.r") not in observed_edges()


def test_condition_wait_integration(witness):
    """wait() fully releases the witnessed RLock (held-stack stays
    exact), the park lands in ``lock.<name>.wait_ms``, and edges taken
    while holding the cv's lock are attributed to its name."""
    from multiverso_tpu.telemetry import get_registry
    from multiverso_tpu.telemetry.lockwitness import observed_edges
    from multiverso_tpu.utils.locks import make_condition, make_lock
    cv = make_condition("t.cv")
    other = make_lock("t.other")
    ready = []

    def consumer():
        with cv:
            while not ready:
                cv.wait(1.0)
            with other:         # edge: t.cv -> t.other
                pass

    t = threading.Thread(target=consumer, daemon=True)
    t.start()
    time.sleep(0.05)
    with cv:
        ready.append(1)
        cv.notify()
    t.join(5)
    assert not t.is_alive()
    assert observed_edges().get(("t.cv", "t.other")) == 1
    hists = get_registry().snapshot()["histograms"]
    assert hists["lock.t.cv.wait_ms"]["count"] >= 1


def test_inversion_detection_counts_and_cycles(witness):
    from multiverso_tpu.telemetry import get_registry
    from multiverso_tpu.telemetry.lockwitness import check_inversions
    from multiverso_tpu.utils.locks import make_lock
    a, b = make_lock("inv.a"), make_lock("inv.b")
    with a:
        with b:
            pass
    with b:
        with a:     # the inverted order, witnessed
            pass
    cycles = check_inversions(postmortem=False)
    assert cycles == [("inv.a", "inv.b")]
    counters = get_registry().snapshot()["counters"]
    assert counters["lock.inversions"]["value"] >= 1


def test_merge_ledgers_finds_cross_process_inversion(witness):
    """Each process's ledger is acyclic on its own; the inversion exists
    only in the union — exactly what the fleet postmortem merge is for."""
    from multiverso_tpu.telemetry.lockwitness import (LEDGER_SCHEMA,
                                                      find_cycles, ledger,
                                                      merge_ledgers)
    from multiverso_tpu.utils.locks import make_lock
    a, b = make_lock("m.a"), make_lock("m.b")
    with a:
        with b:
            pass
    local = ledger()
    assert local["schema"] == LEDGER_SCHEMA
    assert not find_cycles({(e["src"], e["dst"])
                            for e in local["edges"]})
    remote = {"schema": LEDGER_SCHEMA, "locks": {},
              "edges": [{"src": "m.b", "dst": "m.a", "count": 3,
                         "threads": ["remote-worker"]}]}
    merged = merge_ledgers([local, remote])
    assert merged[("m.a", "m.b")] == 1 and merged[("m.b", "m.a")] == 3
    assert find_cycles(merged.keys()) == [("m.a", "m.b")]


def test_reset_telemetry_clears_the_ledger(witness):
    from multiverso_tpu.telemetry import reset_telemetry
    from multiverso_tpu.telemetry.lockwitness import (observed_edges,
                                                      observed_locks)
    from multiverso_tpu.utils.locks import make_lock
    a, b = make_lock("z.a"), make_lock("z.b")
    with a:
        with b:
            pass
    assert observed_edges()
    reset_telemetry()
    assert observed_edges() == {} and observed_locks() == {}


# ---------------------------------------------------------------------------
# Regression: the two real bugs the static triage found
# ---------------------------------------------------------------------------
def test_register_runner_builds_batcher_outside_registry_lock(
        mv_env, monkeypatch):
    """PR-19 triage finding #1: ``register_runner`` used to hold the
    registry lock across batcher construction (dispatcher threads + the
    pipeline-depth device probe), convoying quiesce()/close() and every
    concurrent registration behind one runner's bring-up. The fix
    reserves the id, builds OUTSIDE the lock, publishes under it."""
    import multiverso_tpu.serving.service as service_mod
    gate = threading.Event()
    entered = threading.Event()

    class StubBatcher:
        def __init__(self, runner, buckets, **kw):
            entered.set()
            assert gate.wait(10), "test gate never opened"

        def quiesce(self, timeout_s=0.0):
            return True

        def close(self):
            pass

    monkeypatch.setattr(service_mod, "DynamicBatcher", StubBatcher)
    svc = service_mod.ServingService()
    try:
        t = threading.Thread(
            target=lambda: svc.register_runner(object(), runner_id=7,
                                               continuous=False),
            daemon=True)
        t.start()
        assert entered.wait(5), "batcher build never started"
        # The registry lock must be FREE while the slow build runs ...
        assert svc._lock.acquire(timeout=1.0), \
            "registry lock held across batcher construction"
        svc._lock.release()
        # ... and the id must already be reserved: a duplicate register
        # fails fast instead of double-building.
        with pytest.raises(Exception, match="already registered"):
            svc.register_runner(object(), runner_id=7, continuous=False)
        gate.set()
        t.join(5)
        assert not t.is_alive()
        assert 7 in svc._batchers      # published after the build
    finally:
        gate.set()
        svc.close()


def test_register_runner_failed_build_unreserves_the_id(
        mv_env, monkeypatch):
    import multiverso_tpu.serving.service as service_mod

    class ExplodingBatcher:
        def __init__(self, runner, buckets, **kw):
            raise RuntimeError("boom")

    monkeypatch.setattr(service_mod, "DynamicBatcher", ExplodingBatcher)
    svc = service_mod.ServingService()
    try:
        with pytest.raises(RuntimeError, match="boom"):
            svc.register_runner(object(), runner_id=3, continuous=False)
        assert 3 not in svc._runners and 3 not in svc._batchers
    finally:
        svc.close()


def test_router_feed_control_ops_not_blocked_by_inflight_fetch():
    """PR-19 triage finding #2: ``_RouterFeed`` used one lock for both
    the socket exchange and the tiny control state, so a fetch parked in
    recv (or a 4-attempt backoff dial) blocked ``consume_reconnected``
    and made ``close()`` wait out the exchange. Split locks: control
    ops return promptly, and close() interrupts the in-flight fetch."""
    from multiverso_tpu.fleet.client import _RouterFeed
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    feed = _RouterFeed(srv.getsockname())
    errs = []

    def run_fetch():
        try:
            feed.fetch()
        except (IOError, OSError) as e:
            errs.append(e)

    t = threading.Thread(target=run_fetch, daemon=True)
    t.start()
    conn, _ = srv.accept()          # fetch dialed; it now parks in recv
    try:
        time.sleep(0.05)
        t0 = time.monotonic()
        feed.consume_reconnected()  # control op: must not wait out recv
        assert time.monotonic() - t0 < 1.0, \
            "consume_reconnected blocked behind an in-flight fetch"
        feed.close()                # must interrupt the parked recv
        t.join(5)
        assert not t.is_alive(), "close() did not interrupt the fetch"
        assert errs, "interrupted fetch should surface an OSError"
        # closed-for-good: the next fetch fails fast, no re-dial
        with pytest.raises(OSError):
            feed.fetch()
    finally:
        conn.close()
        srv.close()


# ---------------------------------------------------------------------------
# Concurrent stress: HotRowCache resize vs lookup vs budget clamp
# ---------------------------------------------------------------------------
def test_hot_row_cache_stress_resize_lookup_clamp(witness, mv_env):
    """Three mutators racing the cache for ~0.5s under the witness:
    lookups+inserts, explicit resizes, and autosizer budget clamps. No
    exceptions, the capacity invariant holds throughout, and the
    witness observes no lock-order inversion around ``serve.cache``."""
    from multiverso_tpu.serving.cache import CacheAutosizer, HotRowCache
    from multiverso_tpu.telemetry.lockwitness import check_inversions
    cache = HotRowCache(capacity=128)
    sizer = CacheAutosizer(cache, mem_budget=1 << 20, windows=1,
                           cooldown_s=0.0, min_rows=16)
    stop = time.monotonic() + 0.5
    failures = []

    def guard(fn):
        try:
            while time.monotonic() < stop:
                fn()
        except Exception as e:  # noqa: BLE001 - collected for the assert
            failures.append(e)

    rng = np.random.default_rng(7)

    def lookups():
        keys = rng.integers(0, 512, size=8).astype(np.int64)
        rows = rng.normal(size=(8, 4)).astype(np.float32)
        cache.put_rows(keys, rows, clock=1.0)
        cache.get_rows(keys, now_clock=1.0)
        assert len(cache) <= max(cache.capacity, 1)

    def resizes():
        cache.resize(64)
        cache.resize(256)

    fake_now = [0.0]

    def clamps():
        fake_now[0] += 10.0
        sizer.on_advice({"predicted_hit_rate": 0.5,
                         "predicted_hit_rate_2x": 0.9},
                        now=fake_now[0])

    threads = [threading.Thread(target=guard, args=(fn,), daemon=True)
               for fn in (lookups, lookups, resizes, clamps)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
        assert not t.is_alive()
    assert not failures, failures
    assert len(cache) <= cache.capacity
    assert cache.capacity >= sizer.min_rows
    assert check_inversions(postmortem=False) == []


# ---------------------------------------------------------------------------
# The tier-1 cross-check scenario: train + serve + fleet under the witness
# ---------------------------------------------------------------------------
#: Statically-claimed cross-module edges the scenario deliberately does
#: NOT exercise, each with the reason. An entry here is a conscious
#: decision reviewed with the PR — NOT a way to make the test pass.
#: Keys are (src_witness, dst_witness).
REASONED_SUPPRESSIONS = {
    # (currently empty: every static cross-module claim is exercised
    # live below — keep it that way when possible)
}

ROWS, COLS = 256, 8


def test_witness_scenario_train_serve_fleet(witness, mv_env, tmp_path):
    import jax
    from jax.sharding import Mesh

    from multiverso_tpu.analysis.interproc import \
        cross_module_witness_claims
    from multiverso_tpu.core.table import ServerStore
    from multiverso_tpu.core.updater import get_updater
    from multiverso_tpu.core.wal import WriteAheadLog
    from multiverso_tpu.fleet import FleetClient, FleetMember, FleetRouter
    from multiverso_tpu.fleet.client import request_drain
    from multiverso_tpu.serving import ServingService, SparseLookupRunner
    from multiverso_tpu.telemetry import get_registry
    from multiverso_tpu.telemetry.lockwitness import (check_inversions,
                                                      observed_edges)

    # -- train plane: WAL group commit under the witnessed lock pair ----
    wal = WriteAheadLog(str(tmp_path / "wal"))
    for i in range(64):
        wal.append(b"rec-%03d" % i)
    wal.append(b"sync", sync=True)
    wal.close()

    # -- serve + fleet planes: router + two replicas + routed client ----
    rng = np.random.default_rng(0)
    data = rng.normal(size=(ROWS, COLS)).astype(np.float32)
    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1), ("server",))
    router = FleetRouter(heartbeat_ms=40.0, liveness_misses=5, proxy=True)
    services, members, cli = [], [], None
    try:
        for i in range(2):
            store = ServerStore(f"wit_t{i}", (ROWS, COLS), np.float32,
                                get_updater(np.float32, "default"), mesh,
                                num_workers=1, init_array=data.copy())
            svc = ServingService()
            svc.register_runner(SparseLookupRunner(store), buckets=(4, 8),
                                max_batch=4, max_wait_ms=1.0)
            svc.warmup()
            services.append(svc)
            members.append(FleetMember(router.address, svc,
                                       member_id=f"r{i}").start())
        deadline = time.monotonic() + 20
        while len(router.group.member_ids()) < 2:
            assert time.monotonic() < deadline, "members never joined"
            time.sleep(0.02)

        cli = FleetClient(router.address)
        for _ in range(6):
            keys = rng.integers(0, ROWS, size=5).astype(np.int32)
            got = cli.lookup(keys, deadline_ms=10_000, timeout=30)
            np.testing.assert_array_equal(got, data[keys])

        # Exercise the two statically-claimed cross-module edges live:
        # the router's lazy proxy client (fleet.router -> fleet.client) …
        router._proxy()
        # … and the wire drain trigger's membership check under the
        # router lock (fleet.router -> fleet.membership).
        ack = request_drain(router.address, member_id="no-such-member",
                            timeout_s=1.0)
        assert ack["started"] is False
    finally:
        if cli is not None:
            cli.close()
        for m in members:
            m.close()
        for s in services:
            s.close()
        router.close()

    # -- verdict (a): ZERO observed lock-order inversions ---------------
    edges = observed_edges()
    assert edges, "scenario recorded no acquisition-order edges at all"
    cycles = check_inversions(postmortem=False)
    assert cycles == [], (
        "witnessed lock-order inversion(s): "
        + "; ".join(" -> ".join(c + (c[0],)) for c in cycles))

    # -- verdict (b): every static cross-module claim observed live -----
    claims = cross_module_witness_claims(
        [os.path.join(_REPO, "multiverso_tpu")], _REPO)
    assert claims, "static side produced no cross-module claims — " \
                   "the call graph or the witness-name join broke"
    unmatched = []
    for c in claims:
        key = (c.src_witness, c.dst_witness)
        if key in edges or key in REASONED_SUPPRESSIONS:
            continue
        unmatched.append(f"{key[0]} -> {key[1]} "
                         f"(claimed at {c.rel}:{c.line} via {c.via})")
    assert not unmatched, (
        "statically-claimed cross-module edges never observed live — "
        "exercise them in this scenario or add a reasoned suppression:\n"
        + "\n".join(unmatched))

    # -- and the lock.* histogram family actually populated -------------
    hists = get_registry().snapshot()["histograms"]
    for name in ("lock.wal.staging.held_ms", "lock.wal.io.held_ms",
                 "lock.fleet.router.held_ms",
                 "lock.fleet.membership.held_ms"):
        assert hists.get(name, {}).get("count", 0) > 0, \
            f"{name} never observed a hold"
