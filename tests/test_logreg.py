"""LogisticRegression end-to-end tests (the reference's first workload)."""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.models.logreg import (ArrayBatcher, LogReg, LogRegConfig,
                                          SampleReader, parse_libsvm_line)


def _synthetic_binary(n=400, f=10, seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=f)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X @ w_true > 0).astype(np.float32)
    return X, y


def _synthetic_multiclass(n=600, f=8, c=3, seed=1):
    rng = np.random.default_rng(seed)
    W = rng.normal(size=(f, c))
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X @ W).argmax(axis=1).astype(np.float32)
    return X, y


def test_parse_libsvm():
    label, idx, val = parse_libsvm_line("1 3:0.5 17:2.0")
    assert label == 1.0 and idx == [3, 17] and val == [0.5, 2.0]


def test_libsvm_reader_roundtrip(tmp_path, mv_env):
    p = tmp_path / "data.libsvm"
    p.write_text("1 0:1.0 2:3.0\n0 1:2.0\n1 0:0.5\n")
    reader = SampleReader(str(p), num_feature=4, minibatch_size=2,
                          prefetch=True)
    batches = list(reader)
    assert len(batches) == 2
    X0, y0 = batches[0]
    assert X0.shape == (2, 5)  # +bias column
    np.testing.assert_allclose(X0[0], [1.0, 0, 3.0, 0, 1.0])
    np.testing.assert_allclose(y0, [1.0, 0.0])


def test_local_model_converges(mv_env):
    X, y = _synthetic_binary()
    cfg = LogRegConfig(objective="sigmoid", num_feature=10, use_ps=False,
                       learning_rate=1.0, minibatch_size=32)
    lr = LogReg(cfg)
    lr.train(ArrayBatcher(X, y, 32), epochs=20)
    assert lr.test(ArrayBatcher(X, y, 64)) > 0.9


def test_ps_model_converges(mv_env):
    X, y = _synthetic_binary()
    cfg = LogRegConfig(objective="sigmoid", num_feature=10, use_ps=True,
                       learning_rate=1.0, minibatch_size=32,
                       sync_frequency=1)
    lr = LogReg(cfg)
    losses = lr.train(ArrayBatcher(X, y, 32), epochs=20)
    assert losses[-1] < losses[0]
    assert lr.test(ArrayBatcher(X, y, 64)) > 0.9


def test_ps_pipeline_mode(mv_env):
    """Pipelined double-buffered pull must still converge
    (ref ps_model.cpp:236-271)."""
    X, y = _synthetic_binary()
    cfg = LogRegConfig(objective="sigmoid", num_feature=10, use_ps=True,
                       learning_rate=1.0, minibatch_size=32,
                       sync_frequency=2, pipeline=True)
    lr = LogReg(cfg)
    lr.train(ArrayBatcher(X, y, 32), epochs=25)
    assert lr.test(ArrayBatcher(X, y, 64)) > 0.85


def test_softmax_multiclass(mv_env):
    X, y = _synthetic_multiclass()
    cfg = LogRegConfig(objective="softmax", num_feature=8, num_class=3,
                       use_ps=True, learning_rate=1.0, minibatch_size=50)
    lr = LogReg(cfg)
    lr.train(ArrayBatcher(X, y, 50), epochs=25)
    assert lr.test(ArrayBatcher(X, y, 100)) > 0.85


def test_ftrl_objective(mv_env):
    X, y = _synthetic_binary(n=300)
    cfg = LogRegConfig(objective="ftrl", num_feature=10, use_ps=True,
                       minibatch_size=32, ftrl_alpha=0.5, ftrl_beta=1.0,
                       ftrl_l1=0.01, ftrl_l2=0.01)
    lr = LogReg(cfg)
    lr.train(ArrayBatcher(X, y, 32), epochs=15)
    assert lr.test(ArrayBatcher(X, y, 64)) > 0.85


def test_l2_regularization(mv_env):
    X, y = _synthetic_binary(n=200)
    cfg = LogRegConfig(objective="sigmoid", num_feature=10, use_ps=False,
                       learning_rate=1.0, minibatch_size=32,
                       regular="l2", regular_coef=0.5)
    lr = LogReg(cfg)
    lr.train(ArrayBatcher(X, y, 32), epochs=10)
    w_reg = np.abs(lr.model.get_weights()).mean()
    cfg2 = LogRegConfig(objective="sigmoid", num_feature=10, use_ps=False,
                        learning_rate=1.0, minibatch_size=32)
    lr2 = LogReg(cfg2)
    lr2.train(ArrayBatcher(X, y, 32), epochs=10)
    assert w_reg < np.abs(lr2.model.get_weights()).mean()


def test_config_from_file(tmp_path, mv_env):
    p = tmp_path / "logreg.conf"
    p.write_text("objective=softmax\nnum_feature=100\nnum_class=5\n"
                 "learning_rate=0.01\npipeline=true\n# comment\n")
    cfg = LogRegConfig.from_file(str(p))
    assert cfg.objective == "softmax"
    assert cfg.num_feature == 100
    assert cfg.num_class == 5
    assert cfg.learning_rate == 0.01
    assert cfg.pipeline is True


def test_config_reference_key_aliases(tmp_path, mv_env):
    """The reference's own key spellings (configure.h:19-96) are honored."""
    p = tmp_path / "ref.conf"
    p.write_text("input_size=40\noutput_size=3\ntrain_epoch=7\n"
                 "objective_type=softmax\nregular_type=L2\n"
                 "train_file=a.svm\ntest_file=b.svm\noutput_file=o.txt\n"
                 "alpha=0.25\nlambda1=2.5\n")
    cfg = LogRegConfig.from_file(str(p))
    assert cfg.num_feature == 40 and cfg.num_class == 3 and cfg.epochs == 7
    assert cfg.objective == "softmax" and cfg.regular == "l2"
    assert cfg.train_file == "a.svm" and cfg.test_file == "b.svm"
    assert cfg.output_file == "o.txt"
    assert cfg.ftrl_alpha == 0.25 and cfg.ftrl_l1 == 2.5


def test_model_save_load_roundtrip(tmp_path, mv_env):
    """init_model_file / output_model_file (ref configure.h:53,77): saved
    weights warm-start a fresh model with identical predictions, in both
    local and PS modes."""
    X, y = _synthetic_binary()
    for use_ps in (False, True):
        cfg = LogRegConfig(objective="sigmoid", num_feature=10,
                           use_ps=use_ps, learning_rate=1.0,
                           minibatch_size=32)
        lr = LogReg(cfg)
        lr.train(ArrayBatcher(X, y, 32), epochs=5)
        path = tmp_path / f"model_{use_ps}.npy"
        lr.save_model(str(path))

        cfg2 = LogRegConfig(objective="sigmoid", num_feature=10,
                            use_ps=use_ps, init_model_file=str(path))
        lr2 = LogReg(cfg2)
        np.testing.assert_allclose(lr2.model.get_weights(),
                                   lr.model.get_weights(), rtol=1e-6)
        Xb = np.concatenate([X[:16], np.ones((16, 1), X.dtype)], axis=1)
        np.testing.assert_allclose(lr2.predict(Xb), lr.predict(Xb),
                                   rtol=1e-5)


def test_predictions_written(tmp_path, mv_env):
    X, y = _synthetic_binary(n=64)
    cfg = LogRegConfig(objective="sigmoid", num_feature=10, use_ps=False)
    lr = LogReg(cfg)
    lr.train(ArrayBatcher(X, y, 32), epochs=2)
    out = tmp_path / "preds.txt"
    lr.test(ArrayBatcher(X, y, 32), output_path=str(out))
    lines = out.read_text().strip().split("\n")
    assert len(lines) == 64
    float(lines[0])  # parseable


def test_bsparse_binary_roundtrip(tmp_path, mv_env):
    """Reference bsparse format (configure.h:67-69): count(u64) label(i32)
    weight(f64) keys(u64...) per sample — write, stream back, and batch
    through the reader with weight-scaled implicit-1 features."""
    from multiverso_tpu.models.logreg.reader import (read_bsparse,
                                                     write_bsparse)
    p = tmp_path / "samples.bin"
    samples = [(1.0, 2.0, [0, 3]), (0.0, 1.0, [1]), (1.0, 0.5, [2, 3])]
    assert write_bsparse(str(p), samples) == 3

    back = list(read_bsparse(str(p)))
    assert [(l, w, list(k)) for l, w, k in back] == \
        [(1.0, 2.0, [0, 3]), (0.0, 1.0, [1]), (1.0, 0.5, [2, 3])]

    reader = SampleReader(str(p), num_feature=4, minibatch_size=3,
                          input_format="bsparse", bias=True,
                          prefetch=False)
    (X, y), = list(reader)
    assert X.shape == (3, 5) and y.tolist() == [1.0, 0.0, 1.0]
    np.testing.assert_allclose(X[0], [2.0, 0, 0, 2.0, 1.0])  # w=2 features
    np.testing.assert_allclose(X[1], [0, 1.0, 0, 0, 1.0])
    np.testing.assert_allclose(X[2], [0, 0, 0.5, 0.5, 1.0])


def test_weight_text_format(tmp_path, mv_env):
    """label:weight key:value ... — values scale by the sample weight
    (ref WeightedSampleReader, reader.cpp:243-281)."""
    p = tmp_path / "w.txt"
    p.write_text("1:2.0 0:1.5 2:1.0\n0:0.5 1:4.0\n")
    reader = SampleReader(str(p), num_feature=3, minibatch_size=2,
                          input_format="weight", bias=True, prefetch=False)
    (X, y), = list(reader)
    assert y.tolist() == [1.0, 0.0]
    np.testing.assert_allclose(X[0], [3.0, 0, 2.0, 1.0])
    np.testing.assert_allclose(X[1], [0, 2.0, 0, 1.0])


def test_bsparse_trains_end_to_end(tmp_path, mv_env):
    """A model trains from a binary sample file exactly as from libsvm."""
    from multiverso_tpu.models.logreg.reader import write_bsparse
    rng = np.random.default_rng(0)
    # two separable classes on binary features
    samples = []
    for _ in range(200):
        if rng.random() < 0.5:
            samples.append((1.0, 1.0, [0, 1]))
        else:
            samples.append((0.0, 1.0, [2, 3]))
    p = tmp_path / "train.bin"
    write_bsparse(str(p), samples)
    cfg = LogRegConfig(num_feature=4, objective="sigmoid", use_ps=False,
                       learning_rate=0.5, minibatch_size=32,
                       input_format="bsparse")
    lr = LogReg(cfg)
    reader = SampleReader(str(p), num_feature=4, minibatch_size=32,
                          input_format="bsparse", prefetch=False)
    lr.train(reader, epochs=4)
    acc = lr.test(reader)
    assert acc > 0.95, acc
