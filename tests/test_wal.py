"""Write-ahead delta log: framing, edge cases, and crash-recovery parity.

The durability contract (docs/DURABILITY.md): a PS shard killed mid-stream
and recovered from checkpoint + WAL replay serves table bytes BITWISE
EQUAL to a shard that was never killed — and every edge the crash can
carve into the log (torn tail, double replay, checkpoint/prune races,
empty logs) degrades to at most the documented bounded-loss window,
never to corruption.
"""

import os
import threading
import time

import numpy as np
import pytest

from multiverso_tpu.core import checkpoint as ckpt
from multiverso_tpu.core import wal as W
from multiverso_tpu.parallel.ps_service import (DistributedArrayTable,
                                                DistributedSparseMatrixTable,
                                                PSService)


# ---------------------------------------------------------------------------
# Frame / segment mechanics
# ---------------------------------------------------------------------------
def test_roundtrip_and_lsn_sequence(tmp_path):
    w = W.WriteAheadLog(str(tmp_path), flush_interval_ms=10_000)
    lsns = [w.append(f"r{i}".encode()) for i in range(5)]
    assert lsns == [1, 2, 3, 4, 5]
    w.flush()
    got = list(W.replay(str(tmp_path)))
    assert [(lsn, p.decode()) for lsn, p in got] == \
        [(i + 1, f"r{i}") for i in range(5)]
    w.close()


def test_zero_length_log_recovers_to_nothing(tmp_path):
    # No segments at all, then an empty segment: both replay to [].
    assert list(W.replay(str(tmp_path))) == []
    w = W.WriteAheadLog(str(tmp_path), flush_interval_ms=10_000)
    w.close()       # creates wal_000000.log with zero records
    assert os.path.exists(os.path.join(str(tmp_path), "wal_000000.log"))
    assert list(W.replay(str(tmp_path))) == []
    assert W.last_lsn(os.path.join(str(tmp_path), "wal_000000.log")) == 0


@pytest.mark.parametrize("cut", ["header", "payload", "crc"])
def test_torn_final_record_dropped_at_frame_boundary(tmp_path, cut):
    """A record cut mid-write (the crash shape) — partial header, partial
    payload, or a corrupted byte — is dropped; every record BEFORE the
    tear replays intact."""
    w = W.WriteAheadLog(str(tmp_path), flush_interval_ms=10_000)
    w.append(b"good-one")
    w.append(b"good-two")
    w.flush()
    path = w.path
    w.close()
    whole = open(path, "rb").read()
    torn = W._frame(3, b"torn-record")
    if cut == "header":
        torn = torn[:W._HEADER.size - 2]
    elif cut == "payload":
        torn = torn[:-3]
    else:           # crc: flip a payload byte AFTER the crc was stamped
        torn = bytearray(torn)
        torn[-1] ^= 0xFF
        torn = bytes(torn)
    with open(path, "wb") as f:
        f.write(whole + torn)
    got = [p.decode() for _, p in W.replay(str(tmp_path))]
    assert got == ["good-one", "good-two"]


def test_torn_middle_stops_before_following_records(tmp_path):
    """Corruption is a crash boundary, not a skip: a record after a bad
    frame is UNTRUSTED (its framing was only ever validated relative to
    the torn one) and must not replay."""
    w = W.WriteAheadLog(str(tmp_path), flush_interval_ms=10_000)
    w.append(b"keep")
    w.flush()
    path = w.path
    w.close()
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data)
        f.write(b"\x00" * 7)                       # garbage
        f.write(W._frame(99, b"after-garbage"))    # valid frame after it
    got = [p.decode() for _, p in W.replay(str(tmp_path))]
    assert got == ["keep"]


def test_corrupt_length_field_cannot_balloon_reader(tmp_path):
    w = W.WriteAheadLog(str(tmp_path), flush_interval_ms=10_000)
    w.append(b"ok")
    w.flush()
    path = w.path
    w.close()
    with open(path, "ab") as f:
        f.write(W._HEADER.pack(W._MAGIC, (1 << 32) - 1, 2, 0))
    assert [p for _, p in W.replay(str(tmp_path))] == [b"ok"]


def test_rotate_prune_and_restart_continue_lsns(tmp_path):
    w = W.WriteAheadLog(str(tmp_path), flush_interval_ms=10_000)
    for i in range(3):
        w.append(f"a{i}".encode())
    sealed = w.rotate()
    w.append(b"b0", sync=True)
    # Prune covering the sealed segment only.
    removed = w.prune(3)
    assert removed == [sealed]
    assert [p.decode() for _, p in W.replay(str(tmp_path))] == ["b0"]
    w.close()
    # Restart continues the sequence past everything on disk.
    w2 = W.WriteAheadLog(str(tmp_path), flush_interval_ms=10_000)
    assert w2.append(b"c0", sync=True) == 5
    w2.close()
    assert [lsn for lsn, _ in W.replay(str(tmp_path))] == [4, 5]


def test_prune_never_touches_segments_with_uncovered_records(tmp_path):
    w = W.WriteAheadLog(str(tmp_path), flush_interval_ms=10_000)
    w.append(b"x1")
    w.rotate()
    w.append(b"x2", sync=True)
    w.rotate()
    # Checkpoint only covers lsn 1: segment holding lsn 2 must survive.
    w.prune(1)
    lsns = [lsn for lsn, _ in W.replay(str(tmp_path))]
    assert lsns == [2]
    w.close()


def test_abandoned_atomic_stream_never_publishes(tmp_path):
    """utils/stream: a with-less writer abandoned mid-write (exception
    unwound) must NOT publish its partial temp over the intact previous
    file when GC finalizes it (review finding — IOBase.__del__ calls
    close())."""
    import gc

    from multiverso_tpu.utils.stream import open_stream

    path = str(tmp_path / "meta.json")
    with open_stream(path, "w") as s:
        s.write(b"GOOD")
    s2 = open_stream(path, "w")
    s2.write(b"PART")            # abandoned: no close, no with-exit
    del s2
    gc.collect()
    with open(path, "rb") as f:
        assert f.read() == b"GOOD", "GC published a partial write"
    # ...and the explicit-close path still publishes.
    with open_stream(path, "w") as s3:
        s3.write(b"NEXT")
    assert open(path, "rb").read() == b"NEXT"


def test_group_commit_flushes_on_interval(tmp_path):
    w = W.WriteAheadLog(str(tmp_path), flush_interval_ms=20)
    w.append(b"deferred")
    deadline = time.monotonic() + 5
    while not list(W.replay(str(tmp_path))):
        assert time.monotonic() < deadline, "flusher never committed"
        time.sleep(0.01)
    w.close()


# ---------------------------------------------------------------------------
# PS-shard crash recovery (the tier-1 bitwise-parity witness)
# ---------------------------------------------------------------------------
TABLE = 471
SIZE = 48


def _crash(svc: PSService) -> None:
    """Simulate an abrupt death: tear the sockets down WITHOUT flushing
    the WAL or checkpointing — whatever the group commit already fsynced
    is all recovery gets (sync_acks mode: everything acked)."""
    svc._running = False
    try:
        svc._listener.close()
    except OSError:
        pass
    for sock in list(svc._decoders):
        try:
            sock.close()
        except OSError:
            pass


def _recover_seat(rank, peers, wal_dir, restore_uri, tmp_path):
    """The documented recovery order: attach WAL -> restore checkpoint ->
    replay tail -> ONLY THEN announce (restore-before-announce is the
    acked-write-loss guard the elastic fuzz pinned)."""
    svc = PSService()
    svc.attach_wal(wal_dir, sync_acks=True)
    peers = list(peers)
    peers[rank] = svc.address
    table = DistributedArrayTable(TABLE, SIZE, svc, peers, rank=rank,
                                  announce=False)
    if restore_uri:
        ckpt.load_table(table, restore_uri)
    report = svc.replay_wal()
    svc.enable_directory(rank, peers)
    return svc, table, peers, report


def test_killed_shard_recovers_bitwise_equal_to_unkilled(mv_env, tmp_path):
    """THE parity witness: two worlds driven by the same deterministic
    add stream; one shard is crashed and recovered from checkpoint+WAL,
    the other never dies. Recovered table bytes (params AND updater
    state) must be bitwise identical."""
    wal_dir = str(tmp_path / "wal")

    def build_world(with_wal):
        s0, s1 = PSService(), PSService()
        if with_wal:
            s1.attach_wal(wal_dir, sync_acks=True)
        peers = [s0.address, s1.address]
        t0 = DistributedArrayTable(TABLE, SIZE, s0, peers, rank=0)
        t1 = DistributedArrayTable(TABLE, SIZE, s1, peers, rank=1)
        return s0, s1, t0, t1, peers

    def stream(seed):
        rng = np.random.default_rng(seed)
        return [rng.normal(size=SIZE).astype(np.float32)
                for _ in range(24)]

    deltas = stream(3)

    # Reference world: never killed.
    r0, r1, rt0, rt1, _ = build_world(False)
    for d in deltas:
        rt0.add(d)
    ref_state = rt1.store_state()

    # Durable world: checkpoint at 1/3, crash at 2/3, recover, finish.
    s0, s1, t0, t1, peers = build_world(True)
    for d in deltas[:8]:
        t0.add(d)
    uri = f"file://{tmp_path}/seat1.npz"
    ckpt.save_table(t1, uri)
    s1.wal_checkpoint()
    for d in deltas[8:16]:
        t0.add(d)
    _crash(s1)
    s1b, t1b, peers, report = _recover_seat(1, peers, wal_dir, uri,
                                            tmp_path)
    assert report["applied"] == 8     # exactly the post-checkpoint tail
    for d in deltas[16:]:
        t0.add(d)

    got_state = t1b.store_state()
    for key in ("data", "shard_meta"):
        np.testing.assert_array_equal(
            got_state[key], ref_state[key],
            err_msg=f"recovered '{key}' differs from never-killed shard")
    got_state.pop("wal_meta", None)
    assert set(got_state) == set(ref_state)
    for key in ref_state:
        np.testing.assert_array_equal(got_state[key], ref_state[key])

    # The CLIENT's full-table view agrees too (both halves).
    np.testing.assert_array_equal(np.asarray(t0.get()),
                                  np.asarray(rt0.get()))
    for s in (r0, r1, s0, s1b):
        s.close()


def test_replay_is_idempotent_and_skips_checkpointed_records(mv_env,
                                                             tmp_path):
    """Replay twice == replay once, and a checkpoint that never got its
    prune (crash between save and truncation — the checkpoint-truncation
    race) still recovers exactly: the lsn filter skips everything the
    restore already holds even though the records are still on disk."""
    wal_dir = str(tmp_path / "wal")
    s0, s1 = PSService(), PSService()
    s1.attach_wal(wal_dir, sync_acks=True)
    peers = [s0.address, s1.address]
    t0 = DistributedArrayTable(TABLE, SIZE, s0, peers, rank=0)
    t1 = DistributedArrayTable(TABLE, SIZE, s1, peers, rank=1)

    rng = np.random.default_rng(11)
    acked = np.zeros(SIZE, np.float32)
    for _ in range(6):
        d = rng.integers(1, 4, SIZE).astype(np.float32)
        t0.add(d)
        acked += d
    uri = f"file://{tmp_path}/seat1.npz"
    ckpt.save_table(t1, uri)
    # DELIBERATELY no wal_checkpoint(): the pre-checkpoint records stay
    # in the log, exactly as a crash-before-prune would leave them.
    for _ in range(6):
        d = rng.integers(1, 4, SIZE).astype(np.float32)
        t0.add(d)
        acked += d
    _crash(s1)

    s1b, t1b, peers, report = _recover_seat(1, peers, wal_dir, uri,
                                            tmp_path)
    assert report["applied"] == 6 and report["skipped"] == 6
    second = s1b.replay_wal()
    assert second == {"applied": 0, "skipped": 0}
    np.testing.assert_array_equal(np.asarray(t0.get()), acked)
    for s in (s0, s1b):
        s.close()


def test_recovery_with_zero_length_log(mv_env, tmp_path):
    """A shard that checkpointed and then died before any further add
    (or whose log was fully pruned) recovers from the checkpoint alone —
    an empty/absent WAL tail is a no-op, not an error."""
    wal_dir = str(tmp_path / "wal")
    s0, s1 = PSService(), PSService()
    s1.attach_wal(wal_dir, sync_acks=True)
    peers = [s0.address, s1.address]
    t0 = DistributedArrayTable(TABLE, SIZE, s0, peers, rank=0)
    t1 = DistributedArrayTable(TABLE, SIZE, s1, peers, rank=1)
    t0.add(np.full(SIZE, 2.0, np.float32))
    uri = f"file://{tmp_path}/seat1.npz"
    ckpt.save_table(t1, uri)
    s1.wal_checkpoint()
    _crash(s1)
    s1b, t1b, peers, report = _recover_seat(1, peers, wal_dir, uri,
                                            tmp_path)
    assert report == {"applied": 0, "skipped": 0}
    np.testing.assert_array_equal(np.asarray(t0.get()),
                                  np.full(SIZE, 2.0, np.float32))
    for s in (s0, s1b):
        s.close()


def test_recovered_shard_dedups_retransmit_of_logged_add(mv_env, tmp_path):
    """A peer whose add was applied+logged but whose ACK died with the
    shard retransmits the SAME message after recovery; the replayed
    reply cache must answer it from dedup instead of double-applying."""
    from multiverso_tpu.core.actor import Message, MsgType
    from multiverso_tpu.parallel.ps_service import (_opt_to_array,
                                                    pack_payload)
    from multiverso_tpu.core.options import AddOption

    wal_dir = str(tmp_path / "wal")
    s0, s1 = PSService(), PSService()
    s1.attach_wal(wal_dir, sync_acks=True)
    peers = [s0.address, s1.address]
    t0 = DistributedArrayTable(TABLE, SIZE, s0, peers, rank=0)
    t1 = DistributedArrayTable(TABLE, SIZE, s1, peers, rank=1)
    delta = np.full(SIZE, 1.0, np.float32)
    t0.add(delta)
    _crash(s1)
    s1b, t1b, peers, report = _recover_seat(1, peers, wal_dir, None,
                                            tmp_path)
    assert report["applied"] == 1
    # Hand-retransmit the exact message the WAL logged (src 0, the
    # logged msg_id) straight into the recovered seat.
    lsn, payload = next(W.replay(wal_dir))
    from multiverso_tpu.parallel.net import parse_frame
    logged, _ = parse_frame(bytearray(payload))
    import socket as _socket
    from multiverso_tpu.parallel.net import recv_message, send_message
    with _socket.create_connection(s1b.address, timeout=10) as sock:
        send_message(sock, logged)
        reply = recv_message(sock)
    assert reply is not None and reply.msg_id == logged.msg_id
    assert reply.type != MsgType.Reply_Error
    # Applied once, not twice: seat 1's half of the table reads 1.0.
    lo = t0.offsets[1]
    np.testing.assert_array_equal(np.asarray(t0.get())[lo:],
                                  delta[lo:])
    for s in (s0, s1b):
        s.close()


def test_restart_never_reissues_checkpoint_covered_lsns(mv_env, tmp_path):
    """Crash in the group-commit window: the checkpoint durably covers
    lsns whose RECORDS died unfsynced, so the on-disk max lsn is BEHIND
    the restore mark. The restarted appender must resume PAST the
    restore lsn — resuming from the disk max would re-issue covered
    numbers to fresh adds, and a second recovery's filter would then
    silently drop those acked durable writes (review finding)."""
    wal_dir = str(tmp_path / "wal")
    s0, s1 = PSService(), PSService()
    # Async group commit with a huge interval: appended records stay
    # UNFSYNCED — the crash window, made deterministic.
    s1.attach_wal(wal_dir, flush_interval_ms=10_000_000)
    peers = [s0.address, s1.address]
    t0 = DistributedArrayTable(TABLE, SIZE, s0, peers, rank=0)
    t1 = DistributedArrayTable(TABLE, SIZE, s1, peers, rank=1)
    acked = np.zeros(SIZE, np.float32)
    for _ in range(5):
        d = np.full(SIZE, 2.0, np.float32)
        t0.add(d)
        acked += d
    uri = f"file://{tmp_path}/seat1.npz"
    ckpt.save_table(t1, uri)        # wal_meta = 5; records 1-5 UNFSYNCED
    _crash(s1)                      # ...and lost with the crash
    assert list(W.replay(wal_dir)) == []    # disk max lsn = 0

    # First recovery: checkpoint only. Fresh adds MUST be assigned lsns
    # past the restore mark, not 1..5 again.
    s1b, t1b, peers, report = _recover_seat(1, peers, wal_dir, uri,
                                            tmp_path)
    assert report == {"applied": 0, "skipped": 0}
    for _ in range(5):
        d = np.full(SIZE, 3.0, np.float32)
        t0.add(d)
        acked += d
    lsns = [lsn for lsn, _ in W.replay(wal_dir)]
    assert lsns and min(lsns) > 5, \
        f"restarted appender re-issued checkpoint-covered lsns: {lsns}"

    # Second crash WITHOUT a new checkpoint: replay must apply the
    # post-restore adds on top of the old checkpoint — exactly.
    _crash(s1b)
    s1c, t1c, peers, report2 = _recover_seat(1, peers, wal_dir, uri,
                                             tmp_path)
    assert report2["applied"] == 5, report2
    np.testing.assert_array_equal(np.asarray(t0.get()), acked)
    for s in (s0, s1c):
        s.close()


def test_retransmit_of_checkpoint_covered_add_dedups(mv_env, tmp_path):
    """A peer whose add was applied AND snapshotted but whose ack died
    with the shard retransmits after recovery; the record is replay-
    SKIPPED (the checkpoint holds it) but must still land in the reply
    cache — a double-apply on top of the restored state is the exact
    corruption the WAL exists to prevent (review finding)."""
    from multiverso_tpu.core.actor import MsgType
    from multiverso_tpu.parallel.net import (parse_frame, recv_message,
                                             send_message)
    import socket as _socket

    wal_dir = str(tmp_path / "wal")
    s0, s1 = PSService(), PSService()
    s1.attach_wal(wal_dir, sync_acks=True)
    peers = [s0.address, s1.address]
    t0 = DistributedArrayTable(TABLE, SIZE, s0, peers, rank=0)
    t1 = DistributedArrayTable(TABLE, SIZE, s1, peers, rank=1)
    t0.add(np.full(SIZE, 1.0, np.float32))
    uri = f"file://{tmp_path}/seat1.npz"
    ckpt.save_table(t1, uri)        # the add's lsn is COVERED
    _crash(s1)
    s1b, t1b, peers, report = _recover_seat(1, peers, wal_dir, uri,
                                            tmp_path)
    assert report["skipped"] >= 1 and report["applied"] == 0, report
    # Retransmit the covered add verbatim into the recovered seat.
    lsn, payload = next(W.replay(wal_dir))
    logged, _ = parse_frame(bytearray(payload))
    with _socket.create_connection(s1b.address, timeout=10) as sock:
        send_message(sock, logged)
        reply = recv_message(sock)
    assert reply is not None and reply.type != MsgType.Reply_Error
    lo = t0.offsets[1]
    np.testing.assert_array_equal(
        np.asarray(t0.get())[lo:], np.full(SIZE, 1.0, np.float32)[lo:],
        err_msg="covered add was re-applied on retransmit")
    for s in (s0, s1b):
        s.close()


def test_wal_under_concurrent_writer_snapshot_race(mv_env, tmp_path):
    """Checkpoint-truncation race, live flavor: snapshots are taken WHILE
    a writer streams adds (no external lock). The dispatcher-atomic
    (payload, lsn) capture must place every add on exactly one side of
    the cut — recovery equals the acked stream exactly."""
    wal_dir = str(tmp_path / "wal")
    s0, s1 = PSService(), PSService()
    s1.attach_wal(wal_dir, sync_acks=True)
    peers = [s0.address, s1.address]
    t0 = DistributedArrayTable(TABLE, SIZE, s0, peers, rank=0)
    t1 = DistributedArrayTable(TABLE, SIZE, s1, peers, rank=1)

    acked = np.zeros(SIZE, np.float64)
    stop = threading.Event()
    errors = []

    def writer():
        rng = np.random.default_rng(5)
        while not stop.is_set():
            d = rng.integers(1, 5, SIZE).astype(np.float32)
            try:
                t0.add(d)
            except Exception as e:  # noqa: BLE001 - surfaced below
                errors.append(e)
                return
            acked[:] += d

    th = threading.Thread(target=writer)
    th.start()
    uri = f"file://{tmp_path}/seat1.npz"
    try:
        for _ in range(3):
            time.sleep(0.05)
            ckpt.save_table(t1, uri)       # races the live add stream
            s1.wal_checkpoint()
    finally:
        stop.set()
        th.join(timeout=60)
    assert not errors, errors
    _crash(s1)
    s1b, t1b, peers, report = _recover_seat(1, peers, wal_dir, uri,
                                            tmp_path)
    np.testing.assert_allclose(np.asarray(t0.get(), dtype=np.float64),
                               acked, rtol=0, atol=0)
    for s in (s0, s1b):
        s.close()


# ---------------------------------------------------------------------------
# Sparse matrix shards: the same parity witness over row-granular adds.
# The WAL journals the raw Request_Add frame and replays it through the
# normal dispatch path, so it is table-kind agnostic by construction —
# this pins that a row-sharded SPARSE seat (server-side staleness bitmap,
# stamped add options) satisfies the identical contract: killed and
# recovered == never killed, bitwise, with the restore re-arming the
# staleness plane (all-stale) so incremental pulls re-ship restored rows
# instead of trusting a pre-crash cache.
# ---------------------------------------------------------------------------
MTABLE = 473
ROWS, COLS = 24, 6


def _recover_matrix_seat(rank, peers, wal_dir, restore_uri):
    svc = PSService()
    svc.attach_wal(wal_dir, sync_acks=True)
    peers = list(peers)
    peers[rank] = svc.address
    table = DistributedSparseMatrixTable(MTABLE, ROWS, COLS, svc, peers,
                                         rank=rank, announce=False)
    if restore_uri:
        ckpt.load_table(table, restore_uri)
    report = svc.replay_wal()
    svc.enable_directory(rank, peers)
    return svc, table, peers, report


def test_killed_sparse_matrix_shard_recovers_bitwise(mv_env, tmp_path):
    """Parity witness, sparse-matrix flavor: two worlds driven by the
    same deterministic row-granular add stream; one seat is crashed and
    recovered from checkpoint + WAL tail, the other never dies. The
    recovered shard's bytes (params AND updater state) must be bitwise
    identical, and the clients' row reads must agree."""
    wal_dir = str(tmp_path / "wal")

    def build_world(with_wal):
        s0, s1 = PSService(), PSService()
        if with_wal:
            s1.attach_wal(wal_dir, sync_acks=True)
        peers = [s0.address, s1.address]
        t0 = DistributedSparseMatrixTable(MTABLE, ROWS, COLS, s0, peers,
                                          rank=0)
        t1 = DistributedSparseMatrixTable(MTABLE, ROWS, COLS, s1, peers,
                                          rank=1)
        return s0, s1, t0, t1, peers

    def stream(seed):
        rng = np.random.default_rng(seed)
        ops = []
        for _ in range(18):
            ids = np.sort(rng.choice(ROWS, size=4,
                                     replace=False)).astype(np.int32)
            ops.append((ids, rng.normal(size=(4, COLS))
                        .astype(np.float32)))
        return ops

    ops = stream(7)

    # Reference world: never killed.
    r0, r1, rt0, rt1, _ = build_world(False)
    for ids, d in ops:
        rt0.add_rows(ids, d)
    ref_state = rt1.store_state()

    # Durable world: checkpoint at 1/3, crash at 2/3, recover, finish.
    s0, s1, t0, t1, peers = build_world(True)
    for ids, d in ops[:6]:
        t0.add_rows(ids, d)
    uri = f"file://{tmp_path}/mseat1.npz"
    ckpt.save_table(t1, uri)
    s1.wal_checkpoint()
    for ids, d in ops[6:12]:
        t0.add_rows(ids, d)
    _crash(s1)
    s1b, t1b, peers, report = _recover_matrix_seat(1, peers, wal_dir, uri)
    # Only the ops that routed any row to seat 1 wrote a record; the
    # rows are random, so derive the expectation instead of pinning it.
    split = int(t1b.row_offsets[1])
    expect = sum(1 for ids, _ in ops[6:12] if (ids >= split).any())
    assert report["applied"] == expect, report
    for ids, d in ops[12:]:
        t0.add_rows(ids, d)

    got_state = t1b.store_state()
    got_state.pop("wal_meta", None)
    assert set(got_state) == set(ref_state)
    for key in ref_state:
        np.testing.assert_array_equal(
            got_state[key], ref_state[key],
            err_msg=f"recovered sparse shard '{key}' differs from "
                    "never-killed shard")

    # Row-granular client reads agree too — including rows the restore
    # marked stale (the incremental plane re-pulls; a pre-crash cache
    # must never answer for a restored row).
    all_rows = np.arange(ROWS, dtype=np.int32)
    np.testing.assert_array_equal(np.asarray(t0.get_rows(all_rows)),
                                  np.asarray(rt0.get_rows(all_rows)))
    for s in (r0, r1, s0, s1b):
        s.close()
