"""Tier-1 smoke for the perf-attribution harness.

``scripts/perf_attrib.py`` is the designated tie-breaker for the in-graph
loop de-optimization (docs/BENCHMARK.md Round 4) and runs for real only
inside a live-chip window — without an off-chip smoke it can bit-rot
between windows (and HAD never executed before one). ``--dry-run``
shrinks every leg to seconds on CPU, including the Pallas grid leg in
interpret mode."""

import glob
import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPT = os.path.join(_REPO, "scripts", "perf_attrib.py")


def test_perf_attrib_dry_run_cpu(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    tdir = tmp_path / "telemetry"
    proc = subprocess.run([sys.executable, _SCRIPT, "--dry-run",
                           f"--telemetry-dir={tdir}"],
                          cwd=_REPO, env=env, capture_output=True,
                          text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = proc.stdout
    # every formulation leg reported a number (E legitimately skips when
    # the dry-run vocab is already sub-table-sized)
    for leg in ("A standalone", "B fori-full", "C fori-gather",
                "D fori-scatter", "F fori-sub", "G pallas-grid",
                "H fori @ Vg"):
        assert leg in out, f"missing leg {leg!r}:\n{out}"
    assert out.count("ms/chunk") >= 7
    # telemetry snapshots + Chrome trace are emitted alongside the numbers
    from multiverso_tpu.telemetry import (validate_chrome_trace,
                                          validate_snapshot)
    snaps = sorted(glob.glob(str(tdir / "metrics-*.json")))
    assert snaps, f"no telemetry snapshots in {tdir}"
    with open(snaps[-1]) as f:
        snap = json.load(f)
    validate_snapshot(snap)
    spans = [n for n, h in snap["histograms"].items()
             if n.startswith("span.perf_attrib.") and h["count"]]
    assert spans, sorted(snap["histograms"])
    traces = glob.glob(str(tdir / "trace-*.json"))
    assert len(traces) == 1
    with open(traces[0]) as f:
        trace = json.load(f)
    validate_chrome_trace(trace)
    assert any(e.get("ph") == "X" for e in trace["traceEvents"])


def test_graftlint_json_output_stays_parseable():
    """Ride-along for the dry-run smoke: the graftlint ``--format json``
    path is part of the CI tooling surface (editors / report diffing),
    so its schema must stay machine-parseable even when the tree is
    clean and the findings list is empty."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    script = os.path.join(_REPO, "scripts", "graftlint.py")
    proc = subprocess.run(
        [sys.executable, script, "--format", "json",
         os.path.join(_REPO, "multiverso_tpu", "analysis")],
        cwd=_REPO, env=env, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["version"] == 1
    assert isinstance(payload["findings"], list)
    assert {"files", "suppressed", "baselined", "stale_baseline",
            "parse_errors"} <= set(payload)
