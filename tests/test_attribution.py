"""Unit tests for the latency truth layer (telemetry/critical_path.py,
telemetry/profile.py, telemetry/roofline.py): critical-path conservation
on deterministic synthetic traces, tail-exemplar reservoir bounds and the
heartbeat round-trip, the profiler's folded-stack merge + memory bound,
and the roofline truth table. Everything here drives the code with
synthetic inputs — no sleeps against tickers, no wall-clock assertions
beyond coarse sample counts."""

import threading
import time

import pytest

from multiverso_tpu.telemetry.critical_path import (CONCURRENT_PHASES,
                                                    ExemplarReservoir,
                                                    analyze_critical_paths,
                                                    decompose,
                                                    exemplar_payload,
                                                    get_reservoir,
                                                    phase_for_span,
                                                    reset_critical_path,
                                                    set_exemplars_enabled)
from multiverso_tpu.telemetry.profile import (PROFILE_SCHEMA, FoldedStacks,
                                              SamplingProfiler,
                                              merge_profiles,
                                              plane_for_thread)
from multiverso_tpu.telemetry.roofline import (BOUND_CODES, BOUNDS, classify,
                                               reset_roofline, verdict)


# ---------------------------------------------------------------------------
# synthetic Chrome-trace spans
# ---------------------------------------------------------------------------

def _ev(name, ts_us, dur_us, trace="t01", parent="root", **args):
    a = {"trace": trace}
    if parent:
        a["parent"] = parent
    a.update(args)
    return {"ph": "X", "name": name, "ts": float(ts_us),
            "dur": float(dur_us), "args": a}


def _pipeline_trace(trace="t01"):
    """A fully contiguous 10ms request: every microsecond covered by
    exactly one phase span — the ledger must conserve exactly."""
    return [
        _ev("serve.client", 0, 10000, trace=trace, parent=None),
        _ev("serve.send", 0, 300, trace=trace),          # wire
        _ev("serve.admission", 300, 300, trace=trace),
        _ev("serve.admit_wait", 600, 1400, trace=trace),  # queue
        _ev("serve.batch_form", 2000, 600, trace=trace),
        _ev("serve.dispatch", 2600, 600, trace=trace),
        _ev("serve.device", 3200, 4000, trace=trace),
        _ev("serve.collect", 7200, 600, trace=trace),
        _ev("serve.reply", 7800, 800, trace=trace),       # wire
        _ev("serve.deliver", 8600, 1400, trace=trace),
    ]


def test_decompose_conserves_contiguous_pipeline():
    d = decompose(_pipeline_trace(), publish=False)
    assert d is not None
    assert d["root"] == "serve.client"
    assert d["e2e_ms"] == pytest.approx(10.0)
    assert d["conserved"] is True
    assert d["unattributed_ms"] == pytest.approx(0.0, abs=1e-6)
    assert d["bridged_ms"] == pytest.approx(0.0, abs=1e-6)
    # phase durations are the spans', in ms
    assert d["phases"]["device"] == pytest.approx(4.0)
    assert d["phases"]["queue"] == pytest.approx(1.4)
    assert d["phases"]["wire"] == pytest.approx(0.3 + 0.8)
    assert d["attributed_ms"] == pytest.approx(10.0)


def test_decompose_bridges_typed_transit_gaps():
    """Gaps at the three allowlisted boundaries (send->admission,
    collect->reply, reply->deliver) are wire transit: bridged into the
    wire phase and tracked in bridged_ms — the ledger still conserves."""
    spans = [
        _ev("serve.client", 0, 10000, parent=None),
        _ev("serve.send", 0, 300),            # wire ... 400us gap
        _ev("serve.admission", 700, 300),
        _ev("serve.admit_wait", 1000, 1000),
        _ev("serve.batch_form", 2000, 600),
        _ev("serve.dispatch", 2600, 600),
        _ev("serve.device", 3200, 3800),
        _ev("serve.collect", 7000, 500),      # ... 300us gap
        _ev("serve.reply", 7800, 800),        # wire ... 600us gap
        _ev("serve.deliver", 9200, 800),
    ]
    d = decompose(spans, publish=False)
    assert d["conserved"] is True
    assert d["bridged_ms"] == pytest.approx(1.3)
    assert d["unattributed_ms"] == pytest.approx(0.0, abs=1e-6)
    # bridges land in the wire phase: 0.3 + 0.8 measured + 1.3 bridged
    assert d["phases"]["wire"] == pytest.approx(2.4)


def test_decompose_inner_gap_stays_unattributed():
    """A hole at a NON-allowlisted boundary (queue -> batch_form) is an
    uncovered wait: it must land in the residual and break conservation
    — this is the property the unattributed-wait lint exists to keep."""
    spans = [
        _ev("serve.client", 0, 10000, parent=None),
        _ev("serve.send", 0, 300),
        _ev("serve.admission", 300, 300),
        _ev("serve.admit_wait", 600, 400),
        # 3000us uncovered hole: 1000 -> 4000
        _ev("serve.batch_form", 4000, 600),
        _ev("serve.dispatch", 4600, 400),
        _ev("serve.device", 5000, 3000),
        _ev("serve.collect", 8000, 400),
        _ev("serve.reply", 8400, 600),
        _ev("serve.deliver", 9000, 1000),
    ]
    d = decompose(spans, publish=False)
    assert d["bridged_ms"] == pytest.approx(0.0, abs=1e-6)
    assert d["unattributed_ms"] == pytest.approx(3.0)
    assert d["unattributed_frac"] == pytest.approx(0.30)
    assert d["conserved"] is False


def test_decompose_hedge_reported_but_excluded():
    """A hedge overlaps the primary attempt in wall clock: its duration
    is reported as the hedge phase but excluded from the conservation
    sum (a losing hedge added no e2e latency)."""
    spans = _pipeline_trace()
    spans.append(_ev("fleet.attempt", 2000, 5000, hedge=1))
    d = decompose(spans, publish=False)
    assert d["phases"]["hedge"] == pytest.approx(5.0)
    assert "hedge" in CONCURRENT_PHASES
    # conservation unchanged: attributed excludes the concurrent phase
    assert d["attributed_ms"] == pytest.approx(10.0)
    assert d["conserved"] is True


def test_phase_for_span_attempt_taxonomy():
    assert phase_for_span("fleet.attempt", {"hedge": 1}) == "hedge"
    assert phase_for_span("fleet.attempt", {"attempt": 2}) == "retry"
    assert phase_for_span("fleet.attempt", {"attempt": 1}) is None
    assert phase_for_span("serve.device") == "device"
    assert phase_for_span("serve.request") is None      # container
    assert phase_for_span("no.such.span") is None


def test_decompose_clips_overshooting_span_to_root():
    """A child stamped past the root's end (clock skew, late flush)
    contributes only its in-root portion."""
    spans = [
        _ev("serve.client", 0, 10000, parent=None),
        _ev("serve.device", 0, 9000),
        _ev("serve.deliver", 9000, 5000),   # overshoots by 4000us
    ]
    d = decompose(spans, publish=False)
    assert d["phases"]["deliver"] == pytest.approx(1.0)
    assert d["attributed_ms"] == pytest.approx(10.0)


def test_analyze_critical_paths_aggregates(mv_env):
    from multiverso_tpu.telemetry import get_registry
    spans = []
    spans += _pipeline_trace("aaaa")                    # conserved
    bad = [
        _ev("serve.client", 0, 10000, trace="bbbb", parent=None),
        _ev("serve.device", 0, 5000, trace="bbbb"),     # 50% uncovered
    ]
    spans += bad
    # single-span trace: no decomposition signal, must be skipped
    spans.append(_ev("serve.client", 0, 1000, trace="cccc", parent=None))
    reg = get_registry()
    before = reg.histogram("latency.unattributed").snapshot()["count"]
    out = analyze_critical_paths(spans, slow_k=2)
    assert out["n_traces"] == 3
    assert out["n_decomposed"] == 2
    assert out["n_conserved"] == 1
    assert out["conserved_frac"] == pytest.approx(0.5)
    assert out["slowest"][0]["e2e_ms"] >= out["slowest"][-1]["e2e_ms"]
    assert out["phases"]["device"]["total_ms"] == pytest.approx(9.0)
    shares = sum(v["share"] for k, v in out["phases"].items()
                 if k not in CONCURRENT_PHASES)
    assert shares == pytest.approx(1.0, abs=1e-3)
    # publish=True (default): the residual histogram saw both traces
    after = reg.histogram("latency.unattributed").snapshot()["count"]
    assert after == before + 2
    assert reg.gauge("latency.unattributed_frac").last is not None


# ---------------------------------------------------------------------------
# tail exemplars
# ---------------------------------------------------------------------------

@pytest.fixture
def exemplars_on():
    set_exemplars_enabled(True)
    yield
    reset_critical_path()       # drops reservoirs AND the override


def test_exemplar_reservoir_keeps_slowest_n(exemplars_on):
    r = ExemplarReservoir("t", capacity=4, window_s=60.0)
    for ms in (3.0, 9.0, 1.0, 7.0, 5.0, 10.0, 2.0, 8.0):
        r.offer(ms, {"device": ms / 2}, trace=f"t{ms}")
    snap = r.snapshot()
    assert [e["total_ms"] for e in snap] == [10.0, 9.0, 8.0, 7.0]
    assert len(r) <= 4
    # floor = slowest kept entry: cheap reject below it, admit above
    assert not r.would_admit(6.5)
    assert r.would_admit(7.5)
    assert not r.offer(6.5, trace="reject")
    for e in snap:
        assert e["phases"]["device"] == pytest.approx(e["total_ms"] / 2)
        assert e["trace"]
        assert e["age_s"] >= 0.0


def test_exemplar_window_rotation(exemplars_on):
    r = ExemplarReservoir("t", capacity=4, window_s=0.05)
    r.offer(10.0, trace="old")
    time.sleep(0.06)
    r.offer(5.0, trace="new")       # rotates: old -> prev window
    snap = r.snapshot()
    assert [e["trace"] for e in snap] == ["old", "new"]
    time.sleep(0.06)
    r.offer(4.0, trace="newer")     # second rotation: "old" ages out
    traces = [e["trace"] for e in r.snapshot()]
    assert "old" not in traces
    assert set(traces) == {"new", "newer"}


def test_exemplar_gate_off_rejects():
    set_exemplars_enabled(False)
    try:
        r = ExemplarReservoir("t", capacity=4)
        assert r.offer(100.0, trace="x") is False
        assert len(r) == 0
    finally:
        reset_critical_path()


def test_exemplar_heartbeat_roundtrip(mv_env, exemplars_on):
    """A replica's reservoir rides the health heartbeat: the payload the
    router rolls into Fleet_Stats carries the trace id verbatim."""
    from multiverso_tpu.fleet.health import metrics_payload
    get_reservoir("serve").offer(123.4, {"device": 100.0, "queue": 20.0},
                                 trace="deadbeef")
    payload = metrics_payload()
    ex = payload["exemplars"]
    assert ex and ex[0]["trace"] == "deadbeef"
    assert ex[0]["plane"] == "serve"
    assert ex[0]["total_ms"] == pytest.approx(123.4)
    assert ex[0]["phases"]["device"] == pytest.approx(100.0)
    assert payload["roofline"].get("bound") in BOUNDS
    # and the generic payload helper agrees
    assert exemplar_payload("serve")[0]["trace"] == "deadbeef"
    reset_roofline()


# ---------------------------------------------------------------------------
# sampling profiler
# ---------------------------------------------------------------------------

def test_folded_stacks_bound_folds_into_other():
    fs = FoldedStacks(max_stacks=3)
    for i in range(6):
        fs.add(f"host;mod:f{i}")
    fs.add("host;mod:f0")           # existing stack still increments
    assert fs.total() == 7          # counts stay exact in total
    assert len(fs) == 4             # 3 kept + <other>
    lines = fs.folded_lines()
    assert lines[0] == "host;mod:f0 2"
    assert lines[-1] == f"{FoldedStacks.OTHER} 3"


def test_folded_stacks_merge_state_sums():
    a = FoldedStacks(max_stacks=10)
    b = FoldedStacks(max_stacks=10)
    a.add("s1", 3)
    a.add("s2", 1)
    b.add("s1", 2)
    b.add("s3", 5)
    a.merge_state(b.to_state())
    merged = dict(line.rsplit(" ", 1) for line in a.folded_lines())
    assert merged == {"s1": "5", "s2": "1", "s3": "5"}
    assert a.total() == 11
    # merging past the bound preserves totals via <other>
    tiny = FoldedStacks(max_stacks=1)
    tiny.merge_state(a.to_state())
    assert tiny.total() == 11
    assert len(tiny) == 2


def test_merge_profiles_sums_planes_and_skips_alien_schemas():
    st1 = {"schema": PROFILE_SCHEMA, "pid": 100, "samples": 10,
           "wall_s": 2.0, "stacks": {"serve;a:b": 4}, "other": 0,
           "planes": {"serve": {"samples": 4, "cpu_s": 0.5}}}
    st2 = {"schema": PROFILE_SCHEMA, "pid": 200, "samples": 6,
           "wall_s": 3.0, "stacks": {"serve;a:b": 1, "host;c:d": 2},
           "other": 1,
           "planes": {"serve": {"samples": 1, "cpu_s": 0.25},
                      "host": {"samples": 2, "cpu_s": 1.0}}}
    alien = {"schema": "something/else", "samples": 999}
    out = merge_profiles([st1, alien, st2])
    assert out["schema"] == PROFILE_SCHEMA
    assert out["pids"] == [100, 200]
    assert out["samples"] == 16
    assert out["wall_s"] == pytest.approx(3.0)
    assert out["stacks"]["serve;a:b"] == 5
    assert out["planes"]["serve"]["samples"] == 5
    assert out["planes"]["serve"]["cpu_s"] == pytest.approx(0.75)
    assert out["planes"]["host"]["cpu_s"] == pytest.approx(1.0)


def test_plane_for_thread_prefixes():
    assert plane_for_thread("serve-client-0") == "client"
    assert plane_for_thread("serve-collector") == "serve"
    assert plane_for_thread("fleet-heartbeat") == "fleet"
    assert plane_for_thread("router-0") == "fleet"
    assert plane_for_thread("telemetry-profiler") == "telemetry"
    assert plane_for_thread("MainThread") == "host"


def test_sampling_profiler_samples_and_stays_bounded(mv_env):
    p = SamplingProfiler(hz=50.0, max_stacks=64)
    stop = threading.Event()

    def spin():
        while not stop.is_set():
            sum(i * i for i in range(500))

    t = threading.Thread(target=spin, name="serve-spin", daemon=True)
    t.start()
    p.start()
    try:
        deadline = time.monotonic() + 5.0
        while p.state()["samples"] < 3 and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        p.stop()
        stop.set()
        t.join(timeout=2.0)
    st = p.state()
    assert st["schema"] == PROFILE_SCHEMA
    assert st["samples"] >= 3
    assert st["planes"]["serve"]["samples"] >= 1   # the spinner, by name
    assert len(st["stacks"]) + (1 if st["other"] else 0) <= 65
    assert any(line.startswith("serve;") for line in p.stacks.folded_lines())


# ---------------------------------------------------------------------------
# roofline truth table
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("util,expect", [
    ({}, "idle"),
    ({"qps": 0.0, "host_cpu": 0.02, "device_frac": 0.01}, "idle"),
    ({"qps": 100.0, "device_occ": 0.80}, "device"),
    ({"qps": 100.0, "device_frac": 0.65}, "device"),
    # precedence: a saturated device binds regardless of host noise
    ({"qps": 100.0, "device_occ": 0.80, "host_cpu": 0.95}, "device"),
    ({"qps": 100.0, "host_cpu": 0.90}, "host"),
    ({"qps": 100.0, "wire_frac": 0.40, "dispatch_frac": 0.20}, "wire"),
    # wire loses its rule when dispatch exceeds it, dispatch rule fires
    ({"qps": 100.0, "wire_frac": 0.40, "dispatch_frac": 0.45}, "dispatch"),
    ({"qps": 100.0, "dispatch_frac": 0.32}, "dispatch"),
    # argmax fallback: traffic present, nothing over a rule threshold
    ({"qps": 10.0, "wire_frac": 0.10, "host_cpu": 0.06}, "wire"),
    # traffic but every resource under 5%: nothing binds
    ({"qps": 10.0, "wire_frac": 0.04, "host_cpu": 0.03}, "idle"),
])
def test_roofline_classify_truth_table(util, expect):
    assert classify(util) == expect


def test_roofline_verdict_publishes_and_takes_overrides(mv_env):
    from multiverso_tpu.telemetry import get_registry
    reset_roofline()
    try:
        v = verdict("client", overrides={"qps": 100.0, "host_cpu": 0.95})
        assert v["plane"] == "client"
        assert v["bound"] == "host"
        assert v["util"]["host_cpu"] == pytest.approx(0.95)
        g = get_registry().gauge("roofline.client.bound")
        assert g.last == BOUND_CODES["host"]
        # second call differentiates against the first's baseline
        v2 = verdict("client")
        assert v2["bound"] in BOUNDS
        assert v2["util"]["window_s"] < 10.0
    finally:
        reset_roofline()
