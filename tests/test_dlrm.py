"""DLRM online-recommender subsystem tests (ISSUE 20, docs/RECSYS.md).

Five contracts:

* **PS-vs-local bitwise parity** — the PS-backed hybrid step and the
  local twin produce IDENTICAL bytes (scores, dense params, every
  embedding table) because both planes run the same jitted programs and
  the same server-side adagrad row math.
* **Sharded-state parity** — the same bitwise equality holds when the
  server runs with ``-state_sharding=on`` over a multi-seat mesh (the
  updater's row math is layout-invariant; test_state_sharding.py proves
  the primitive, this proves the model end-to-end).
* **Streaming AUC** — the histogram estimator tracks the exact
  rank-based AUC within binning error, and nails separable/degenerate
  cases.
* **Serve-during-train staleness bound** — a live-table serving runner
  with a HotRowCache under the real BSP clock serves cached rows only
  within the configured staleness bound; once training advances the
  clock past the bound, the cache misses and the fresh bytes flow.
* **recsys_bench dry run** — the committed BENCH_RECSYS record shape is
  reproducible: training sustained while serving answered lookups with
  zero errors, a monotone freshness curve, int8 quality within
  tolerance.
"""

import json
import math
import os
import subprocess
import sys

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.core.options import AddOption, MatrixTableOption
from multiverso_tpu.models.dlrm import (DLRMConfig, DLRMModel,
                                        ImpressionStream, StreamConfig,
                                        StreamingAUC, exact_auc)
from multiverso_tpu.serving.cache import HotRowCache

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SMALL = dict(fields=3, vocab=64, embed_dim=8, dense_dim=4,
              bottom_mlp=(8,), top_mlp=(8,))


def _run_parity(steps: int = 4, batch: int = 16) -> None:
    """Drive PS model and local twin on identical impression sequences;
    every observable must match bitwise."""
    cfg = DLRMConfig(**_SMALL)
    scfg = StreamConfig(fields=cfg.fields, vocab=cfg.vocab,
                        dense_dim=cfg.dense_dim, zipf=1.3, seed=1,
                        drift_every=0)
    ps = DLRMModel(cfg, mode="ps")
    local = DLRMModel(cfg, mode="local")
    s_ps, s_lo = ImpressionStream(scfg), ImpressionStream(scfg)
    for i in range(steps):
        b_ps, b_lo = s_ps.batch(batch), s_lo.batch(batch)
        # zipf=1.3 guarantees duplicate ids within a batch — the
        # combine_duplicate_rows path must agree across planes too.
        loss_ps, sc_ps = ps.step(b_ps.ids, b_ps.dense, b_ps.labels)
        loss_lo, sc_lo = local.step(b_lo.ids, b_lo.dense, b_lo.labels)
        assert loss_ps == loss_lo, (i, loss_ps, loss_lo)
        assert np.array_equal(sc_ps, sc_lo), \
            (i, np.abs(sc_ps - sc_lo).max())
    all_rows = np.arange(cfg.vocab, dtype=np.int32)
    for f in range(cfg.fields):
        t_ps = ps.tables[f].get_rows(all_rows)
        t_lo = local.local_rows(f)
        assert np.array_equal(t_ps, t_lo), \
            (f, np.abs(t_ps - t_lo).max())
    for (w_ps, b_ps_), (w_lo, b_lo_) in zip(ps.dense_params,
                                            local.dense_params):
        assert np.array_equal(np.asarray(w_ps), np.asarray(w_lo))
        assert np.array_equal(np.asarray(b_ps_), np.asarray(b_lo_))


def test_ps_local_bitwise_parity():
    mv.init([])
    try:
        _run_parity()
    finally:
        mv.shutdown()


def test_ps_local_parity_under_state_sharding():
    """Same parity with the adagrad g2 state SHARDED across a mesh —
    the layout must never leak into the row math (64 rows / 2 server
    seats divide evenly, the =on requirement)."""
    mv.init(["-mesh_shape=server:2,worker:2", "-state_sharding=on"])
    try:
        _run_parity(steps=3)
    finally:
        mv.shutdown()


def test_streaming_auc_tracks_exact():
    rng = np.random.default_rng(7)
    scores = rng.random(4000)
    labels = (rng.random(4000) < scores).astype(np.float32)
    want = exact_auc(scores, labels)
    auc = StreamingAUC(bins=2048)
    for i in range(0, len(scores), 500):     # streamed in chunks
        auc.update(scores[i:i + 500], labels[i:i + 500])
    assert abs(auc.value() - want) < 2e-3, (auc.value(), want)
    assert auc.positives + auc.negatives == 4000


def test_streaming_auc_edges():
    auc = StreamingAUC(bins=64)
    assert math.isnan(auc.value())           # no data
    auc.update(np.array([0.9, 0.8]), np.array([1.0, 1.0]))
    assert math.isnan(auc.value())           # one class only
    auc = StreamingAUC(bins=64)
    auc.update(np.array([0.1, 0.2, 0.8, 0.9]),
               np.array([0.0, 0.0, 1.0, 1.0]))
    assert auc.value() == pytest.approx(1.0)  # perfectly separable
    auc = StreamingAUC(bins=64)
    auc.update(np.array([0.8, 0.9, 0.1, 0.2]),
               np.array([0.0, 0.0, 1.0, 1.0]))
    assert auc.value() == pytest.approx(0.0)  # perfectly inverted


def test_serve_during_train_staleness_bound():
    """BSP-clocked live serving: a staleness-1 cache may serve rows one
    training tick old, but once the clock advances past the bound the
    runner must refetch — and the refetched bytes are the TRAINED rows.
    Adds from both (threaded-worker) seats advance the add clock without
    blocking; serving reads never gate (serving_runner contract)."""
    rows, cols = 32, 4
    mv.init(["-sync=true"], num_local_workers=2)
    try:
        table = mv.create_table(MatrixTableOption(
            num_row=rows, num_col=cols, random_init=True, seed=5,
            updater="adagrad", name="stale_bound", comm_policy="ps"))
        cache = HotRowCache(16, staleness=1)
        runner = table.serving_runner(cache=cache)
        plain = table.serving_runner()          # uncached fresh reader
        keys = np.arange(8, dtype=np.int32)
        batch, lengths = keys[None, :], np.array([8])

        def serve():
            return runner.slice_result(runner.run(batch, lengths), 0, 8)

        def fresh():
            return plain.slice_result(plain.run(batch, lengths), 0, 8)

        def train_tick(value):
            delta = np.full((len(keys), cols), value, np.float32)
            for w in range(2):
                table.add_rows(keys, delta,
                               AddOption(worker_id=w, learning_rate=0.1,
                                         rho=0.1))

        v0 = serve().copy()                      # populates cache @ clock 0
        assert runner.try_cached(keys) is not None

        train_tick(0.5)                          # clock -> 1: bound edge
        hit = runner.try_cached(keys)
        assert hit is not None, "within staleness bound must still hit"
        assert np.array_equal(hit, v0), "bounded hit serves the old bytes"

        train_tick(0.5)                          # clock -> 2: past bound
        assert runner.try_cached(keys) is None, \
            "stale beyond the bound must miss"
        v2 = serve()
        assert not np.array_equal(v2, v0), "refetch must see training"
        assert np.array_equal(v2, fresh()), "refetch serves live bytes"
        # The miss re-populated the cache at the current clock.
        hit = runner.try_cached(keys)
        assert hit is not None and np.array_equal(hit, v2)
    finally:
        mv.shutdown()


def test_recsys_span_taxonomy():
    """ISSUE 20 satellite: the online loop's spans land in the
    critical-path phase taxonomy — a synthetic recsys.step trace
    decomposes with zero unattributed residual."""
    from multiverso_tpu.telemetry.critical_path import (decompose,
                                                        phase_for_span)
    assert phase_for_span("recsys.pull") == "collect"
    assert phase_for_span("recsys.compute") == "device"
    assert phase_for_span("recsys.push") == "dispatch"
    assert phase_for_span("recsys.publish") == "wire"
    assert phase_for_span("recsys.score") == "device"
    assert phase_for_span("recsys.step") is None      # container

    trace = [
        {"name": "recsys.step", "ts": 0, "dur": 10_000, "args": {}},
        {"name": "recsys.pull", "ts": 0, "dur": 2_000,
         "args": {"parent": "r"}},
        {"name": "recsys.compute", "ts": 2_000, "dur": 6_000,
         "args": {"parent": "r"}},
        {"name": "recsys.push", "ts": 8_000, "dur": 2_000,
         "args": {"parent": "r"}},
    ]
    led = decompose(trace, publish=False)
    assert led is not None and led["root"] == "recsys.step"
    assert led["phases"] == {"collect": 2.0, "device": 6.0,
                             "dispatch": 2.0}
    assert led["unattributed_ms"] == 0.0
    assert led["conserved"] is True


def test_recsys_bench_dry_run(tmp_path):
    """The committed-record shape end-to-end: train-while-serve with
    zero serve errors, monotone freshness, int8 within tolerance."""
    out = tmp_path / "BENCH_RECSYS.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "recsys_bench.py"),
         "--dry-run", f"--out={out}"],
        cwd=_REPO, env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-3000:]

    record = json.loads(out.read_text())
    assert record["schema"] == "multiverso_tpu.bench_recsys/v1"
    assert record["ok"] is True and record["failures"] == []
    assert record["serve"]["errors"] == 0
    assert record["serve"]["requests"] > 0
    assert record["train"]["updates_per_sec"] > 0
    assert record["train"]["publishes"] >= 3
    # Monotone freshness with fresh strictly above frozen: the measured
    # proof that publishing fresher tables buys quality under drift.
    aucs = [lane["auc"] for lane in record["freshness"]]
    assert all(a >= b - 1e-9 for a, b in zip(aucs, aucs[1:])), aucs
    assert aucs[0] > aucs[-1], aucs
    assert record["quant"]["auc_delta"] <= 0.01
    # The trend point landed beside the record for bench_guard.
    history = tmp_path / "BENCH_SERVE_HISTORY.jsonl"
    assert history.exists()
    line = json.loads(history.read_text().splitlines()[-1])
    assert line["benchmark"] == "recsys_online"
