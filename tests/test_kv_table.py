"""KVTable tests (ref include/multiverso/table/kv_table.h semantics)."""

import numpy as np

import multiverso_tpu as mv


def test_add_then_get(mv_env):
    t = mv.create_table(mv.KVTableOption())
    t.add([1, 5, 9], [1.0, 2.0, 3.0])
    np.testing.assert_allclose(t.get([1, 5, 9]), [1.0, 2.0, 3.0])
    t.add([5], [10.0])  # += semantics (ref kv_table.h:86-93)
    np.testing.assert_allclose(t.get([5]), [12.0])


def test_missing_keys_are_zero(mv_env):
    t = mv.create_table(mv.KVTableOption())
    np.testing.assert_allclose(t.get([42]), [0.0])


def test_worker_cache(mv_env):
    t = mv.create_table(mv.KVTableOption())
    t.add([7], [3.5])
    t.get([7])
    assert t.raw()[7] == 3.5  # local cache (ref kv_table.h:30-40)


def test_partition_by_hash(mv_env):
    t = mv.create_table(mv.KVTableOption())
    keys = list(range(100))
    parts = t.partition(keys)
    n = mv.num_servers()
    assert sum(len(v) for v in parts.values()) == 100
    for sid, ks in parts.items():
        assert all(int(k) % n == sid for k in ks)  # ref kv_table.h:48-50


def test_store_load_roundtrip(mv_env):
    t = mv.create_table(mv.KVTableOption())
    t.add([1, 2, 3], [1.0, 2.0, 3.0])
    snap = t.store_state()
    t.add([1], [100.0])
    t.load_state(snap)
    np.testing.assert_allclose(t.get([1, 2, 3]), [1.0, 2.0, 3.0])
