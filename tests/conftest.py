"""Test env: virtual 8-device CPU mesh (must run before jax backend init).

Mirrors the reference's test ladder (SURVEY.md §4): a world-of-size-N on one
box — the reference uses ``mpirun -np N``; we use XLA's forced host platform
device count so the same sharded code paths compile and execute as on an
8-chip TPU slice.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags +
                               " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

# The axon PJRT sitecustomize force-sets jax_platforms="axon,cpu" at
# interpreter start (overriding the env var), which would silently route
# "CPU" tests onto the real tunneled TPU chip. Forcing the config here —
# before any backend initializes — pins tests to the 8 virtual CPU devices.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def clean_framework_state():
    """Reset flags/zoo/dashboard between tests (the reference re-creates its
    MultiversoEnv fixture per suite, Test/unittests/multiverso_env.h:9-29)."""
    yield
    from multiverso_tpu.core.zoo import Zoo
    from multiverso_tpu.telemetry import reset_telemetry
    from multiverso_tpu.utils.configure import reset_flags
    from multiverso_tpu.utils.dashboard import Dashboard

    zoo = Zoo._instance
    if zoo is not None and zoo.started:
        try:
            zoo.stop()
        except Exception:
            pass
    Zoo._reset_for_tests()
    reset_flags()
    Dashboard.reset()
    reset_telemetry()   # registry + span buffer + exporter (monitors'
    # backing histograms live in the telemetry registry)


@pytest.fixture
def mv_env():
    """MultiversoEnv analog: init with default flags, world size 1."""
    import multiverso_tpu as mv
    mv.init([])
    yield mv
    mv.shutdown()


@pytest.fixture
def sync_env():
    """SyncMultiversoEnv analog (-sync=true)."""
    import multiverso_tpu as mv
    mv.init(["-sync=true"])
    yield mv
    mv.shutdown()
