"""Lua + C# binding artifacts (VERDICT r4 #5).

The reference ships a LuaJIT-FFI package (binding/lua/init.lua:7-66) and a
managed C# wrapper (binding/C#/MultiversoCLR/MultiversoCLR.h:12-43). Here
both ride the framed-TCP C boundary (runtime/src/mv_client.cpp). Neither
luajit nor a CLR ships in this image, so the artifacts are validated in two
tiers: (1) ALWAYS — every function the Lua ffi.cdef / C# DllImport block
declares must exist in libmvtpu_host.so with those exact names (a renamed
or removed export breaks this test, keeping the artifacts honest); (2) if
``luajit`` is on PATH, the demo runs live against two Python-served shards,
exactly like the C demo in test_c_api_ffi.py.
"""

import ctypes
import os
import re
import shutil
import subprocess

import numpy as np
import pytest

from multiverso_tpu.runtime import ffi

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LUA_DIR = os.path.join(REPO, "multiverso_tpu", "binding", "lua")
CS_FILE = os.path.join(REPO, "multiverso_tpu", "binding", "csharp",
                       "MultiversoTpu.cs")


def _so_path():
    ffi.load()
    return os.path.join(REPO, "multiverso_tpu", "runtime",
                        "libmvtpu_host.so")


def _declared_lua_symbols():
    src = open(os.path.join(LUA_DIR, "init.lua")).read()
    cdef = re.search(r"ffi\.cdef\[\[(.*?)\]\]", src, re.S).group(1)
    return re.findall(r"\b(MV_\w+)\s*\(", cdef)


def _declared_cs_symbols():
    src = open(CS_FILE).read()
    return re.findall(r"extern\s+\w+\s+(MV_\w+)\s*\(", src)


def test_lua_cdef_symbols_match_so():
    lib = ctypes.CDLL(_so_path())
    syms = _declared_lua_symbols()
    assert len(syms) >= 13, "cdef block lost declarations"
    for sym in syms:
        assert hasattr(lib, sym), f"init.lua declares missing symbol {sym}"


def test_csharp_dllimport_symbols_match_so():
    lib = ctypes.CDLL(_so_path())
    syms = _declared_cs_symbols()
    assert len(syms) >= 13, "DllImport block lost declarations"
    for sym in syms:
        assert hasattr(lib, sym), f"MultiversoTpu.cs declares missing {sym}"


def test_lua_and_csharp_cover_same_surface():
    assert set(_declared_lua_symbols()) == set(_declared_cs_symbols())


@pytest.mark.skipif(shutil.which("luajit") is None,
                    reason="luajit not installed (artifact gated like gs://)")
def test_lua_demo_against_python_shards(mv_env):
    from multiverso_tpu.parallel.ps_service import (DistributedArrayTable,
                                                    DistributedKVTable,
                                                    DistributedMatrixTable,
                                                    PSService)

    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    AID, MID, KID = 211, 212, 213
    try:
        a0 = DistributedArrayTable(AID, 10, svc0, peers, rank=0)
        a1 = DistributedArrayTable(AID, 10, svc1, peers, rank=1)
        m0 = DistributedMatrixTable(MID, 8, 3, svc0, peers, rank=0)
        DistributedMatrixTable(MID, 8, 3, svc1, peers, rank=1)
        k0 = DistributedKVTable(KID, svc0, peers, rank=0)
        DistributedKVTable(KID, svc1, peers, rank=1)

        a0.add(np.arange(100, 110, dtype=np.float32))
        m0.add_rows([1, 3, 6], np.full((3, 3), 10.0, dtype=np.float32))
        k0.add([4, 7], [1000, 1000])

        peer_str = ";".join(f"{h}:{p}" for h, p in peers)
        proc = subprocess.run(
            ["luajit", os.path.join(LUA_DIR, "demo.lua"), _so_path(),
             peer_str, str(AID), str(MID), str(KID)],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, \
            f"lua demo failed:\n{proc.stdout}\n{proc.stderr}"
        assert "LUA_DEMO_OK" in proc.stdout

        np.testing.assert_allclose(
            a1.get(), np.arange(100, 110, dtype=np.float32)
            + np.arange(10, dtype=np.float32))
        np.testing.assert_allclose(m0.get_rows([1, 3, 6]),
                                   np.full((3, 3), 11.0))
        np.testing.assert_array_equal(k0.get([4, 7]), [1004, 1007])
    finally:
        svc0.close()
        svc1.close()
