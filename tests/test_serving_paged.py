"""Decode-side memory hierarchy (ISSUE 11): paged KV slots, prefix-cache
reuse, quantized storage.

The headline contract: paged decode with f32 storage produces tokens
BITWISE-identical to the preallocated drain path — the page gather only
appends exactly-masked keys (softmax weight exactly 0.0), so which
physical pages a slot happens to draw can never change its tokens. On
top of that: prefix sharers alias prompt pages without re-prefilling
(copy-on-extend for the straddle page), pool exhaustion queues at the
admission boundary instead of crashing, and the quantized codecs carry
a bounded-error + greedy-token-parity story."""

import threading
import time

import numpy as np
import pytest


def _lm(max_new=6, max_batch=3, **runner_kw):
    import jax

    from multiverso_tpu.models.attention_lm import LMConfig, init_params
    from multiverso_tpu.serving import AttentionLMRunner

    cfg = LMConfig(vocab=61, dim=32, heads=4, layers=2, seq=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    runner = AttentionLMRunner({k: np.asarray(v) for k, v in
                                params.items()}, cfg, max_new=max_new,
                               max_batch=max_batch, **runner_kw)
    return runner, params, cfg


def _solo_drain_tokens(runner, prompt, bucket):
    mat = np.zeros((runner.max_batch, bucket), np.int32)
    mat[0, :len(prompt)] = prompt
    lens = np.zeros(runner.max_batch, np.int32)
    lens[0] = len(prompt)
    return runner.run(mat, lens)[0].tolist()


# ---------------------------------------------------------------------------
# Page-plan math
# ---------------------------------------------------------------------------
def test_page_plan_classification():
    from multiverso_tpu.serving import page_plan

    # bucket 8, max_new 8, page 4: logical pages 0..3; prompt pages 0-1,
    # gen pages 2-3; page size divides the bucket -> no straddle.
    p = page_plan(3, 8, 8, 4)
    assert p.n_logical == 4 and p.n_prompt == 2
    assert p.shared == (0,)          # holds tokens 0..2
    assert p.pad == (1,)             # pure pad: unbacked
    assert p.private == (2, 3)
    assert p.straddle is None
    assert p.n_backed == 3           # < n_logical: held scales with length

    # page 3 does NOT divide bucket 8: page 2 (positions 6..8) holds
    # prompt tail AND gen head -> the straddle, private, copy-on-extend.
    p = page_plan(7, 8, 6, 3)
    assert p.straddle == 2 and p.straddle in p.private
    assert p.straddle_has_prompt
    # a short prompt leaves the straddle pad-only: no copy needed
    p = page_plan(2, 8, 6, 3)
    assert p.straddle == 2 and not p.straddle_has_prompt
    assert p.shared == (0,) and p.pad == (1,)

    # longer prompts back more pages — the HBM-scales-with-length claim
    assert page_plan(1, 64, 16, 16).n_backed \
        < page_plan(60, 64, 16, 16).n_backed


def test_page_pool_refcounts_and_exhaustion():
    from multiverso_tpu.serving import PagePool

    pool = PagePool(4, layers=1, heads=1, page=2, dh=2)
    a = pool.alloc(3)
    assert a is not None and len(a) == 3 and 0 not in a
    assert pool.alloc(2) is None          # exhausted: caller queues
    pool.incref(a)
    assert pool.decref(a) == 0            # still referenced
    assert pool.decref(a) == 3            # now free
    assert pool.free_pages() == 4


# ---------------------------------------------------------------------------
# Paged continuous decode: bitwise parity with the drain path
# ---------------------------------------------------------------------------
def test_paged_late_join_bitwise_equal_drain_path(mv_env):
    """The PR-9 late-join parity test, paged flavor: joiners mid-decode
    land in pool pages, tokens stay bitwise-equal to solo drain."""
    from multiverso_tpu.serving import ContinuousBatcher

    runner, _, _ = _lm(max_new=8, max_batch=3)
    prompts = [[5, 9, 2], [1], [7, 3, 3, 3, 8, 2, 40]]
    solo = {tuple(p): _solo_drain_tokens(runner, p, bucket=8)
            for p in prompts}

    cb = ContinuousBatcher(runner, buckets=(8,), max_batch=3,
                           max_queue=16, paged=True, page=4)
    try:
        f1 = cb.submit(np.asarray(prompts[0], np.int32),
                       deadline_ms=60_000)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            eng = cb._engines.get(8)
            if eng is not None and eng.n_active() and eng.t.max() >= 1:
                break
            time.sleep(0.001)
        f2 = cb.submit(np.asarray(prompts[1], np.int32),
                       deadline_ms=60_000)
        f3 = cb.submit(np.asarray(prompts[2], np.int32),
                       deadline_ms=60_000)
        for p, f in zip(prompts, (f1, f2, f3)):
            assert f.wait(60).tolist() == solo[tuple(p)], p
    finally:
        cb.close()
    # every page returned at the step-boundary frees
    assert cb.pool.used_pages() == 0


def test_paged_slot_churn_returns_pages(mv_env):
    """3x max_batch requests churn through 2 slots: reused slots stay
    bitwise (stale page contents never leak — the mask contract) and
    the pool drains back to zero used pages."""
    from multiverso_tpu.serving import ContinuousBatcher

    runner, _, _ = _lm(max_new=4, max_batch=2)
    rng = np.random.default_rng(11)
    prompts = [rng.integers(1, 60, int(n)).tolist()
               for n in rng.integers(1, 8, 6)]
    solo = [_solo_drain_tokens(runner, p, bucket=8) for p in prompts]

    cb = ContinuousBatcher(runner, buckets=(8,), max_batch=2,
                           max_queue=16, paged=True, page=4)
    try:
        futs = [cb.submit(np.asarray(p, np.int32), deadline_ms=60_000)
                for p in prompts]
        for p, want, f in zip(prompts, solo, futs):
            assert f.wait(60).tolist() == want, p
    finally:
        cb.close()
    assert cb.pool.used_pages() == 0


def test_paged_multi_bucket_shares_one_pool(mv_env):
    """Engines for different buckets draw from the SAME pool (one jitted
    prefill+step per bucket; exercising a new bucket allocates pages,
    not a fresh max-shape cache)."""
    from multiverso_tpu.serving import ContinuousBatcher

    runner, _, _ = _lm(max_new=3, max_batch=2)
    cb = ContinuousBatcher(runner, buckets=(4, 8), max_batch=2,
                           max_queue=16, paged=True, page=4)
    try:
        s4 = _solo_drain_tokens(runner, [5, 9], bucket=4)
        assert cb.submit(np.asarray([5, 9], np.int32),
                         deadline_ms=60_000).wait(60).tolist() == s4
        assert cb.jit_cache_size() == 1
        s8 = _solo_drain_tokens(runner, [7, 3, 3, 3, 8], bucket=8)
        assert cb.submit(np.asarray([7, 3, 3, 3, 8], np.int32),
                         deadline_ms=60_000).wait(60).tolist() == s8
        assert cb.jit_cache_size() == 2
        assert cb._step_cache_size() == 2
        # re-serving an old bucket never retraces
        assert cb.submit(np.asarray([5, 9], np.int32),
                         deadline_ms=60_000).wait(60).tolist() == s4
        assert cb.jit_cache_size() == 2
    finally:
        cb.close()
    assert cb.pool.used_pages() == 0


def test_paged_non_dividing_page_size_bitwise(mv_env):
    """page=3 leaves a straddle page (prompt tail + gen head) and a
    masked alignment tail past bucket+max_new — tokens still bitwise."""
    from multiverso_tpu.serving import ContinuousBatcher

    runner, _, _ = _lm(max_new=6, max_batch=2)
    prompts = [[7, 3, 3, 3, 8, 2, 40], [5, 9, 2]]
    solo = [_solo_drain_tokens(runner, p, bucket=8) for p in prompts]
    cb = ContinuousBatcher(runner, buckets=(8,), max_batch=2,
                           max_queue=16, paged=True, page=3)
    try:
        futs = [cb.submit(np.asarray(p, np.int32), deadline_ms=60_000)
                for p in prompts]
        for want, f in zip(solo, futs):
            assert f.wait(60).tolist() == want
    finally:
        cb.close()


def test_page_pool_exhaustion_queues_not_crashes(mv_env):
    """A pool sized for ~one request forces the others to QUEUE at the
    step-boundary admission; everyone completes bitwise eventually and
    the exhaustion counter shows the queueing happened."""
    from multiverso_tpu.serving import ContinuousBatcher
    from multiverso_tpu.telemetry import get_registry

    runner, _, _ = _lm(max_new=6, max_batch=3)
    prompts = [[5, 9, 2], [1], [7, 3, 3, 3, 8, 2, 40]]
    solo = [_solo_drain_tokens(runner, p, bucket=8) for p in prompts]
    cb = ContinuousBatcher(runner, buckets=(8,), max_batch=3,
                           max_queue=16, paged=True, page=4,
                           pool_pages=4)
    try:
        futs = [cb.submit(np.asarray(p, np.int32), deadline_ms=60_000)
                for p in prompts]
        for want, f in zip(solo, futs):
            assert f.wait(60).tolist() == want
        snap = get_registry().snapshot(buckets=False)
        assert snap["counters"]["serve.kv.pool_exhausted"]["value"] >= 1
    finally:
        cb.close()
    assert cb.pool.used_pages() == 0


# ---------------------------------------------------------------------------
# Prefix-cache reuse
# ---------------------------------------------------------------------------
def test_prefix_share_skips_prefill_and_stays_bitwise(mv_env):
    """A repeated prompt hits the prefix store: prefill skipped, prompt
    pages shared, tokens bitwise-equal. page=3 forces the straddle
    copy-on-extend path on the long prompt."""
    from multiverso_tpu.serving import ContinuousBatcher
    from multiverso_tpu.telemetry import get_registry

    runner, _, _ = _lm(max_new=6, max_batch=3)
    long_p = [7, 3, 3, 3, 8, 2, 40]
    want = _solo_drain_tokens(runner, long_p, bucket=8)
    cb = ContinuousBatcher(runner, buckets=(8,), max_batch=3,
                           max_queue=16, paged=True, page=3,
                           prefix_entries=8)
    try:
        assert cb.submit(np.asarray(long_p, np.int32),
                         deadline_ms=60_000).wait(60).tolist() == want
        assert cb.submit(np.asarray(long_p, np.int32),
                         deadline_ms=60_000).wait(60).tolist() == want
        snap = get_registry().snapshot(buckets=False)
        assert snap["counters"]["serve.prefix.hits"]["value"] == 1
        assert snap["counters"]["serve.prefix.prefill_skipped"][
            "value"] == 1
        assert snap["counters"]["serve.prefix.shared_pages"]["value"] >= 1
    finally:
        cb.close()
    # the store (not the slots) still holds the prompt pages
    assert cb.pool.used_pages() == len(cb.prefix) \
        or cb.pool.used_pages() >= 1


def test_prefix_share_under_concurrent_free_and_extend(mv_env):
    """Donor slots free while sharers join and extend: interleaved
    repeats of two prompts across slot churn stay bitwise — shared
    prompt pages are never written after prefill, every extension goes
    to private pages."""
    from multiverso_tpu.serving import ContinuousBatcher

    runner, _, _ = _lm(max_new=4, max_batch=2)
    a = [7, 3, 3, 3, 8, 2, 40]
    b = [5, 9, 2]
    want = {tuple(p): _solo_drain_tokens(runner, p, bucket=8)
            for p in (a, b)}
    cb = ContinuousBatcher(runner, buckets=(8,), max_batch=2,
                           max_queue=32, paged=True, page=3,
                           prefix_entries=4)
    try:
        order = [a, b, a, a, b, a, b, a]
        futs = [cb.submit(np.asarray(p, np.int32), deadline_ms=60_000)
                for p in order]
        for p, f in zip(order, futs):
            assert f.wait(60).tolist() == want[tuple(p)], p
    finally:
        cb.close()


def test_prefix_eviction_returns_pages(mv_env):
    """A capacity-1 store evicts the older entry when a second prompt
    publishes; the evicted pages return to the pool once no slot holds
    them (serve.kv.page_evictions counts them)."""
    from multiverso_tpu.serving import ContinuousBatcher
    from multiverso_tpu.telemetry import get_registry

    runner, _, _ = _lm(max_new=3, max_batch=2)
    cb = ContinuousBatcher(runner, buckets=(8,), max_batch=2,
                           max_queue=16, paged=True, page=4,
                           prefix_entries=1)
    try:
        for p in ([5, 9, 2], [7, 3, 3, 3, 8]):
            want = _solo_drain_tokens(runner, p, bucket=8)
            assert cb.submit(np.asarray(p, np.int32),
                             deadline_ms=60_000).wait(60).tolist() == want
        assert len(cb.prefix) == 1
        snap = get_registry().snapshot(buckets=False)
        assert snap["counters"]["serve.kv.page_evictions"]["value"] >= 1
    finally:
        cb.close()


def test_prefix_invalidated_by_param_swap(mv_env):
    """A checkpoint hot-swap must drop every prefix entry — prefill
    output under old weights can never serve new-weight requests."""
    import jax

    from multiverso_tpu.models.attention_lm import init_params
    from multiverso_tpu.serving import ContinuousBatcher

    runner, _, cfg = _lm(max_new=5, max_batch=2)
    prompt = [5, 9, 2]
    want = _solo_drain_tokens(runner, prompt, bucket=8)
    cb = ContinuousBatcher(runner, buckets=(8,), max_batch=2,
                           max_queue=16, paged=True, page=4,
                           prefix_entries=8)
    try:
        assert cb.submit(np.asarray(prompt, np.int32),
                         deadline_ms=60_000).wait(60).tolist() == want
        runner.swap_params({k: np.asarray(v) for k, v in init_params(
            cfg, jax.random.PRNGKey(9)).items()})
        want2 = _solo_drain_tokens(runner, prompt, bucket=8)
        assert want2 != want
        got = cb.submit(np.asarray(prompt, np.int32),
                        deadline_ms=60_000).wait(60).tolist()
        assert got == want2, "prefix served stale-weight prefill output"
    finally:
        cb.close()


# ---------------------------------------------------------------------------
# Quantized storage
# ---------------------------------------------------------------------------
def test_quant_roundtrip_bounded_error():
    from multiverso_tpu.serving.quant import (decode_rows, encode_rows,
                                              roundtrip_bound)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(5, 4, 16)).astype(np.float32) * 3.0
    for dt in ("f32", "bf16", "int8"):
        q, s = encode_rows(x, dt)
        back = np.asarray(decode_rows(q, s, dt))
        err = float(np.max(np.abs(back - x)))
        assert err <= roundtrip_bound(x, dt) + 1e-7, (dt, err)
    # f32 is the identity codec: the SAME object, bit-for-bit
    q, _ = encode_rows(x, "f32")
    assert np.asarray(q) is not None and np.array_equal(np.asarray(q), x)


def test_kv_dtype_greedy_token_parity(mv_env):
    """bf16/int8 KV pages: greedy tokens match the f32 reference on the
    seeded tiny model (bounded dequant error does not flip argmaxes
    here — the parity witness quantized serving ships with)."""
    from multiverso_tpu.serving import ContinuousBatcher

    runner, _, _ = _lm(max_new=6, max_batch=3)
    prompts = [[7, 3, 3, 3, 8, 2, 40], [5, 9, 2], [1]]
    want = [_solo_drain_tokens(runner, p, bucket=8) for p in prompts]
    for dt in ("bf16", "int8"):
        cb = ContinuousBatcher(runner, buckets=(8,), max_batch=3,
                               max_queue=16, paged=True, page=4,
                               kv_dtype=dt)
        try:
            got = [cb.submit(np.asarray(p, np.int32),
                             deadline_ms=60_000).wait(60).tolist()
                   for p in prompts]
            assert got == want, (dt, got)
        finally:
            cb.close()


def test_quantized_kv_requires_paged(mv_env):
    from multiverso_tpu.serving import ContinuousBatcher
    from multiverso_tpu.utils.log import FatalError

    runner, _, _ = _lm(max_new=2, max_batch=1)
    with pytest.raises((FatalError, RuntimeError)):
        ContinuousBatcher(runner, buckets=(8,), max_batch=1,
                          paged=False, kv_dtype="int8")


class _StubReplica:
    """A frozen one-table replica snapshot without checkpoint plumbing."""

    def __init__(self, data, dtype):
        from multiverso_tpu.serving.quant import encode_table
        from multiverso_tpu.serving.replica import ReplicaSnapshot
        self._snap = ReplicaSnapshot(
            3, "stub", {"emb": encode_table(data, dtype)}, dtype)

    def snapshot(self):
        return self._snap


def test_replica_table_dtype_storage(mv_env):
    """f32 replica lookups stay bitwise; bf16/int8 dequant-on-read stays
    within the codec's bound — through the real runner dispatch path."""
    from multiverso_tpu.serving import ReplicaLookupRunner
    from multiverso_tpu.serving.quant import roundtrip_bound

    rng = np.random.default_rng(0)
    data = rng.normal(size=(64, 16)).astype(np.float32)
    keys = rng.integers(0, 64, 8).astype(np.int32)
    mat = np.zeros((2, 8), np.int32)
    mat[0] = keys
    lens = np.asarray([8, 0], np.int32)
    for dt in ("f32", "bf16", "int8"):
        runner = ReplicaLookupRunner(_StubReplica(data, dt), "emb")
        out = runner.run(mat, lens)
        got = runner.slice_result(out, 0, 8)
        if dt == "f32":
            assert np.array_equal(got, data[keys])
        else:
            assert np.max(np.abs(got - data[keys])) \
                <= roundtrip_bound(data, dt) + 1e-7
        assert runner.clock() == 3.0     # the checkpoint step stamp


# ---------------------------------------------------------------------------
# Paged drain path (AttentionLMRunner)
# ---------------------------------------------------------------------------
def test_drain_paged_bitwise_and_pool_returns(mv_env):
    """AttentionLMRunner paged=True: batch tokens bitwise-equal to the
    preallocated drain decode across buckets, pages freed at collect,
    one executable per bucket, one pool across buckets."""
    runner, params, cfg = _lm(max_new=6, max_batch=3)
    paged, _, _ = _lm(max_new=6, max_batch=3, paged=True, page=4)

    rng = np.random.default_rng(3)
    for bucket in (8, 4):
        mat = np.zeros((3, bucket), np.int32)
        lens = np.zeros(3, np.int32)
        for i in range(3):
            n = int(rng.integers(1, bucket + 1))
            mat[i, :n] = rng.integers(1, 60, n)
            lens[i] = n
        assert np.array_equal(runner.run(mat, lens),
                              paged.run(mat, lens)), bucket
    assert paged.jit_cache_size() == 2
    assert paged._pool.used_pages() == 0


def test_drain_paged_pool_grows_instead_of_deadlocking(mv_env):
    """A drain batch larger than the configured pool GROWS the pool
    (logged + counted) — the correctness valve; serving-side budgets
    belong to the continuous engine's queueing admission."""
    from multiverso_tpu.telemetry import get_registry

    paged, _, _ = _lm(max_new=4, max_batch=2, paged=True, page=4,
                      pool_pages=2)
    mat = np.zeros((2, 8), np.int32)
    mat[0, :3] = [5, 9, 2]
    mat[1, :2] = [7, 3]
    out = paged.run(mat, np.asarray([3, 2], np.int32))
    assert out.shape == (2, 4)
    snap = get_registry().snapshot(buckets=False)
    assert snap["counters"]["serve.kv.pool_grows"]["value"] >= 1
    assert paged._pool.used_pages() == 0


# ---------------------------------------------------------------------------
# Satellite regressions: cache-hit stamp + continuous degrade
# ---------------------------------------------------------------------------
def test_cache_hit_reply_carries_entry_stamp(mv_env):
    """ROADMAP 5a: with -serve_cache_staleness>0, a cache-hit reply must
    claim the STAMP OF ITS BYTES, not runner.clock() (which a fresher
    batch for other keys may have advanced past the cached rows)."""
    import jax
    from jax.sharding import Mesh

    from multiverso_tpu.core.table import ServerStore
    from multiverso_tpu.core.updater import get_updater
    from multiverso_tpu.serving import (HotRowCache, ServingClient,
                                        ServingService,
                                        SparseLookupRunner)

    rng = np.random.default_rng(0)
    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1), ("server",))
    store = ServerStore("t", (64, 8), np.float32,
                        get_updater(np.float32, "default"), mesh,
                        num_workers=1,
                        init_array=rng.normal(size=(64, 8))
                        .astype(np.float32))
    clock = [0.0]
    svc = ServingService()
    svc.register_runner(
        SparseLookupRunner(store, clock_fn=lambda: (clock[0], 0.0),
                           cache=HotRowCache(64, staleness=2)),
        buckets=(8,), max_batch=2, max_wait_ms=0.5, continuous=False,
        pipeline_depth=0)
    cli = ServingClient(*svc.address)
    try:
        keys_a = np.asarray([1, 2, 3], np.int32)
        vals_a, c_a = cli.request_async(keys_a,
                                        deadline_ms=10_000).wait(30)
        assert c_a == 0
        clock[0] = 1.0      # training tick: a fresh batch for OTHER keys
        _, c_b = cli.request_async(np.asarray([9, 10], np.int32),
                                   deadline_ms=10_000).wait(30)
        assert c_b == 1     # runner.clock() now reads 1
        vals_hit, c_hit = cli.request_async(keys_a,
                                            deadline_ms=10_000).wait(30)
        assert np.array_equal(vals_hit, vals_a)
        assert c_hit == 0, \
            "cache-hit reply claimed a newer version than its bytes"
    finally:
        cli.close()
        svc.close()


class _UnsupportedDecodeRunner:
    """A decode runner for a checkpoint shape ContinuousBatcher refuses
    (MoE / pipeline attention_lm)."""

    name = "unsupported_lm"
    payload_dtype = np.int32
    pad_id = 0
    max_new = 4

    def __init__(self, cfg):
        self.cfg = cfg

    def params_ref(self):
        return {}

    def run(self, batch, lengths):
        return np.zeros((batch.shape[0], self.max_new), np.int32)

    def slice_result(self, out, i, length):
        return out[i]

    def jit_cache_size(self):
        return 0


@pytest.mark.parametrize("shape", ["moe", "pipeline"])
def test_continuous_degrades_to_drain_on_unsupported_checkpoints(
        mv_env, shape):
    """ROADMAP 5b: -serve_continuous=true on a MoE/pipeline attention_lm
    checkpoint degrades to drain batching (logged) instead of crashing
    serving bring-up — and the degraded service still answers."""
    from multiverso_tpu.models.attention_lm import LMConfig
    from multiverso_tpu.serving import (ContinuousBatcher, DynamicBatcher,
                                        ServingService)

    cfg = LMConfig(moe_experts=2) if shape == "moe" \
        else LMConfig(pipeline_stages=2, layers=2)
    svc = ServingService()
    try:
        svc.register_runner(_UnsupportedDecodeRunner(cfg), buckets=(8,),
                            max_batch=2, continuous=True,
                            pipeline_depth=0)
        b = svc.batcher(0)
        assert isinstance(b, DynamicBatcher)
        assert not isinstance(b, ContinuousBatcher)
        out = b.submit(np.asarray([1, 2], np.int32),
                       deadline_ms=10_000).wait(30)
        assert out.shape == (4,)
    finally:
        svc.close()


def test_paged_through_service_with_swap(mv_env):
    """Full plane, paged flavor: register with continuous+paged+prefix,
    serve decodes over the wire, hot-swap params mid-life — the NEXT
    request serves the new weights (prefix store invalidated)."""
    import jax

    from multiverso_tpu.models.attention_lm import init_params
    from multiverso_tpu.serving import ServingClient, ServingService

    runner, _, cfg = _lm(max_new=5, max_batch=2)
    svc = ServingService()
    svc.register_runner(runner, buckets=(8,), max_batch=2,
                        max_wait_ms=1.0, continuous=True, paged=True,
                        kv_dtype="f32", kv_page=4, kv_pages=0,
                        prefix_entries=8)
    assert svc.warmup() == 2
    cli = ServingClient(*svc.address)
    try:
        prompt = [5, 9, 2]
        want = _solo_drain_tokens(runner, prompt, bucket=8)
        got = cli.generate(np.asarray(prompt, np.int32),
                           deadline_ms=60_000, timeout=120)
        assert got.tolist() == want
        # repeat -> prefix hit over the wire
        got = cli.generate(np.asarray(prompt, np.int32),
                           deadline_ms=60_000, timeout=120)
        assert got.tolist() == want

        runner.swap_params({k: np.asarray(v) for k, v in init_params(
            cfg, jax.random.PRNGKey(9)).items()})
        want2 = _solo_drain_tokens(runner, prompt, bucket=8)
        assert want2 != want
        got2 = cli.generate(np.asarray(prompt, np.int32),
                            deadline_ms=60_000, timeout=120)
        assert got2.tolist() == want2
    finally:
        cli.close()
        svc.close()


def test_paged_quiesce_and_cancel_release_claims(mv_env):
    """Shed paths must release reserved pages/pins: cancel a queued
    request while the single slot is busy, then quiesce — the pool must
    drain to zero used pages (no leaked claims)."""
    from multiverso_tpu.serving import ContinuousBatcher, ShedError

    runner, _, _ = _lm(max_new=12, max_batch=1)
    cb = ContinuousBatcher(runner, buckets=(8,), max_batch=1,
                           max_queue=8, paged=True, page=4)
    try:
        running = cb.submit(np.asarray([5, 9, 2], np.int32),
                            deadline_ms=60_000)
        done = threading.Event()
        outcome = []

        def on_done(result):
            outcome.append(result)
            done.set()

        token = cb.submit_callback(np.asarray([7], np.int32), 60_000.0,
                                   on_done)
        if token is not None and cb.cancel(token):
            assert done.wait(30)
            assert isinstance(outcome[0], ShedError)
        running.wait(60)
        assert cb.quiesce(timeout_s=60)
    finally:
        cb.close()
    assert cb.pool.used_pages() == 0


# ---------------------------------------------------------------------------
# Review-fix regressions: never-fits shed, retention reclaim, params
# token soundness, config fail-fast
# ---------------------------------------------------------------------------
def test_request_larger_than_pool_is_shed_not_hung(mv_env):
    """A request whose page need exceeds TOTAL pool capacity can never
    be served by waiting — it must shed with a clear reason instead of
    queueing forever (and the worker must not wedge)."""
    from multiverso_tpu.serving import ContinuousBatcher, ShedError

    runner, _, _ = _lm(max_new=6, max_batch=2)
    cb = ContinuousBatcher(runner, buckets=(8,), max_batch=2,
                           max_queue=8, paged=True, page=4,
                           pool_pages=1)     # one page: nothing fits
    try:
        with pytest.raises(ShedError) as e:
            cb.submit(np.asarray([7, 3, 3, 3, 8, 2, 40], np.int32),
                      deadline_ms=60_000).wait(30)
        assert e.value.reason == "oversize"
        # the batcher is still alive for admission-level decisions
        with pytest.raises(ShedError):
            cb.submit(np.arange(9, dtype=np.int32) + 1,
                      deadline_ms=60_000).wait(30)
    finally:
        cb.close()


def test_prefix_retention_yields_pages_to_live_admissions(mv_env):
    """Store-retained pages must never starve the pool: with a pool
    sized for ~one request and a prefix store holding the previous
    prompt's pages, the NEXT (different) prompt must still complete —
    the allocator reclaims LRU entries instead of queueing forever."""
    from multiverso_tpu.serving import ContinuousBatcher
    from multiverso_tpu.telemetry import get_registry

    runner, _, _ = _lm(max_new=6, max_batch=2)
    prompts = [[7, 3, 3, 3, 8, 2, 40], [5, 9, 2], [1, 2, 3, 4, 5, 6]]
    solo = [_solo_drain_tokens(runner, p, bucket=8) for p in prompts]
    cb = ContinuousBatcher(runner, buckets=(8,), max_batch=2,
                           max_queue=8, paged=True, page=4,
                           pool_pages=4, prefix_entries=8)
    try:
        for p, want in zip(prompts, solo):
            got = cb.submit(np.asarray(p, np.int32),
                            deadline_ms=60_000).wait(60).tolist()
            assert got == want, p
        snap = get_registry().snapshot(buckets=False)
        assert snap["counters"]["serve.kv.page_evictions"]["value"] >= 1
    finally:
        cb.close()


def test_params_token_is_monotonic_not_identity(mv_env):
    """The prefix store's weights token must be the runner's monotonic
    swap version — id() of the params dict can be REUSED by the
    allocator after two swaps, silently validating stale entries."""
    import jax

    from multiverso_tpu.models.attention_lm import init_params

    runner, _, cfg = _lm(max_new=2, max_batch=1)
    _, v0 = runner.params_versioned()
    runner.swap_params({k: np.asarray(v) for k, v in init_params(
        cfg, jax.random.PRNGKey(1)).items()})
    _, v1 = runner.params_versioned()
    runner.swap_params({k: np.asarray(v) for k, v in init_params(
        cfg, jax.random.PRNGKey(2)).items()})
    _, v2 = runner.params_versioned()
    assert v0 < v1 < v2


def test_register_runner_bad_paged_config_fails_fast(mv_env):
    """A flag MISCONFIGURATION (quantized KV without paged mode, bad
    dtype, zero page) must crash bring-up loudly — only genuine
    checkpoint-layout incompatibilities degrade to drain batching."""
    from multiverso_tpu.models.attention_lm import LMConfig
    from multiverso_tpu.serving import ServingService
    from multiverso_tpu.utils.log import FatalError

    svc = ServingService()
    try:
        for kw in ({"paged": False, "kv_dtype": "int8"},
                   {"paged": True, "kv_dtype": "fp4"},
                   {"paged": True, "kv_page": 0},
                   {"paged": False, "prefix_entries": 8}):
            cfg_kw = dict(paged=False, kv_dtype="f32", kv_page=4,
                          kv_pages=0, prefix_entries=0)
            cfg_kw.update(kw)
            with pytest.raises((FatalError, RuntimeError)):
                svc.register_runner(
                    _UnsupportedDecodeRunner(LMConfig()), buckets=(8,),
                    max_batch=2, continuous=True, pipeline_depth=0,
                    **cfg_kw)
    finally:
        svc.close()
