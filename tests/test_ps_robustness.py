"""PSService dispatch robustness (VERDICT r3 next-round #5 + ADVICE r3).

The service must stay live under misbehaving peers and loose timing:
* a peer that never reads its replies only fills ITS OWN write buffer —
  other clients' table ops proceed unimpeded (reply writes live on the IO
  thread, not the dispatcher);
* a retransmitted Add (elastic retry after a lost reply) is answered from
  the reply cache, not re-applied — exactly-once, not at-least-once;
* a request arriving before its table registers is parked and replayed,
  never blocking the dispatcher;
* BSP ops wait without a deadline (the reference Waiter blocks), and
  row-routed tables tick every server's clock uniformly so sparse access
  patterns can't wedge the gates (ADVICE r3 medium #2);
* Server_Finish_Train is scoped to its table (ADVICE r3 low #4).
"""

import socket
import threading
import time

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.core.actor import Message, MsgType
from multiverso_tpu.core.options import AddOption, GetOption
from multiverso_tpu.parallel.net import recv_message, send_message
from multiverso_tpu.parallel.ps_service import (DistributedArrayTable,
                                                DistributedMatrixTable,
                                                PSService, _opt_to_array,
                                                pack_payload, unpack_payload)


@pytest.fixture
def one_rank_world(mv_env):
    svc = PSService()
    yield svc, [svc.address]
    svc.close()


def test_stalled_peer_does_not_block_other_clients(one_rank_world):
    """A peer that sends Gets but never reads the replies must not freeze
    the dispatcher: a well-behaved client's ops complete promptly while
    the stalled peer's replies pile up in its own write buffer."""
    svc, peers = one_rank_world
    size = 20000     # 80KB replies: a handful exceeds the socket buffers
    table = DistributedArrayTable(1, size, svc, peers, rank=0)
    table.add(np.ones(size, dtype=np.float32))

    stalled = socket.create_connection(svc.address, timeout=10)
    # Shrink the receive window so the server-side write buffer backs up
    # after very few replies (forcing the old code's blocking-send path).
    stalled.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
    for i in range(40):
        send_message(stalled, Message(
            src=9, type=MsgType.Request_Get, table_id=1, msg_id=10_000 + i,
            data=[np.empty(0, np.int32)]))
    # ... and never read a single reply.

    time.sleep(0.5)   # let the dispatcher chew through the stalled Gets
    t0 = time.monotonic()
    with socket.create_connection(svc.address, timeout=10) as good:
        for i in range(5):
            send_message(good, Message(
                src=8, type=MsgType.Request_Get, table_id=1,
                msg_id=20_000 + i, data=[np.empty(0, np.int32)]))
            reply = recv_message(good)
            assert reply is not None and reply.type == MsgType.Reply_Get
            np.testing.assert_allclose(
                unpack_payload(reply.data).ravel()[:size], 1.0)
    elapsed = time.monotonic() - t0
    # Old code: each stalled reply could hold the dispatcher up to 60s.
    assert elapsed < 10.0, f"good client starved for {elapsed:.1f}s"
    stalled.close()


def test_duplicate_add_is_applied_exactly_once(one_rank_world):
    """Resending an identical Add (same src, msg_id — the elastic retrier's
    behavior after a lost reply) must answer from the reply cache without
    touching the table again."""
    svc, peers = one_rank_world
    size = 8
    table = DistributedArrayTable(2, size, svc, peers, rank=0)
    delta = np.full(size, 3.0, dtype=np.float32)
    msg = Message(src=7, type=MsgType.Request_Add, table_id=2, msg_id=555,
                  data=[np.empty(0, np.int32), _opt_to_array(AddOption()),
                        *pack_payload(delta, "none")])
    with socket.create_connection(svc.address, timeout=10) as conn:
        send_message(conn, msg)
        assert recv_message(conn).type == MsgType.Reply_Add
        send_message(conn, msg)     # retransmit on the same connection
        assert recv_message(conn).type == MsgType.Reply_Add
    # A second connection models the retry-after-reconnect path.
    with socket.create_connection(svc.address, timeout=10) as conn:
        send_message(conn, msg)
        assert recv_message(conn).type == MsgType.Reply_Add
    np.testing.assert_allclose(table.get(), delta)   # once, not thrice


def test_early_request_parks_until_registration(one_rank_world):
    """A Get that arrives before register_shard is deferred (the dispatcher
    keeps serving other traffic) and replayed once the table appears."""
    svc, peers = one_rank_world
    conn = socket.create_connection(svc.address, timeout=10)
    send_message(conn, Message(src=4, type=MsgType.Request_Get, table_id=77,
                               msg_id=1234, data=[np.empty(0, np.int32)]))
    time.sleep(0.3)
    # The dispatcher must NOT be blocked on table 77: a registered-table op
    # on another connection completes while 77's Get is parked.
    probe = DistributedArrayTable(3, 4, svc, peers, rank=0)
    probe.add(np.ones(4, dtype=np.float32))
    np.testing.assert_allclose(probe.get(), 1.0)

    late = DistributedArrayTable(77, 6, svc, peers, rank=0)
    late.add(np.full(6, 2.0, dtype=np.float32))
    conn.settimeout(15)
    reply = recv_message(conn)     # the parked Get finally answers
    assert reply is not None and reply.msg_id == 1234
    assert unpack_payload(reply.data).ravel().shape[0] >= 6
    conn.close()


def test_bsp_waits_have_no_deadline_async_keeps_one(mv_env):
    """ADVICE r3 medium #1: sync-mode ops wait indefinitely (straggler skew
    is routine); async mode keeps the 60s fail-loud deadline."""
    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    try:
        t = DistributedArrayTable(1, 8, svc0, peers, rank=0)
        assert t._op_timeout == 60.0
    finally:
        svc0.close(); svc1.close()
    mv.shutdown()
    mv.init(["-sync=true"], num_local_workers=1)
    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    try:
        t = DistributedArrayTable(1, 8, svc0, peers, rank=0)
        assert t._bsp and t._op_timeout is None
    finally:
        svc0.close(); svc1.close()


@pytest.fixture
def sync_world():
    mv.init(["-sync=true"], num_local_workers=1)
    svc0 = PSService()
    svc1 = PSService()
    yield svc0, svc1, [svc0.address, svc1.address]
    svc0.close()
    svc1.close()
    mv.shutdown()


def _rows_loop(table, wid, rows, rounds, views, errors):
    deltas = np.ones((len(rows), table.num_col), dtype=np.float32)
    try:
        for i in range(rounds):
            table.add_rows(rows, deltas, AddOption(worker_id=wid))
            got = table.get_rows(rows, GetOption(worker_id=wid))
            views.append((i, got.copy()))
    except Exception as e:  # noqa: BLE001 - surfaced by the main thread
        errors.append(e)


def test_bsp_row_routed_matrix_does_not_wedge(sync_world):
    """ADVICE r3 medium #2: each worker touches rows on only ONE server
    (w2v-style sparse access). Empty clock-tick messages to the untouched
    servers keep every gate's vector clock uniform, so the ops drain
    instead of caching forever."""
    svc0, svc1, peers = sync_world
    # rows 0-9 on rank 0, 10-19 on rank 1
    m0 = DistributedMatrixTable(5, 20, 4, svc0, peers, rank=0)
    m1 = DistributedMatrixTable(5, 20, 4, svc1, peers, rank=1)
    assert m0._bsp
    rounds = 3
    views0, views1, errors = [], [], []
    threads = [
        threading.Thread(target=_rows_loop,
                         args=(m0, 0, [1, 3], rounds, views0, errors)),
        threading.Thread(target=_rows_loop,
                         args=(m1, 0, [15, 17], rounds, views1, errors)),
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=60)
        assert not th.is_alive(), "BSP row-routed worker wedged"
    assert not errors, errors
    # Disjoint rows: each worker's i-th view shows exactly its own adds.
    for i, got in views0:
        np.testing.assert_allclose(got, float(i + 1))
    for i, got in views1:
        np.testing.assert_allclose(got, float(i + 1))


def test_finish_train_scoped_to_one_table(sync_world):
    """Retiring a worker from table A must not set its clocks to infinity
    on table B (ADVICE r3 low #4)."""
    svc0, svc1, peers = sync_world
    ta0 = DistributedArrayTable(6, 8, svc0, peers, rank=0)
    DistributedArrayTable(6, 8, svc1, peers, rank=1)
    tb0 = DistributedArrayTable(7, 8, svc0, peers, rank=0)
    DistributedArrayTable(7, 8, svc1, peers, rank=1)
    ta0.finish_train(0)
    inf = float("inf")
    for svc in (svc0, svc1):
        assert svc._sync[6]._adds.value(0) == inf     # retired on A
        assert svc._sync[7]._adds.value(0) == 0.0     # still live on B
    tb0.close(); ta0.close()


def test_rank0_restart_rediscovered_via_replicated_directory(mv_env):
    """The one seat round-3 rediscovery could not cover: rank 0 (the
    directory host) dies and restarts at a NEW address. The directory is
    now replicated on every service and a restarting rank registers with
    every live peer, so rank 1 rediscovers rank 0 from its OWN replica —
    automatically, with no manual reconnect()."""
    import os
    import tempfile

    from multiverso_tpu.core import checkpoint as ckpt

    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    t0 = DistributedArrayTable(9, 40, svc0, peers, rank=0)
    t1 = DistributedArrayTable(9, 40, svc1, peers, rank=1)
    t1.add(np.arange(40, dtype=np.float32))
    np.testing.assert_allclose(t1.get(), np.arange(40))

    uri = f"file://{os.path.join(tempfile.mkdtemp(), 'shard0.npz')}"
    ckpt.save_table(t0, uri)
    svc0.close()
    time.sleep(0.2)

    # rank 0 restarts at a NEW address; enable_directory registers the
    # new seat with rank 1's directory replica during table construction.
    svc0b = PSService()
    t0b = DistributedArrayTable(9, 40, svc0b,
                                [svc0b.address, peers[1]], rank=0)
    ckpt.load_table(t0b, uri)

    # rank 1's next op hits the dead connection, retries through its own
    # replica, and lands on the restarted rank 0 — no reconnect() call.
    t1.add(np.ones(40, dtype=np.float32))
    got = t1.get()
    np.testing.assert_allclose(got, np.arange(40) + 1.0)
    np.testing.assert_allclose(t0b.get(), np.arange(40) + 1.0)
    svc0b.close(); svc1.close()


def test_malformed_wire_traffic_does_not_kill_the_service(one_rank_world):
    """Garbage bytes, truncated frames, bogus blob headers, and
    structurally-valid-but-semantically-broken requests must at worst
    cost the sender its connection — a well-behaved client keeps
    working afterwards."""
    import struct

    svc, peers = one_rank_world
    table = DistributedArrayTable(90, 8, svc, peers, rank=0)
    table.add(np.arange(8, dtype=np.float32))

    def frame(n_blobs, blob=b""):
        return struct.pack("<Iiiqii", 0x4D565450, 1, 90, 7, 0,
                           n_blobs) + blob

    def blob(dtype_tag, ndim, dims=(), nbytes=0, payload=b""):
        return (struct.pack("<16sI", dtype_tag, ndim)
                + b"".join(struct.pack("<q", d) for d in dims)
                + struct.pack("<q", nbytes) + payload)

    attacks = [
        b"\x00" * 64,                                   # bad magic
        struct.pack("<I", 0x4D565450),                  # magic only (EOF)
        # COMPLETE frames with malformed blobs — these must drive the
        # parser into its error paths, not just wait for more bytes:
        frame(1, blob(b"\x01bogus", 1, (2,), 8, b"\x00" * 8)),  # dtype
        frame(1, blob(b"<f4", 1, (999,), 8, b"\x00" * 8)),  # shape lie
        frame(1, blob(b"<f4", 64)),                     # absurd ndim
        frame(1, blob(b"<f4", 1, (2,), -8)),            # negative size
        frame(1 << 20),                                 # absurd n_blobs
        frame(1, blob(b"<f4", 1, (2,), 1 << 40)),       # absurd nbytes
    ]
    for payload in attacks:
        with socket.create_connection(svc.address, timeout=5) as s:
            s.sendall(payload)
            s.settimeout(2)
            try:
                s.recv(1024)    # server may drop us; must not crash
            except (socket.timeout, OSError):
                pass

    # Semantically broken but well-framed: Add with a corrupt payload
    # marker. The dispatcher logs, drops the connection, and lives.
    bad = Message(src=5, type=MsgType.Request_Add, table_id=90,
                  msg_id=424242,
                  data=[np.empty(0, np.int32), _opt_to_array(AddOption()),
                        np.asarray([99, 1, 8], dtype=np.int64),  # mode 99
                        np.ones(8, np.float32)])
    with socket.create_connection(svc.address, timeout=5) as s:
        send_message(s, bad)
        s.settimeout(3)
        try:
            s.recv(1024)
        except (socket.timeout, OSError):
            pass

    # The service survived everything: a clean client still round-trips.
    assert svc.num_service_threads == 2
    np.testing.assert_allclose(table.get(), np.arange(8, dtype=np.float32))
    table.add(np.ones(8, dtype=np.float32))
    np.testing.assert_allclose(table.get(),
                               np.arange(8, dtype=np.float32) + 1.0)
