"""Pallas grid-resident sg-ns chunk loop: interpret-mode numerics on CPU.

The kernel's contract (ISSUE 2 tentpole) is that swapping the chunk-loop
execution NEVER changes training semantics: the sequential grid with
VMEM-resident tables must reproduce the jitted in-graph ``fori_loop`` and
the host-dispatched chunk chain bitwise. These tests pin that at the
kernel level; the end-to-end three-way test lives in test_word2vec.py."""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from multiverso_tpu.ops.pallas_sgns import (build_sgns_grid_step,
                                            sgns_grid_bytes,
                                            sgns_grid_eligible)


def _tables(V, D, dtype=jnp.float32, seed=1):
    w = jnp.asarray(np.random.default_rng(seed)
                    .normal(size=(V, D)).astype(np.float32)).astype(dtype)
    return [w, jnp.zeros((V, D), dtype),
            jnp.zeros((V, D), jnp.float32), jnp.zeros((V, D), jnp.float32)]


def _streams(V, C, K, N, seed=0):
    rng = np.random.default_rng(seed)
    return (jnp.asarray(rng.integers(0, V, (N, C)).astype(np.int32)),
            jnp.asarray(rng.integers(0, V, (N, C)).astype(np.int32)),
            jnp.asarray(rng.integers(0, V, (N, C, K)).astype(np.int32)))


def _fori_reference(adagrad, V, C, K, N, streams, n_pairs, lr, dtype):
    """The in-graph formulation the kernel must match bitwise."""
    from multiverso_tpu.models.word2vec.model import raw_sg_ns_step
    raw = raw_sg_ns_step(adagrad)
    centers, contexts, negs = streams

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
    def loop(w_in, w_out, g_in, g_out):
        lane = jnp.arange(C)

        def body(i, carry):
            *t, loss = carry
            m = ((i * C + lane) < n_pairs).astype(jnp.float32)
            out = raw(*t, centers[i], contexts[i], negs[i], m, lr)
            return (*out[:4], loss + out[4])

        return jax.lax.fori_loop(
            0, N, body, (w_in, w_out, g_in, g_out, jnp.float32(0)))

    return loop(*_tables(V, 16, dtype))


@pytest.mark.parametrize("adagrad", [True, False])
def test_grid_step_matches_fori_bitwise(adagrad):
    """Full chunks + a partially masked tail: bitwise-identical tables and
    an identical loss against the jitted in-graph loop."""
    V, D, C, K, N = 64, 16, 8, 3, 4
    streams = _streams(V, C, K, N)
    n_pairs = jnp.int32(N * C - 5)
    lr = jnp.float32(0.05)
    ref = _fori_reference(adagrad, V, C, K, N, streams, n_pairs, lr,
                          jnp.float32)
    step = build_sgns_grid_step(chunk=C, negative=K, adagrad=adagrad,
                                interpret=True)
    got = step(*_tables(V, D), *streams, n_pairs, lr)
    for r, g in zip(ref[:4], got[:4]):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g))
    np.testing.assert_allclose(float(got[4]), float(ref[4]), rtol=1e-6)
    assert np.isfinite(float(got[4]))


def test_grid_step_dead_chunks_are_noops():
    """n_pairs masking: chunks past the live count must leave the tables
    bitwise untouched (the static grid may contain all-padding chunks that
    the in-graph dynamic-trip loop never runs)."""
    V, D, C, K, N = 32, 16, 8, 2, 3
    streams = _streams(V, C, K, N, seed=2)
    lr = jnp.float32(0.1)
    step = build_sgns_grid_step(chunk=C, negative=K, adagrad=True,
                                interpret=True)
    live = step(*_tables(V, D), *streams, jnp.int32(C), lr)       # 1 chunk
    # Same single live chunk, but the grid sweeps two extra dead chunks.
    dead = step(*_tables(V, D), *streams[:2], streams[2],
                jnp.int32(C), lr)
    for a, b in zip(live[:4], dead[:4]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # Zero live pairs: the whole sweep is a numerical no-op.
    base = _tables(V, D)
    out = step(*[jnp.array(t) for t in base], *streams, jnp.int32(0), lr)
    for a, b in zip(base, out[:4]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert float(out[4]) == 0.0


def test_grid_step_bfloat16_tables():
    """bf16 embedding storage (f32 accumulators/math) through the kernel
    matches the in-graph loop bitwise."""
    V, D, C, K, N = 48, 16, 8, 2, 3
    streams = _streams(V, C, K, N, seed=3)
    n_pairs = jnp.int32(N * C)
    lr = jnp.float32(0.05)
    ref = _fori_reference(True, V, C, K, N, streams, n_pairs, lr,
                          jnp.bfloat16)
    step = build_sgns_grid_step(chunk=C, negative=K, adagrad=True,
                                interpret=True)
    got = step(*_tables(V, D, jnp.bfloat16), *streams, n_pairs, lr)
    assert got[0].dtype == jnp.bfloat16
    for r, g in zip(ref[:4], got[:4]):
        np.testing.assert_array_equal(
            np.asarray(r).view(np.uint16) if r.dtype == jnp.bfloat16
            else np.asarray(r),
            np.asarray(g).view(np.uint16) if g.dtype == jnp.bfloat16
            else np.asarray(g))


def test_vmem_eligibility_model():
    """The AUTO gate: small vocabs fit, the 50K-vocab bench shape does
    not (that is exactly why pipelined_host/in_graph still exist)."""
    assert sgns_grid_eligible(2048, 2048, 128, 8192, 5, np.float32)
    assert not sgns_grid_eligible(50_000, 50_000, 128, 8192, 5, np.float32)
    # bf16 embeddings shrink the resident bytes but accumulators stay f32
    assert (sgns_grid_bytes(4096, 4096, 128, 8192, 5, np.dtype("bfloat16"))
            < sgns_grid_bytes(4096, 4096, 128, 8192, 5, np.float32))
