"""Tier-1 smoke: ``state_bench.py --dry-run`` end to end (ISSUE 12).

Drives the sharded-state + fused-kernel bench at smoke shape in a
subprocess (its own XLA_FLAGS/platform pinning must work standalone) and
asserts the witness block: the memory claim (adagrad-class state bytes
drop >= 40% at replicas >= 2), the parity claims (sharded params bitwise,
Pallas fused kernel bitwise vs XLA), and the fused-over-unfused dispatch
win — so none of them can silently regress.
"""

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_state_bench_dry_run():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)       # the script pins cpu itself
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "state_bench.py"),
         "--dry-run"],
        capture_output=True, text=True, timeout=420, cwd=_REPO, env=env)
    assert proc.returncode == 0, proc.stderr[-4000:]
    record = json.loads(proc.stdout.strip().splitlines()[-1])
    assert record["metric"] == "state_sharding_bench"
    assert record["dry_run"] is True

    w = record["witnesses"]
    assert w["sharded_params_bitwise"], w
    assert w["pallas_fused_bitwise_vs_xla"], w
    assert w["adagrad_state_reduction_ge_40pct"], w
    assert w["sharded_capacity_gain_gt_1"], w
    # The >= 1.3x dispatch-fusion ratio is a TIMING claim: asserted on
    # full runs (state_bench exits 1, gating the committed record), but
    # a smoke on a loaded CI box only checks it was measured and
    # recorded — a wall-clock dip must not fail tier-1.
    assert "fused_over_unfused_ge_1_3" in w
    for upd, rec in record["stateful_sparse"]["per_updater"].items():
        for leg in rec.values():
            assert leg["fused_updates_per_sec"] > 0, (upd, leg)
            assert leg["unfused_updates_per_sec"] > 0, (upd, leg)

    mem = record["state_memory"]
    if mem["replicas"] >= 2:
        ada = mem["per_updater"]["adagrad"]
        assert ada["state_reduction_pct"] >= 40.0
        assert ada["on"]["state_sharded"] and not ada["off"]["state_sharded"]
        # gauge-backed: bytes scale exactly with the replica count
        assert (ada["off"]["state_bytes"]
                == ada["on"]["state_bytes"] * mem["replicas"])
