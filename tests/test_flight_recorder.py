"""Unit tests for the flight recorder, wedge watchdog, and postmortem
dumps (ISSUE 13).

Trips are driven through ``_WatchdogMonitor.check_once()`` or tiny
timeouts + a fast poll — never by waiting out production timeouts — so
the module stays cheap in tier-1.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np  # noqa: F401 - conftest's device mesh setup

from multiverso_tpu.telemetry import (build_postmortem, dump_postmortem,
                                      flight_recorder, get_registry, span,
                                      start_watchdog, stop_watchdog,
                                      validate_postmortem,
                                      watchdog_handles, watchdog_register)
from multiverso_tpu.utils.log import log

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_watchdog_trips_wedged_loop_and_dumps_postmortem(mv_env, tmp_path):
    """A loop that stops beating trips exactly once per wedge, and the
    dump is a schema-valid postmortem carrying every live thread's
    stack."""
    reg = get_registry()
    trips0 = reg.counter("telemetry.watchdog.trips").value

    wedged = threading.Event()

    def loop(handle):
        while not wedged.is_set():
            handle.beat()
            time.sleep(0.01)
        time.sleep(10)          # the wedge: alive, no progress

    h = watchdog_register("wedge-unit", timeout_s=0.15)
    t = threading.Thread(target=loop, args=(h,), daemon=True)
    t.start()
    start_watchdog(poll_s=0.03, out_dir=str(tmp_path))
    try:
        time.sleep(0.3)
        assert reg.counter("telemetry.watchdog.trips").value == trips0, \
            "a beating loop tripped (steady state must be quiet)"
        wedged.set()
        deadline = time.monotonic() + 5
        while reg.counter("telemetry.watchdog.trips").value == trips0 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert reg.counter("telemetry.watchdog.trips").value == trips0 + 1
        # one trip per wedge: the monitor must not re-trip every poll
        time.sleep(0.2)
        assert reg.counter("telemetry.watchdog.trips").value == trips0 + 1
    finally:
        stop_watchdog()
        h.close()

    path = tmp_path / f"postmortem-{os.getpid()}.json"
    # The dump runs detached from the monitor (bounded join — a wedged
    # lock holder must not wedge the watchdog too): poll for the file.
    deadline = time.monotonic() + 5
    while not path.exists() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert path.exists(), "tripped watchdog wrote no postmortem"
    pm = json.loads(path.read_text())
    validate_postmortem(pm)
    assert pm["reason"]["kind"] == "watchdog"
    assert pm["reason"]["loop"] == "wedge-unit"
    # >= all live threads: the wedged loop AND the main thread both show
    names = {t["name"] for t in pm["threads"]}
    assert "MainThread" in names
    assert len(pm["threads"]) >= 2
    assert pm["watchdogs"]["wedge-unit"]["tripped"] is True
    # the trip itself is a flight event inside its own dump
    assert any(e["kind"] == "watchdog_trip"
               for e in pm["flight"]["events"])


def test_watchdog_rearms_after_beat(mv_env):
    from multiverso_tpu.telemetry.flight import _WatchdogMonitor
    h = watchdog_register("rearm-unit", timeout_s=0.05)
    mon = _WatchdogMonitor(poll_s=3600.0, out_dir=None)  # manual sweeps
    try:
        time.sleep(0.1)
        assert mon.check_once() == ["rearm-unit"]
        assert mon.check_once() == []       # tripped: no re-fire
        h.beat()                            # progress resumed: re-armed
        assert h.tripped is False
        time.sleep(0.1)
        assert mon.check_once() == ["rearm-unit"]
    finally:
        mon.stop()
        h.close()


def test_watchdog_handle_names_unique_and_gauge_tracks(mv_env):
    reg = get_registry()
    a = watchdog_register("dup-unit", timeout_s=1.0)
    b = watchdog_register("dup-unit", timeout_s=1.0)
    try:
        names = {h.name for h in watchdog_handles()}
        assert {"dup-unit", "dup-unit#2"} <= names
        assert reg.gauge("telemetry.watchdog.loops").last >= 2
    finally:
        a.close()
        b.close()
    assert not any(h.name.startswith("dup-unit")
                   for h in watchdog_handles())


def test_postmortem_carries_flight_logs_spans_and_metrics(mv_env):
    log.info("flight-unit: a breadcrumb before the crash")
    with span("flight.unit_probe"):
        pass
    flight_recorder().note("unit_event", detail="payload")
    get_registry().counter("flight.unit_counter").inc(3)

    pm = build_postmortem({"kind": "test", "why": "unit"})
    validate_postmortem(pm)
    assert any("flight-unit: a breadcrumb" in line
               for line in pm["flight"]["logs"])
    assert any(e.get("kind") == "unit_event"
               for e in pm["flight"]["events"])
    assert any(s.get("name") == "flight.unit_probe"
               for s in pm["flight"]["spans"])
    assert pm["metrics"]["counters"]["flight.unit_counter"]["value"] == 3
    # no -telemetry_dir flag, no explicit dir: build-only, not written
    assert dump_postmortem({"kind": "test"}) is None


def test_batcher_and_pipeline_loops_register_watchdogs(mv_env):
    """The serving daemon loops ship instrumented: constructing a
    pipelined batcher registers (and beats) its watchdog handles, and
    close() deregisters them — the graftlint rule's runtime witness."""
    from multiverso_tpu.serving.batcher import DynamicBatcher

    class Runner:
        payload_dtype = np.int32
        pad_id = 0

        def dispatch(self, mat, lengths):
            return mat

        def collect(self, handle):
            return handle

        def run(self, mat, lengths):
            return mat

        def slice_result(self, out, i, n):
            return out[i, :n]

    before = {h.name for h in watchdog_handles()}
    b = DynamicBatcher(Runner(), buckets=(4,), max_batch=2,
                       max_wait_ms=0.0, max_queue=8, pipeline_depth=2)
    try:
        deadline = time.monotonic() + 5
        want = {"serve-batcher", "serve-collector"}
        while time.monotonic() < deadline:
            names = {h.name.split("#")[0]
                     for h in watchdog_handles()} - before
            if want <= names:
                break
            time.sleep(0.01)
        assert want <= names
        b.submit(np.asarray([1, 2], np.int32), 10_000).wait(10)
        batcher_h = [h for h in watchdog_handles()
                     if h.name.startswith("serve-batcher")][0]
        assert batcher_h.beats >= 1
    finally:
        b.close()
    assert not any(h.name.startswith(("serve-batcher", "serve-collector"))
                   and h.name not in before for h in watchdog_handles())


def test_fatal_signal_dumps_postmortem_subprocess(mv_env, tmp_path):
    """SIGABRT on a process with crash handlers installed leaves a
    schema-valid postmortem AND still dies by the signal's own
    semantics (abrupt, non-zero) — the fault-drill contract."""
    script = (
        "import os, signal\n"
        "from multiverso_tpu.telemetry import install_crash_handlers\n"
        f"assert install_crash_handlers(out_dir={str(tmp_path)!r})\n"
        "os.kill(os.getpid(), signal.SIGABRT)\n"
        "raise SystemExit('unreachable: handler must re-raise fatally')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", script], cwd=_REPO,
                          env=env, capture_output=True, text=True,
                          timeout=180)
    assert proc.returncode != 0, proc.stdout + proc.stderr
    dumps = list(tmp_path.glob("postmortem-*.json"))
    assert len(dumps) == 1, (proc.stdout, proc.stderr)
    pm = json.loads(dumps[0].read_text())
    validate_postmortem(pm)
    assert pm["reason"]["kind"] == "signal"
    assert pm["reason"]["signal_name"] == "SIGABRT"


def test_telemetry_report_postmortem_cli(mv_env, tmp_path, capsys):
    dump_postmortem({"kind": "test", "why": "cli"},
                    out_dir=str(tmp_path))
    from scripts.telemetry_report import print_postmortems
    assert print_postmortems(str(tmp_path)) == 1
    out = capsys.readouterr().out
    assert "reason: test" in out and "threads:" in out
    # a corrupt dump is reported INVALID, not crashed on
    (tmp_path / "postmortem-99.json").write_text("{}")
    assert print_postmortems(str(tmp_path)) == 1
