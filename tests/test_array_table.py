"""ArrayTable tests — ports of the reference invariants.

* ``Test/unittests/test_array.cpp:10-50``: Add/Get round-trips (sync and
  async) and direct ``Partition`` output checks.
* ``Test/test_array_table.cpp:14-42``: after i rounds where every worker adds
  the same delta, ``data[k] == delta[k] * (i+1) * num_workers`` (here the
  multi-worker contribution is emulated by repeated adds, the same arithmetic
  the reference asserts scaled by ``MV_NumWorkers()``).
"""

import numpy as np
import pytest

import multiverso_tpu as mv


def test_add_get_roundtrip(mv_env):
    table = mv.create_table(mv.ArrayTableOption(size=100))
    assert np.all(table.get() == 0)
    delta = np.arange(100, dtype=np.float32)
    table.add(delta)
    np.testing.assert_allclose(table.get(), delta)
    table.add(delta)
    np.testing.assert_allclose(table.get(), 2 * delta)


def test_async_roundtrip(mv_env):
    table = mv.create_table(mv.ArrayTableOption(size=64))
    delta = np.ones(64, dtype=np.float32)
    add_id = table.add_async(delta)
    table.wait(add_id)
    get_id = table.get_async()
    out = table.wait(get_id)
    np.testing.assert_allclose(out, delta)


def test_worker_scaled_accumulation(mv_env):
    """Invariant of Test/test_array_table.cpp:14-42."""
    size = 50
    workers = mv.num_workers()
    table = mv.create_table(mv.ArrayTableOption(size=size))
    delta = (np.arange(size) + 1).astype(np.float32)
    for i in range(5):
        for _ in range(workers):
            table.add(delta)
        data = table.get()
        np.testing.assert_allclose(data, delta * (i + 1) * workers)


def test_partition_offsets(mv_env):
    """Direct Partition check (ref unittests/test_array.cpp:30-50): contiguous
    even split, last server takes the remainder."""
    table = mv.create_table(mv.ArrayTableOption(size=100))
    n = mv.num_servers()
    values = np.arange(100, dtype=np.float32)
    parts = table.partition(values)
    assert len(parts) == n
    each = 100 // n
    reassembled = np.concatenate([parts[s] for s in sorted(parts)])
    np.testing.assert_allclose(reassembled, values)
    for sid in range(n - 1):
        assert len(parts[sid]) == each
    assert len(parts[n - 1]) == 100 - each * (n - 1)


def test_int_table_uses_plain_adder(mv_env):
    """Integer tables always get the accumulating updater
    (ref src/updater/updater.cpp:40-43) even when another type is flagged."""
    mv.set_flag("updater_type", "sgd")
    table = mv.create_table(mv.ArrayTableOption(size=10, dtype=np.int32))
    table.add(np.ones(10, dtype=np.int32))
    np.testing.assert_array_equal(table.get(), np.ones(10, dtype=np.int32))


def test_odd_size_not_divisible_by_servers(mv_env):
    """Sizes not divisible by the shard count (physical padding must be
    invisible)."""
    table = mv.create_table(mv.ArrayTableOption(size=101))
    delta = np.random.default_rng(0).normal(size=101).astype(np.float32)
    table.add(delta)
    np.testing.assert_allclose(table.get(), delta, rtol=1e-6)


def test_add_synced_single_process(mv_env):
    """add_synced == add at world size 1 (aggregate over one contributor)."""
    t = mv.create_table(mv.ArrayTableOption(size=16))
    t.add_synced(np.ones(16, dtype=np.float32))
    np.testing.assert_allclose(t.get(), np.ones(16))
