"""Binding-parity tests: flat c_api surface + param managers
(ports of binding/python/multiverso/tests/test_multiverso.py:18-71 asserts)."""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.binding import (PyTreeParamManager, SyncCallback,
                                    TorchParamManager)
from multiverso_tpu.binding import c_api


def test_c_api_array_roundtrip(mv_env):
    h = c_api.MV_NewArrayTable(10, init_value=np.arange(10))
    got = c_api.MV_GetArrayTable(h)
    np.testing.assert_allclose(got, np.arange(10))
    c_api.MV_AddArrayTable(h, np.ones(10))
    np.testing.assert_allclose(c_api.MV_GetArrayTable(h), np.arange(10) + 1)
    msg = c_api.MV_AddAsyncArrayTable(h, np.ones(10))
    c_api.MV_WaitArrayTable(h, msg)
    np.testing.assert_allclose(c_api.MV_GetArrayTable(h), np.arange(10) + 2)


def test_c_api_matrix_roundtrip(mv_env):
    h = c_api.MV_NewMatrixTable(6, 4)
    c_api.MV_AddMatrixTableAll(h, np.ones((6, 4)))
    np.testing.assert_allclose(c_api.MV_GetMatrixTableAll(h), np.ones((6, 4)))
    rows = [1, 3]
    c_api.MV_AddMatrixTableByRows(h, rows, np.full((2, 4), 2.0))
    got = c_api.MV_GetMatrixTableByRows(h, rows)
    np.testing.assert_allclose(got, np.full((2, 4), 3.0))


def test_c_api_ids(mv_env):
    assert c_api.MV_NumWorkers() == mv.num_workers()
    assert c_api.MV_WorkerId() == 0
    assert c_api.MV_NumServers() >= 1
    c_api.MV_Barrier()


def test_pytree_param_manager(mv_env):
    import jax.numpy as jnp
    params = {"w": jnp.ones((3, 2)), "b": jnp.zeros(2)}
    mgr = PyTreeParamManager(params, name="t1")
    # initial pull returns the seeded values
    got = mgr.get()
    np.testing.assert_allclose(np.asarray(got["w"]), np.ones((3, 2)))
    # local update -> sync pushes delta and pulls merged
    params2 = {"w": params["w"] + 1.0, "b": params["b"] + 0.5}
    merged = mgr.sync(params2)
    np.testing.assert_allclose(np.asarray(merged["w"]), np.full((3, 2), 2.0))
    np.testing.assert_allclose(np.asarray(merged["b"]), np.full(2, 0.5))
    # second sync with no change is a no-op
    merged2 = mgr.sync(merged)
    np.testing.assert_allclose(np.asarray(merged2["w"]),
                               np.asarray(merged["w"]))


def test_torch_param_manager(mv_env):
    torch = pytest.importorskip("torch")
    model = torch.nn.Linear(4, 2)
    mgr = TorchParamManager(model, name="torch1")
    before = model.weight.detach().numpy().copy()
    with torch.no_grad():
        model.weight += 1.0
    mgr.sync()
    np.testing.assert_allclose(model.weight.detach().numpy(), before + 1.0,
                               rtol=1e-6)


def test_sync_callback_frequency(mv_env):
    import jax.numpy as jnp
    params = {"w": jnp.zeros(4)}
    mgr = PyTreeParamManager(params, name="cb")
    cb = SyncCallback(mgr, freq=2)
    assert cb.on_batch_end({"w": jnp.ones(4)}) is None       # batch 1
    out = cb.on_batch_end({"w": jnp.ones(4)})                # batch 2 syncs
    np.testing.assert_allclose(np.asarray(out["w"]), np.ones(4))
