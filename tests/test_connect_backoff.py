"""Jittered connect backoff: the retry envelope, pinned.

A router restart disconnects EVERY client at once; deterministic retry
delays re-synchronize them into reconnect stampedes that land on the
fresh listener's backlog together. The fix is uniform jitter per retry:
delay ``i`` draws from ``[(1 - J) * d_i, d_i]`` with
``d_i = min(base * 2^i, cap)`` — this suite pins that envelope and that
the live path actually sleeps inside it.
"""

import random
import socket

import pytest

from multiverso_tpu.serving import client as sc


def test_envelope_bounds_hold_for_every_draw():
    rng = random.Random(7)
    for _ in range(200):
        delays = sc.backoff_delays(6, base_delay_s=0.05, rng=rng)
        assert len(delays) == 5        # attempts - 1 sleeps
        for i, d in enumerate(delays):
            cap = min(0.05 * (2 ** i), sc.BACKOFF_CAP_S)
            assert (1.0 - sc.BACKOFF_JITTER) * cap <= d <= cap, \
                f"retry {i}: {d} outside [{(1 - sc.BACKOFF_JITTER) * cap}," \
                f" {cap}]"


def test_delays_are_jittered_not_deterministic():
    """Two clients dialing the same dead address must not share a retry
    schedule — that is the stampede."""
    a = sc.backoff_delays(6, rng=random.Random(1))
    b = sc.backoff_delays(6, rng=random.Random(2))
    assert a != b
    # And successive schedules from one stream differ too.
    rng = random.Random(3)
    assert sc.backoff_delays(6, rng=rng) != sc.backoff_delays(6, rng=rng)


def test_cap_bounds_total_dial_time():
    """The jitter must never EXTEND the envelope: total worst-case dial
    time stays at the undithered sum of caps."""
    worst = sum(min(0.05 * (2 ** i), sc.BACKOFF_CAP_S) for i in range(5))
    for seed in range(50):
        total = sum(sc.backoff_delays(6, rng=random.Random(seed)))
        assert total <= worst + 1e-9


def test_connect_with_backoff_sleeps_within_envelope(monkeypatch):
    """Live path: a refused port makes connect_with_backoff sleep exactly
    its schedule — each observed sleep inside the jitter envelope."""
    sleeps = []
    monkeypatch.setattr(sc.time, "sleep", sleeps.append)
    # A bound-but-unaccepting listener with backlog 0 still accepts on
    # linux; use a closed port instead: bind, grab the port, close.
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    with pytest.raises(sc.ReplicaUnavailableError):
        sc.connect_with_backoff("127.0.0.1", port, attempts=4,
                                base_delay_s=0.05)
    assert len(sleeps) == 3
    for i, d in enumerate(sleeps):
        cap = min(0.05 * (2 ** i), sc.BACKOFF_CAP_S)
        assert (1.0 - sc.BACKOFF_JITTER) * cap <= d <= cap


def test_single_attempt_never_sleeps(monkeypatch):
    sleeps = []
    monkeypatch.setattr(sc.time, "sleep", sleeps.append)
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    with pytest.raises(sc.ReplicaUnavailableError):
        sc.connect_with_backoff("127.0.0.1", port, attempts=1)
    assert sleeps == []
