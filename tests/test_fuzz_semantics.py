"""Property tests: random op sequences vs a numpy reference model.

The reference validates tables with exact-arithmetic invariants (SURVEY.md
§4); this extends that idea to randomized sequences — any divergence between
the sharded device tables and a plain numpy model is a bug.
"""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.core.options import AddOption


def test_matrix_random_ops_match_numpy_model(mv_env):
    rng = np.random.default_rng(0)
    R, C = 37, 5    # odd row count: exercises shard padding
    table = mv.create_table(mv.MatrixTableOption(num_row=R, num_col=C))
    model = np.zeros((R, C), dtype=np.float32)
    for step in range(60):
        op = rng.integers(0, 4)
        if op == 0:      # dense add
            delta = rng.normal(size=(R, C)).astype(np.float32)
            table.add(delta)
            model += delta
        elif op == 1:    # row add (with duplicates)
            n = int(rng.integers(1, 8))
            rows = rng.integers(0, R, size=n)
            deltas = rng.normal(size=(n, C)).astype(np.float32)
            table.add_rows(rows, deltas)
            np.add.at(model, rows, deltas)
        elif op == 2:    # row get
            n = int(rng.integers(1, 8))
            rows = rng.integers(0, R, size=n)
            np.testing.assert_allclose(table.get_rows(rows), model[rows],
                                       rtol=1e-4, atol=1e-5)
        else:            # whole get
            np.testing.assert_allclose(table.get(), model,
                                       rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(table.get(), model, rtol=1e-4, atol=1e-5)


def test_array_updater_sequences_match_model(mv_env):
    """Random interleavings of momentum updates track the closed form."""
    rng = np.random.default_rng(1)
    N = 23
    m = 0.7
    table = mv.create_table(mv.ArrayTableOption(size=N,
                                                updater="momentum_sgd"))
    data = np.zeros(N, dtype=np.float64)
    smooth = np.zeros(N, dtype=np.float64)
    for _ in range(40):
        delta = rng.normal(size=N).astype(np.float32)
        table.add(delta, AddOption(momentum=m))
        smooth = m * smooth + (1 - m) * delta
        data = data - smooth
        np.testing.assert_allclose(table.get(), data, rtol=1e-3, atol=1e-4)


def test_distributed_tables_match_model():
    """Random routed row traffic across two ranks equals the numpy model."""
    from multiverso_tpu.parallel.ps_service import (DistributedMatrixTable,
                                                    PSService)

    mv.init([])
    try:
        rng = np.random.default_rng(2)
        R, C = 31, 4
        svc0, svc1 = PSService(), PSService()
        peers = [svc0.address, svc1.address]
        t0 = DistributedMatrixTable(11, R, C, svc0, peers, rank=0)
        t1 = DistributedMatrixTable(11, R, C, svc1, peers, rank=1)
        model = np.zeros((R, C), dtype=np.float32)
        tables = [t0, t1]
        for _ in range(40):
            t = tables[int(rng.integers(0, 2))]
            n = int(rng.integers(1, 6))
            rows = rng.integers(0, R, size=n)
            deltas = rng.normal(size=(n, C)).astype(np.float32)
            t.add_rows(rows, deltas)
            np.add.at(model, rows, deltas)
        all_rows = np.arange(R, dtype=np.int32)
        np.testing.assert_allclose(t0.get_rows(all_rows), model,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(t1.get_rows(all_rows), model,
                                   rtol=1e-4, atol=1e-5)
        svc0.close(); svc1.close()
    finally:
        mv.shutdown()


def test_distributed_kv_and_sparse_fuzz_match_model():
    """Random interleaved traffic on the r4 tables: hash-routed KV adds
    equal a dict model exactly (int64); sparse incremental gets converge
    to the dense numpy model after every pull, with wire volume bounded
    by rows touched since that worker's last pull."""
    from multiverso_tpu.core.options import AddOption, GetOption
    from multiverso_tpu.parallel.ps_service import (
        DistributedKVTable, DistributedSparseMatrixTable, PSService)

    mv.init([])
    try:
        rng = np.random.default_rng(7)
        svc0, svc1 = PSService(), PSService()
        peers = [svc0.address, svc1.address]
        kv0 = DistributedKVTable(21, svc0, peers, rank=0)
        kv1 = DistributedKVTable(21, svc1, peers, rank=1)
        R, C = 23, 3
        sp0 = DistributedSparseMatrixTable(22, R, C, svc0, peers, rank=0)
        sp1 = DistributedSparseMatrixTable(22, R, C, svc1, peers, rank=1)

        kv_model: dict = {}
        sp_model = np.zeros((R, C), dtype=np.float32)
        kvs = [kv0, kv1]
        sps = [sp0, sp1]
        touched_since = [0, 0]     # rows touched since rank i's last pull
        pulled_once = [False, False]

        for step in range(60):
            r = int(rng.integers(0, 2))
            kind = int(rng.integers(0, 3))
            if kind == 0:           # kv add
                n = int(rng.integers(1, 5))
                keys = rng.integers(0, 50, size=n).astype(np.int64)
                vals = rng.integers(-100, 100, size=n).astype(np.int64)
                kvs[r].add(keys, vals)
                for k, v in zip(keys.tolist(), vals.tolist()):
                    kv_model[k] = kv_model.get(k, 0) + v
            elif kind == 1:         # sparse row add (worker gid = rank)
                n = int(rng.integers(1, 4))
                rows = np.unique(rng.integers(0, R, size=n))
                deltas = rng.normal(size=(len(rows), C)) \
                    .astype(np.float32)
                sps[r].add_rows(rows, deltas, AddOption(worker_id=0))
                np.add.at(sp_model, rows, deltas)
                touched_since = [t + len(rows) for t in touched_since]
            else:                   # sparse incremental whole-table get
                got = sps[r].get(GetOption(worker_id=0))
                np.testing.assert_allclose(got, sp_model, rtol=1e-4,
                                           atol=1e-5,
                                           err_msg=f"step {step} rank {r}")
                # first pull may ship the initial all-stale table;
                # later pulls are bounded by rows touched since.
                bound = R if not pulled_once[r] \
                    else min(touched_since[r], R)
                assert sps[r].last_incremental_rows <= bound
                pulled_once[r] = True
                touched_since[r] = 0

        keys = np.asarray(sorted(kv_model), dtype=np.int64)
        want = np.asarray([kv_model[int(k)] for k in keys])
        np.testing.assert_array_equal(kv0.get(keys), want)
        np.testing.assert_array_equal(kv1.get(keys), want)
        svc0.close(); svc1.close()
    finally:
        mv.shutdown()
