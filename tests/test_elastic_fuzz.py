"""Rolling-restart chaos fuzz for the elastic PS path.

The targeted drills (tests/test_ps_robustness.py, the app drills in
test_distributed_word2vec.py) each prove ONE failure scenario; this fuzz
sweeps many: server seats go down in random order at random times
(orderly close with a shard checkpoint — the reference's recovery story,
``table_interface.h:61-75``) and come back at NEW addresses, while a
client hammers adds and gets throughout. Invariant: no client op ever
fails, and the final table value equals the sum of every acknowledged
add exactly once — retries through the replicated directory plus the
server's exactly-once caches must never drop or double-apply a delta.
"""

import os
import tempfile
import threading
import time

import numpy as np
import pytest

from multiverso_tpu.core import checkpoint as ckpt
from multiverso_tpu.parallel.ps_service import (DistributedArrayTable,
                                                PSService)

SIZE = 60          # 3 shards x 20
TABLE = 400


def _seat(rank, peers, restore_uri=None):
    """Restart protocol: restore the shard FIRST, announce to the
    directory SECOND (announce=False + enable_directory) — announcing
    early would let a peer's retried add land on the fresh shard and be
    overwritten by the restore (an acked-write loss this fuzz caught)."""
    svc = PSService()
    peers = list(peers)
    peers[rank] = svc.address
    table = DistributedArrayTable(TABLE, SIZE, svc, peers, rank=rank,
                                  announce=False)
    if restore_uri:
        ckpt.load_table(table, restore_uri)
    svc.enable_directory(rank, peers)
    return svc, table, peers


@pytest.mark.slow
def test_rolling_restart_fuzz(mv_env, tmp_path):
    rng = np.random.default_rng(0)
    world = 3
    services = [PSService() for _ in range(world)]
    peers = [s.address for s in services]
    tables = [DistributedArrayTable(TABLE, SIZE, services[r], peers, rank=r)
              for r in range(world)]

    stop = threading.Event()
    acked = np.zeros(SIZE, dtype=np.float64)
    errors = []
    # Held by the chaos loop across [checkpoint shard -> close seat] so an
    # add cannot be acknowledged between the snapshot and the death (it
    # would be acked-but-lost: orderly shutdown means quiesce THEN save —
    # the window the real shutdown protocol also closes). Ops issued any
    # other time — including the whole down/re-registration window — run
    # concurrently with the chaos.
    mu = threading.Lock()

    def writer():
        wrng = np.random.default_rng(1)
        while not stop.is_set():
            delta = wrng.integers(1, 5, size=SIZE).astype(np.float32)
            try:
                with mu:
                    tables[0].add(delta)  # synchronous: ack == applied
                acked[:] += delta
                if wrng.random() < 0.3:
                    tables[0].get()
            except Exception as e:  # noqa: BLE001 - the invariant
                errors.append(e)
                return
            time.sleep(0.002)

    t = threading.Thread(target=writer)
    t.start()
    try:
        # Rolling restarts: every seat except the client's own goes down
        # and comes back several times, in random order, at new addresses.
        for round_i in range(6):
            victim = int(rng.integers(1, world))
            uri = f"file://{tmp_path}/shard{victim}_{round_i}.npz"
            with mu:
                ckpt.save_table(tables[victim], uri)
                services[victim].close()
            time.sleep(float(rng.random() * 0.1))   # seat stays DOWN here
            services[victim], tables[victim], peers = _seat(
                victim, peers, restore_uri=uri)
            time.sleep(float(rng.random() * 0.2))
    finally:
        stop.set()
        t.join(timeout=120)
    assert not t.is_alive(), "writer hung"
    assert not errors, f"client op failed during rolling restarts: {errors}"

    got = np.asarray(tables[0].get(), dtype=np.float64)
    np.testing.assert_allclose(got, acked, rtol=0, atol=0)
    # cross-check from a freshly-restarted seat's own view
    got1 = np.asarray(tables[1].get(), dtype=np.float64)
    np.testing.assert_allclose(got1, acked, rtol=0, atol=0)
    for s in services:
        s.close()


def test_supervisor_kill_respawn_fuzz(mv_env):
    """Kill-respawn chaos over the SUPERVISOR (ISSUE 15 satellite): an
    in-process fleet of serving replicas under a ReplicaSupervisor; a
    seeded schedule abruptly kills random victims at random times (no
    leave, no drain — heartbeats just stop, exactly a SIGKILL's shape).
    Invariants: the fleet converges back to FULL membership after every
    round, every respawn is supervisor-driven (counted + event-logged),
    lookups answer correct bytes at the end, and no monitored daemon
    loop wedged (watchdog trips 0)."""
    import jax
    from jax.sharding import Mesh

    from multiverso_tpu.core.table import ServerStore
    from multiverso_tpu.core.updater import get_updater
    from multiverso_tpu.fleet import (FleetClient, FleetRouter,
                                      FleetMember, LocalFleetView,
                                      ReplicaSupervisor)
    from multiverso_tpu.serving import ServingService, SparseLookupRunner
    from multiverso_tpu.telemetry import get_registry
    from multiverso_tpu.telemetry.flight import start_watchdog

    start_watchdog()
    trips0 = get_registry().counter("telemetry.watchdog.trips").value
    ROWS, COLS, N = 256, 8, 3
    rng = np.random.default_rng(42)
    data = np.random.default_rng(0).normal(
        size=(ROWS, COLS)).astype(np.float32)
    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1), ("server",))
    router = FleetRouter(heartbeat_ms=40.0, liveness_misses=4)

    class InProcReplica:
        """Supervisor handle over an in-process service+member pair."""

        def __init__(self, slot: int):
            store = ServerStore(f"fuzz_t{slot}", (ROWS, COLS), np.float32,
                                get_updater(np.float32, "default"), mesh,
                                num_workers=1, init_array=data.copy())
            self.service = ServingService()
            self.service.register_runner(SparseLookupRunner(store),
                                         buckets=(4, 8), max_batch=4,
                                         max_wait_ms=1.0)
            self.service.warmup()
            self.member = FleetMember(router.address, self.service,
                                      member_id=f"replica-{slot}").start()
            self.dead = False

        def poll(self):
            return 1 if self.dead else None

        def kill_abruptly(self):
            """SIGKILL shape: heartbeats stop mid-cadence, no goodbye."""
            self.dead = True
            self.member._stop.set()
            self.member._close_sock()
            self.service.close()

        def terminate(self):
            self.dead = True
            self.member.close()
            self.service.close()

    sup = ReplicaSupervisor(LocalFleetView(router), InProcReplica,
                            min_replicas=N, max_replicas=N,
                            cooldown_s=0.5, poll_s=0.05,
                            join_grace_s=20.0)
    replicas = {i: InProcReplica(i) for i in range(N)}
    for i, r in replicas.items():
        sup.adopt(i, r)

    def await_members(n, deadline_s):
        deadline = time.monotonic() + deadline_s
        while len(router.group.member_ids()) != n:
            assert time.monotonic() < deadline, \
                (f"fleet never converged to {n} members; have "
                 f"{router.group.member_ids()}; events {sup.events()}")
            time.sleep(0.02)

    await_members(N, 20)
    sup.start()
    cli = FleetClient(router.address, refresh_s=0.05, hedge="off")
    try:
        kills = 0
        for round_i in range(4):
            victims = {int(rng.integers(0, N))}
            if rng.random() < 0.4:          # sometimes a double kill
                victims.add(int(rng.integers(0, N)))
            time.sleep(float(rng.random() * 0.3))
            for v in victims:
                sup.slots()[v].kill_abruptly()
                kills += 1
            # Convergence: sweep reaps the corpses, the supervisor
            # respawns the slots (counted), fresh members warm + rejoin.
            deadline = time.monotonic() + 60
            while sup.status()["respawns"] < kills:
                assert time.monotonic() < deadline, \
                    (f"supervisor never respawned round {round_i} "
                     f"victims {victims}: {sup.status()}")
                time.sleep(0.02)
            await_members(N, 60)
        status = sup.status()
        assert status["respawns"] >= kills, status
        assert sorted(status["slots"]) == list(range(N))
        respawn_events = [e for e in status["events"]
                          if e["kind"] == "respawn"]
        assert {e["trigger"] for e in respawn_events} <= \
            {"process_exit", "heartbeat_loss", "missing_timeout"}
        # The healed fleet still serves correct bytes from every owner.
        for _ in range(8):
            rows = rng.integers(0, ROWS, 4).astype(np.int32)
            got = cli.lookup(rows, deadline_ms=10_000, timeout=30)
            np.testing.assert_array_equal(got, data[rows])
        trips = get_registry().counter("telemetry.watchdog.trips").value
        assert trips == trips0, "a daemon loop wedged during the chaos"
    finally:
        cli.close()
        sup.stop()
        for handle in sup.slots().values():
            handle.terminate()
        router.close()


def test_elastic_membership_join_leave_fuzz(mv_env):
    """Elastic clock-group fuzz (ISSUE 16 satellite): a live BSP group
    under a seeded schedule of joins, graceful leaves, and SIGKILL-shaped
    silent deaths — including one that dies BETWEEN acquire_add and
    commit_add, the worst point (an in-flight add that would wedge every
    peer's get gate forever without the quorum fallback's cleanup).
    Invariants: no surviving worker's op ever fails, every silent death
    is evicted by the quorum fallback (counted exactly), the group
    re-forms and keeps making progress after every event, freed slots
    are reused by later joins, and no monitored daemon loop wedged."""
    from multiverso_tpu.core.sync_coordinator import SyncCoordinator
    from multiverso_tpu.telemetry import get_registry
    from multiverso_tpu.telemetry.flight import start_watchdog

    start_watchdog()
    trips0 = get_registry().counter("telemetry.watchdog.trips").value
    rng = np.random.default_rng(16)
    sc = SyncCoordinator(3, name="fuzz16", leave_timeout_s=0.4)

    stop = threading.Event()
    errors = []
    rounds = {}
    mu = threading.Lock()
    silent = {}        # wid -> "boundary" | "inflight" (simulated SIGKILL)
    departing = set()  # wid -> graceful leave requested

    def worker(wid):
        rounds[wid] = 0
        try:
            while not stop.is_set():
                with mu:
                    if silent.get(wid) == "boundary":
                        return          # vanish: no leave, no finish
                    if wid in departing:
                        sc.leave(wid)   # orderly goodbye, slot freed
                        return
                sc.acquire_add(wid, timeout=30.0)
                with mu:
                    if silent.get(wid) == "inflight":
                        return          # die holding an in-flight add
                sc.commit_add(wid)
                sc.acquire_get(wid, timeout=30.0)
                sc.commit_get(wid)
                rounds[wid] += 1
                time.sleep(0.001)
            sc.finish_train(wid)        # test teardown: retire cleanly
        except Exception as e:  # noqa: BLE001 - the invariant
            errors.append((wid, e))

    threads = {}

    def spawn(wid):
        t = threading.Thread(target=worker, args=(wid,), daemon=True)
        threads[wid] = t
        t.start()

    def await_world(n, deadline_s=20.0):
        deadline = time.monotonic() + deadline_s
        while sc.status()["world"] != n:
            assert time.monotonic() < deadline, \
                f"group never re-formed to {n}: {sc.status()}, {errors}"
            time.sleep(0.01)

    def await_progress(deadline_s=20.0):
        with mu:
            base = dict(rounds)
        live = sc.status()["active"]
        deadline = time.monotonic() + deadline_s
        while any(rounds.get(w, 0) <= base.get(w, 0) for w in live):
            assert time.monotonic() < deadline, \
                f"surviving quorum stalled: {rounds} vs {base}, {errors}"
            time.sleep(0.01)

    live = {0, 1, 2}
    for w in live:
        spawn(w)
    kills = leaves = joins = 0
    # Seeded schedule: every event class fires, order fixed, victims
    # random. "kill" alternates the death point so both the clean
    # round-boundary death and the in-flight-add death are exercised.
    try:
        for i, event in enumerate(
                ["join", "kill", "leave", "join", "kill", "join"]):
            time.sleep(float(rng.random() * 0.1))
            if event == "join":
                w = sc.join(timeout=30.0)
                with mu:
                    # The slot id may be a reused corpse's: a stale kill
                    # flag must not shoot the fresh tenant.
                    silent.pop(w, None)
                    departing.discard(w)
                live.add(w)
                spawn(w)
                joins += 1
                await_world(len(live))
            elif event == "kill":
                victim = int(rng.choice(sorted(live)))
                point = "inflight" if kills % 2 else "boundary"
                with mu:
                    silent[victim] = point
                live.discard(victim)
                kills += 1
                threads[victim].join(timeout=30)
                assert not threads[victim].is_alive()
                # The survivors' stalled gates must evict the corpse.
                await_world(len(live))
            else:
                victim = int(rng.choice(sorted(live)))
                with mu:
                    departing.add(victim)
                live.discard(victim)
                leaves += 1
                threads[victim].join(timeout=30)
                assert not threads[victim].is_alive()
                await_world(len(live))
            await_progress()
    finally:
        stop.set()
        for t in threads.values():
            t.join(timeout=60)
    assert not errors, f"surviving worker op failed: {errors}"

    status = sc.status()
    assert status["quorum_evictions"] == kills, status
    # Every membership change bumped the version exactly once.
    assert status["version"] == kills + leaves + joins, status
    # Slot reuse: freed slots (2 kills + 1 leave) cover the later joins,
    # so the slot table never grows past the peak concurrent world.
    assert status["slots"] <= 4, status
    trips = get_registry().counter("telemetry.watchdog.trips").value
    assert trips == trips0, "a daemon loop wedged during the chaos"


def test_restart_restore_before_announce_keeps_acked_writes(mv_env,
                                                            tmp_path):
    """The acked-write-loss race the fuzz caught, pinned deterministically:
    while a seat is down, a peer's add sits in the directory-retry loop.
    If the restarted seat announced BEFORE restoring, that add could land
    on the fresh shard and be overwritten by the restore. With
    announce=False + restore + enable_directory, the un-announced seat is
    unreachable until its state is back, so the acked add survives."""
    import time as _time

    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    t0 = DistributedArrayTable(90, 40, svc0, peers, rank=0)
    t1 = DistributedArrayTable(90, 40, svc1, peers, rank=1)
    t0.add(np.full(40, 2.0, dtype=np.float32))
    uri = f"file://{tmp_path}/seat1.npz"
    ckpt.save_table(t1, uri)
    svc1.close()

    done = []

    def bg_add():
        t0.add(np.ones(40, dtype=np.float32))   # retries until reachable
        done.append(True)

    th = threading.Thread(target=bg_add)
    th.start()
    _time.sleep(1.0)                 # the add is now in the retry loop
    svc1b = PSService()
    peers2 = [peers[0], svc1b.address]
    t1b = DistributedArrayTable(90, 40, svc1b, peers2, rank=1,
                                announce=False)
    ckpt.load_table(t1b, uri)
    _time.sleep(1.0)
    assert not done, "un-announced seat must not be discoverable"
    svc1b.enable_directory(1, peers2)
    th.join(timeout=30)
    assert done, "add never landed after announce"
    np.testing.assert_allclose(t0.get(), 3.0)   # baseline 2 + acked 1
    np.testing.assert_allclose(t1b.get(), 3.0)
    svc1b.close()
    svc0.close()
