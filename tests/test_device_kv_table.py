"""DeviceKVTable: HBM value slab + host directory."""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.core.options import KVTableOption
from multiverso_tpu.tables.device_kv_table import DeviceKVTable


def test_scalar_values_accumulate(mv_env):
    t = DeviceKVTable(KVTableOption(capacity=64))
    t.add([10, 99, 10**12], [1.0, 2.0, 3.0])
    np.testing.assert_allclose(t.get([10, 99, 10**12]), [1.0, 2.0, 3.0])
    t.add([99], [10.0])
    np.testing.assert_allclose(t.get([99]), [12.0])
    assert len(t) == 3


def test_missing_keys_zero(mv_env):
    t = DeviceKVTable(KVTableOption(capacity=8))
    np.testing.assert_allclose(t.get([123, 456]), [0.0, 0.0])
    assert len(t) == 0   # gets don't allocate


def test_vector_values_in_hbm(mv_env):
    """The lightLDA shape: per-key vectors resident on device."""
    t = DeviceKVTable(KVTableOption(capacity=128), value_dim=16)
    t.add([7, 8], np.ones((2, 16), dtype=np.float32))
    got = t.get([8, 7, 9])
    assert got.shape == (3, 16)
    np.testing.assert_allclose(got[:2], np.ones((2, 16)))
    np.testing.assert_allclose(got[2], np.zeros(16))
    # values actually live on device shards
    assert len(t.store.data.sharding.device_set) == mv.num_servers()


def test_capacity_exhaustion_is_fatal(mv_env):
    from multiverso_tpu.utils.log import FatalError
    t = DeviceKVTable(KVTableOption(capacity=2))
    t.add([1, 2], [1.0, 1.0])
    with pytest.raises(FatalError):
        t.add([3], [1.0])


def test_updater_applies(mv_env):
    t = DeviceKVTable(KVTableOption(capacity=8, updater="sgd"))
    t.add([5], [2.0])
    np.testing.assert_allclose(t.get([5]), [-2.0])   # sgd: data -= delta


def test_checkpoint_roundtrip(mv_env):
    from multiverso_tpu.core import checkpoint as ckpt

    t = DeviceKVTable(KVTableOption(capacity=32, name="dkv"))
    t.add([100, 200], [1.0, 2.0])
    snap_uri = None
    import tempfile, os
    d = tempfile.mkdtemp()
    uri = f"file://{os.path.join(d, 'dkv.npz')}"
    ckpt.save_table(t, uri)
    t.add([100, 300], [50.0, 7.0])
    ckpt.load_table(t, uri)
    np.testing.assert_allclose(t.get([100, 200, 300]), [1.0, 2.0, 0.0])
    assert len(t) == 2


def test_factory_routes_device_flag(mv_env):
    t = mv.create_table(KVTableOption(device=True, capacity=16,
                                      value_dim=4))
    assert isinstance(t, DeviceKVTable)
    t.add([3], np.ones((1, 4), dtype=np.float32))
    np.testing.assert_allclose(t.get([3]), np.ones((1, 4)))
