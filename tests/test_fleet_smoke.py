"""Tier-1 CPU smoke for the fleet serving fabric + distributed tracing.

Drives ``scripts/serve_bench.py --replicas 2 --dry-run`` end to end: a
router SUBPROCESS (control plane + data proxy), two replica SUBPROCESSES
serving the same seeded synthetic table, and a hedged FleetClient —
asserting the contracts the record carries:

* routed lookups (affinity AND ring-split) are bitwise-equal to a direct
  gather of the table (``parity_ok``),
* a wire-triggered rolling drain of every replica mid-load completes
  with ZERO failed requests,
* the load window finishes with no request errors and a non-trivial
  achieved QPS, and the record lands in BENCH_SERVE_HISTORY.jsonl so the
  serving trend file grows with every bench run,
* distributed tracing: one sampled request stitches to a SINGLE Chrome
  trace with correctly-parented spans from >= 3 distinct processes
  (client, router, replica), hedged attempts appear as siblings tagged
  ``hedge=1``, the record carries a trace-derived per-stage breakdown
  plus traced/untraced QPS, and the ``Fleet_Stats`` rollup's fleet sums
  equal the sum of its per-replica records.
"""

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BENCH = os.path.join(_REPO, "scripts", "serve_bench.py")


def test_serve_bench_fleet_dry_run(tmp_path):
    out = tmp_path / "BENCH_SERVE.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, _BENCH, "--dry-run", "--replicas", "2",
         f"--out={out}"],
        cwd=_REPO, env=env, capture_output=True, text=True, timeout=540)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-3000:]

    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["benchmark"] == "serve_fleet_lookup"
    assert line["replicas"] == 2

    record = json.loads(out.read_text())
    assert record["schema"] == "multiverso_tpu.bench_serve/v12"
    assert record["replicas"] == 2

    # Routed lookups bitwise-equal to the direct table gather.
    assert record["parity_ok"] is True

    # Rolling drain mid-load: completed, zero dropped requests.
    drain = record["drill"]["drain"]
    assert drain["completed"] is True
    assert drain["failed_requests"] == 0

    # -- ISSUE-13 fault drill: detection + artifact ------------------------
    # The SIGABRT'd replica must (a) trigger the ROUTER's heartbeat-loss
    # alert (its own alert engine, over rate.fleet.member_dead), and
    # (b) leave a schema-valid postmortem with every live thread's stack.
    fault = record["drill"]["fault"]
    assert fault["signal"] == "SIGABRT"
    assert fault["heartbeat_loss_alert"]["fired"] is True, fault
    pm = fault["postmortem"]
    assert pm["found"] is True and pm["valid"] is True, pm
    assert pm["reason_kind"] == "signal"
    assert pm["signal"] == "SIGABRT"
    # >= all live threads: a serving replica runs at least the main
    # thread + batcher + heartbeat + exporter + collector...
    assert pm["n_threads"] >= 4, pm

    # -- ISSUE-13 SLO-burn alert shipping: replica engine -> heartbeat
    # -> router rollup (replica-0 ran with an unreachable SLO).
    slo = record["observability"]["slo_breach"]
    assert slo["fired"] is True, slo
    assert any(a["name"] == "serve.slo_burn" for a in slo["alerts"])
    assert slo["alerts_active_fleet"] >= 1
    # ...and nothing in the FLEET wedged all run: trips are counted in
    # the replica/router subprocesses (where the monitored daemon loops
    # actually live) and shipped on the heartbeat into the rollup — the
    # bench client process registers no watchdog handles, so its own
    # counter would be a vacuous witness.
    wd = record["observability"]["watchdog"]
    assert wd["monitored_replicas"] == 2, wd
    assert wd["fleet_trips"] == 0, wd
    assert wd["router_trips"] == 0, wd

    # -- ISSUE-14 shard-imbalance drill: a window where every request
    # routes to ONE ring owner must drive the replicas' heartbeat-
    # shipped key rates apart, fire the ROUTER's fleet.shard_imbalance
    # rule, and ship it into Fleet_Stats (router_alerts) while the skew
    # lasts.
    skew = record["observability"]["skew"]
    assert skew["fired"] is True, skew
    assert any(a["name"] == "fleet.shard_imbalance"
               for a in skew["router_alerts"]), skew
    rates = skew["per_replica_keys_rate"]
    assert len(rates) == 2
    assert max(rates.values()) > 2 * max(min(rates.values()), 1.0), \
        f"drill did not actually skew the shard load: {rates}"

    # -- ISSUE-15 recovery drill: durable shards + self-healing -----------
    # (a) A WAL-journaled PS shard SIGKILL'd mid-stream was respawned by
    # the supervisor through checkpoint+WAL recovery, and the resumed
    # world's table equals the acked add stream EXACTLY.
    rec = record["recovery"]
    assert rec["wal"]["parity_ok"] is True, rec["wal"]
    assert rec["wal"]["supervisor_respawns"] >= 1, rec["wal"]
    assert rec["wal"]["respawn_trigger"] == "process_exit", rec["wal"]
    assert rec["wal"]["time_to_recover_s"] > 0
    # (b) A serving replica SIGKILL'd under load was automatically
    # replaced — and the replacement was driven by the ROUTER's
    # fleet.heartbeat_loss alert (the supervisor is deliberately blind
    # to the victim's process liveness, like a cross-host supervisor):
    # the acceptance chain alert -> replacement -> rejoins the ring,
    # with no client-visible errors after the recovery + hedging window.
    rep = rec["replica"]
    assert rep["recovered"] is True, rep
    assert rep["supervisor_respawns"] >= 1, rep
    assert rep["respawn_trigger"] == "heartbeat_loss", rep
    assert rep["errors_after_recovery_and_hedge_window"] == 0, rep
    assert rep["time_to_recover_s"] > 0
    assert rep["window"]["n_ok"] > 0
    # (c) WAL hot-path priced: the dispatch-thread append cost vs the
    # measured add round trip — deterministic, so the <=2% acceptance
    # gates here too. The end-to-end A/B (commit cost included) ships
    # alongside but is box-noise-limited on 1-core CI, so no hard gate.
    ab = rec["wal_overhead"]
    assert ab["overhead_pct"] <= 2.0, ab
    assert ab["hot_path_us_per_add"] > 0
    assert ab["adds_per_sec_plain"] > 0 and ab["adds_per_sec_wal"] > 0

    # The load window itself served cleanly.
    assert record["n_error"] == 0
    assert record["n_ok"] > 0
    assert record["achieved_qps"] > 0
    lat = record["latency_ms"]
    assert lat["p50"] <= lat["p95"] <= lat["p99"] <= lat["max"]

    # fleet.* metrics ride along with the record.
    assert any(k.startswith("fleet.")
               for k in record["serve_metrics"]["counters"])

    # Every record appends to the serving trend file beside --out.
    history = tmp_path / "BENCH_SERVE_HISTORY.jsonl"
    assert history.exists()
    entries = [json.loads(l) for l in history.read_text().splitlines()]
    assert entries and entries[-1]["benchmark"] == "serve_fleet_lookup"

    # -- distributed tracing acceptance -----------------------------------
    tracing = record["tracing"]
    # Both QPS numbers (traced + untraced) so sampling overhead is a
    # measured fact of the record, not a claim.
    assert tracing["qps_untraced"] > 0 and tracing["qps_traced"] > 0
    # One sampled request stitched to ONE trace: >= 5 correctly-parented
    # spans spanning >= 3 distinct processes (client, router, replica).
    smoke = tracing["trace_smoke"]
    assert smoke["found"] is True
    assert smoke["n_spans"] >= 5
    assert smoke["n_pids"] >= 3
    assert smoke["parented_ok"] is True
    # Hedged duplicates appear as tagged sibling attempts.
    hedged = smoke["hedged_siblings"]
    assert hedged["found"] is True
    assert hedged["n_attempts"] >= 2
    assert all(tag == 1 for tag in hedged["hedge_tags"])
    # Trace-derived per-stage breakdown covers the serving pipeline.
    breakdown = tracing["stage_breakdown"]
    for stage in ("admit_wait", "batch_form", "device", "reply",
                  "server_total", "proxy_hop"):
        assert breakdown[stage]["count"] > 0, stage
    # K slowest stitched timelines exist and include a cross-process
    # one. Not necessarily the single slowest: with the fault drill in
    # the traced window, the slowest trace can legitimately be a
    # single-pid failure exemplar from the kill (root + attempt spans,
    # both client-side, riding out the dead replica's timeout).
    assert tracing["slowest"]
    assert any(len(s["pids"]) >= 2 for s in tracing["slowest"])

    # -- Fleet_Stats rollup: fleet sums == sum of per-replica records -----
    # (captured BEFORE the fault drill, so both replicas are present.)
    stats = record["fleet_stats"]
    per = stats["replicas"]
    assert len(per) == 2
    fleet = stats["fleet"]
    for key in ("requests", "replies", "shed", "cancelled",
                "slo_violations", "cache_hits", "watchdog_trips"):
        assert fleet[key] == sum(r[key] for r in per.values()), key
    assert abs(fleet["qps"] - sum(r["qps"] for r in per.values())) < 1e-6
    assert fleet["replies"] > 0
    assert stats["version"] > 0
    # every rollup row carries the heartbeat-shipped alerts list, and
    # the fleet block counts the firing ones (replica-0's SLO burn).
    for r in per.values():
        assert "alerts" in r
    assert fleet["alerts_active"] >= 1
    assert "router_alerts" in stats
    # ...and the data-plane load columns (ISSUE 14): per-replica key
    # rates + skew + hot keys ride the heartbeat; the fleet block
    # carries the merged hot keys and the shard-load ratio fleet_top's
    # SKEW column renders.
    for r in per.values():
        assert "keys_rate" in r and "skew" in r and "hot_keys" in r
    assert "shard_load_ratio" in fleet and "hot_keys" in fleet
    assert fleet["keys_rate"] >= 0.0

    # -- PR-9 serving optimizations engaged across the fleet --------------
    # Replica heartbeats carry dispatch-window occupancy; the dry run's
    # load must have overlapped batches on at least one replica, and the
    # repeated-key witness must have landed a hot-row cache hit.
    pipe = record["pipeline"]
    assert pipe["max_inflight"] >= 2, pipe
    assert pipe["cache_hits"] >= 1, pipe
    for r in per.values():
        assert "pipeline_inflight" in r and "cache_hits" in r

    # -- ISSUE-18 attribution layer across the fleet ----------------------
    # Every replica self-classifies its serve plane via the heartbeat
    # (roofline verdict rides metrics_payload), the bench client
    # classifies its own plane locally, and the fleet rollup carries the
    # merged tail exemplars with phase ledgers.
    rl = record["roofline"]
    assert rl["client"]["bound"] in (
        "dispatch", "host", "wire", "device", "idle"), rl
    assert len(rl["replicas"]) >= 1, rl
    for rid, v in rl["replicas"].items():
        assert v.get("bound") in (
            "dispatch", "host", "wire", "device", "idle"), (rid, v)
    for r in per.values():
        assert "roofline" in r and "exemplars" in r
    assert "exemplars" in record
    assert "critical_path" in tracing


def test_serve_bench_chaos_drill_dry_run(tmp_path):
    """ISSUE-16 chaos drill smoke: one seeded round over a 2-shard
    supervised PS fleet (WAL'd, sync acks) under live training, with 2
    serving replicas taking lookup load — the round's random subset of
    SIGKILL/SIGSTOP (possibly under a lossy link) must converge back to
    full membership with ZERO acked-write loss (exact WAL parity), no
    serving errors outside the recovery+hedge window, and the elastic
    leave+rejoin round must re-form the clock group with the slot
    reused."""
    out = tmp_path / "BENCH_SERVE.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, _BENCH, "--dry-run", "--replicas", "2",
         "--chaos-drill", "--chaos-rounds", "1", "--chaos-seed", "16",
         f"--out={out}"],
        cwd=_REPO, env=env, capture_output=True, text=True, timeout=540)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-3000:]

    record = json.loads(out.read_text())
    assert record["schema"] == "multiverso_tpu.bench_serve/v12"
    chaos = record["chaos"]
    assert chaos["seed"] == 16
    assert chaos["shards"] == 2

    # Every round: faults actually landed, the fleet converged back to
    # full membership, and the acked training stream survived bitwise.
    assert len(chaos["rounds"]) == 1
    for rnd in chaos["rounds"]:
        assert rnd["faults"], "round planned no faults"
        assert rnd["converged"] is True, rnd
        assert rnd["parity_ok"] is True, rnd
        assert rnd["serving_errors_outside_window"] == 0, rnd
    assert chaos["converged_all_rounds"] is True
    assert chaos["zero_acked_loss"] is True, chaos["train_errors"]
    assert chaos["acked_adds"] > 0
    assert chaos["train_errors"] == []

    # Router-kill round (ISSUE 17): SIGKILL the router under load,
    # respawn on the same port — every replica must rejoin (heartbeat
    # loops re-dial via connect_with_backoff) and client errors stay
    # confined to the recovery window.
    rk = chaos["router_kill"]
    assert rk["rejoined_all"] is True, rk
    assert rk["errors_outside_window"] == 0, rk

    # Elastic membership: join drained to the epoch floor, leave freed
    # the slot, the rejoin reused it, version advanced every step.
    elastic = chaos["elastic"]
    assert elastic["reformed"] is True, elastic
    assert elastic["slot_reused"] is True, elastic
    assert elastic["quorum_evictions"] == 0, elastic
