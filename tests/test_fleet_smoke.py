"""Tier-1 CPU smoke for the fleet serving fabric.

Drives ``scripts/serve_bench.py --replicas 2 --dry-run`` end to end: an
in-process FleetRouter, two replica SUBPROCESSES serving the same seeded
synthetic table, and a hedged FleetClient — asserting the three fleet
contracts the record carries:

* routed lookups (affinity AND ring-split) are bitwise-equal to a direct
  gather of the table (``parity_ok``),
* a rolling drain of every replica mid-load completes with ZERO failed
  requests,
* the load window finishes with no request errors and a non-trivial
  achieved QPS, and the record lands in BENCH_SERVE_HISTORY.jsonl so the
  serving trend file grows with every bench run.
"""

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BENCH = os.path.join(_REPO, "scripts", "serve_bench.py")


def test_serve_bench_fleet_dry_run(tmp_path):
    out = tmp_path / "BENCH_SERVE.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, _BENCH, "--dry-run", "--replicas", "2",
         f"--out={out}"],
        cwd=_REPO, env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-3000:]

    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["benchmark"] == "serve_fleet_lookup"
    assert line["replicas"] == 2

    record = json.loads(out.read_text())
    assert record["schema"] == "multiverso_tpu.bench_serve/v2"
    assert record["replicas"] == 2

    # Routed lookups bitwise-equal to the direct table gather.
    assert record["parity_ok"] is True

    # Rolling drain mid-load: completed, zero dropped requests.
    drain = record["drill"]["drain"]
    assert drain["completed"] is True
    assert drain["failed_requests"] == 0

    # The load window itself served cleanly.
    assert record["n_error"] == 0
    assert record["n_ok"] > 0
    assert record["achieved_qps"] > 0
    lat = record["latency_ms"]
    assert lat["p50"] <= lat["p95"] <= lat["p99"] <= lat["max"]

    # fleet.* metrics ride along with the record.
    assert any(k.startswith("fleet.")
               for k in record["serve_metrics"]["counters"])

    # Every record appends to the serving trend file beside --out.
    history = tmp_path / "BENCH_SERVE_HISTORY.jsonl"
    assert history.exists()
    entries = [json.loads(l) for l in history.read_text().splitlines()]
    assert entries and entries[-1]["benchmark"] == "serve_fleet_lookup"
