"""Zoo lifecycle / roles / barrier / aggregate / mesh sharding tests."""

import numpy as np
import pytest

import multiverso_tpu as mv


def test_init_shutdown_cycle():
    mv.init([])
    assert mv.rank() == 0
    assert mv.size() == 1
    assert mv.num_workers() == 1
    assert mv.num_servers() >= 1
    assert mv.is_master_worker()
    mv.barrier()
    mv.shutdown()
    # restartable
    mv.init([])
    mv.shutdown()


def test_init_parses_flags_and_returns_rest():
    rest = mv.init(["prog", "-sync=true", "user_arg"])
    assert rest == ["prog", "user_arg"]
    from multiverso_tpu.core.zoo import Zoo
    assert Zoo.get().sync_mode
    mv.shutdown()


def test_roles(mv_env):
    assert mv.worker_id() == 0
    assert mv.server_id() == 0


def test_ps_role_none():
    mv.init(["-ps_role=none"])
    assert mv.worker_id() == -1
    assert mv.server_id() == -1
    mv.shutdown()


def test_ma_mode_disables_tables():
    mv.init(["-ma=true"])
    with pytest.raises(Exception):
        mv.create_table(mv.ArrayTableOption(size=10))
    mv.shutdown()


def test_aggregate_sum_is_world_size(mv_env):
    """Port of Test/test_allreduce.cpp:11-20: each rank contributes 1.0;
    the aggregate equals the world size."""
    data = np.ones(16, dtype=np.float32)
    out = mv.aggregate(data)
    np.testing.assert_allclose(out, np.ones(16) * mv.size())


def test_table_is_actually_sharded(mv_env):
    """The server store must be device-sharded across the 8 virtual devices
    (the whole point of the TPU-native design)."""
    import jax
    n = mv.num_servers()
    assert n == len(jax.devices())
    t = mv.create_table(mv.ArrayTableOption(size=800))
    data = t.store.data
    assert len(data.sharding.device_set) == n
    shard_sizes = {tuple(s.data.shape) for s in data.addressable_shards}
    assert shard_sizes == {(800 // n,)}


def test_matrix_row_sharded(mv_env):
    import jax
    n = mv.num_servers()
    t = mv.create_table(mv.MatrixTableOption(num_row=80, num_col=4))
    shard_shapes = {tuple(s.data.shape) for s in t.store.data.addressable_shards}
    assert shard_shapes == {(80 // n, 4)}


def test_create_table_requires_init():
    from multiverso_tpu.utils.log import FatalError
    with pytest.raises((FatalError, Exception)):
        mv.create_table(mv.ArrayTableOption(size=10))


def test_device_allreduce(mv_env):
    """psum over the server axis sums per-device contributions."""
    import jax
    from multiverso_tpu.core.zoo import Zoo
    from multiverso_tpu.parallel.collectives import device_allreduce

    mesh = Zoo.get().mesh
    n = mv.num_servers()
    x = np.ones((n, 4), dtype=np.float32)
    out = device_allreduce(jax.numpy.asarray(x), mesh)
    np.testing.assert_allclose(np.asarray(out), np.ones((1, 4)) * n)


def test_device_allgather(mv_env):
    import jax
    import jax.numpy as jnp
    from multiverso_tpu.core.zoo import Zoo
    from multiverso_tpu.parallel.collectives import device_allgather

    mesh = Zoo.get().mesh
    n = mv.num_servers()
    x = jax.device_put(
        np.arange(n * 2, dtype=np.float32).reshape(n * 2, 1),
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("server")))
    out = device_allgather(x, mesh)
    np.testing.assert_allclose(
        np.asarray(out), np.arange(n * 2, dtype=np.float32).reshape(n * 2, 1))


def test_device_reduce_scatter(mv_env):
    import jax
    import jax.numpy as jnp
    from multiverso_tpu.core.zoo import Zoo
    from multiverso_tpu.parallel.collectives import device_reduce_scatter

    mesh = Zoo.get().mesh
    n = mv.num_servers()
    x = jnp.ones((n * 2, 3), dtype=jnp.float32)
    out = device_reduce_scatter(x, mesh)
    # every element reduced over n contributors
    np.testing.assert_allclose(np.asarray(out), np.full((n * 2, 3), n))


def test_mesh_shape_flag():
    """-mesh_shape builds a named multi-axis mesh."""
    mv.init(["-mesh_shape=server:4,worker:2"])
    try:
        from multiverso_tpu.core.zoo import Zoo
        mesh = Zoo.get().mesh
        assert dict(mesh.shape) == {"server": 4, "worker": 2}
        assert mv.num_servers() == 4
        t = mv.create_table(mv.ArrayTableOption(size=80))
        t.add(np.ones(80, dtype=np.float32))
        np.testing.assert_allclose(t.get(), np.ones(80))
    finally:
        mv.shutdown()


def test_finish_train_api():
    """mv.finish_train releases the calling worker from all BSP tables."""
    import threading
    from multiverso_tpu.core.options import AddOption, GetOption

    mv.init(["-sync=true"], num_local_workers=2)
    try:
        t = mv.create_table(mv.ArrayTableOption(size=4))
        d = np.ones(4, dtype=np.float32)

        def short():
            t.add(d, AddOption(worker_id=0))
            t.get(GetOption(worker_id=0))
            mv.finish_train(0)

        def long():
            for _ in range(3):
                t.add(d, AddOption(worker_id=1))
                t.get(GetOption(worker_id=1))

        th = [threading.Thread(target=short), threading.Thread(target=long)]
        for x in th:
            x.start()
        for x in th:
            x.join(timeout=30)
            assert not x.is_alive()
    finally:
        mv.shutdown()


def test_finish_train_noop_without_worker():
    """A server-only process must not release worker 0's clocks."""
    mv.init(["-ps_role=server", "-sync=true"], num_local_workers=2)
    try:
        t = mv.create_table(mv.ArrayTableOption(size=4))
        coord = t._sync
        mv.finish_train()          # no local worker: must be a no-op
        if coord is not None:
            assert coord._adds.value(0) != float("inf")
    finally:
        mv.shutdown()
