"""Cross-process async PS service tests.

Tier 1: two PSServices inside one process (loopback TCP) exercising the full
wire path — framing, routing, local-forward vs remote fan-out, waiter
completion. Tier 2 (slow): two real processes doing async Get/Add.
"""

import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.core.actor import Message, MsgType
from multiverso_tpu.parallel.net import pack_message, recv_message, send_message
from multiverso_tpu.parallel.ps_service import (DistributedArrayTable,
                                                DistributedMatrixTable,
                                                PSService)


def test_wire_roundtrip():
    """Framing parity: header + blobs survive a socket round trip."""
    a, b = socket.socketpair()
    msg = Message(src=3, type=MsgType.Request_Add, table_id=7, msg_id=42,
                  data=[np.arange(5, dtype=np.int32),
                        np.ones((2, 3), dtype=np.float32)])
    send_message(a, msg)
    got = recv_message(b)
    assert got.src == 3 and got.type == MsgType.Request_Add
    assert got.table_id == 7 and got.msg_id == 42
    np.testing.assert_array_equal(got.data[0], np.arange(5, dtype=np.int32))
    np.testing.assert_allclose(got.data[1], np.ones((2, 3)))
    a.close(); b.close()


@pytest.fixture
def two_rank_world(mv_env):
    """Two services in one process simulating ranks 0 and 1."""
    svc0 = PSService()
    svc1 = PSService()
    peers = [svc0.address, svc1.address]
    yield svc0, svc1, peers
    svc0.close()
    svc1.close()


def test_distributed_array_add_get(two_rank_world):
    svc0, svc1, peers = two_rank_world
    t0 = DistributedArrayTable(1, 100, svc0, peers, rank=0)
    t1 = DistributedArrayTable(1, 100, svc1, peers, rank=1)
    delta = np.arange(100, dtype=np.float32)
    t0.add(delta)                      # local shard + remote to rank 1
    np.testing.assert_allclose(t0.get(), delta)
    np.testing.assert_allclose(t1.get(), delta)   # rank 1 sees it too
    t1.add(delta)
    np.testing.assert_allclose(t0.get(), 2 * delta)


def test_distributed_array_updater(two_rank_world):
    svc0, svc1, peers = two_rank_world
    t0 = DistributedArrayTable(2, 10, svc0, peers, rank=0, updater="sgd")
    DistributedArrayTable(2, 10, svc1, peers, rank=1, updater="sgd")
    t0.add(np.ones(10, dtype=np.float32))
    np.testing.assert_allclose(t0.get(), -np.ones(10))  # sgd: data -= delta


def test_distributed_matrix_rows(two_rank_world):
    svc0, svc1, peers = two_rank_world
    m0 = DistributedMatrixTable(3, 20, 4, svc0, peers, rank=0)
    m1 = DistributedMatrixTable(3, 20, 4, svc1, peers, rank=1)
    # rows 0-9 live on rank 0, rows 10-19 on rank 1
    rows = [2, 15, 9, 10]
    deltas = np.stack([np.full(4, float(r)) for r in rows]).astype(np.float32)
    m0.add_rows(rows, deltas)
    got = m1.get_rows(rows)
    np.testing.assert_allclose(got, deltas)
    # duplicate adds accumulate across rank boundaries
    m1.add_rows(rows, deltas)
    np.testing.assert_allclose(m0.get_rows(rows), 2 * deltas)


_WORKER = r"""
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import multiverso_tpu as mv
from multiverso_tpu.parallel.ps_service import DistributedArrayTable, PSService

rank = int(sys.argv[1]); rendezvous = sys.argv[2]
mv.init([])
svc = PSService()
# rendezvous: write my address, wait for the peer's
with open(os.path.join(rendezvous, f"addr{rank}"), "w") as f:
    f.write(f"{svc.address[0]}:{svc.address[1]}")
other = os.path.join(rendezvous, f"addr{1 - rank}")
for _ in range(600):
    if os.path.exists(other):
        break
    time.sleep(0.05)
host, port = open(other).read().split(":")
peers = [None, None]
peers[rank] = svc.address
peers[1 - rank] = (host, int(port))
table = DistributedArrayTable(1, 64, svc, peers, rank=rank)
delta = np.full(64, float(rank + 1), dtype=np.float32)
table.add(delta)   # async: no barrier with the peer
# poll until both contributions are visible (ASGD eventual visibility)
expected = np.full(64, 3.0)
for _ in range(600):
    if np.allclose(table.get(), expected):
        print(f"RANK{rank}_OK")
        break
    time.sleep(0.05)
else:
    raise SystemExit(f"rank {rank} never saw the merged table")
# Done-rendezvous: keep serving until the peer also confirmed, or its
# in-flight gets would hit a dead service.
with open(os.path.join(rendezvous, f"done{rank}"), "w") as f:
    f.write("ok")
peer_done = os.path.join(rendezvous, f"done{1 - rank}")
for _ in range(600):
    if os.path.exists(peer_done):
        break
    time.sleep(0.05)
mv.shutdown()
"""


@pytest.mark.slow
def test_two_process_async_ps(tmp_path):
    script = tmp_path / "psworker.py"
    script.write_text(_WORKER)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(r), str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for r in range(2)]
    for r, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            p.kill()
            pytest.fail("ps worker timed out")
        assert p.returncode == 0, f"rank {r} failed:\n{err[-2000:]}"
        assert f"RANK{r}_OK" in out


def test_heartbeat_failure_detection(mv_env):
    from multiverso_tpu.parallel.ps_service import PeerClient

    svc = PSService()
    t = DistributedArrayTable(9, 10, svc, [svc.address], rank=0)
    client = PeerClient(*svc.address)
    tables = client.ping(timeout=10)
    assert tables == [9]
    # dead peer: pings eventually come back None (the conn thread may serve
    # one last in-flight message before noticing shutdown)
    svc.close()
    for _ in range(10):
        if client.ping(timeout=1) is None:
            break
    else:
        pytest.fail("dead peer never detected")
    client.close()


def test_peer_death_fails_fast_not_hangs(mv_env):
    """Failure semantics: a worker whose peer dies mid-training gets a
    prompt FatalError (fail-fast waiter release), never a hang."""
    import time as _time
    from multiverso_tpu.utils.log import FatalError

    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    t0 = DistributedArrayTable(4, 20, svc0, peers, rank=0)
    DistributedArrayTable(4, 20, svc1, peers, rank=1)
    t0.add(np.ones(20, dtype=np.float32))        # healthy round trip
    svc1.close()                                  # peer dies
    _time.sleep(0.2)
    start = _time.perf_counter()
    with pytest.raises((FatalError, OSError)):
        for _ in range(50):                       # conn may die lazily
            t0.add(np.ones(20, dtype=np.float32))
            _time.sleep(0.05)
    assert _time.perf_counter() - start < 30      # fail-fast, not timeout
    svc0.close()


def test_elastic_rank_restart_and_readmission(mv_env):
    """Kill rank 1, restart it from a checkpoint of its shard, reconnect —
    traffic resumes with no lost state."""
    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    t0 = DistributedArrayTable(6, 40, svc0, peers, rank=0)
    t1 = DistributedArrayTable(6, 40, svc1, peers, rank=1)
    t0.add(np.arange(40, dtype=np.float32))
    np.testing.assert_allclose(t0.get(), np.arange(40))

    # rank 1 checkpoints its shard, then dies
    shard_snapshot = t1.local_store.store_state()
    svc1.close()
    time.sleep(0.2)
    with pytest.raises(Exception):
        for _ in range(50):
            t0.add(np.ones(40, dtype=np.float32))
            time.sleep(0.05)
    state_before_restart = t0.local_store.store_state()["data"]

    # rank 1 restarts at a NEW address, restores its shard, re-registers
    svc1b = PSService()
    t1b = DistributedArrayTable(6, 40, svc1b, 
                                [peers[0], svc1b.address], rank=1)
    t1b.local_store.load_state(shard_snapshot)
    t0.reconnect(1, svc1b.address)

    # traffic resumes; rank-1 shard content survived the restart
    full = t0.get()
    np.testing.assert_allclose(full[20:40], np.arange(20, 40))
    t0.add(np.ones(40, dtype=np.float32))
    assert t0.get()[39] == pytest.approx(40.0)
    svc0.close(); svc1b.close()


def test_net_bind_connect_api():
    """MV_NetBind/MV_NetConnect parity surface over the PS service."""
    import multiverso_tpu as mv2

    mv2.init([])
    try:
        addr = mv2.net_bind()
        assert addr[1] > 0
        mv2.net_connect([addr])
        t = mv2.create_distributed_array_table(77, 16, rank=0)
        t.add(np.ones(16, dtype=np.float32))
        np.testing.assert_allclose(t.get(), np.ones(16))
    finally:
        mv2.shutdown()
