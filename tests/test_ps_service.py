"""Cross-process async PS service tests.

Tier 1: two PSServices inside one process (loopback TCP) exercising the full
wire path — framing, routing, local-forward vs remote fan-out, waiter
completion. Tier 2 (slow): two real processes doing async Get/Add.
"""

import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.core.actor import Message, MsgType
from multiverso_tpu.parallel.net import pack_message, recv_message, send_message
from multiverso_tpu.parallel.ps_service import (DistributedArrayTable,
                                                DistributedMatrixTable,
                                                PSService)


def test_wire_roundtrip():
    """Framing parity: header + blobs survive a socket round trip."""
    a, b = socket.socketpair()
    msg = Message(src=3, type=MsgType.Request_Add, table_id=7, msg_id=42,
                  data=[np.arange(5, dtype=np.int32),
                        np.ones((2, 3), dtype=np.float32)])
    send_message(a, msg)
    got = recv_message(b)
    assert got.src == 3 and got.type == MsgType.Request_Add
    assert got.table_id == 7 and got.msg_id == 42
    np.testing.assert_array_equal(got.data[0], np.arange(5, dtype=np.int32))
    np.testing.assert_allclose(got.data[1], np.ones((2, 3)))
    a.close(); b.close()


@pytest.fixture
def two_rank_world(mv_env):
    """Two services in one process simulating ranks 0 and 1."""
    svc0 = PSService()
    svc1 = PSService()
    peers = [svc0.address, svc1.address]
    yield svc0, svc1, peers
    svc0.close()
    svc1.close()


def test_distributed_array_add_get(two_rank_world):
    svc0, svc1, peers = two_rank_world
    t0 = DistributedArrayTable(1, 100, svc0, peers, rank=0)
    t1 = DistributedArrayTable(1, 100, svc1, peers, rank=1)
    delta = np.arange(100, dtype=np.float32)
    t0.add(delta)                      # local shard + remote to rank 1
    np.testing.assert_allclose(t0.get(), delta)
    np.testing.assert_allclose(t1.get(), delta)   # rank 1 sees it too
    t1.add(delta)
    np.testing.assert_allclose(t0.get(), 2 * delta)


def test_distributed_array_updater(two_rank_world):
    svc0, svc1, peers = two_rank_world
    t0 = DistributedArrayTable(2, 10, svc0, peers, rank=0, updater="sgd")
    DistributedArrayTable(2, 10, svc1, peers, rank=1, updater="sgd")
    t0.add(np.ones(10, dtype=np.float32))
    np.testing.assert_allclose(t0.get(), -np.ones(10))  # sgd: data -= delta


def test_distributed_matrix_rows(two_rank_world):
    svc0, svc1, peers = two_rank_world
    m0 = DistributedMatrixTable(3, 20, 4, svc0, peers, rank=0)
    m1 = DistributedMatrixTable(3, 20, 4, svc1, peers, rank=1)
    # rows 0-9 live on rank 0, rows 10-19 on rank 1
    rows = [2, 15, 9, 10]
    deltas = np.stack([np.full(4, float(r)) for r in rows]).astype(np.float32)
    m0.add_rows(rows, deltas)
    got = m1.get_rows(rows)
    np.testing.assert_allclose(got, deltas)
    # duplicate adds accumulate across rank boundaries
    m1.add_rows(rows, deltas)
    np.testing.assert_allclose(m0.get_rows(rows), 2 * deltas)


_WORKER = r"""
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import multiverso_tpu as mv
from multiverso_tpu.parallel.ps_service import DistributedArrayTable, PSService

rank = int(sys.argv[1]); rendezvous = sys.argv[2]
mv.init([])
svc = PSService()
# rendezvous: write my address, wait for the peer's
with open(os.path.join(rendezvous, f"addr{rank}"), "w") as f:
    f.write(f"{svc.address[0]}:{svc.address[1]}")
other = os.path.join(rendezvous, f"addr{1 - rank}")
for _ in range(600):
    if os.path.exists(other):
        break
    time.sleep(0.05)
host, port = open(other).read().split(":")
peers = [None, None]
peers[rank] = svc.address
peers[1 - rank] = (host, int(port))
table = DistributedArrayTable(1, 64, svc, peers, rank=rank)
delta = np.full(64, float(rank + 1), dtype=np.float32)
table.add(delta)   # async: no barrier with the peer
# poll until both contributions are visible (ASGD eventual visibility)
expected = np.full(64, 3.0)
for _ in range(600):
    if np.allclose(table.get(), expected):
        print(f"RANK{rank}_OK")
        break
    time.sleep(0.05)
else:
    raise SystemExit(f"rank {rank} never saw the merged table")
# Done-rendezvous: keep serving until the peer also confirmed, or its
# in-flight gets would hit a dead service.
with open(os.path.join(rendezvous, f"done{rank}"), "w") as f:
    f.write("ok")
peer_done = os.path.join(rendezvous, f"done{1 - rank}")
for _ in range(600):
    if os.path.exists(peer_done):
        break
    time.sleep(0.05)
mv.shutdown()
"""


@pytest.mark.slow
def test_two_process_async_ps(tmp_path):
    script = tmp_path / "psworker.py"
    script.write_text(_WORKER)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(r), str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for r in range(2)]
    for r, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            p.kill()
            pytest.fail("ps worker timed out")
        assert p.returncode == 0, f"rank {r} failed:\n{err[-2000:]}"
        assert f"RANK{r}_OK" in out


_CKPT_WORKER = r"""
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import multiverso_tpu as mv
from multiverso_tpu.core.checkpoint import CheckpointManager

rank = int(sys.argv[1]); rendezvous = sys.argv[2]; ckpt_dir = sys.argv[3]
mv.init([])
addr = mv.net_bind()
with open(os.path.join(rendezvous, f"addr{rank}"), "w") as f:
    f.write(f"{addr[0]}:{addr[1]}")
other = os.path.join(rendezvous, f"addr{1 - rank}")
for _ in range(600):
    if os.path.exists(other):
        break
    time.sleep(0.05)
host, port = open(other).read().split(":")
peers = [None, None]
peers[rank] = addr
peers[1 - rank] = (host, int(port))
mv.net_connect(peers)
table = mv.create_distributed_matrix_table(9, 20, 4, rank=rank)

# both ranks push rows landing on BOTH shards (rows 0-9 rank0, 10-19 rank1)
rows = [2, 15]
table.add_rows(rows, np.full((2, 4), float(rank + 1), dtype=np.float32))
expected = np.full((2, 4), 3.0)
for _ in range(600):
    if np.allclose(table.get_rows(rows), expected):
        break
    time.sleep(0.05)
else:
    raise SystemExit(f"rank {rank} never saw merged rows")

def rendezvous_phase(tag):
    with open(os.path.join(rendezvous, f"{tag}{rank}"), "w") as f:
        f.write("ok")
    peer = os.path.join(rendezvous, f"{tag}{1 - rank}")
    for _ in range(600):
        if os.path.exists(peer):
            return
        time.sleep(0.05)
    raise SystemExit(f"peer never reached phase {tag}")

mgr = CheckpointManager(ckpt_dir, save_every_steps=1)
path = mgr.maybe_save(step=1)
assert path, "maybe_save skipped"
rendezvous_phase("saved")        # both shards + manifests on disk

# diverge (sync adds land on both shards before returning) ...
table.add_rows(rows, np.full((2, 4), 100.0, dtype=np.float32))
rendezvous_phase("mutated")
# ... then restore each rank's own shard: state returns to the checkpoint
step = mgr.restore_latest()
assert step == 1, step
rendezvous_phase("restored")
got = table.get_rows(rows)
np.testing.assert_allclose(got, expected)
print(f"CKPT_RANK{rank}_OK")

with open(os.path.join(rendezvous, f"done{rank}"), "w") as f:
    f.write("ok")
peer_done = os.path.join(rendezvous, f"done{1 - rank}")
for _ in range(600):
    if os.path.exists(peer_done):
        break
    time.sleep(0.05)
mv.shutdown()
"""


@pytest.mark.slow
def test_two_process_checkpoint_manager(tmp_path):
    """VERDICT r2 #3: CheckpointManager round-trips a world with
    DistributedMatrixTables — each rank saves its own shard (suffixed
    file + per-rank manifest) into a shared directory and restores it."""
    script = tmp_path / "ckptworker.py"
    script.write_text(_CKPT_WORKER)
    ckpt_dir = tmp_path / "ckpts"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(r), str(tmp_path), str(ckpt_dir)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for r in range(2)]
    for r, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            p.kill()
            pytest.fail("ckpt worker timed out")
        assert p.returncode == 0, f"rank {r} failed:\n{err[-2000:]}"
        assert f"CKPT_RANK{r}_OK" in out


def test_heartbeat_failure_detection(mv_env):
    from multiverso_tpu.parallel.ps_service import PeerClient

    svc = PSService()
    t = DistributedArrayTable(9, 10, svc, [svc.address], rank=0)
    client = PeerClient(*svc.address)
    tables = client.ping(timeout=10)
    assert tables == [9]
    # dead peer: pings eventually come back None (the conn thread may serve
    # one last in-flight message before noticing shutdown)
    svc.close()
    for _ in range(10):
        if client.ping(timeout=1) is None:
            break
    else:
        pytest.fail("dead peer never detected")
    client.close()


def test_peer_death_fails_fast_not_hangs(mv_env):
    """Failure semantics: a worker whose peer dies mid-training gets a
    prompt FatalError (fail-fast waiter release), never a hang."""
    import time as _time
    from multiverso_tpu.utils.log import FatalError

    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    t0 = DistributedArrayTable(4, 20, svc0, peers, rank=0)
    DistributedArrayTable(4, 20, svc1, peers, rank=1)
    t0.add(np.ones(20, dtype=np.float32))        # healthy round trip
    svc1.close()                                  # peer dies
    _time.sleep(0.2)
    start = _time.perf_counter()
    with pytest.raises((FatalError, OSError)):
        for _ in range(50):                       # conn may die lazily
            t0.add(np.ones(20, dtype=np.float32))
            _time.sleep(0.05)
    assert _time.perf_counter() - start < 30      # fail-fast, not timeout
    svc0.close()


def test_elastic_rank_restart_and_readmission(mv_env):
    """Kill rank 1, restart it from a checkpoint of its shard, reconnect —
    traffic resumes with no lost state."""
    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    t0 = DistributedArrayTable(6, 40, svc0, peers, rank=0)
    t1 = DistributedArrayTable(6, 40, svc1, peers, rank=1)
    t0.add(np.arange(40, dtype=np.float32))
    np.testing.assert_allclose(t0.get(), np.arange(40))

    # rank 1 checkpoints its shard through the checkpoint layer (the
    # DistributedTableBase store_state/load_state surface), then dies
    import tempfile

    from multiverso_tpu.core import checkpoint as ckpt
    uri = f"file://{os.path.join(tempfile.mkdtemp(), 'shard1.npz')}"
    ckpt.save_table(t1, uri)
    svc1.close()
    time.sleep(0.2)
    with pytest.raises(Exception):
        for _ in range(50):
            t0.add(np.ones(40, dtype=np.float32))
            time.sleep(0.05)

    # rank 1 restarts at a NEW address, restores its shard, re-registers
    svc1b = PSService()
    t1b = DistributedArrayTable(6, 40, svc1b,
                                [peers[0], svc1b.address], rank=1)
    ckpt.load_table(t1b, uri)
    t0.reconnect(1, svc1b.address)

    # traffic resumes; rank-1 shard content survived the restart
    full = t0.get()
    np.testing.assert_allclose(full[20:40], np.arange(20, 40))
    t0.add(np.ones(40, dtype=np.float32))
    assert t0.get()[39] == pytest.approx(40.0)
    svc0.close(); svc1b.close()


def test_net_bind_connect_api():
    """MV_NetBind/MV_NetConnect parity surface over the PS service."""
    import multiverso_tpu as mv2

    mv2.init([])
    try:
        addr = mv2.net_bind()
        assert addr[1] > 0
        mv2.net_connect([addr])
        t = mv2.create_distributed_array_table(77, 16, rank=0)
        t.add(np.ones(16, dtype=np.float32))
        np.testing.assert_allclose(t.get(), np.ones(16))
    finally:
        mv2.shutdown()


# -- real async surface (round 2: VERDICT #3) -------------------------------
def test_add_async_staging_merges_wire_messages(two_rank_world, monkeypatch):
    """N staged add_async calls must become ONE Request_Add frame per remote
    server at flush, and the merged sum must land."""
    import multiverso_tpu.parallel.ps_service as pss

    svc0, svc1, peers = two_rank_world
    t0 = DistributedArrayTable(30, 64, svc0, peers, rank=0)
    DistributedArrayTable(30, 64, svc1, peers, rank=1)

    sent_adds = []
    orig = pss.send_message

    def counting(sock, msg):
        if msg.type == MsgType.Request_Add:
            sent_adds.append(msg)
        orig(sock, msg)

    monkeypatch.setattr(pss, "send_message", counting)
    ids = [t0.add_async(np.full(64, float(i + 1), dtype=np.float32))
           for i in range(8)]
    assert sent_adds == []            # all staged, nothing on the wire yet
    got = t0.get()                    # get flushes first (read-your-writes)
    assert len(sent_adds) == 1        # one merged frame to the one peer
    np.testing.assert_allclose(got, np.full(64, 36.0))
    for i in ids:                     # staged ids resolve to the flush batch
        t0.wait(i)


def test_get_async_returns_before_reply(two_rank_world):
    """get_async must issue the wire request and return immediately even
    when the serving peer is slow; wait() then assembles the reply."""
    svc0, svc1, peers = two_rank_world
    t0 = DistributedArrayTable(31, 40, svc0, peers, rank=0)
    DistributedArrayTable(31, 40, svc1, peers, rank=1)
    t0.add(np.arange(40, dtype=np.float32))

    orig = svc1._dispatch_control

    def slow(msg):
        time.sleep(0.5)
        return orig(msg)

    svc1._dispatch_control = slow
    start = time.perf_counter()
    msg_id = t0.get_async()
    issue_time = time.perf_counter() - start
    result = t0.wait(msg_id)
    total_time = time.perf_counter() - start
    assert issue_time < 0.2, f"get_async blocked for {issue_time:.2f}s"
    assert total_time >= 0.5          # the reply really was slow
    np.testing.assert_allclose(result, np.arange(40))


def test_stateful_updater_fire_and_forget_matches_blocking(two_rank_world):
    """AdaGrad (non-stageable) adds fire without waiting but apply in FIFO
    order per connection — final state must equal the blocking sequence."""
    from multiverso_tpu.core.options import AddOption

    svc0, svc1, peers = two_rank_world
    t_async = DistributedArrayTable(32, 20, svc0, peers, rank=0,
                                    updater="adagrad")
    DistributedArrayTable(32, 20, svc1, peers, rank=1, updater="adagrad")
    t_block = DistributedArrayTable(33, 20, svc0, peers, rank=0,
                                    updater="adagrad")
    DistributedArrayTable(33, 20, svc1, peers, rank=1, updater="adagrad")

    opt = AddOption(learning_rate=0.1, rho=0.9)
    for i in range(3):
        delta = np.full(20, float(i + 1), dtype=np.float32)
        t_async.add_async(delta, opt)
        t_block.add(delta, opt)
    t_async.flush(wait=True)
    t_async.local_store.block()
    np.testing.assert_allclose(t_async.get(), t_block.get(), rtol=1e-6)


def test_pipelined_pull_overlaps_compute(two_rank_world):
    """The double-buffer pattern (ref ps_model.cpp:236-271): with a slow
    server, issue-next-pull-then-compute must beat pull-then-compute."""
    svc0, svc1, peers = two_rank_world
    t0 = DistributedArrayTable(34, 16, svc0, peers, rank=0)
    DistributedArrayTable(34, 16, svc1, peers, rank=1)
    t0.add(np.ones(16, dtype=np.float32))

    delay, compute, rounds = 0.15, 0.15, 4
    orig = svc1._dispatch_control

    def slow(msg):
        time.sleep(delay)
        return orig(msg)

    svc1._dispatch_control = slow

    start = time.perf_counter()
    for _ in range(rounds):
        t0.get()
        time.sleep(compute)           # un-overlapped: serial pull + compute
    serial = time.perf_counter() - start

    start = time.perf_counter()
    pending = t0.get_async()
    for _ in range(rounds):
        time.sleep(compute)           # compute overlaps the in-flight pull
        t0.wait(pending)
        pending = t0.get_async()
    t0.wait(pending)
    pipelined = time.perf_counter() - start
    assert pipelined < serial * 0.85, (
        f"no overlap: pipelined {pipelined:.2f}s vs serial {serial:.2f}s")


def test_matrix_add_rows_async_staging(two_rank_world, monkeypatch):
    """Row adds stage in the native buffer: duplicates merge, one wire frame
    per touched server at flush."""
    import multiverso_tpu.parallel.ps_service as pss

    svc0, svc1, peers = two_rank_world
    m0 = DistributedMatrixTable(35, 20, 4, svc0, peers, rank=0)
    DistributedMatrixTable(35, 20, 4, svc1, peers, rank=1)

    sent_adds = []
    orig = pss.send_message

    def counting(sock, msg):
        if msg.type == MsgType.Request_Add:
            sent_adds.append(msg)
        orig(sock, msg)

    monkeypatch.setattr(pss, "send_message", counting)
    # rows 5 (local shard) and 15 (remote shard), added twice each
    for _ in range(2):
        m0.add_rows_async([5, 15], np.ones((2, 4), dtype=np.float32))
    assert sent_adds == []
    got = m0.get_rows([5, 15])
    assert len(sent_adds) == 1
    np.testing.assert_allclose(got, np.full((2, 4), 2.0))


def test_world16_stress_bounded_threads(mv_env):
    """Hardening (VERDICT r1 #10): 16 ranks, all-to-all traffic — each
    service must hold its fixed 2-thread budget (selector IO + dispatcher),
    and every rank must observe the full accumulated state."""
    import threading as _threading

    world = 16
    services = [PSService() for _ in range(world)]
    peers = [s.address for s in services]
    tables = [DistributedArrayTable(40, 160, services[r], peers, rank=r)
              for r in range(world)]
    for svc in services:
        assert svc.num_service_threads == 2

    before = _threading.active_count()
    errors = []

    def worker(r):
        try:
            for i in range(5):
                tables[r].add_async(np.full(160, 1.0, dtype=np.float32))
            tables[r].flush(wait=True)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [_threading.Thread(target=worker, args=(r,))
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errors, errors[0]
    # each service still exactly 2 threads despite 15 inbound connections
    for svc in services:
        assert svc.num_service_threads == 2
    expected = np.full(160, float(world * 5), dtype=np.float32)
    for r in (0, 7, 15):
        np.testing.assert_allclose(tables[r].get(), expected)
    for t_ in tables:
        t_.close()
    for s in services:
        s.close()


# -- wire compression (round 2: VERDICT #5) ---------------------------------
def _count_wire_bytes(monkeypatch, kinds):
    """Patch net.pack_message to tally packed bytes by msg type — the one
    choke point BOTH legs go through (requests via send_message, replies
    via the IO thread's function-local pack_message import)."""
    import multiverso_tpu.parallel.net as net

    counts = {k: 0 for k in kinds}
    orig = net.pack_message

    def counting(msg):
        data = orig(msg)
        if msg.type in counts:
            counts[msg.type] += len(data)
        return data

    monkeypatch.setattr(net, "pack_message", counting)
    return counts


def test_wire_sparse_filter_reduces_bytes(two_rank_world, monkeypatch):
    """A 95%-zero delta must cross the wire sparse (FilterIn analog) and
    reconstruct exactly (FilterOut); bytes on the wire must shrink."""
    from multiverso_tpu.utils.configure import set_flag

    svc0, svc1, peers = two_rank_world
    t0 = DistributedArrayTable(50, 4000, svc0, peers, rank=0)
    DistributedArrayTable(50, 4000, svc1, peers, rank=1)

    rng = np.random.default_rng(0)
    delta = np.zeros(4000, dtype=np.float32)
    hot = rng.choice(4000, size=200, replace=False)
    delta[hot] = rng.normal(size=200).astype(np.float32)

    counts = _count_wire_bytes(monkeypatch,
                               (MsgType.Request_Add, MsgType.Reply_Get))
    set_flag("wire_compression", "none")
    t0.add(delta)
    raw_add = counts[MsgType.Request_Add]

    set_flag("wire_compression", "sparse")
    t0.add(delta)
    sparse_add = counts[MsgType.Request_Add] - raw_add
    assert sparse_add < raw_add * 0.35, (raw_add, sparse_add)

    got = t0.get()                  # reply leg also filtered (mostly zeros)
    np.testing.assert_allclose(got, 2 * delta)
    assert counts[MsgType.Reply_Get] < raw_add * 0.5


def test_wire_bf16_halves_bytes_both_legs(two_rank_world, monkeypatch):
    """bf16 wire mode: dense deltas AND get replies cross the wire as
    uint16 bf16 halves (~50% of raw bytes), with values within bf16
    rounding of the f32 path."""
    from multiverso_tpu.utils.configure import set_flag

    svc0, svc1, peers = two_rank_world
    t0 = DistributedArrayTable(52, 4096, svc0, peers, rank=0)
    DistributedArrayTable(52, 4096, svc1, peers, rank=1)

    rng = np.random.default_rng(2)
    delta = rng.normal(size=4096).astype(np.float32)   # dense: no sparsify

    counts = _count_wire_bytes(monkeypatch,
                               (MsgType.Request_Add, MsgType.Reply_Get))
    try:
        set_flag("wire_compression", "none")
        t0.add(delta)
        raw_add = counts[MsgType.Request_Add]
        _ = t0.get()
        raw_reply = counts[MsgType.Reply_Get]

        set_flag("wire_compression", "bf16")
        t0.add(delta)
        bf16_add = counts[MsgType.Request_Add] - raw_add
        got = t0.get()
        bf16_reply = counts[MsgType.Reply_Get] - raw_reply
    finally:
        set_flag("wire_compression", "sparse")

    # headers/keys are small next to a 16KB payload: expect ~0.5x
    assert bf16_add < raw_add * 0.62, (raw_add, bf16_add)
    assert bf16_reply < raw_reply * 0.62, (raw_reply, bf16_reply)
    # local shard exact-f32 add + bf16 read; remote shard bf16 add too.
    # bf16 has 8 mantissa bits -> relative error ~2^-8 per rounding, a few
    # roundings deep here.
    np.testing.assert_allclose(got, 2 * delta, rtol=0.03, atol=0.02)


def test_wire_bf16_bits_roundtrip():
    """RNE truncation: bf16-representable values round-trip exactly;
    arbitrary values within 2^-8 relative."""
    from multiverso_tpu.utils.quantization import (bf16_bits_to_f32,
                                                   f32_to_bf16_bits)

    exact = np.array([0.0, 1.0, -2.5, 0.15625, 2.0 ** 100, -2.0 ** -100],
                     dtype=np.float32)
    np.testing.assert_array_equal(
        bf16_bits_to_f32(f32_to_bf16_bits(exact)), exact)
    rng = np.random.default_rng(3)
    x = rng.normal(size=10_000).astype(np.float32)
    y = bf16_bits_to_f32(f32_to_bf16_bits(x))
    rel = np.abs(y - x) / np.maximum(np.abs(x), 1e-30)
    assert rel.max() <= 2.0 ** -8, rel.max()


def test_wire_onebit_error_feedback_converges(two_rank_world):
    """OneBit mode quantizes add payloads to sign bits + scales with
    sender-held error feedback: K pushes of the same delta must accumulate
    to ~K*delta (residual stays bounded), and the flag must not corrupt
    get replies (absolute values never quantized)."""
    from multiverso_tpu.utils.configure import set_flag

    svc0, svc1, peers = two_rank_world
    t0 = DistributedArrayTable(51, 64, svc0, peers, rank=0)
    DistributedArrayTable(51, 64, svc1, peers, rank=1)

    rng = np.random.default_rng(1)
    delta = rng.normal(size=64).astype(np.float32)
    set_flag("wire_compression", "onebit")
    try:
        K = 50
        for _ in range(K):
            t0.add(delta)
        got = t0.get()
    finally:
        set_flag("wire_compression", "sparse")
    # local shard (rank 0's half) is exact; remote half is 1-bit quantized
    # with error feedback: accumulated error == the sender-held residual,
    # which stays BOUNDED independent of K (measured ~14 for this seed at
    # K=50..5000), so the relative error vanishes as 1/K.
    np.testing.assert_allclose(got[:32], K * delta[:32], rtol=1e-5)
    err = np.abs(got[32:] - K * delta[32:])
    assert err.max() < 20.0, err.max()
    assert err.max() / K < np.abs(delta[32:]).mean()


def test_elastic_auto_readmission_no_manual_reconnect(mv_env):
    """Round-2 elastic membership (VERDICT #7): rank 1 dies and restarts at
    a NEW address. Its table construction re-registers with the rank-0
    directory; rank 0's next failed request rediscovers the address through
    the directory and traffic resumes — NO reconnect() call anywhere."""
    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    t0 = DistributedArrayTable(60, 40, svc0, peers, rank=0)
    t1 = DistributedArrayTable(60, 40, svc1, peers, rank=1)
    t0.add(np.arange(40, dtype=np.float32))
    np.testing.assert_allclose(t0.get(), np.arange(40))

    import tempfile

    from multiverso_tpu.core import checkpoint as ckpt
    uri = f"file://{os.path.join(tempfile.mkdtemp(), 'shard1.npz')}"
    ckpt.save_table(t1, uri)
    svc1.close()                 # rank 1 dies
    time.sleep(0.3)

    # rank 1 restarts at a new port; enable_directory re-registers it
    svc1b = PSService()
    t1b = DistributedArrayTable(60, 40, svc1b,
                                [peers[0], svc1b.address], rank=1)
    ckpt.load_table(t1b, uri)

    # rank 0 still points at the DEAD address; the failed request must
    # rediscover the new one through the directory automatically
    got = t0.get()
    np.testing.assert_allclose(got, np.arange(40))
    t0.add(np.ones(40, dtype=np.float32))
    assert t0.get()[39] == pytest.approx(40.0)
    svc0.close(); svc1b.close()


def test_reply_leg_never_clips_parameter_values(two_rank_world):
    """A user clip threshold sparsifies add DELTAS; Get replies carry
    absolute parameters and must come back exact even when most weights are
    inside the clip band (regression: review r2 finding)."""
    from multiverso_tpu.utils.configure import set_flag

    svc0, svc1, peers = two_rank_world
    t0 = DistributedArrayTable(70, 40, svc0, peers, rank=0)
    DistributedArrayTable(70, 40, svc1, peers, rank=1)
    small = np.full(40, 0.01, dtype=np.float32)   # all inside the clip band
    set_flag("wire_compression_clip", 0.5)
    try:
        t0.add(np.ones(40, dtype=np.float32))     # deltas above clip: exact
        got = t0.get()
    finally:
        set_flag("wire_compression_clip", 0.0)
    np.testing.assert_allclose(got, np.ones(40))
    # now push values INTO the band and confirm the pull stays exact
    set_flag("wire_compression_clip", 0.0)
    t0.add(small - 1.0)
    np.testing.assert_allclose(t0.get(), small, rtol=1e-6)
