"""Dispatch-pipeline + hot-row-cache tests (PR 9's serving perf work).

The contracts under test:

* the depth-N pipeline actually OVERLAPS — a slow collect lets multiple
  dispatched batches ride in flight, bounded by depth (backpressure);
* delivery stays exactly-once and FIFO through the pipelined path, and
  quiesce still means "nothing queued, nothing mid-flight" (the rolling
  checkpoint-swap barrier);
* pipelined serving is BITWISE-equal to the serialized path against a
  live table (same gather, same snapshot discipline);
* cache hits are bitwise-equal to a direct ``table.get_rows`` and the
  staleness bound is respected under concurrent training writes: a
  clock advance past the bound forces the device path, a within-bound
  age serves the stamped snapshot.
"""

import threading
import time

import numpy as np
import pytest

from multiverso_tpu.serving import (DispatchPipeline, DynamicBatcher,
                                    HotRowCache, ShedError,
                                    resolve_pipeline_depth)
from multiverso_tpu.serving.pipeline import InflightBatch


class TwoPhaseRunner:
    """Runner double speaking the dispatch/collect contract: dispatch is
    instant (records the call), collect blocks ``collect_s`` to simulate
    device execution so the window can fill."""

    name = "two_phase"
    payload_dtype = np.int32
    pad_id = 0

    def __init__(self, collect_s: float = 0.0):
        self.collect_s = collect_s
        self.dispatches = []
        self.collected = []
        self.max_concurrent = 0
        self._outstanding = 0
        self._lock = threading.Lock()

    def dispatch(self, batch, lengths):
        with self._lock:
            self._outstanding += 1
            self.max_concurrent = max(self.max_concurrent,
                                      self._outstanding)
            self.dispatches.append((batch.copy(), lengths.copy()))
        return (batch.copy(), lengths.copy())

    def collect(self, handle):
        if self.collect_s:
            time.sleep(self.collect_s)
        batch, lengths = handle
        with self._lock:
            self._outstanding -= 1
            self.collected.append(lengths.copy())
        return batch

    def run(self, batch, lengths):
        return self.collect(self.dispatch(batch, lengths))

    def slice_result(self, out, i, length):
        return out[i, :length]

    def jit_cache_size(self):
        return 1


def test_resolve_pipeline_depth_values():
    assert resolve_pipeline_depth(0) == 0
    assert resolve_pipeline_depth(1) == 1
    assert resolve_pipeline_depth(5) == 5
    assert resolve_pipeline_depth("3") == 3
    # auto probes the (CPU) dispatch latency: fast launch -> small depth,
    # always within the documented window
    assert 2 <= resolve_pipeline_depth("auto") <= 4
    assert 2 <= resolve_pipeline_depth(None) <= 4
    with pytest.raises(Exception):
        resolve_pipeline_depth("fast")


def test_pipeline_overlaps_and_bounds_inflight(mv_env):
    """With collect slower than dispatch, the window fills to depth (and
    NEVER past it), proving batches genuinely overlap."""
    from multiverso_tpu.telemetry import get_registry

    runner = TwoPhaseRunner(collect_s=0.05)
    b = DynamicBatcher(runner, buckets=(4,), max_batch=1, max_wait_ms=0.0,
                       max_queue=64, pipeline_depth=3)
    try:
        futs = [b.submit(np.asarray([i], np.int32), deadline_ms=30_000)
                for i in range(8)]
        results = [f.wait(30) for f in futs]
        for i, r in enumerate(results):
            np.testing.assert_array_equal(r, [i])
        assert runner.max_concurrent >= 2, "dispatches never overlapped"
        snap = get_registry().snapshot(buckets=False)
        g = snap["gauges"]["serve.pipeline.inflight"]
        assert g["max"] >= 2
        assert g["max"] <= 3 + 1        # window + the one mid-collect
        assert snap["counters"]["serve.pipeline.backpressure"]["value"] > 0
        # FIFO delivery: collected lengths in dispatch order
        assert [int(l[0]) for l in runner.collected] == [1] * 8
    finally:
        b.close()


def test_pipelined_delivery_order_and_parity(mv_env):
    """Every request's payload comes back exactly-once and intact (the
    parrot runner) through the pipelined path."""
    runner = TwoPhaseRunner(collect_s=0.002)
    b = DynamicBatcher(runner, buckets=(4, 8), max_batch=4,
                       max_wait_ms=0.5, pipeline_depth=2)
    seen = []
    lock = threading.Lock()

    def on_done(i):
        def cb(result):
            with lock:
                seen.append((i, result))
        return cb

    try:
        for i in range(20):
            b.submit_callback(np.asarray([i, i + 1], np.int32), 30_000.0,
                              on_done(i))
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with lock:
                if len(seen) == 20:
                    break
            time.sleep(0.01)
        with lock:
            assert len(seen) == 20
            for i, result in seen:
                assert not isinstance(result, BaseException), result
                np.testing.assert_array_equal(result, [i, i + 1])
    finally:
        b.close()


def test_pipelined_quiesce_waits_for_inflight(mv_env):
    """quiesce() must not report idle while a dispatched batch is still
    riding the window — the straddling batch IS what the checkpoint-swap
    barrier exists to stop."""
    runner = TwoPhaseRunner(collect_s=0.15)
    b = DynamicBatcher(runner, buckets=(4,), max_batch=1, max_wait_ms=0.0,
                       pipeline_depth=2)
    try:
        futs = [b.submit(np.asarray([1], np.int32), deadline_ms=30_000)
                for _ in range(3)]
        t0 = time.monotonic()
        assert b.quiesce(timeout_s=30)
        # idle only after every batch collected: >= 1 collect period
        assert time.monotonic() - t0 >= 0.05
        assert len(runner.collected) == 3
        for f in futs:
            f.wait(5)
        assert b._pipeline.empty()
    finally:
        b.close()


def test_pipelined_collect_error_sheds_batch_only(mv_env):
    """A collect() blow-up sheds THAT batch exactly-once and the worker
    + collector survive for the next request."""
    class Exploding(TwoPhaseRunner):
        def collect(self, handle):
            batch, lengths = handle
            if int(batch[0, 0]) == 13:
                with self._lock:
                    self._outstanding -= 1
                raise RuntimeError("boom")
            return super().collect(handle)

    runner = Exploding()
    b = DynamicBatcher(runner, buckets=(4,), max_batch=1, max_wait_ms=0.0,
                       pipeline_depth=2)
    try:
        bad = b.submit(np.asarray([13], np.int32), deadline_ms=30_000)
        with pytest.raises(ShedError):
            bad.wait(20)
        good = b.submit(np.asarray([2], np.int32), deadline_ms=30_000)
        np.testing.assert_array_equal(good.wait(20), [2])
    finally:
        b.close()


def test_pipelined_live_table_bitwise_parity(mv_env):
    """Pipelined serving over a live table == direct get_rows, and the
    one-executable-per-bucket contract holds through the new path."""
    import multiverso_tpu as mv
    from multiverso_tpu.serving import ServingClient, ServingService

    t = mv.create_table(mv.MatrixTableOption(num_row=128, num_col=8))
    rng = np.random.default_rng(3)
    t.add_rows(np.arange(128, dtype=np.int32),
               rng.normal(size=(128, 8)).astype(np.float32))
    runner = t.serving_runner()
    svc = ServingService()
    svc.register_runner(runner, buckets=(4, 8), max_batch=4,
                        max_wait_ms=1.0, pipeline_depth=2)
    cli = ServingClient(*svc.address)
    try:
        for n in (2, 4, 7, 8, 3):
            q = rng.integers(0, 128, n).astype(np.int32)
            np.testing.assert_array_equal(
                cli.lookup(q, deadline_ms=10_000), t.get_rows(q))
        assert runner.jit_cache_size() == 2         # buckets 4 and 8
        assert svc.batcher(0).pipeline_depth == 2
    finally:
        cli.close()
        svc.close()


# ---------------------------------------------------------------------------
# Hot-row cache
# ---------------------------------------------------------------------------
def test_cache_lru_eviction_and_capacity():
    c = HotRowCache(capacity=2, staleness=0)
    c.put_rows(np.asarray([1]), np.ones((1, 4), np.float32), 0)
    c.put_rows(np.asarray([2]), np.ones((1, 4), np.float32) * 2, 0)
    assert len(c) == 2
    # touch 1 (full hit), then insert 3: LRU victim must be 2
    assert c.get_rows(np.asarray([1]), 0) is not None
    c.put_rows(np.asarray([3]), np.ones((1, 4), np.float32) * 3, 0)
    assert len(c) == 2
    assert c.get_rows(np.asarray([2]), 0) is None
    assert c.get_rows(np.asarray([1]), 0) is not None
    # all-or-nothing: one cold key fails the whole request
    assert c.get_rows(np.asarray([1, 9]), 0) is None


def test_cache_hits_bitwise_equal_under_training_writes(mv_env):
    """The headline parity: cached lookups == direct ``table.get_rows``
    while a concurrent writer mutates the table, with the staleness
    bound deciding exactly when the cache must refetch.

    Clock discipline (BSP): writes land, THEN the clock ticks. With
    staleness=1 an entry stamped at clock c serves through c+1 and must
    refetch at c+2."""
    import multiverso_tpu as mv
    from multiverso_tpu.serving import ServingClient, ServingService
    from multiverso_tpu.serving.runners import SparseLookupRunner
    from multiverso_tpu.telemetry import get_registry

    t = mv.create_table(mv.MatrixTableOption(num_row=64, num_col=4))
    rng = np.random.default_rng(0)
    t.add_rows(np.arange(64, dtype=np.int32),
               rng.normal(size=(64, 4)).astype(np.float32))
    clock = [0.0]
    cache = HotRowCache(capacity=64, staleness=1)
    runner = SparseLookupRunner(t.store, clock_fn=lambda: (clock[0], 0.0),
                                cache=cache)
    svc = ServingService()
    svc.register_runner(runner, buckets=(8,), max_batch=2,
                        max_wait_ms=0.5, pipeline_depth=0)
    cli = ServingClient(*svc.address)
    reg = get_registry()
    q = np.asarray([5, 17, 30], np.int32)
    try:
        v0 = cli.lookup(q, deadline_ms=10_000)      # miss: populate @0
        np.testing.assert_array_equal(v0, t.get_rows(q))
        hits0 = reg.counter("serve.cache.hit").value
        v1 = cli.lookup(q, deadline_ms=10_000)      # hit @0
        assert reg.counter("serve.cache.hit").value == hits0 + 1
        np.testing.assert_array_equal(v1, t.get_rows(q))

        # Training write + clock tick: age 1 <= staleness -> still a
        # hit, serving the STAMPED snapshot (the documented bound).
        old = t.get_rows(q)
        t.add_rows(q, np.ones((3, 4), np.float32))
        clock[0] = 1.0
        v2 = cli.lookup(q, deadline_ms=10_000)
        assert reg.counter("serve.cache.hit").value == hits0 + 2
        np.testing.assert_array_equal(v2, old)      # bounded staleness

        # Second tick: age 2 > staleness -> stale, device refetch, and
        # the refetched rows are bitwise the CURRENT table rows.
        clock[0] = 2.0
        stale0 = reg.counter("serve.cache.stale").value
        v3 = cli.lookup(q, deadline_ms=10_000)
        assert reg.counter("serve.cache.stale").value == stale0 + 1
        np.testing.assert_array_equal(v3, t.get_rows(q))

        # The refetch restamped @2: an immediate repeat hits again,
        # bitwise-fresh.
        v4 = cli.lookup(q, deadline_ms=10_000)
        np.testing.assert_array_equal(v4, t.get_rows(q))
        assert reg.counter("serve.cache.hit").value == hits0 + 3
    finally:
        cli.close()
        svc.close()


def test_cache_staleness_zero_always_fresh_under_writes(mv_env):
    """staleness=0: every clock tick invalidates — cached serving is
    indistinguishable (bitwise) from direct reads at every step."""
    import multiverso_tpu as mv
    from multiverso_tpu.serving import ServingClient, ServingService
    from multiverso_tpu.serving.runners import SparseLookupRunner

    t = mv.create_table(mv.MatrixTableOption(num_row=32, num_col=4))
    t.add_rows(np.arange(32, dtype=np.int32),
               np.arange(128, dtype=np.float32).reshape(32, 4))
    clock = [0.0]
    runner = SparseLookupRunner(t.store, clock_fn=lambda: (clock[0], 0.0),
                                cache=HotRowCache(32, staleness=0))
    svc = ServingService()
    svc.register_runner(runner, buckets=(8,), max_batch=2,
                        max_wait_ms=0.5)
    cli = ServingClient(*svc.address)
    q = np.asarray([1, 2, 3], np.int32)
    try:
        for step in range(4):
            direct = t.get_rows(q)
            for _ in range(2):      # miss-then-hit at each step
                np.testing.assert_array_equal(
                    cli.lookup(q, deadline_ms=10_000), direct)
            t.add_rows(q, np.full((3, 4), float(step + 1), np.float32))
            clock[0] += 1.0
        # final state also bitwise
        np.testing.assert_array_equal(
            cli.lookup(q, deadline_ms=10_000), t.get_rows(q))
    finally:
        cli.close()
        svc.close()


def test_clockless_live_table_never_serves_from_cache(mv_env):
    """A LIVE table without a BSP clock (async mode) must ignore the
    cache entirely: with no version to age entries by, a cached row
    would mask training writes forever (regression guard)."""
    import multiverso_tpu as mv
    from multiverso_tpu.serving.runners import SparseLookupRunner

    t = mv.create_table(mv.MatrixTableOption(num_row=16, num_col=4))
    t.add_rows(np.arange(16, dtype=np.int32),
               np.arange(64, dtype=np.float32).reshape(16, 4))
    cache = HotRowCache(16, staleness=0)
    runner = SparseLookupRunner(t.store, clock_fn=None, cache=cache)
    q = np.asarray([1, 2], np.int32)
    mat = np.zeros((2, 4), np.int32)
    mat[0, :2] = q
    lens = np.asarray([2, 0], np.int32)
    runner.run(mat, lens)
    assert len(cache) == 0                  # never populated
    assert runner.try_cached(q) is None     # never served
    # training write is immediately visible (no cache in the way)
    t.add_rows(q, np.ones((2, 4), np.float32))
    out = runner.run(mat, lens)
    np.testing.assert_array_equal(out[0, :2], t.get_rows(q))


def test_pipeline_close_delivers_everything(mv_env):
    """close() with batches mid-flight: every future completes (served
    or shed) — nothing hangs, nothing double-delivers."""
    runner = TwoPhaseRunner(collect_s=0.03)
    b = DynamicBatcher(runner, buckets=(4,), max_batch=1, max_wait_ms=0.0,
                       pipeline_depth=2)
    futs = [b.submit(np.asarray([i], np.int32), deadline_ms=30_000)
            for i in range(6)]
    b.close()
    outcomes = 0
    for f in futs:
        try:
            f.wait(10)
            outcomes += 1
        except ShedError:
            outcomes += 1
    assert outcomes == 6


def test_bare_pipeline_submit_after_close():
    p = DispatchPipeline(depth=2)
    p.close()
    delivered = []
    item = InflightBatch(handle=None, collect=lambda h: h,
                         deliver=lambda i, r: delivered.append(r),
                         n_requests=1)
    assert p.submit(item) is False
    assert not delivered
