"""SparseFilter / OneBitsFilter / DC-ASGD tests."""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.utils.quantization import OneBitsFilter, SparseFilter


def test_sparse_filter_compresses_sparse():
    f = SparseFilter(clip=0.01)
    v = np.zeros(100, dtype=np.float32)
    v[[3, 50, 99]] = [1.0, -2.0, 0.5]
    compressed, payload, idx = f.filter_in(v)
    assert compressed
    assert len(payload) == 3
    out = f.filter_out(compressed, payload, idx, 100)
    np.testing.assert_allclose(out, v)


def test_sparse_filter_passes_dense():
    f = SparseFilter(clip=0.01)
    v = np.ones(100, dtype=np.float32)
    compressed, payload, idx = f.filter_in(v)
    assert not compressed and idx is None
    np.testing.assert_allclose(f.filter_out(compressed, payload, None, 100),
                               v)


def test_sparse_filter_zero_length_round_trip():
    """Zero-length buffers must round-trip through every path: raw by
    definition (no tie-break reliance in the >50% rule), and the
    compressed decode path must tolerate empty/None indices without the
    ``out[None] = payload`` broadcast-corruption footgun."""
    for clip in (0.0, 0.5):
        f = SparseFilter(clip=clip)
        for empty in (np.zeros(0, np.float32), np.zeros((0, 4), np.float32),
                      np.zeros((3, 0), np.float32)):
            compressed, payload, idx = f.filter_in(empty)
            assert not compressed and idx is None
            out = f.filter_out(compressed, payload, idx, 0)
            assert out.shape == (0,) and out.dtype == np.float32
    # compressed decode with an all-clipped (empty) payload: exact zeros,
    # never a broadcast over the whole buffer
    f = SparseFilter(clip=0.5)
    compressed, payload, idx = f.filter_in(np.zeros(6, np.float32))
    assert compressed and len(payload) == 0
    np.testing.assert_array_equal(
        f.filter_out(True, payload, idx, 6), np.zeros(6, np.float32))
    np.testing.assert_array_equal(
        f.filter_out(True, np.zeros(0, np.float32), None, 4),
        np.zeros(4, np.float32))


def test_zero_length_wire_payload_round_trip():
    """The PS wire codec and the serving codec both carry empty payloads
    (empty shard reply, zero-row lookup) without dtype/shape loss."""
    from multiverso_tpu.parallel.net import (pack_serve_payload,
                                             unpack_serve_payload)
    from multiverso_tpu.parallel.ps_service import (pack_payload,
                                                    unpack_payload)

    for shape in ((0,), (0, 16), (4, 0)):
        empty = np.zeros(shape, np.float32)
        for mode in ("none", "sparse", "bf16"):
            out = unpack_payload(pack_payload(empty, mode))
            assert out.shape == shape and out.dtype == np.float32
        for wire in ("f32", "bf16"):
            out = unpack_serve_payload(pack_serve_payload(empty, wire))
            assert out.shape == shape and out.dtype == np.float32


def test_bf16_wire_zero_length():
    from multiverso_tpu.utils.quantization import (bf16_bits_to_f32,
                                                   f32_to_bf16_bits)
    bits = f32_to_bf16_bits(np.zeros(0, np.float32))
    assert bits.shape == (0,) and bits.dtype == np.uint16
    assert bf16_bits_to_f32(bits).shape == (0,)


def test_one_bit_error_feedback_converges():
    """With error feedback, the running sum of decoded values tracks the
    running sum of true values."""
    f = OneBitsFilter(size=64)
    rng = np.random.default_rng(0)
    true_sum = np.zeros(64)
    decoded_sum = np.zeros(64)
    for _ in range(200):
        v = rng.normal(size=64).astype(np.float32)
        bits, ps, ns = f.encode(v)
        decoded = OneBitsFilter.decode(bits, ps, ns, 64)
        true_sum += v
        decoded_sum += decoded
    drift = np.abs(decoded_sum - true_sum).mean()
    assert drift < 3.0, drift  # bounded residual, not diverging


def test_dcasgd_updater(mv_env):
    """data -= lr*(g + lambda*g^2*(data - backup)); backup refreshed."""
    lr, lam = 0.1, 0.5
    t = mv.create_table(mv.ArrayTableOption(size=3, updater="dcasgd"))
    g = np.array([1.0, -1.0, 2.0], dtype=np.float32)
    opt = mv.AddOption(worker_id=0, learning_rate=lr, lambda_=lam)
    # step 1: backup == data == 0 -> plain sgd step
    t.add(g, opt)
    d1 = -lr * g
    np.testing.assert_allclose(t.get(), d1, rtol=1e-6)
    # step 2: backup was refreshed to d1, so again staleness term is zero
    t.add(g, opt)
    d2 = d1 - lr * g
    np.testing.assert_allclose(t.get(), d2, rtol=1e-6)


def test_dcasgd_compensates_stale_worker():
    """A second worker whose backup is stale gets the compensation term
    (needs a 2-worker world for the per-worker backup axis)."""
    lr, lam = 0.1, 0.5
    mv.init([], num_local_workers=2)
    try:
        t = mv.create_table(mv.ArrayTableOption(size=1, updater="dcasgd"))
        g = np.array([1.0], dtype=np.float32)
        t.add(g, mv.AddOption(worker_id=0, learning_rate=lr, lambda_=lam))
        d1 = float(t.get()[0])
        # worker 1 backup is still 0 -> compensated step != plain sgd
        t.add(g, mv.AddOption(worker_id=1, learning_rate=lr, lambda_=lam))
        expected = d1 - lr * (1.0 + lam * 1.0 * (d1 - 0.0))
        np.testing.assert_allclose(t.get(), [expected], rtol=1e-6)
    finally:
        mv.shutdown()


def test_one_bit_partial_byte():
    """Sizes not divisible by 8 decode exactly size elements."""
    f = OneBitsFilter(size=13)
    v = np.linspace(-1, 1, 13).astype(np.float32)
    bits, ps, ns = f.encode(v)
    out = OneBitsFilter.decode(bits, ps, ns, 13)
    assert out.shape == (13,)
    assert set(np.unique(out)).issubset({np.float32(ps), np.float32(ns)})
