"""SparseFilter / OneBitsFilter / DC-ASGD tests."""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.utils.quantization import OneBitsFilter, SparseFilter


def test_sparse_filter_compresses_sparse():
    f = SparseFilter(clip=0.01)
    v = np.zeros(100, dtype=np.float32)
    v[[3, 50, 99]] = [1.0, -2.0, 0.5]
    compressed, payload, idx = f.filter_in(v)
    assert compressed
    assert len(payload) == 3
    out = f.filter_out(compressed, payload, idx, 100)
    np.testing.assert_allclose(out, v)


def test_sparse_filter_passes_dense():
    f = SparseFilter(clip=0.01)
    v = np.ones(100, dtype=np.float32)
    compressed, payload, idx = f.filter_in(v)
    assert not compressed and idx is None
    np.testing.assert_allclose(f.filter_out(compressed, payload, None, 100),
                               v)


def test_one_bit_error_feedback_converges():
    """With error feedback, the running sum of decoded values tracks the
    running sum of true values."""
    f = OneBitsFilter(size=64)
    rng = np.random.default_rng(0)
    true_sum = np.zeros(64)
    decoded_sum = np.zeros(64)
    for _ in range(200):
        v = rng.normal(size=64).astype(np.float32)
        bits, ps, ns = f.encode(v)
        decoded = OneBitsFilter.decode(bits, ps, ns, 64)
        true_sum += v
        decoded_sum += decoded
    drift = np.abs(decoded_sum - true_sum).mean()
    assert drift < 3.0, drift  # bounded residual, not diverging


def test_dcasgd_updater(mv_env):
    """data -= lr*(g + lambda*g^2*(data - backup)); backup refreshed."""
    lr, lam = 0.1, 0.5
    t = mv.create_table(mv.ArrayTableOption(size=3, updater="dcasgd"))
    g = np.array([1.0, -1.0, 2.0], dtype=np.float32)
    opt = mv.AddOption(worker_id=0, learning_rate=lr, lambda_=lam)
    # step 1: backup == data == 0 -> plain sgd step
    t.add(g, opt)
    d1 = -lr * g
    np.testing.assert_allclose(t.get(), d1, rtol=1e-6)
    # step 2: backup was refreshed to d1, so again staleness term is zero
    t.add(g, opt)
    d2 = d1 - lr * g
    np.testing.assert_allclose(t.get(), d2, rtol=1e-6)


def test_dcasgd_compensates_stale_worker():
    """A second worker whose backup is stale gets the compensation term
    (needs a 2-worker world for the per-worker backup axis)."""
    lr, lam = 0.1, 0.5
    mv.init([], num_local_workers=2)
    try:
        t = mv.create_table(mv.ArrayTableOption(size=1, updater="dcasgd"))
        g = np.array([1.0], dtype=np.float32)
        t.add(g, mv.AddOption(worker_id=0, learning_rate=lr, lambda_=lam))
        d1 = float(t.get()[0])
        # worker 1 backup is still 0 -> compensated step != plain sgd
        t.add(g, mv.AddOption(worker_id=1, learning_rate=lr, lambda_=lam))
        expected = d1 - lr * (1.0 + lam * 1.0 * (d1 - 0.0))
        np.testing.assert_allclose(t.get(), [expected], rtol=1e-6)
    finally:
        mv.shutdown()


def test_one_bit_partial_byte():
    """Sizes not divisible by 8 decode exactly size elements."""
    f = OneBitsFilter(size=13)
    v = np.linspace(-1, 1, 13).astype(np.float32)
    bits, ps, ns = f.encode(v)
    out = OneBitsFilter.decode(bits, ps, ns, 13)
    assert out.shape == (13,)
    assert set(np.unique(out)).issubset({np.float32(ps), np.float32(ns)})
