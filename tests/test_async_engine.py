"""Async ASGD engine + native runtime tests."""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.parallel.async_engine import AsyncTableEngine, WorkerPool
from multiverso_tpu.runtime import ffi


def test_native_queue_and_waiter():
    q = ffi.MtQueue()
    q.push(7)
    q.push(8)
    assert len(q) == 2
    assert q.pop(100) == 7
    assert q.pop(100) == 8
    assert q.pop(10) is None  # timeout
    q.exit()
    assert q.pop(-1) is None  # poison releases blocked pop

    w = ffi.Waiter(3)
    assert not w.wait(10)
    for _ in range(3):
        w.notify()
    assert w.wait(100)
    w.reset(1)
    assert not w.wait(10)


def test_delta_buffer_threaded_accumulation():
    import threading
    buf = ffi.DeltaBuffer(64, 4)
    n_threads, n_adds = 8, 100

    def hammer():
        d = np.ones((64, 4), dtype=np.float32)
        for _ in range(n_adds):
            buf.add_dense(d)

    ts = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    merged, count = buf.drain_dense()
    assert count == n_threads * n_adds
    np.testing.assert_allclose(merged, np.full((64, 4),
                                               float(n_threads * n_adds)))


def test_async_staged_array(mv_env):
    table = mv.create_table(mv.ArrayTableOption(size=32))
    eng = AsyncTableEngine(table, flush_pending=1000)
    d = np.ones(32, dtype=np.float32)
    for _ in range(10):
        eng.add_async(d)
    assert eng.pending == 10       # staged, not yet applied
    out = eng.get()                # get flushes: read-your-writes
    np.testing.assert_allclose(out, d * 10)
    assert eng.pending == 0


def test_async_staged_matrix_sparse_drain(mv_env):
    table = mv.create_table(mv.MatrixTableOption(num_row=1000, num_col=8))
    eng = AsyncTableEngine(table, flush_pending=1000)
    rows = np.array([3, 500, 999], dtype=np.int32)
    d = np.ones((3, 8), dtype=np.float32)
    for _ in range(5):
        eng.add_rows_async(rows, d)
    got = eng.get_rows(rows)
    np.testing.assert_allclose(got, d * 5)
    # untouched rows stayed zero (sparse drain only moved 3 rows)
    assert np.all(eng.get_rows([0, 1, 2]) == 0)


def test_async_auto_flush_threshold(mv_env):
    table = mv.create_table(mv.ArrayTableOption(size=8))
    eng = AsyncTableEngine(table, flush_pending=4)
    d = np.ones(8, dtype=np.float32)
    for _ in range(4):
        eng.add_async(d)
    assert eng.pending == 0  # hit threshold -> flushed
    np.testing.assert_allclose(table.get(), d * 4)


def test_async_stateful_updater_bypasses_staging(mv_env):
    table = mv.create_table(mv.ArrayTableOption(size=4, updater="adagrad"))
    eng = AsyncTableEngine(table)
    d = np.ones(4, dtype=np.float32)
    eng.add_async(d, mv.AddOption(rho=0.1, learning_rate=0.1))
    assert eng.pending == 0  # applied directly, not staged
    assert np.all(eng.get() < 0)  # adagrad stepped downhill


def test_worker_pool_asgd_convergence(mv_env):
    """N async workers hammer one table; total must equal the sum of all
    contributions (ASGD loses no updates)."""
    table = mv.create_table(mv.ArrayTableOption(size=16))
    eng = AsyncTableEngine(table, flush_pending=32)
    adds_per_worker = 50
    pool = WorkerPool(8)

    def work(wid):
        d = np.full(16, float(wid + 1), dtype=np.float32)
        for _ in range(adds_per_worker):
            eng.add_async(d)

    pool.run(work)
    out = eng.get()
    expected = sum(w + 1 for w in range(8)) * adds_per_worker
    np.testing.assert_allclose(out, np.full(16, float(expected)))


def test_worker_pool_propagates_errors(mv_env):
    pool = WorkerPool(2)
    with pytest.raises(ValueError):
        pool.run(lambda wid: (_ for _ in ()).throw(ValueError("boom")))


def test_background_flusher(mv_env):
    import time
    table = mv.create_table(mv.ArrayTableOption(size=8))
    eng = AsyncTableEngine(table, flush_pending=10_000,
                           flush_interval=0.05)
    d = np.ones(8, dtype=np.float32)
    eng.add_async(d)
    # below the count threshold, but the timer must flush it
    for _ in range(100):
        if eng.pending == 0:
            break
        time.sleep(0.02)
    assert eng.pending == 0
    np.testing.assert_allclose(table.get(), d)
    eng.close()


def test_fire_and_forget_adds_do_not_leak(mv_env):
    """Unwaited add_async must not grow the pending waiter map."""
    table = mv.create_table(mv.ArrayTableOption(size=8))
    d = np.ones(8, dtype=np.float32)
    for _ in range(1000):
        table.add_async(d)
    assert len(table._pending) == 0
    # an add handle still waits correctly
    msg_id = table.add_async(d)
    table.wait(msg_id)
    np.testing.assert_allclose(table.get(), d * 1001)


def test_async_engine_rejects_sparse_tables(mv_env):
    from multiverso_tpu.utils.log import FatalError
    t = mv.create_table(mv.MatrixTableOption(num_row=4, num_col=2,
                                             is_sparse=True))
    with pytest.raises(FatalError):
        AsyncTableEngine(t)
