"""Functional port of the reference perf harness
(``Test/test_matrix_perf.cpp:32-80``): Get-all -> Add at 10%..100% row
coverage -> Get-all sweeps, with exact-value verification at every coverage
level (shrunk matrix; the timing version lives in bench.py)."""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.utils.timer import Timer


@pytest.mark.parametrize("coverage", [0.1, 0.5, 1.0])
def test_get_add_get_sweep(mv_env, coverage):
    num_row, num_col = 10_000, 50
    table = mv.create_table(mv.MatrixTableOption(num_row, num_col))
    model = np.zeros((num_row, num_col), dtype=np.float32)
    rng = np.random.default_rng(int(coverage * 10))

    timer = Timer()
    # Get-all (cold)
    np.testing.assert_allclose(table.get(), model)
    # Add at this row coverage
    n_rows = int(num_row * coverage)
    rows = rng.choice(num_row, size=n_rows, replace=False)
    deltas = rng.normal(size=(n_rows, num_col)).astype(np.float32)
    table.add_rows(rows, deltas)
    model[rows] += deltas
    # Get the touched rows and the whole table
    np.testing.assert_allclose(table.get_rows(rows[:100]),
                               model[rows[:100]], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(table.get(), model, rtol=1e-5, atol=1e-5)
    assert timer.elapse() > 0   # harness plumbing (timing lives in bench.py)
