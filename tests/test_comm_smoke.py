"""Tier-1 CommPolicy smoke: drives ``scripts/comm_bench.py --dry-run``
end to end (ISSUE 10 CI satellite).

Asserts the AUTO decision table picks the expected policy for the
canonical shapes, that the hybrid word2vec dry run really ran BOTH
planes (PS add counter AND ``comm.allreduce.bytes`` nonzero — the
script's own witness block, re-checked here), that the logreg allreduce
params are bitwise-equal to the PS path, and that the measured
policy ordering matches AUTO's choices. A regression that silently
routes everything back onto one plane fails here, not in a bench
review.
"""

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_comm_bench_dry_run_witnesses(tmp_path):
    out = tmp_path / "BENCH_COMM.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "comm_bench.py"),
         "--dry-run", f"--out={out}"],
        capture_output=True, text=True, timeout=420, cwd=_REPO, env=env)
    assert proc.returncode == 0, proc.stderr[-3000:]
    rec = json.loads(out.read_text())
    assert rec["metric"] == "comm_policy_bench" and rec["dry_run"]

    # AUTO decision table, canonical shapes (deterministic rows).
    canon = rec["auto"]["canonical"]
    assert canon["w2v_embedding_50000x128"] == "ps"      # sparse
    assert canon["hbm_scale_1Mx128"] == "ps"             # HBM-scale
    assert canon["override_wins"] == "ps"                # explicit wins
    # The probed rows must match the probe evidence they carry.
    probed = {d["table"]: d for d in
              rec["auto"]["evidence"]["decisions"] if "probe_ms" in d}
    for name in ("logreg_weights_785x1", "wordcount_1"):
        lat = probed[name]["probe_ms"]
        want = "ps" if lat["ps"] < lat["allreduce"] else "allreduce"
        assert canon[name] == want, (name, lat)

    # Both planes ran in the hybrid word2vec dry run.
    wit = rec["witnesses"]
    assert wit["hybrid_ps_adds_nonzero"], wit
    assert wit["hybrid_allreduce_bytes_nonzero"], wit
    assert all(wit.values()), wit

    # Policy parity + ordering: allreduce == ps bitwise, and the
    # same-semantics plane AUTO picked is the measured fastest.
    assert rec["logreg"]["allreduce_bitwise_eq_ps"]
    assert rec["logreg"]["allreduce_over_ps"] > 1.0
    assert rec["word2vec"]["hybrid_over_ps"] > 1.0
    matches = rec["auto"]["auto_matches_fastest"]
    assert matches["logreg_weights"]["match"], matches
    assert matches["w2v_tables"]["match"], matches

    # model_average convergence-vs-averaging-period leg (ROADMAP 5d):
    # every period trains (improves on the initial loss) and the record
    # carries the quality gap AUTO's decision table can weigh.
    ma = rec["ma_convergence"]
    assert wit["ma_convergence_all_periods_improve"], ma
    assert len(ma["periods"]) >= 2
    for leg in ma["periods"]:
        assert leg["final_full_loss"] < ma["initial_full_loss"], leg
    assert set(ma["quality_gap_vs_sequential"]) == \
        {str(leg["period"]) for leg in ma["periods"]}

    # Per-policy telemetry is embedded per leg.
    assert rec["word2vec"]["ps"]["comm"]["comm.ps.bytes"] > 0
    assert rec["word2vec"]["model_average"]["comm"][
        "comm.model_average.bytes"] > 0
    assert rec["logreg"]["allreduce"]["comm"]["comm.allreduce.bytes"] > 0
