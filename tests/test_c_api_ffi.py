"""Foreign-host C FFI against Python-served PS shards (VERDICT r3 #2).

The reference's ``c_api`` is an ``extern "C"`` boundary any language can
dlopen (include/multiverso/c_api.h:16-54). Here the equivalent boundary is
the framed TCP wire protocol spoken by ``src/mv_client.cpp`` inside
``libmvtpu_host.so``: this test COMPILES a plain C program
(examples/c_table_demo.c), runs it against two Python PSService shards,
and asserts full cross-language visibility — C reads what Python wrote,
Python reads what C wrote.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from multiverso_tpu.parallel.ps_service import (DistributedArrayTable,
                                                DistributedKVTable,
                                                DistributedMatrixTable,
                                                PSService)
from multiverso_tpu.runtime import ffi

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO_SRC = os.path.join(REPO, "examples", "c_table_demo.c")


@pytest.fixture(scope="module")
def demo_binary(tmp_path_factory):
    ffi.load()      # (re)build libmvtpu_host.so with the client compiled in
    out = tmp_path_factory.mktemp("cdemo") / "c_table_demo"
    cc = os.environ.get("CC", "gcc")
    subprocess.run([cc, "-O2", "-Wall", "-o", str(out), DEMO_SRC, "-ldl"],
                   check=True, capture_output=True, text=True)
    return str(out)


def test_c_client_against_python_shards(demo_binary, mv_env):
    svc0, svc1 = PSService(), PSService()
    peers = [svc0.address, svc1.address]
    AID, MID, KID = 201, 202, 203
    try:
        a0 = DistributedArrayTable(AID, 10, svc0, peers, rank=0)
        a1 = DistributedArrayTable(AID, 10, svc1, peers, rank=1)
        m0 = DistributedMatrixTable(MID, 8, 3, svc0, peers, rank=0)
        DistributedMatrixTable(MID, 8, 3, svc1, peers, rank=1)
        k0 = DistributedKVTable(KID, svc0, peers, rank=0)
        DistributedKVTable(KID, svc1, peers, rank=1)

        # Python-side seeds the C program asserts against.
        a0.add(np.arange(100, 110, dtype=np.float32))      # array: 100+i
        m0.add_rows([1, 3, 6], np.full((3, 3), 10.0, dtype=np.float32))
        k0.add([4, 7, 1000000007], [1000, 1000, 1000])

        peer_str = ";".join(f"{h}:{p}" for h, p in peers)
        so = os.path.join(REPO, "multiverso_tpu", "runtime",
                          "libmvtpu_host.so")
        proc = subprocess.run(
            [demo_binary, so, peer_str, str(AID), str(MID), str(KID)],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, \
            f"C demo failed:\n{proc.stdout}\n{proc.stderr}"
        assert "C_DEMO_OK" in proc.stdout

        # ...and Python sees every value the C host pushed.
        np.testing.assert_allclose(
            a1.get(), np.arange(100, 110, dtype=np.float32)
            + np.arange(10, dtype=np.float32))
        np.testing.assert_allclose(
            m0.get_rows([1, 3, 6]),
            np.arange(1, 10, dtype=np.float32).reshape(3, 3) + 10.0)
        np.testing.assert_array_equal(k0.get([4, 7, 1000000007]),
                                      [1040, 1070, 1007])
    finally:
        svc0.close()
        svc1.close()


def test_c_client_symbols_exported():
    """The flat MV_* surface is present in the shared object (parity rows
    for Lua/C#/CLR hosts rest on this boundary being real)."""
    import ctypes
    ffi.load()
    so = os.path.join(REPO, "multiverso_tpu", "runtime",
                      "libmvtpu_host.so")
    lib = ctypes.CDLL(so)
    for sym in ("MV_ConnectClient", "MV_CloseClient", "MV_NumServers",
                "MV_NewArrayTable", "MV_GetArrayTable", "MV_AddArrayTable",
                "MV_NewMatrixTable", "MV_AddMatrixTableByRows",
                "MV_GetMatrixTableByRows", "MV_NewKVTable", "MV_AddKVTable",
                "MV_GetKVTable", "MV_FreeTable"):
        assert hasattr(lib, sym), f"missing symbol {sym}"
