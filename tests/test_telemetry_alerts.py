"""Unit tests for the timeseries store + alert rule engine (ISSUE 13).

Windows are driven DETERMINISTICALLY: tests call ``store.tick(now=...)``
and ``manager.evaluate()`` by hand instead of sleeping against the
engine's ticker thread, so the state-machine contracts (fires after N
bad windows, never on a single spike, resolves with hysteresis) are
asserted exactly, not probabilistically.
"""

import time

import numpy as np  # noqa: F401 - conftest's device mesh setup

from multiverso_tpu.telemetry import get_registry
from multiverso_tpu.telemetry.alerts import (AlertManager, BurnRateRule,
                                             SaturationRule, StragglerRule,
                                             ThresholdRule,
                                             active_alert_summaries,
                                             default_serving_rules,
                                             start_alert_engine,
                                             stop_alert_engine)
from multiverso_tpu.telemetry.timeseries import TimeseriesStore


# ---------------------------------------------------------------------------
# TimeseriesStore
# ---------------------------------------------------------------------------
def test_timeseries_counter_rate_gauge_last(mv_env):
    reg = get_registry()
    store = TimeseriesStore(capacity=8)
    c = reg.counter("ts.events")
    g = reg.gauge("ts.depth")
    store.tick(now=0.0)
    c.inc(10)
    g.set(3.0)
    store.tick(now=1.0)
    c.inc(40)
    g.set(7.0)
    store.tick(now=3.0)           # 2-second window: rate halves
    assert store.series("rate.ts.events") == [10.0, 20.0]
    assert store.series("gauge.ts.depth")[-2:] == [3.0, 7.0]
    assert store.latest("gauge.ts.depth") == 7.0


def test_timeseries_windowed_p95_and_threshold(mv_env):
    reg = get_registry()
    store = TimeseriesStore()
    h = reg.histogram("ts.lat")
    store.set_threshold("ts.lat", 50.0)
    store.tick(now=0.0)
    for _ in range(20):
        h.observe(1.0)
    store.tick(now=1.0)
    for _ in range(20):
        h.observe(400.0)
    store.tick(now=2.0)
    p95 = store.series("p95.ts.lat")
    # Windowed, not cumulative: the second window's p95 reflects ONLY
    # the 400ms batch (cumulative p95 would blend both).
    assert p95[0] < 10.0 and p95[1] > 100.0
    assert store.series("count.ts.lat") == [20.0, 20.0]
    assert store.series("bad.ts.lat") == [0.0, 20.0]


def test_timeseries_ring_is_bounded(mv_env):
    reg = get_registry()
    store = TimeseriesStore(capacity=4)
    g = reg.gauge("ts.bound")
    for i in range(12):
        g.set(float(i))
        store.tick(now=float(i))
    series = store.series("gauge.ts.bound")
    assert len(series) == 4
    assert series == [8.0, 9.0, 10.0, 11.0]
    snap = store.snapshot(last_n=2)
    assert snap["series"]["gauge.ts.bound"] == [10.0, 11.0]
    assert snap["ticks"] == 12


def test_timeseries_series_cardinality_bounded(mv_env):
    reg = get_registry()
    store = TimeseriesStore()
    store.MAX_SERIES = 8        # instance attribute shadows the class cap
    for i in range(20):
        reg.gauge(f"ts.card.{i}").set(1.0)
    store.tick(now=0.0)
    assert len(store.names()) <= 8
    assert reg.counter("telemetry.timeseries.series_dropped").value > 0


# ---------------------------------------------------------------------------
# Burn-rate rule: multi-window state machine
# ---------------------------------------------------------------------------
def _burn_env(reg, store):
    rule = BurnRateRule("slo", "burn.lat", slo_ms=50.0, budget=0.05,
                        fast_windows=5, slow_windows=30,
                        burn_threshold=2.0, min_count=8,
                        for_windows=2, clear_windows=3)
    mgr = AlertManager(store, [rule])
    h = reg.histogram("burn.lat")
    clock = [0.0]

    def window(good, bad):
        for _ in range(good):
            h.observe(1.0)
        for _ in range(bad):
            h.observe(500.0)
        clock[0] += 1.0
        store.tick(now=clock[0])
        mgr.evaluate()
    return mgr, window


def test_burn_alert_fires_only_on_sustained_breach(mv_env):
    reg = get_registry()
    mgr, window = _burn_env(reg, TimeseriesStore())
    fired0 = reg.counter("telemetry.alerts.fired").value
    for _ in range(30):
        window(20, 0)
    assert mgr.active() == []
    # ONE fully-bad window: the fast window burns but the slow window
    # dilutes it below threshold — a spike never pages.
    window(0, 20)
    assert mgr.active() == []
    for _ in range(3):          # recovery: state machine resets clean
        window(20, 0)
    assert mgr.active() == []
    assert reg.counter("telemetry.alerts.fired").value == fired0
    # Sustained breach: both windows saturate -> fires (and only once).
    n = 0
    while not mgr.active() and n < 40:
        window(0, 20)
        n += 1
    assert mgr.active(), "sustained SLO breach never fired"
    assert mgr.active()[0]["name"] == "slo"
    assert reg.counter("telemetry.alerts.fired").value == fired0 + 1
    assert reg.gauge("telemetry.alerts.active").last == 1.0


def test_burn_alert_resolves_with_hysteresis(mv_env):
    reg = get_registry()
    mgr, window = _burn_env(reg, TimeseriesStore())
    for _ in range(10):
        window(20, 0)
    for _ in range(20):
        window(0, 20)
    assert mgr.active()
    resolved0 = reg.counter("telemetry.alerts.resolved").value
    # A couple of good windows are NOT enough: the fast window is still
    # burning (bad windows age out of it first), and clear_windows=3
    # consecutive clean evaluations must follow — no flapping.
    window(20, 0)
    window(20, 0)
    assert mgr.active()
    n = 2
    while mgr.active() and n < 40:
        window(20, 0)
        n += 1
    assert mgr.active() == [], "recovery never resolved the alert"
    # fast window (5) must drain of bad windows + 3 clean evaluations
    assert n >= 5 + 3 - 1
    assert reg.counter("telemetry.alerts.resolved").value == resolved0 + 1
    # Alert transitions landed in the flight recorder ring.
    from multiverso_tpu.telemetry import flight_recorder
    kinds = [e["kind"] for e in flight_recorder().events()]
    assert "alert_fired" in kinds and "alert_resolved" in kinds


def test_burn_alert_quiet_without_traffic(mv_env):
    """No observations: no page (zero traffic evaluates as burn 0, and
    a never-ticked histogram keeps the rule fully dormant)."""
    reg = get_registry()
    store = TimeseriesStore()
    rule = BurnRateRule("slo", "quiet.lat", slo_ms=50.0)
    dormant = BurnRateRule("slo2", "never.registered", slo_ms=50.0)
    mgr = AlertManager(store, [rule, dormant])
    reg.histogram("quiet.lat")      # exists, never observed
    for i in range(10):
        store.tick(now=float(i))
        mgr.evaluate()
    assert mgr.active() == []
    states = mgr.snapshot()["states"]
    assert "slo2" not in states     # absent series: rule dormant
    assert all(s["state"] == "ok" for s in states.values())


def test_burn_alert_resolves_through_traffic_trough(mv_env):
    """A FIRING burn alert must resolve when traffic stops entirely —
    zero requests means zero violations, not a latched page (review
    finding: the old no-data guard silenced the resolve path too)."""
    reg = get_registry()
    mgr, window = _burn_env(reg, TimeseriesStore())
    for _ in range(10):
        window(20, 0)
    for _ in range(20):
        window(0, 20)
    assert mgr.active()
    n = 0
    while mgr.active() and n < 40:
        window(0, 0)                # the trough: no traffic at all
        n += 1
    assert mgr.active() == [], "alert latched through a traffic trough"


# ---------------------------------------------------------------------------
# Saturation / threshold / straggler rules
# ---------------------------------------------------------------------------
def test_saturation_rule_needs_consecutive_windows(mv_env):
    reg = get_registry()
    store = TimeseriesStore()
    rule = SaturationRule("qsat", "gauge.sat.depth", "gauge.sat.bound",
                          frac=0.9, for_windows=3, clear_windows=2)
    mgr = AlertManager(store, [rule])
    reg.gauge("sat.bound").set(10.0)
    depth = reg.gauge("sat.depth")
    clock = [0.0]

    def window(d):
        depth.set(d)
        clock[0] += 1.0
        store.tick(now=clock[0])
        mgr.evaluate()

    window(9.0)
    window(9.5)
    assert mgr.active() == []       # 2 of 3 required windows
    window(2.0)                     # dip resets the count
    window(10.0)
    window(10.0)
    assert mgr.active() == []
    window(10.0)
    assert mgr.active() and mgr.active()[0]["name"] == "qsat"
    window(1.0)
    window(1.0)
    assert mgr.active() == []


def test_threshold_rule_heartbeat_loss_shape(mv_env):
    """rate.fleet.member_dead > 0 fires in ONE window (for_windows=1):
    the router's sweep of a SIGKILLed replica is the alert, immediately."""
    reg = get_registry()
    store = TimeseriesStore()
    rule = ThresholdRule("fleet.heartbeat_loss", "rate.fleet.member_dead",
                         above=0.0, for_windows=1, clear_windows=2)
    mgr = AlertManager(store, [rule])
    dead = reg.counter("fleet.member_dead")
    store.tick(now=0.0)
    store.tick(now=1.0)
    mgr.evaluate()
    assert mgr.active() == []
    dead.inc()                      # the sweep removed a member
    store.tick(now=2.0)
    mgr.evaluate()
    assert [a["name"] for a in mgr.active()] == ["fleet.heartbeat_loss"]
    store.tick(now=3.0)
    mgr.evaluate()
    store.tick(now=4.0)
    mgr.evaluate()
    assert mgr.active() == []       # rate back to 0 for clear_windows


def test_straggler_rule_names_the_worker(mv_env):
    reg = get_registry()
    store = TimeseriesStore()
    rule = StragglerRule("ps.straggler",
                         "gauge.ps_service.staleness.worker_",
                         above=32.0, for_windows=2, clear_windows=2)
    mgr = AlertManager(store, [rule])
    reg.gauge("ps_service.staleness.worker_0").set(1.0)
    reg.gauge("ps_service.staleness.worker_3").set(80.0)
    for i in range(3):
        store.tick(now=float(i))
        mgr.evaluate()
    names = [a["name"] for a in mgr.active()]
    assert names == ["ps.straggler.3"]      # the straggler is NAMED


# ---------------------------------------------------------------------------
# Engine + payload integration
# ---------------------------------------------------------------------------
def test_engine_ticks_and_embeds_in_snapshot(mv_env):
    from multiverso_tpu.telemetry import metrics_snapshot, validate_snapshot
    reg = get_registry()
    reg.counter("eng.events").inc(5)
    eng = start_alert_engine(rules=default_serving_rules(),
                             interval_s=0.03)
    try:
        deadline = time.monotonic() + 5
        while eng.store.ticks < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.store.ticks >= 3, "engine ticker never ran"
        snap = metrics_snapshot(seq=1)
        validate_snapshot(snap)     # additive sections stay schema-valid
        assert "alerts" in snap and "timeseries" in snap
        assert snap["alerts"]["n_rules"] == len(default_serving_rules())
        assert "rate.eng.events" in snap["timeseries"]["series"]
        # idempotent: a second start returns the same engine
        assert start_alert_engine() is eng
    finally:
        stop_alert_engine()
    assert active_alert_summaries() == []   # no engine -> empty, no raise


def test_alerts_ride_heartbeat_payload_and_fleet_rollup(mv_env):
    """A firing alert in the replica's engine reaches metrics_payload,
    the router's Fleet_Stats rollup, and the fleet_top ALERTS column —
    the whole shipping path without a wire."""
    from multiverso_tpu.apps.fleet_top import render_stats
    from multiverso_tpu.fleet.health import metrics_payload
    from multiverso_tpu.fleet.membership import ReplicaGroup

    reg = get_registry()
    eng = start_alert_engine(
        rules=[ThresholdRule("unit.always", "gauge.unit.bad", above=0.0,
                             for_windows=1)],
        interval_s=0.03)
    try:
        reg.gauge("unit.bad").set(5.0)
        deadline = time.monotonic() + 5
        while not active_alert_summaries() and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        alerts = active_alert_summaries()
        assert [a["name"] for a in alerts] == ["unit.always"]

        payload = metrics_payload()
        assert [a["name"] for a in payload["alerts"]] == ["unit.always"]

        group = ReplicaGroup(heartbeat_ms=40.0)
        group.join("r0", "127.0.0.1", 1)
        group.heartbeat("r0", {"max_queue": 8, "max_batch": 4},
                        metrics=payload)
        stats = group.stats_payload()
        assert [a["name"] for a in stats["replicas"]["r0"]["alerts"]] \
            == ["unit.always"]
        # router-side engine alerts also counted (same process here)
        assert stats["fleet"]["alerts_active"] >= 1
        rendered = render_stats(stats)
        assert "unit.always"[:11] in rendered
        assert "alerts=" in rendered.splitlines()[0]
    finally:
        stop_alert_engine()


def test_finished_worker_retires_from_straggler_staleness(mv_env):
    """A worker that declared Finish_Train stops aging in the staleness
    gauges: before this fix the leader sweep kept growing a departed
    worker's published lag forever, so the ps.straggler alert latched a
    permanently-firing phantom that named a worker that left cleanly and
    could never resolve. Retired workers publish 0; an add un-retires
    and the next sweep restores the true lag."""
    from multiverso_tpu.parallel.ps_service import PSService
    reg = get_registry()
    svc = PSService()
    try:
        for _ in range(3):          # worker 0 leads at count 3
            svc._note_worker_add(0)
        svc._note_worker_add(1)     # worker 1 trails by 2
        g1 = reg.gauge("ps_service.staleness.worker_1")
        assert g1.last == 2.0
        # Clean goodbye: gauge zeroes immediately...
        svc._retire_worker_staleness(1)
        assert g1.last == 0.0
        # ...and STAYS zero while the leader keeps advancing (the old
        # sweep republished a monotonically growing lag here).
        for _ in range(5):
            svc._note_worker_add(0)
        assert g1.last == 0.0
        # An add un-retires: real lag (top=8, own count=2) republishes.
        svc._note_worker_add(1)
        assert g1.last == 6.0
    finally:
        svc.close()


def test_engine_ring_holds_largest_rule_window(mv_env):
    """A small tick interval must not silently shrink the slow-burn
    horizon: the engine's ring grows to hold every rule's largest
    window (600 wanted windows over a 240-deep ring would turn the 60s
    spike-veto guard into a 24s one with no warning)."""
    from multiverso_tpu.telemetry.alerts import AlertEngine
    eng = AlertEngine(
        [BurnRateRule("unit.burn", hist="unit.lat", slo_ms=50.0,
                      budget=0.05, fast_windows=50, slow_windows=600,
                      burn_threshold=2.0)],
        interval_s=0.1)
    try:
        assert eng.store.capacity >= 600
    finally:
        eng.stop()
    # the default stays at the documented 240 when no rule needs more
    eng2 = AlertEngine(
        [ThresholdRule("unit.thr", "gauge.unit.g", above=0.0,
                       for_windows=1)], interval_s=1.0)
    try:
        assert eng2.store.capacity == 240
    finally:
        eng2.stop()
