"""Tier-1 gate: the full graftlint pass over ``multiverso_tpu/`` and
``scripts/`` must come back clean.

Any new finding fails this test: either fix the code, add an inline
``# graftlint: disable=<rule>`` with a justifying comment at the site,
or (for deliberate long-lived exceptions) add a reasoned entry to
``graftlint-baseline.json``.  Stale baseline entries also fail — the
baseline only ever shrinks.

This test subsumes the old ``tests/test_bare_print_lint.py`` (the
``bare-print`` rule carries that coverage through the engine now) and
adds a seeded-violation check: a fixture copy of a runtime module with a
bare print and an ``.item()`` inside a jitted step MUST trip the pass —
proving the gate guards the exact regressions it exists for.
"""

import json
import os
import shutil
import textwrap

from multiverso_tpu.analysis import LintEngine, run_lint

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BASELINE = os.path.join(_REPO, "graftlint-baseline.json")

# The ratchet ceiling: the checked-in baseline may hold AT MOST this
# many entries.  Lower it when the baseline shrinks; never raise it —
# new findings get fixed or inline-suppressed with a reviewed comment,
# not grandfathered.  (Stale entries already fail the gate above, so
# the file can only move in one direction: toward and staying at zero.)
_BASELINE_RATCHET = 0


def test_repo_is_lint_clean():
    result = run_lint(
        [os.path.join(_REPO, "multiverso_tpu"),
         os.path.join(_REPO, "scripts")],
        root=_REPO, baseline_path=_BASELINE)
    assert not result.parse_errors, result.parse_errors
    msgs = [f.render() for f in result.findings]
    assert not msgs, (
        "graftlint found new issues (fix, suppress inline with a "
        "comment, or baseline with a reason):\n" + "\n".join(msgs))
    assert not result.stale_baseline, (
        "baseline entries no longer fire — delete them from "
        f"{_BASELINE}: {result.stale_baseline}")
    # the pass actually covered the tree (81 files at the time of
    # writing; a collapse to near-zero means the walker broke)
    assert result.files > 50


def test_baseline_ratchet_only_shrinks():
    """The baseline is a one-way valve.  Growing it means a new finding
    was grandfathered instead of fixed or visibly suppressed — that is
    a review decision, so it must show up as an edit to BOTH the json
    and this ceiling, not as a silent json-only change."""
    with open(_BASELINE, encoding="utf-8") as f:
        payload = json.load(f)
    assert payload["version"] == 1, payload
    entries = payload["entries"]
    assert len(entries) <= _BASELINE_RATCHET, (
        f"baseline grew to {len(entries)} entries (ratchet is "
        f"{_BASELINE_RATCHET}) — fix the finding or suppress it inline "
        "with a justifying comment instead of baselining it")
    for e in entries:
        assert e.get("reason", "").strip(), e
        assert "FIXME" not in e["reason"], (
            "bootstrap placeholder reason left in the baseline", e)


def test_gate_trips_on_seeded_violations(tmp_path):
    """Copy a real runtime module aside, seed the two canonical
    violations, and assert the same engine configuration rejects it."""
    src = os.path.join(_REPO, "multiverso_tpu", "parallel",
                       "async_engine.py")
    victim_dir = tmp_path / "multiverso_tpu" / "parallel"
    victim_dir.mkdir(parents=True)
    victim = victim_dir / "async_engine.py"
    shutil.copy(src, victim)
    with open(victim, "a", encoding="utf-8") as f:
        f.write(textwrap.dedent("""

            def _seeded_debug_step(table_step):
                import jax

                def step(w, g):
                    print("step", w.shape)
                    lr = w.sum().item()
                    return w - lr * g

                return jax.jit(step)
        """))
    result = LintEngine(str(tmp_path)).run([str(tmp_path)])
    rules = {f.rule for f in result.findings
             if f.path.endswith("async_engine.py")}
    assert "bare-print" in rules, result.findings
    assert "implicit-host-sync" in rules, result.findings


def test_gate_honors_new_suppression(tmp_path):
    """The escape hatch works end to end: the same seeded file with
    inline disables passes the gate."""
    victim = tmp_path / "multiverso_tpu" / "mod.py"
    victim.parent.mkdir(parents=True)
    victim.write_text(textwrap.dedent("""
        import jax


        def make(table_step):
            def step(w, g):
                print("dbg")  # graftlint: disable=bare-print
                lr = w.sum().item()  # graftlint: disable=implicit-host-sync
                return w - lr * g

            return jax.jit(step, donate_argnums=(0,))
    """), encoding="utf-8")
    result = LintEngine(str(tmp_path)).run([str(tmp_path)])
    assert not result.findings, [f.render() for f in result.findings]
    assert result.suppressed == 2
