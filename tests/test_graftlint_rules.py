"""Fixture-based unit tests for the graftlint engine.

Each rule has a positive fixture (offending lines marked with
``# expect: <rule-id>``) and a negative fixture (idiomatic counterparts,
zero findings for that rule) under ``tests/fixtures/graftlint/``.  The
tests assert rule id AND line numbers, plus suppression behavior and the
baseline/stale-entry mechanics — so a rule that silently stops firing
breaks here, not in production triage.
"""

import json
import os
import re
import subprocess
import sys
import textwrap

import pytest

from multiverso_tpu.analysis import (Baseline, LintEngine, all_rules,
                                     run_lint)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURES = os.path.join(_REPO, "tests", "fixtures", "graftlint")
_EXPECT_RE = re.compile(r"#\s*expect:\s*([a-z0-9\-]+)")

RULES = ("implicit-host-sync", "block-until-ready-in-loop",
         "retrace-hazard", "missing-donation", "host-jnp-in-loop",
         "lock-order-cycle", "unlocked-registry-mutation",
         "bare-thread-no-join", "bare-print", "unbounded-queue-append",
         "span-in-traced-fn", "daemon-loop-no-watchdog",
         "unbounded-metric-name", "blocking-call-no-timeout",
         "poll-loop-no-backoff", "unattributed-wait",
         "lock-held-across-blocking", "condition-wait-no-predicate-loop")


def _expected_lines(path, rule):
    out = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            m = _EXPECT_RE.search(line)
            if m and m.group(1) == rule:
                out.append(i)
    return out


def _findings(paths, rule=None):
    result = LintEngine(_FIXTURES).run(
        [os.path.join(_FIXTURES, p) for p in paths])
    fs = result.findings
    return [f for f in fs if rule is None or f.rule == rule]


def _fixture_name(rule):
    return rule.replace("-", "_")


def test_registry_has_all_rules():
    ids = {r.id for r in all_rules()}
    assert set(RULES) <= ids
    for r in all_rules():
        assert r.severity in ("warning", "error"), r.id
        assert r.rationale, f"rule {r.id} must document its rationale"


@pytest.mark.parametrize("rule", RULES)
def test_positive_fixture_fires_at_marked_lines(rule):
    name = f"{_fixture_name(rule)}_pos.py"
    path = os.path.join(_FIXTURES, name)
    expected = _expected_lines(path, rule)
    assert expected, f"fixture {name} has no '# expect: {rule}' markers"
    got = sorted(f.line for f in _findings([name], rule))
    assert got == expected, (
        f"{rule}: expected findings at lines {expected}, got {got}")


@pytest.mark.parametrize("rule", [r for r in RULES
                                  if r != "lock-order-cycle"])
def test_negative_fixture_is_clean(rule):
    name = f"{_fixture_name(rule)}_neg.py"
    got = _findings([name], rule)
    assert not got, [f.render() for f in got]


def test_lock_order_cycle_negative_and_rlock():
    got = _findings(["lock_order_cycle_neg.py"], "lock-order-cycle")
    assert not got, [f.render() for f in got]


def test_lock_order_cycle_cross_module():
    """A-then-B in one module against B-then-A in another, linked by
    imported-function call edges, must still form a detected cycle."""
    got = _findings(["lock_cycle_xmod_a.py", "lock_cycle_xmod_b.py"],
                    "lock-order-cycle")
    assert got, "cross-module lock cycle not detected"
    msg = got[0].message
    assert "_SERVICE_LOCK" in msg and "_REG_LOCK" in msg, msg


def test_self_deadlock_through_call_chain():
    name = "self_deadlock_pos.py"
    expected = _expected_lines(os.path.join(_FIXTURES, name),
                               "lock-order-cycle")
    got = _findings([name], "lock-order-cycle")
    assert [f.line for f in got] == expected, \
        [f.render() for f in got]
    assert "self-deadlock" in got[0].message


def test_cross_module_lock_order_positive():
    """A-then-B in one module against B-then-A in another, each half
    locally consistent — only the whole-program graph shows it."""
    got = _findings(["cross_module_lock_order_pos_a.py",
                     "cross_module_lock_order_pos_b.py"],
                    "cross-module-lock-order")
    assert len(got) == 1, [f.render() for f in got]
    msg = got[0].message
    assert "_SERVE_LOCK" in msg and "_REG_LOCK" in msg, msg
    assert "cross_module_lock_order_pos_a" in msg, msg
    assert "cross_module_lock_order_pos_b" in msg, msg
    assert "docs/CONCURRENCY.md" in msg, msg


def test_cross_module_lock_order_negative():
    """Two modules that agree on one order produce no finding."""
    got = _findings(["cross_module_lock_order_neg_a.py",
                     "cross_module_lock_order_neg_b.py"],
                    "cross-module-lock-order")
    assert not got, [f.render() for f in got]


def test_cross_module_rule_leaves_same_module_cycles_alone():
    """Same-module cycles are lock-order-cycle's turf — the cross-module
    rule must not double-report them."""
    got = _findings(["lock_order_cycle_pos.py"], "cross-module-lock-order")
    assert not got, [f.render() for f in got]


def test_historical_pr15_fsync_shape_still_fires():
    """PR-15 regression pin: fdatasync one call below a held staging
    lock. If this stops firing, the rule regressed — not the fixture."""
    name = "hist_pr15_fsync_pos.py"
    expected = _expected_lines(os.path.join(_FIXTURES, name),
                               "lock-held-across-blocking")
    got = _findings([name], "lock-held-across-blocking")
    assert sorted(f.line for f in got) == expected, \
        [f.render() for f in got]
    assert "os.fdatasync" in got[0].message, got[0].message


def test_historical_pr16_json_dump_shape_still_fires():
    """PR-16 regression pin: json.dump (serialize+write) under a held
    membership lock, one call deep."""
    name = "hist_pr16_json_dump_pos.py"
    expected = _expected_lines(os.path.join(_FIXTURES, name),
                               "lock-held-across-blocking")
    got = _findings([name], "lock-held-across-blocking")
    assert sorted(f.line for f in got) == expected, \
        [f.render() for f in got]
    assert "json.dump" in got[0].message, got[0].message


def test_historical_pr14_cross_module_shape_still_fires():
    """PR-14 regression pin: the slots-lock-vs-fleet-view inversion,
    split across two files so each looks locally consistent."""
    got = _findings(["hist_pr14_slots_a.py", "hist_pr14_slots_b.py"],
                    "cross-module-lock-order")
    assert len(got) == 1, [f.render() for f in got]
    msg = got[0].message
    assert "_SLOTS_LOCK" in msg and "_VIEW_LOCK" in msg, msg


def test_suppressions_all_forms():
    """Same-line, line-above, and file-scoped disables each hold; the
    engine still counts what it swallowed."""
    result = LintEngine(_FIXTURES).run(
        [os.path.join(_FIXTURES, "suppression_fixture.py")])
    assert not result.findings, [f.render() for f in result.findings]
    assert result.suppressed >= 3


def test_baseline_absorbs_and_reports_stale(tmp_path):
    """Baselined findings don't fail the run; a stale entry (finding
    gone) is reported so the baseline only shrinks; counts bound how
    many findings one entry may absorb."""
    name = "bare_print_pos.py"
    raw = _findings([name], "bare-print")
    assert len(raw) == 2
    entries = [dict(rule="bare-print", path=name,
                    symbol=raw[0].symbol, count=2,
                    reason="fixture: grandfathered for the unit test")]
    engine = LintEngine(_FIXTURES, baseline=Baseline(entries))
    result = engine.run([os.path.join(_FIXTURES, name)])
    assert not [f for f in result.findings if f.rule == "bare-print"]
    assert result.baselined >= 2
    # same entry against a clean file -> stale
    engine2 = LintEngine(_FIXTURES, baseline=Baseline(
        [dict(entries[0], path="bare_print_neg.py")]))
    result2 = engine2.run([os.path.join(_FIXTURES, "bare_print_neg.py")])
    assert result2.stale_baseline and not result2.clean
    # count=1 absorbs only one of the two findings
    engine3 = LintEngine(_FIXTURES, baseline=Baseline(
        [dict(entries[0], count=1)]))
    result3 = engine3.run([os.path.join(_FIXTURES, name)])
    assert len([f for f in result3.findings
                if f.rule == "bare-print"]) == 1


def test_trailing_disable_does_not_leak_to_next_line(tmp_path):
    """A trailing same-line disable governs only its own line; only a
    comment ALONE on a line also covers the line below."""
    mod = tmp_path / "mod.py"
    mod.write_text(textwrap.dedent("""\
        import jax


        @jax.jit
        def step(x, y):
            a = float(x.sum())  # graftlint: disable=implicit-host-sync
            b = float(y.sum())
            return a + b
    """), encoding="utf-8")
    result = LintEngine(str(tmp_path)).run([str(mod)])
    hits = [f for f in result.findings if f.rule == "implicit-host-sync"]
    assert [f.line for f in hits] == [7], \
        [f.render() for f in result.findings]
    assert result.suppressed == 1


def test_stale_reporting_scoped_to_scanned_paths():
    """A scoped run must not flag baseline entries for files it never
    scanned — but entries for files that no longer exist are stale
    regardless."""
    entry = dict(rule="bare-print", path="bare_print_pos.py",
                 symbol="report", count=2, reason="scoped-run test")
    target = [os.path.join(_FIXTURES, "bare_print_neg.py")]
    result = LintEngine(_FIXTURES, baseline=Baseline([entry])).run(target)
    assert not result.stale_baseline, result.stale_baseline
    gone = dict(entry, path="deleted_long_ago.py")
    result2 = LintEngine(_FIXTURES, baseline=Baseline([gone])).run(target)
    assert result2.stale_baseline and not result2.clean


def test_baseline_rejects_reasonless_entries():
    with pytest.raises(ValueError):
        Baseline([{"rule": "bare-print", "path": "x.py",
                   "symbol": "f", "count": 1}])


def test_baseline_size_gauge_exported():
    from multiverso_tpu.telemetry import get_registry
    entries = [dict(rule="bare-print", path="bare_print_pos.py",
                    symbol="report", count=2, reason="gauge test")]
    LintEngine(_FIXTURES, baseline=Baseline(entries)).run(
        [os.path.join(_FIXTURES, "bare_print_pos.py")])
    gauges = get_registry().snapshot()["gauges"]
    assert gauges["lint.baseline_size"]["last"] == 2.0


def test_cli_json_output_and_exit_codes(tmp_path):
    """CLI contract: exit 1 + parseable JSON on findings, exit 0 on a
    clean tree, exit 2 on bogus paths."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    script = os.path.join(_REPO, "scripts", "graftlint.py")
    proc = subprocess.run(
        [sys.executable, script, "--format", "json", "--no-baseline",
         "--root", _FIXTURES,
         os.path.join(_FIXTURES, "bare_print_pos.py")],
        capture_output=True, text=True, env=env, timeout=240)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["version"] == 1
    rules = {f["rule"] for f in payload["findings"]}
    assert "bare-print" in rules
    for f in payload["findings"]:
        assert {"rule", "path", "line", "col", "message", "symbol",
                "severity"} <= set(f)

    proc = subprocess.run(
        [sys.executable, script, "--format", "json", "--no-baseline",
         "--root", _FIXTURES,
         os.path.join(_FIXTURES, "bare_print_neg.py")],
        capture_output=True, text=True, env=env, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout)["findings"] == []

    proc = subprocess.run(
        [sys.executable, script, os.path.join(_FIXTURES, "nope.py")],
        capture_output=True, text=True, env=env, timeout=240)
    assert proc.returncode == 2


def test_cli_changed_mode_lints_only_the_diff(tmp_path):
    """--changed resolves the git diff (committed, unstaged, untracked)
    against a base, scopes it to the lint roots, and lints exactly that
    set — the pre-commit fast path."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    script = os.path.join(_REPO, "scripts", "graftlint.py")

    def git(*argv):
        subprocess.run(("git", "-C", str(tmp_path)) + argv, check=True,
                       capture_output=True, timeout=60)

    git("init", "-q")
    git("config", "user.email", "t@t")
    git("config", "user.name", "t")
    pkg = tmp_path / "multiverso_tpu"
    pkg.mkdir()
    clean = pkg / "clean.py"
    clean.write_text("print('untouched')\n", encoding="utf-8")
    dirty = pkg / "dirty.py"
    dirty.write_text("X = 1\n", encoding="utf-8")
    (tmp_path / "tests").mkdir()
    git("add", "-A")
    git("commit", "-q", "-m", "seed")

    # clean tree first: nothing changed -> exit 0, no lint run at all
    proc = subprocess.run(
        [sys.executable, script, "--changed", "HEAD", "--no-baseline",
         "--root", str(tmp_path)],
        capture_output=True, text=True, env=env, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "no changed python files" in proc.stdout

    # an unstaged edit, an untracked package file, and an out-of-scope
    # tests/ file — only the first two may be linted ('clean.py' holds
    # a bare-print that would fire if the scoping leaked)
    dirty.write_text("def f():\n    print('dbg')\n", encoding="utf-8")
    (pkg / "fresh.py").write_text("def g():\n    print('new')\n",
                                  encoding="utf-8")
    (tmp_path / "tests" / "t.py").write_text("print('fixture')\n",
                                             encoding="utf-8")
    proc = subprocess.run(
        [sys.executable, script, "--changed", "HEAD", "--no-baseline",
         "--format", "json", "--root", str(tmp_path)],
        capture_output=True, text=True, env=env, timeout=240)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["files"] == 2, payload
    hit = {f["path"] for f in payload["findings"]
           if f["rule"] == "bare-print"}
    assert hit == {os.path.join("multiverso_tpu", "dirty.py"),
                   os.path.join("multiverso_tpu", "fresh.py")}, payload

    # --changed with explicit paths is a usage error
    proc = subprocess.run(
        [sys.executable, script, "--changed", "HEAD", "--root",
         str(tmp_path), str(clean)],
        capture_output=True, text=True, env=env, timeout=240)
    assert proc.returncode == 2, proc.stdout + proc.stderr


def test_list_rules_in_sync_with_docs():
    """Every registered rule has a row in docs/LINTS.md's catalog table
    and vice versa — the CLI's --list-rules and the docs cannot drift."""
    doc = open(os.path.join(_REPO, "docs", "LINTS.md"),
               encoding="utf-8").read()
    documented = set(re.findall(r"^\| `([a-z0-9\-]+)` \|", doc,
                                flags=re.MULTILINE))
    registered = {r.id for r in all_rules()}
    assert registered == documented, (
        f"undocumented rules: {sorted(registered - documented)}; "
        f"doc rows with no rule: {sorted(documented - registered)}")


def test_run_lint_one_call_api():
    result = run_lint([os.path.join(_FIXTURES, "bare_print_pos.py")],
                      root=_FIXTURES)
    assert any(f.rule == "bare-print" for f in result.findings)
