"""Updater math vs. closed-form references (ref updater headers)."""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.core.options import AddOption
from multiverso_tpu.core.updater import (AdaGradUpdater, MomentumUpdater,
                                         SGDUpdater, Updater, get_updater)


def test_factory_mapping(mv_env):
    assert isinstance(get_updater(np.float32, "sgd"), SGDUpdater)
    assert isinstance(get_updater(np.float32, "momentum_sgd"), MomentumUpdater)
    assert isinstance(get_updater(np.float32, "adagrad"), AdaGradUpdater)
    assert type(get_updater(np.float32, "default")) is Updater
    # unknown type falls back to default (ref updater.cpp:55-56 default branch)
    assert type(get_updater(np.float32, "bogus")) is Updater
    # flag-driven selection
    mv.set_flag("updater_type", "adagrad")
    assert isinstance(get_updater(np.float32), AdaGradUpdater)
    # int dtype always plain adder
    assert type(get_updater(np.int32, "adagrad")) is Updater


def test_sgd_updater(mv_env):
    """data -= delta (client pre-scales by lr; ref sgd_updater.h:8-27)."""
    t = mv.create_table(mv.ArrayTableOption(size=4, updater="sgd"))
    t.add(np.array([1, 2, 3, 4], dtype=np.float32))
    np.testing.assert_allclose(t.get(), [-1, -2, -3, -4])


def test_momentum_updater(mv_env):
    """smooth = m*smooth + (1-m)*delta; data -= smooth
    (ref momentum_updater.h:9-31)."""
    m = 0.5
    t = mv.create_table(mv.ArrayTableOption(size=3, updater="momentum_sgd"))
    opt = mv.AddOption(momentum=m)
    delta = np.array([2.0, 4.0, 8.0], dtype=np.float32)

    data = np.zeros(3)
    smooth = np.zeros(3)
    for _ in range(3):
        t.add(delta, opt)
        smooth = m * smooth + (1 - m) * delta
        data = data - smooth
        np.testing.assert_allclose(t.get(), data, rtol=1e-6)


def test_adagrad_updater_per_worker_state(mv_env):
    """G[w] += (d/lr)^2; data -= rho/sqrt(G[w]+eps) * d / lr — clients
    pre-scale deltas by lr, so G accumulates squared *gradients*
    (ref adagrad_updater.h:17-41, lr^2-normalized accumulator)."""
    rho, lr = 0.1, 0.2
    t = mv.create_table(mv.ArrayTableOption(size=2, updater="adagrad"))
    d = np.array([1.0, 2.0], dtype=np.float32)
    eps = AdaGradUpdater.eps

    # worker 0 adds twice, worker... num_workers is 1 in this world, so the
    # per-worker axis has one slot; verify the arithmetic over two steps.
    g = np.zeros(2)
    data = np.zeros(2)
    for _ in range(2):
        t.add(d, mv.AddOption(worker_id=0, rho=rho, learning_rate=lr))
        g = g + (d / lr) ** 2
        data = data - rho / np.sqrt(g + eps) * d / lr
        np.testing.assert_allclose(t.get(), data, rtol=1e-5)


def test_adagrad_row_updates(mv_env):
    rho, lr = 0.1, 0.1
    t = mv.create_table(
        mv.MatrixTableOption(num_row=6, num_col=2, updater="adagrad"))
    rows = [1, 4]
    d = np.ones((2, 2), dtype=np.float32)
    t.add_rows(rows, d, mv.AddOption(rho=rho, learning_rate=lr))
    eps = AdaGradUpdater.eps
    grad = 1.0 / lr
    expected_row = -rho / np.sqrt(grad * grad + eps) * grad
    got = t.get()
    np.testing.assert_allclose(got[rows], np.full((2, 2), expected_row),
                               rtol=1e-5)
    assert np.all(got[[0, 2, 3, 5]] == 0)


def test_stateful_updaters_duplicate_rows(mv_env):
    """Duplicate row ids in ONE add must accumulate their state contribution
    (the reference's sequential loop accumulates; gather/set last-wins would
    drop all but one). Deltas are pre-combined per id, so k duplicates of
    delta d behave exactly like a single add of k*d."""
    for updater in ("momentum_sgd", "adagrad", "ftrl", "dcasgd", "dcasgda"):
        t_dup = mv.create_table(
            mv.MatrixTableOption(num_row=8, num_col=4, updater=updater))
        t_one = mv.create_table(
            mv.MatrixTableOption(num_row=8, num_col=4, updater=updater))
        opt = mv.AddOption(worker_id=0, momentum=0.5, learning_rate=0.1,
                           rho=0.1, lambda_=0.01)
        d = np.ones((5, 4), dtype=np.float32)
        # rows 2 appears x3, row 6 x2 -> equivalent single adds of 3d and 2d
        t_dup.add_rows([2, 2, 2, 6, 6], d, opt)
        t_one.add_rows([2, 6], np.stack([3 * d[0], 2 * d[0]]), opt)
        np.testing.assert_allclose(t_dup.get(), t_one.get(), rtol=1e-5,
                                   err_msg=f"updater={updater}")
        # state carried correctly into a second (unique-id) add
        t_dup.add_rows([2, 6], d[:2], opt)
        t_one.add_rows([2, 6], d[:2], opt)
        np.testing.assert_allclose(t_dup.get(), t_one.get(), rtol=1e-5,
                                   err_msg=f"updater={updater} second add")


def test_dcasgda_factory_and_closed_form():
    """dcasgda (ref updater.cpp:53): lambda is scaled elementwise by
    1/sqrt(m + eps) with m an EMA of g^2. TWO workers interleave so
    (data - backup[w]) is nonzero and the compensation term is actually
    exercised (a single worker's backup always equals data)."""
    from multiverso_tpu.core.updater import DCASGDAUpdater
    mv.init([], num_local_workers=2)
    assert isinstance(get_updater(np.float32, "dcasgda"), DCASGDAUpdater)

    lr, lam = 0.1, 0.5
    t = mv.create_table(mv.ArrayTableOption(size=3, updater="dcasgda"))
    g = np.array([1.0, -2.0, 0.5], dtype=np.float32)

    data = np.zeros(3)
    backup = np.zeros((2, 3))
    m = np.zeros(3)
    for step in range(6):
        w = step % 2
        t.add(g, mv.AddOption(worker_id=w, learning_rate=lr, lambda_=lam))
        m = DCASGDAUpdater.eps_m * m + (1 - DCASGDAUpdater.eps_m) * g * g
        lam_eff = lam / np.sqrt(m + DCASGDAUpdater.eps)
        comp = lam_eff * g * g * (data - backup[w])
        if step >= 2:      # the term the adaptive variant exists to damp
            assert np.abs(comp).max() > 0
        data = data - lr * (g + comp)
        backup[w] = data
        np.testing.assert_allclose(t.get(), data, rtol=1e-5)


def test_dcasgda_converges_and_differs_from_fixed():
    """Convergence vs fixed-lambda dcasgd on a genuinely-stale quadratic:
    two workers alternate add(grad at their last pulled view) -> pull, so
    each add's (data - backup[w]) reflects the other worker's intervening
    step. Both variants must converge near the optimum, and their
    trajectories must actually differ — proof the adaptive scaling is
    live, not a dead code path (the two coincide only if lam_eff == lam
    identically)."""
    mv.init([], num_local_workers=2)
    lr, lam = 0.05, 0.5
    w0 = np.array([4.0, -3.0], dtype=np.float32)

    dists = {}
    for updater in ("dcasgd", "dcasgda"):
        t = mv.create_table(mv.ArrayTableOption(size=2, updater=updater))
        t.add(-w0, mv.AddOption(worker_id=0, learning_rate=1.0))  # w = w0
        views = [np.asarray(t.get(), dtype=np.float32) for _ in range(2)]
        for step in range(80):
            w = step % 2
            t.add(views[w],        # grad of 0.5||x||^2 at w's STALE view
                  mv.AddOption(worker_id=w, learning_rate=lr, lambda_=lam))
            views[w] = np.asarray(t.get(), dtype=np.float32)
        dists[updater] = float(np.linalg.norm(t.get()))
    start = float(np.linalg.norm(w0))
    for name, dist in dists.items():
        assert np.isfinite(dist), dists
        assert dist < 0.1 * start, (name, dists)
    assert abs(dists["dcasgda"] - dists["dcasgd"]) > 1e-7, dists


def test_stateful_updater_empty_add_is_noop(mv_env):
    t = mv.create_table(
        mv.MatrixTableOption(num_row=4, num_col=2, updater="adagrad"))
    t.add_rows([], np.zeros((0, 2), dtype=np.float32),
               mv.AddOption(learning_rate=0.1, rho=0.1))
    np.testing.assert_allclose(t.get(), np.zeros((4, 2)))


def test_plain_updater_duplicate_rows(mv_env):
    """Stateless adders use scatter-add, which accumulates duplicates."""
    t = mv.create_table(mv.MatrixTableOption(num_row=4, num_col=2))
    t.add_rows([1, 1, 3, 1], np.ones((4, 2), dtype=np.float32))
    got = t.get()
    np.testing.assert_allclose(got[1], [3.0, 3.0])
    np.testing.assert_allclose(got[3], [1.0, 1.0])
