"""Unit + in-process integration tests for the fleet layer.

Covers the ISSUE-6 test satellite: HashRing ownership-stability property
tests (adding one replica to N moves <= ~1/(N+1) of keys; removal
reassigns ONLY the removed replica's keys), hedge-cancellation semantics
(losing reply discarded, ``on_done`` fires exactly once), plus the
membership/drain/failover machinery end to end with real sockets —
everything in one process so the suite stays fast.
"""

import threading
import time

import numpy as np
import pytest

from multiverso_tpu.fleet import (AdaptiveDelay, FleetClient, FleetMember,
                                  FleetRouter, HashRing, HedgedCall,
                                  ReplicaGroup, health_score)
from multiverso_tpu.fleet.hedge import HedgeBudget, HedgeScheduler

KEYS = np.arange(20_000, dtype=np.int64)


def _owners(ring, keys=KEYS):
    members = ring.members
    return [members[i] for i in ring.owner_indices(keys)]


# ---------------------------------------------------------------------------
# HashRing properties
# ---------------------------------------------------------------------------
def test_ring_deterministic_across_instances():
    a = HashRing(["r2", "r0", "r1"])
    b = HashRing(["r0", "r1", "r2"])     # order must not matter
    assert _owners(a) == _owners(b)


def test_ring_balance_reasonable():
    ring = HashRing([f"r{i}" for i in range(5)])
    counts = np.bincount(ring.owner_indices(KEYS), minlength=5)
    assert counts.min() > 0.5 * KEYS.size / 5
    assert counts.max() < 1.6 * KEYS.size / 5


@pytest.mark.parametrize("n", [2, 4, 7])
def test_ring_add_moves_about_one_over_n_plus_one(n):
    before = HashRing([f"r{i}" for i in range(n)])
    after = HashRing([f"r{i}" for i in range(n + 1)])
    own_b, own_a = _owners(before), _owners(after)
    moved = sum(1 for x, y in zip(own_b, own_a) if x != y)
    ideal = KEYS.size / (n + 1)
    # Minimal movement: within 1.6x of the consistent-hashing ideal —
    # contiguous-offset routing would move ~half the keyspace.
    assert moved < 1.6 * ideal, (moved, ideal)
    # ...and every moved key moved TO the new member, nowhere else.
    new = f"r{n}"
    assert all(y == new for x, y in zip(own_b, own_a) if x != y)


def test_ring_removal_reassigns_only_the_removed_members_keys():
    members = [f"r{i}" for i in range(5)]
    full = HashRing(members)
    own_full = _owners(full)
    reduced = HashRing(members)
    assert reduced.remove("r2")
    own_red = _owners(reduced)
    for x, y in zip(own_full, own_red):
        if x != "r2":
            assert y == x          # survivor keys never move
        else:
            assert y != "r2"       # orphaned keys all found a new home


def test_ring_partition_covers_all_positions():
    ring = HashRing(["a", "b", "c"])
    parts = ring.partition(KEYS[:999])
    got = np.sort(np.concatenate(list(parts.values())))
    np.testing.assert_array_equal(got, np.arange(999))


def test_ring_membership_api():
    ring = HashRing()
    assert ring.add("x") and not ring.add("x")
    assert "x" in ring and len(ring) == 1
    assert ring.remove("x") and not ring.remove("x")


# ---------------------------------------------------------------------------
# Hedging: exactly-once, discard, failover, budget
# ---------------------------------------------------------------------------
def _async_attempt(delay_s, result):
    def attempt(deliver):
        t = threading.Timer(delay_s, deliver, args=(result,))
        t.daemon = True
        t.start()
    return attempt


def test_hedge_loser_discarded_on_done_fires_exactly_once():
    sched = HedgeScheduler()
    done = []
    call = HedgedCall([_async_attempt(0.2, "slow-primary"),
                       _async_attempt(0.01, "fast-hedge")],
                      done.append, delay_ms=20, scheduler=sched)
    call.launch()
    time.sleep(0.4)                # both replies have landed by now
    assert done == ["fast-hedge"]  # hedge won; loser discarded, one fire
    sched.close()


def test_hedge_primary_wins_when_fast():
    sched = HedgeScheduler()
    done = []
    HedgedCall([_async_attempt(0.01, "primary"),
                _async_attempt(0.01, "hedge")],
               done.append, delay_ms=150, scheduler=sched).launch()
    time.sleep(0.3)
    assert done == ["primary"]
    sched.close()


def test_hedge_sync_raise_fails_over_immediately():
    sched = HedgeScheduler()
    done = []

    def dead(deliver):
        raise OSError("connect refused")

    t0 = time.monotonic()
    HedgedCall([dead, _async_attempt(0.01, "backup")], done.append,
               delay_ms=10_000, scheduler=sched).launch()
    time.sleep(0.3)
    assert done == ["backup"]
    assert time.monotonic() - t0 < 5  # did not wait for the hedge timer
    sched.close()


def test_hedge_all_attempts_fail_delivers_last_error():
    sched = HedgeScheduler()
    done = []
    err = OSError("boom")
    HedgedCall([_async_attempt(0.01, OSError("first")),
                _async_attempt(0.01, err)],
               done.append, delay_ms=5, scheduler=sched).launch()
    time.sleep(0.4)
    assert len(done) == 1 and isinstance(done[0], OSError)
    sched.close()


def test_hedge_budget_suppresses_when_dry():
    budget = HedgeBudget(ratio=0.0, burst=0.0)    # never allows a hedge
    sched = HedgeScheduler()
    done = []
    HedgedCall([_async_attempt(0.15, "slow-primary"),
                _async_attempt(0.01, "hedge")],
               done.append, delay_ms=10, scheduler=sched,
               allow_hedge=budget.try_spend).launch()
    time.sleep(0.4)
    assert done == ["slow-primary"]   # hedge never fired: primary answered
    sched.close()


def test_hedge_budget_token_arithmetic():
    budget = HedgeBudget(ratio=0.5, burst=1.0)
    assert budget.try_spend()          # starts with the burst
    assert not budget.try_spend()      # dry
    budget.on_request()
    assert not budget.try_spend()      # 0.5 tokens: still dry
    budget.on_request()
    assert budget.try_spend()          # 1.0 tokens: one hedge


def test_adaptive_delay_tracks_p95():
    d = AdaptiveDelay(floor_ms=1.0, ceil_ms=500.0, initial_ms=25.0,
                      min_samples=10)
    assert d.delay_ms() == 25.0        # no data yet
    for _ in range(64):
        d.observe(10.0)
    assert 10.0 <= d.delay_ms() <= 20.0   # ~1.25 * p95


# ---------------------------------------------------------------------------
# Health + membership state machine (no sockets)
# ---------------------------------------------------------------------------
def test_health_score_shape():
    idle = health_score({"queue_depth": 0, "inflight": 0,
                         "max_queue": 64, "max_batch": 8,
                         "replica_step": 5}, fleet_max_step=5)
    busy = health_score({"queue_depth": 64, "inflight": 8,
                         "max_queue": 64, "max_batch": 8,
                         "replica_step": 5}, fleet_max_step=5)
    stale = health_score({"queue_depth": 0, "inflight": 0,
                          "max_queue": 64, "max_batch": 8,
                          "replica_step": 1}, fleet_max_step=5)
    draining = health_score({"draining": 1.0}, fleet_max_step=5)
    assert idle == 1.0
    assert 0.0 < busy < idle
    assert 0.0 < stale < idle
    assert draining == 0.0


def test_health_score_sees_pipeline_occupancy():
    """A pipelined replica with an EMPTY admission queue but a full
    dispatch window must not look idle to the router — window occupancy
    is load one stage past the queue (ISSUE 9 satellite)."""
    base = {"queue_depth": 0, "inflight": 0, "max_queue": 64,
            "max_batch": 8, "replica_step": 5}
    idle = health_score({**base, "pipeline_inflight": 0,
                         "pipeline_depth": 3}, fleet_max_step=5)
    full_window = health_score({**base, "pipeline_inflight": 3,
                                "pipeline_depth": 3}, fleet_max_step=5)
    half_window = health_score({**base, "pipeline_inflight": 1.5,
                                "pipeline_depth": 3}, fleet_max_step=5)
    assert idle == 1.0
    assert 0.0 < full_window < half_window < idle
    # a full window weighs like a full admission queue (both normalize
    # to load 1.0)
    full_queue = health_score({**base, "queue_depth": 64},
                              fleet_max_step=5)
    assert abs(full_window - full_queue) < 1e-9
    # NO double counting: in pipelined mode serve.inflight counts the
    # SAME window requests, so a realistic saturated pipelined member
    # (inflight = depth * max_batch AND occupancy = depth) must score
    # exactly like a saturated serialized one (inflight = max_batch) —
    # otherwise the router drifts away from the faster path.
    pipelined_sat = health_score({**base, "inflight": 24,
                                  "pipeline_inflight": 3,
                                  "pipeline_depth": 3}, fleet_max_step=5)
    serial_sat = health_score({**base, "inflight": 8}, fleet_max_step=5)
    assert abs(pipelined_sat - serial_sat) < 1e-9
    # pre-pipeline members (no depth field) are unaffected
    legacy = health_score(base, fleet_max_step=5)
    assert legacy == 1.0


def test_replica_group_join_heartbeat_sweep():
    group = ReplicaGroup(heartbeat_ms=20.0, liveness_misses=3)
    reply = group.join("a", "127.0.0.1", 1111)
    assert reply["ok"] and reply["heartbeat_ms"] == 20.0
    group.join("b", "127.0.0.1", 2222)
    v0 = group.version
    assert group.member_ids() == ["a", "b"]
    assert sorted(group.ring.members) == ["a", "b"]
    # heartbeat for an unknown member asks it to rejoin
    assert group.heartbeat("ghost", {})["directive"] == "rejoin"
    # a drain directive is delivered exactly once
    group.drain("a")
    assert group.heartbeat("a", {})["directive"] == "drain"
    assert group.heartbeat("a", {})["directive"] == "none"
    # draining=1 removes from the ring, rejoin restores it
    group.heartbeat("a", {"draining": 1.0})
    assert group.ring.members == ("b",)
    group.heartbeat("a", {"draining": 0.0, "drains_completed": 1.0})
    assert sorted(group.ring.members) == ["a", "b"]
    assert group.drains_completed("a") == 1
    assert group.version > v0
    # b stops heartbeating -> swept after the liveness horizon
    deadline = time.monotonic() + 5
    dead = []
    while time.monotonic() < deadline and not dead:
        group.heartbeat("a", {})
        dead = group.sweep()
        time.sleep(0.02)
    assert dead == ["b"]
    assert group.member_ids() == ["a"]


def test_routing_payload_health_ranking():
    group = ReplicaGroup(heartbeat_ms=50.0)
    group.join("busy", "h", 1)
    group.join("idle", "h", 2)
    group.heartbeat("busy", {"queue_depth": 64, "inflight": 8,
                             "max_queue": 64, "max_batch": 8})
    group.heartbeat("idle", {"queue_depth": 0, "inflight": 0,
                             "max_queue": 64, "max_batch": 8})
    payload = group.routing_payload()
    by_id = {m["id"]: m for m in payload["members"]}
    assert by_id["idle"]["health"] > by_id["busy"]["health"]
    from multiverso_tpu.fleet import RoutingTable
    table = RoutingTable(payload)
    assert table.ranked()[0] == "idle"
    assert table.ranked(exclude=("idle",)) == ["busy"]


def test_json_blob_codec_roundtrip():
    from multiverso_tpu.parallel.net import pack_json_blob, unpack_json_blob
    obj = {"id": "r0", "stats": {"queue_depth": 3.0}, "list": [1, 2]}
    assert unpack_json_blob(pack_json_blob(obj)) == obj
    with pytest.raises(IOError):
        unpack_json_blob(np.frombuffer(b"not json", dtype=np.uint8))


# ---------------------------------------------------------------------------
# In-process fleet integration over real sockets
# ---------------------------------------------------------------------------
ROWS, COLS = 512, 8


@pytest.fixture
def fleet_env(mv_env):
    """Router + two serving replicas (same seeded table) + members."""
    import jax
    from jax.sharding import Mesh

    from multiverso_tpu.core.table import ServerStore
    from multiverso_tpu.core.updater import get_updater
    from multiverso_tpu.serving import ServingService, SparseLookupRunner

    rng = np.random.default_rng(0)
    data = rng.normal(size=(ROWS, COLS)).astype(np.float32)
    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1), ("server",))
    services, members = [], []
    router = FleetRouter(heartbeat_ms=40.0, liveness_misses=5, proxy=True)
    for i in range(2):
        store = ServerStore(f"fleet_t{i}", (ROWS, COLS), np.float32,
                            get_updater(np.float32, "default"), mesh,
                            num_workers=1, init_array=data.copy())
        svc = ServingService()
        svc.register_runner(SparseLookupRunner(store), buckets=(4, 8),
                            max_batch=4, max_wait_ms=1.0)
        svc.warmup()
        services.append(svc)
        members.append(FleetMember(router.address, svc,
                                   member_id=f"r{i}").start())
    deadline = time.monotonic() + 20
    while len(router.group.member_ids()) < 2:
        assert time.monotonic() < deadline, "members never joined"
        time.sleep(0.02)
    yield router, services, members, data
    for m in members:
        m.close()
    for s in services:
        s.close()
    router.close()


def test_fleet_client_lookup_parity(fleet_env):
    router, services, members, data = fleet_env
    cli = FleetClient(router.address)
    try:
        rows = np.asarray([3, 481, 77, 0, 511], np.int32)
        got = cli.lookup(rows, deadline_ms=10_000, timeout=30)
        np.testing.assert_array_equal(got, data[rows])
        got = cli.lookup(rows, deadline_ms=10_000, split=True, timeout=30)
        np.testing.assert_array_equal(got, data[rows])
        # empty lookup keeps the real column shape
        got = cli.lookup(np.zeros(0, np.int32), deadline_ms=10_000,
                         timeout=30)
        assert got.shape == (0, COLS)
    finally:
        cli.close()


def test_fleet_hot_key_replicated_reads_bitwise_fresh(fleet_env):
    """E2E leg-1 witness (docs/DESIGN.md "Skew actuation"): replicate a
    hot row set, round-robin the reads across its replicas, and every
    reply is bitwise the table row — replication changes WHO serves a
    hot key, never WHAT is served."""
    router, services, members, data = fleet_env
    rows = np.asarray([3, 77], np.int32)
    ring = router.group.ring
    router.group.set_hot_keys(
        {int(r): ring.replica_set(int(r), 2) for r in rows})
    cli = FleetClient(router.address, hedge="off", refresh_s=0.05,
                      hot_staleness=1.0)
    try:
        deadline = time.monotonic() + 10.0
        while not cli.routing().hot_replicas:
            assert time.monotonic() < deadline, "hot keys never shipped"
            time.sleep(0.05)
        assert set(cli.routing().hot_replicas) == {int(r) for r in rows}
        from multiverso_tpu.telemetry import counter
        routed = counter("fleet.hotkey.routed")
        base = routed.value
        for _ in range(6):
            got = cli.lookup(rows, deadline_ms=10_000, timeout=30)
            np.testing.assert_array_equal(got, data[rows])
        assert routed.value - base == 6
    finally:
        cli.close()


def test_fleet_router_proxy_serves_plain_clients(fleet_env):
    router, services, members, data = fleet_env
    from multiverso_tpu.serving import ServingClient
    pc = ServingClient(*router.address)
    try:
        rows = np.asarray([1, 500, 42], np.int32)
        got = pc.lookup(rows, deadline_ms=10_000, timeout=30)
        np.testing.assert_array_equal(got, data[rows])
    finally:
        pc.close()


def test_fleet_rolling_drain_zero_drops_under_load(fleet_env):
    router, services, members, data = fleet_env
    cli = FleetClient(router.address, refresh_s=0.05)
    errors = []
    stop = threading.Event()

    def loader():
        rng = np.random.default_rng(3)
        while not stop.is_set():
            rows = rng.integers(0, ROWS, 4).astype(np.int32)
            try:
                got = cli.lookup(rows, deadline_ms=10_000, timeout=30)
                np.testing.assert_array_equal(got, data[rows])
            except Exception as e:  # noqa: BLE001 - the assertion below
                errors.append(e)    # reports every failure mode at once
    t = threading.Thread(target=loader, daemon=True)
    t.start()
    try:
        assert router.rolling_drain(timeout_s_per_member=30)
        time.sleep(0.2)
    finally:
        stop.set()
        t.join(timeout=10)
        cli.close()
    assert not errors, errors[:3]
    # both members completed a full drain cycle
    for mid in ("r0", "r1"):
        assert router.group.drains_completed(mid) == 1


def test_fleet_wire_drain_trigger(fleet_env):
    """Operator path: Fleet_Drain over the wire starts a rolling drain;
    completion is observable via the routing table's per-member
    monotonic drains_completed."""
    router, services, members, data = fleet_env
    from multiverso_tpu.fleet import request_drain
    ack = request_drain(router.address)
    assert ack["started"] and ack["rolling"]
    assert sorted(ack["members"]) == ["r0", "r1"]
    deadline = time.monotonic() + 30
    cli = FleetClient(router.address, refresh_s=0.05)
    try:
        while time.monotonic() < deadline:
            table = {m["id"]: m for m in cli.refresh().members}
            if all(m.get("drains_completed", 0) >= 1
                   and not m.get("draining") for m in table.values()):
                break
            time.sleep(0.05)
        else:
            raise AssertionError("wire-triggered rolling drain never "
                                 "completed")
        # unknown member is refused, not crashed
        assert not request_drain(router.address,
                                 member_id="ghost")["started"]
    finally:
        cli.close()


def test_fleet_drain_runs_swap_fn(fleet_env):
    router, services, members, data = fleet_env
    swapped = threading.Event()
    members[0].swap_fn = swapped.set
    assert router.drain("r0", timeout_s=30)
    assert swapped.is_set()


def test_fleet_failover_masks_killed_replica(fleet_env):
    router, services, members, data = fleet_env
    cli = FleetClient(router.address, refresh_s=0.05)
    try:
        rows = np.asarray([9, 10, 11], np.int32)
        np.testing.assert_array_equal(
            cli.lookup(rows, deadline_ms=10_000, timeout=30), data[rows])
        # hard-kill r1's serving socket + member agent (SIGKILL analog)
        members[1].close()
        services[1].close()
        # every subsequent lookup still answers (failover masks the loss)
        for _ in range(6):
            np.testing.assert_array_equal(
                cli.lookup(rows, deadline_ms=10_000, timeout=30),
                data[rows])
        # the sweep reaps the dead member within the liveness horizon
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                len(router.group.member_ids()) > 1:
            time.sleep(0.05)
        assert router.group.member_ids() == ["r0"]
    finally:
        cli.close()


def test_replica_unavailable_error_is_typed(mv_env):
    from multiverso_tpu.serving import (ReplicaUnavailableError,
                                        ServingClient, connect_with_backoff)
    t0 = time.monotonic()
    with pytest.raises(ReplicaUnavailableError):
        connect_with_backoff("127.0.0.1", 1, attempts=2,
                             base_delay_s=0.01)
    assert time.monotonic() - t0 < 5
    # ...and it IS an OSError, so pre-fleet call sites keep working
    assert issubclass(ReplicaUnavailableError, OSError)
    with pytest.raises(OSError):
        ServingClient("127.0.0.1", 1, connect_attempts=2)


# ---------------------------------------------------------------------------
# ISSUE 7: server-side cancel for hedged losers + fleet stats rollup
# ---------------------------------------------------------------------------
def test_hedged_call_on_settled_reports_winner_and_launched():
    sched = HedgeScheduler()
    settled = []
    try:
        deliver_1 = []

        def a0(deliver):
            deliver_1.append(deliver)       # stays outstanding

        def a1(deliver):
            deliver("second wins")

        HedgedCall([a0, a1], lambda r: None, delay_ms=1.0,
                   scheduler=sched,
                   on_settled=lambda w, n: settled.append((w, n))).launch()
        deadline = time.monotonic() + 5
        while not settled and time.monotonic() < deadline:
            time.sleep(0.01)
        assert settled == [(1, 2)]
        deliver_1[0]("late loser")          # discarded, settled unchanged
        assert settled == [(1, 2)]
    finally:
        sched.close()


def test_hedged_call_on_settled_all_failed():
    sched = HedgeScheduler()
    settled = []
    try:
        def fail(deliver):
            raise OSError("down")

        HedgedCall([fail, fail], lambda r: None, delay_ms=1.0,
                   scheduler=sched,
                   on_settled=lambda w, n: settled.append((w, n))).launch()
        deadline = time.monotonic() + 5
        while not settled and time.monotonic() < deadline:
            time.sleep(0.01)
        assert settled == [(-1, 2)]
    finally:
        sched.close()


def test_batcher_cancel_drops_queued_request(mv_env):
    """A queued hedged loser is dropped at admission: on_done gets
    ShedError('cancelled'), the device never sees it, and
    serve.cancelled counts it.

    DEFLAKED (PR 13): the original runner held the worker with a fixed
    0.05s sleep, racing the main thread's cancel() against the worker
    finishing the head batch and popping cancel_me — on a loaded 1-core
    box a descheduled main thread lost that race and cancel() returned
    False. The runner now blocks on an Event the test only sets AFTER
    the cancel landed, so "cancel_me is still queued when cancelled"
    is guaranteed by construction, not by timing."""
    from multiverso_tpu.serving.batcher import DynamicBatcher, ShedError
    from multiverso_tpu.telemetry import get_registry

    class GatedRunner:
        payload_dtype = np.int32
        pad_id = 0

        def __init__(self):
            self.ran = []
            self.started = threading.Event()
            self.release = threading.Event()

        def run(self, mat, lengths):
            self.ran.append(mat.copy())
            self.started.set()
            assert self.release.wait(10), "test never released the runner"
            return mat

        def slice_result(self, out, i, n):
            return out[i, :n]

    runner = GatedRunner()
    b = DynamicBatcher(runner, buckets=(4,), max_batch=1,
                       max_wait_ms=0.0, max_queue=8)
    try:
        results = {}
        done = threading.Event()

        def on_done(key):
            def cb(result):
                results[key] = result
                if key == "cancel_me":
                    done.set()
            return cb

        # Head request occupies the worker (held on the gate)...
        b.submit_callback(np.asarray([1], np.int32), 10_000,
                          on_done("head"))
        assert runner.started.wait(5), "head batch never reached the runner"
        # ...so the second provably sits queued until we release.
        token = b.submit_callback(np.asarray([2], np.int32), 10_000,
                                  on_done("cancel_me"))
        assert token is not None
        before = get_registry().counter("serve.cancelled").value
        assert b.cancel(token) is True
        runner.release.set()
        assert done.wait(5)
        assert isinstance(results["cancel_me"], ShedError)
        assert results["cancel_me"].reason == "cancelled"
        assert get_registry().counter("serve.cancelled").value == before + 1
        # cancelling an already-delivered request is a harmless no-op
        assert b.cancel(token) is False
    finally:
        b.close()
    # the cancelled payload never reached the runner (close() drained
    # the worker, so this read is not racing it)
    assert not any((mat == 2).any() for mat in runner.ran)


def test_serve_cancel_over_the_wire(fleet_env):
    """Serve_Cancel for a queued request answers the ORIGINAL msg_id
    with Reply_Error('cancelled') — the waiter completes, nothing leaks,
    and an unknown msg_id is a counted no-op.

    DEFLAKED (PR 13 — the tier-1 'flaky fleet-cancel failure'): cancel
    is fire-and-forget, so the victim cancel's miss increment (when the
    victim raced past the queue) is ASYNCHRONOUS with respect to reply
    delivery — replies come from the batcher thread, the miss from the
    conn-reader thread. The old test read the miss baseline AFTER
    victim.wait() and asserted exactly +1 for the unknown-id cancel; if
    the reader thread was descheduled, the victim's own miss landed
    after the baseline read and the counter moved +2. Fixed by reading
    baselines BEFORE any cancel is sent and bounding the total by the
    victim's observed outcome. The bound (not an exact count) is forced
    by a third server-side path: a cancel that races the queue POP
    returns False (counted a miss) but still marks the request, which
    batch FORMATION then drops with ShedError('cancelled') — so a
    client-observed 'cancelled' may carry 0 or 1 victim misses, while
    'completed' always carries exactly 1."""
    from multiverso_tpu.serving import ServingClient, ShedError
    from multiverso_tpu.telemetry import get_registry

    router, services, members, data = fleet_env
    svc = services[0]
    reg = get_registry()
    cli = ServingClient(*svc.address)
    try:
        req0 = reg.counter("serve.cancel.requests").value
        miss0 = reg.counter("serve.cancel.miss").value
        # Saturate the batcher briefly so a second request queues.
        slow = [cli.request_async(np.arange(8, dtype=np.int32), 10_000)
                for _ in range(8)]
        victim = cli.request_async(np.arange(4, dtype=np.int32), 10_000)
        cli.cancel(victim.msg_id)
        try:
            victim.wait(timeout=10)
            outcome = "completed"       # raced past the queue: fine
        except ShedError as e:
            # Wire sheds surface as reason "server" with the server's
            # reason text in the message.
            outcome = "cancelled" if "cancelled" in str(e) else str(e)
        assert outcome in ("cancelled", "completed")
        for r in slow:
            r.wait(timeout=10)
        cli.cancel(999_999_999)         # unknown id: counted, harmless
        # Wait until BOTH cancels were processed (requests counts every
        # cancel frame deterministically), then bound the misses this
        # test can produce: the unknown id ALWAYS misses; a completed
        # victim always adds one more; a cancelled victim adds 0 (still
        # queued) or 1 (the formation-drop race above) — never more.
        min_miss = miss0 + (2 if outcome == "completed" else 1)
        max_miss = miss0 + 2
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and (
                reg.counter("serve.cancel.requests").value < req0 + 2
                or reg.counter("serve.cancel.miss").value < min_miss):
            time.sleep(0.01)
        assert reg.counter("serve.cancel.requests").value == req0 + 2
        assert min_miss <= reg.counter("serve.cancel.miss").value \
            <= max_miss
    finally:
        cli.close()


def test_fleet_stats_rollup_sums_match_per_replica(fleet_env):
    from multiverso_tpu.fleet import fetch_fleet_stats

    router, services, members, data = fleet_env
    cli = FleetClient(router.address, hedge="off", refresh_s=0.05)
    try:
        for _ in range(12):
            cli.lookup(np.arange(6, dtype=np.int32), deadline_ms=10_000,
                       timeout=30)
        # Wait until the heartbeat metrics caught up with the traffic.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            stats = fetch_fleet_stats(router.address)
            if stats["fleet"]["replies"] >= 12:
                break
            time.sleep(0.05)
        assert stats["schema"] == "multiverso_tpu.fleet_stats/v1"
        assert stats["version"] > 0
        per = stats["replicas"]
        assert set(per) == {"r0", "r1"}
        fleet = stats["fleet"]
        for key in ("requests", "replies", "shed", "cancelled",
                    "slo_violations", "watchdog_trips"):
            assert fleet[key] == sum(r[key] for r in per.values()), key
        assert "router_watchdog_trips" in stats
        assert fleet["replicas"] == 2
        # stage percentiles rode along (count-weighted merge is defined
        # whenever any replica served anything)
        assert fleet["stages"]["total"]["count"] >= 12
        # versioned: another metrics-bearing heartbeat bumps it
        v0 = stats["version"]
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if fetch_fleet_stats(router.address)["version"] > v0:
                break
            time.sleep(0.05)
        else:
            raise AssertionError("stats version never advanced")
    finally:
        cli.close()


def test_fleet_top_render_is_stable():
    from multiverso_tpu.apps.fleet_top import render_stats
    stats = {
        "version": 7, "time_unix": 0.0,
        "router_alerts": [{"name": "fleet.heartbeat_loss",
                           "severity": "page", "value": 1.0,
                           "for_s": 2.0}],
        "fleet": {"replicas": 2, "qps": 123.4, "shed_rate": 0.015,
                  "queue_depth": 3.0, "inflight": 2.0,
                  "slo_violations": 9, "alerts_active": 2,
                  "hotkey_replicated": 3,
                  "rebalance": {"overrides": 4, "migrations": 1},
                  "stages": {"total": {"p50": 1.0, "p95": 2.0,
                                       "p99": 3.0, "count": 10}}},
        "replicas": {
            "r0": {"health": 0.9, "qps": 61.7, "shed_rate": 0.01,
                   "queue_depth": 1.0, "inflight": 1.0,
                   "slo_violations": 4, "drains_completed": 1,
                   "draining": False,
                   "hot_replicated": 3, "migrations": 1,
                   "alerts": [{"name": "serve.slo_burn",
                               "severity": "page", "value": 3.2,
                               "for_s": 1.5}],
                   "stages": {"total": {"p50": 1.0, "p95": 2.0,
                                        "p99": 3.0, "count": 5}}},
            "r1": {"health": 0.0, "qps": 61.7, "shed_rate": 0.02,
                   "queue_depth": 2.0, "inflight": 1.0,
                   "slo_violations": 5, "drains_completed": 0,
                   "draining": True, "stages": {}},
        },
    }
    out = render_stats(stats)
    lines = out.splitlines()
    assert lines[0].startswith("fleet_top  v7")
    assert "qps=123.4" in lines[0]
    assert "alerts=2" in lines[0]
    assert "ALERTS" in lines[1] and "REBAL" in lines[1]
    r0 = [l for l in lines if l.startswith("r0")][0]
    assert "up" in r0 and "1:serve.slo_b" in r0
    # REBAL cell: replicated-key count + migrations in flight
    assert "3/m1" in r0
    r1 = [l for l in lines if l.startswith("r1")][0]
    # no alerts key at all renders as the quiet cell, never a KeyError
    assert "drain" in r1 and r1.rstrip().endswith("-")
    assert lines[-1].startswith("FLEET")
    assert "3/m1" in lines[-1]
    # router-scoped alerts (heartbeat loss) render on the FLEET row
    assert "1:fleet.heart" in lines[-1]
    # a missing stages dict renders as zeros, never a KeyError
    assert "0.00" in r1


def test_member_rates_survive_sparse_heartbeats():
    """A heartbeat interval LONGER than the rate window must degrade to
    rate-over-one-beat, not to permanent zeros (review finding)."""
    from multiverso_tpu.fleet.membership import MemberInfo
    info = MemberInfo("r0", "h", 1)
    t = 1000.0
    for beat in range(4):
        info.observe_metrics({"requests": 100.0 * beat,
                              "replies": 100.0 * beat,
                              "shed": 0.0}, t + 10.0 * beat)
    assert len(info.history) >= 2
    rates = info.rates()
    assert rates["qps"] > 0.0
    assert abs(rates["qps"] - 10.0) < 1e-6      # 100 replies / 10 s
