"""net_util machine-file rank identification (ref src/util/net_util.cpp,
zmq_net.h machine-file mode)."""

import pytest

from multiverso_tpu.utils.net_util import (get_local_ips, parse_machine_file,
                                           rank_from_machine_file)


def test_local_ips_include_loopback():
    ips = get_local_ips()
    assert "127.0.0.1" in ips


def test_parse_machine_file(tmp_path):
    p = tmp_path / "machines"
    p.write_text("# cluster\n10.0.0.1:6000\n10.0.0.2\n\n10.0.0.3:7000\n")
    peers = parse_machine_file(str(p))
    assert peers[0] == ("10.0.0.1", 6000)
    assert peers[1] == ("10.0.0.2", 55555)   # -port flag default
    assert peers[2] == ("10.0.0.3", 7000)


def test_rank_from_machine_file(tmp_path):
    p = tmp_path / "machines"
    p.write_text("10.9.9.9\n127.0.0.1:6001\n10.8.8.8\n")
    rank, world, peers = rank_from_machine_file(str(p))
    assert rank == 1 and world == 3
    assert peers[1] == ("127.0.0.1", 6001)


def test_rank_not_found_raises(tmp_path):
    p = tmp_path / "machines"
    p.write_text("10.1.1.1\n10.2.2.2\n")
    with pytest.raises(LookupError):
        rank_from_machine_file(str(p), local_ips=["192.168.0.5"])
