#!/usr/bin/env python
"""End-to-end word2vec demo: generate a corpus, train, inspect neighbors.

Run:  python examples/word2vec_demo.py
(Choose the backend with jax's platform config; everything else is
self-contained — the demo writes its corpus to a temp dir.)
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))



def make_corpus(path: str, n_sentences: int = 2000) -> None:
    """Three word 'topics' with distinct co-occurrence patterns."""
    rng = np.random.default_rng(0)
    topics = {
        "fruit": ["apple", "pear", "banana", "grape", "melon", "juice"],
        "metal": ["iron", "steel", "copper", "forge", "alloy", "rust"],
        "ocean": ["wave", "tide", "coral", "reef", "fish", "salt"],
    }
    with open(path, "w") as f:
        names = list(topics)
        for i in range(n_sentences):
            words = rng.choice(topics[names[i % 3]], size=18)
            f.write(" ".join(words) + "\n")


def main() -> int:
    from examples._backend import pin_backend
    pin_backend()
    import multiverso_tpu as mv
    from multiverso_tpu.models.word2vec import (Dictionary, Word2Vec,
                                                Word2VecConfig, read_corpus)

    workdir = tempfile.mkdtemp(prefix="w2v_demo_")
    corpus = os.path.join(workdir, "corpus.txt")
    make_corpus(corpus)

    mv.init([])
    try:
        dictionary = Dictionary.build(read_corpus(corpus), min_count=1)
        print(f"vocabulary: {len(dictionary)} words, "
              f"{dictionary.total_count} tokens")
        cfg = Word2VecConfig(embedding_size=64, window=4, negative=5,
                             min_count=1, sample=0, epochs=3,
                             batch_size=1024, learning_rate=0.05)
        w2v = Word2Vec(cfg, dictionary)
        stats = w2v.train(corpus_path=corpus)
        print(f"trained {stats['words']} words "
              f"at {stats['words_per_sec']:.0f} words/sec")
        for word in ("apple", "iron", "wave"):
            neighbors = ", ".join(
                f"{w} ({s:.2f})" for w, s in w2v.most_similar(word, 3))
            print(f"  {word:8s} -> {neighbors}")
        out = os.path.join(workdir, "vectors.txt")
        w2v.save(out)
        print(f"embeddings written to {out}")
        return 0
    finally:
        mv.shutdown()


if __name__ == "__main__":
    sys.exit(main())
