/* Foreign-host FFI demo: a plain C program attaches to Python-served PS
 * shards through libmvtpu_host.so's extern "C" table surface (the
 * reference's c_api.h parity boundary) — creates handles for an array, a
 * matrix, and a KV table, Adds known patterns, Gets them back, and
 * asserts the values it reads include what the PYTHON side wrote.
 *
 * Usage: c_table_demo "host:port;host:port" <array_id> <matrix_id> <kv_id>
 * Exit 0 + "C_DEMO_OK" on success. Driven by tests/test_c_api_ffi.py.
 */
#include <dlfcn.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#define ASIZE 10
#define MROWS 8
#define MCOLS 3

#define CHECK(cond, msg)                        \
  do {                                          \
    if (!(cond)) {                              \
      fprintf(stderr, "FAIL: %s\n", msg);       \
      return 1;                                 \
    }                                           \
  } while (0)

typedef int (*connect_fn)(const char *, void **);
typedef void (*close_fn)(void *);
typedef int (*new_array_fn)(void *, int, long long, void **);
typedef int (*array_io_fn)(void *, float *, long long);
typedef int (*array_add_fn)(void *, const float *, long long);
typedef int (*new_matrix_fn)(void *, int, long long, long long, void **);
typedef int (*matrix_add_fn)(void *, const float *, const int *, long long);
typedef int (*matrix_get_fn)(void *, float *, const int *, long long);
typedef int (*new_kv_fn)(void *, int, void **);
typedef int (*kv_add_fn)(void *, const long long *, const long long *,
                         long long);
typedef int (*kv_get_fn)(void *, const long long *, long long *, long long);

int main(int argc, char **argv) {
  CHECK(argc == 6, "usage: demo <libpath> <peers> <aid> <mid> <kid>");
  void *lib = dlopen(argv[1], RTLD_NOW);
  CHECK(lib != NULL, dlerror());
  connect_fn mv_connect = (connect_fn)dlsym(lib, "MV_ConnectClient");
  close_fn mv_close = (close_fn)dlsym(lib, "MV_CloseClient");
  new_array_fn new_array = (new_array_fn)dlsym(lib, "MV_NewArrayTable");
  array_add_fn array_add = (array_add_fn)dlsym(lib, "MV_AddArrayTable");
  array_io_fn array_get = (array_io_fn)dlsym(lib, "MV_GetArrayTable");
  new_matrix_fn new_matrix = (new_matrix_fn)dlsym(lib, "MV_NewMatrixTable");
  matrix_add_fn matrix_add =
      (matrix_add_fn)dlsym(lib, "MV_AddMatrixTableByRows");
  matrix_get_fn matrix_get =
      (matrix_get_fn)dlsym(lib, "MV_GetMatrixTableByRows");
  new_kv_fn new_kv = (new_kv_fn)dlsym(lib, "MV_NewKVTable");
  kv_add_fn kv_add = (kv_add_fn)dlsym(lib, "MV_AddKVTable");
  kv_get_fn kv_get = (kv_get_fn)dlsym(lib, "MV_GetKVTable");
  CHECK(mv_connect && mv_close && new_array && array_add && array_get &&
            new_matrix && matrix_add && matrix_get && new_kv && kv_add &&
            kv_get,
        "missing MV_* symbol");

  void *client = NULL;
  CHECK(mv_connect(argv[2], &client) == 0, "connect failed");
  int aid = atoi(argv[3]), mid = atoi(argv[4]), kid = atoi(argv[5]);

  /* array: Python pre-seeded each slot with 100+i; we add i and expect
   * 100+2i — proving the C host both READS Python writes and WRITES
   * values Python will read. */
  void *at = NULL;
  CHECK(new_array(client, aid, ASIZE, &at) == 0, "new array");
  float delta[ASIZE], got[ASIZE];
  for (int i = 0; i < ASIZE; ++i) delta[i] = (float)i;
  CHECK(array_add(at, delta, ASIZE) == 0, "array add");
  CHECK(array_get(at, got, ASIZE) == 0, "array get");
  for (int i = 0; i < ASIZE; ++i)
    CHECK(got[i] == 100.0f + 2.0f * i, "array value mismatch");

  /* matrix rows spanning both shards */
  void *mt = NULL;
  CHECK(new_matrix(client, mid, MROWS, MCOLS, &mt) == 0, "new matrix");
  int rows[3] = {1, 3, 6};
  float rdelta[3 * MCOLS], rgot[3 * MCOLS];
  for (int i = 0; i < 3 * MCOLS; ++i) rdelta[i] = (float)(i + 1);
  CHECK(matrix_add(mt, rdelta, rows, 3) == 0, "matrix add rows");
  CHECK(matrix_get(mt, rgot, rows, 3) == 0, "matrix get rows");
  for (int i = 0; i < 3 * MCOLS; ++i)
    CHECK(rgot[i] == rdelta[i] + 10.0f, "matrix value mismatch");

  /* kv: += merge on a hash-partitioned map; Python pre-added 1000 each */
  void *kt = NULL;
  CHECK(new_kv(client, kid, &kt) == 0, "new kv");
  long long keys[3] = {4, 7, 1000000007LL};
  long long vals[3] = {40, 70, 7};
  long long kgot[3] = {0, 0, 0};
  CHECK(kv_add(kt, keys, vals, 3) == 0, "kv add");
  CHECK(kv_get(kt, keys, kgot, 3) == 0, "kv get");
  CHECK(kgot[0] == 1040 && kgot[1] == 1070 && kgot[2] == 1007,
        "kv value mismatch");

  mv_close(client);
  printf("C_DEMO_OK\n");
  return 0;
}
