#!/usr/bin/env python
"""Parameter-server table tour: every table type, sync/async, checkpointing.

Run:  python examples/ps_tables_demo.py
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))



def main() -> int:
    from examples._backend import pin_backend
    pin_backend()
    import multiverso_tpu as mv
    from multiverso_tpu.core import checkpoint as ckpt
    from multiverso_tpu.parallel.async_engine import (AsyncTableEngine,
                                                      WorkerPool)

    mv.init([])
    try:
        print(f"runtime: {mv.num_servers()} server shards, "
              f"{mv.num_workers()} workers")

        # 1-D array table with the AdaGrad updater
        arr = mv.create_table(mv.ArrayTableOption(size=1000,
                                                  updater="adagrad"))
        arr.add(np.ones(1000, dtype=np.float32),
                mv.AddOption(learning_rate=0.1, rho=0.1))
        print("array[0:4] after one adagrad add:", arr.get()[:4])

        # row-sharded matrix, row-granular ops
        mat = mv.create_table(mv.MatrixTableOption(num_row=10_000,
                                                   num_col=64))
        rows = [5, 9_999]
        mat.add_rows(rows, np.ones((2, 64), dtype=np.float32))
        print("matrix rows touched:", mat.get_rows(rows)[:, 0])

        # async ASGD through the native staging buffer
        eng = AsyncTableEngine(arr, flush_pending=128)
        WorkerPool(8).run(
            lambda wid: [eng.add_async(np.full(1000, 0.001,
                                               dtype=np.float32))
                         for _ in range(100)])
        print("after 800 async adds, array[0] =", eng.get()[0])

        # KV table
        kv = mv.create_table(mv.KVTableOption())
        kv.add([42, 7], [1.0, 2.0])
        print("kv[42], kv[7] =", kv.get([42, 7]))

        # checkpoint / resume
        workdir = tempfile.mkdtemp(prefix="mv_ckpt_")
        path = ckpt.save_all(workdir, step=1)
        arr.add(np.full(1000, 100.0, dtype=np.float32))
        ckpt.load_all(path)
        print("after save -> clobber -> restore, array[0] =", arr.get()[0])

        # allreduce (model-average mode's aggregate)
        print("aggregate(ones) =", mv.aggregate(np.ones(4))[:2],
              f"(world size {mv.size()})")
        return 0
    finally:
        mv.shutdown()


if __name__ == "__main__":
    sys.exit(main())
