#!/usr/bin/env python
"""Two-process distributed word2vec over the DCN PS service.

Spawns two worker processes on this host; each owns half the embedding
tables, trains on half the corpus (pull-train-push), and the merged global
embeddings separate the corpus topics.

Run:  python examples/distributed_word2vec_demo.py
"""

import os
import subprocess
import sys
import tempfile

import numpy as np

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

WORKER = r"""
import os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")   # demo runs anywhere
import numpy as np
import multiverso_tpu as mv
from multiverso_tpu.models.word2vec import Dictionary, Word2VecConfig
from multiverso_tpu.models.word2vec.distributed import DistributedWord2Vec
from multiverso_tpu.parallel.ps_service import PSService

rank, workdir = int(sys.argv[1]), sys.argv[2]
mv.init([])
svc = PSService()
with open(os.path.join(workdir, f"addr{rank}"), "w") as f:
    f.write(f"{svc.address[0]}:{svc.address[1]}")
other = os.path.join(workdir, f"addr{1 - rank}")
while not os.path.exists(other):
    time.sleep(0.05)
host, port = open(other).read().split(":")
peers = [None, None]
peers[rank] = svc.address
peers[1 - rank] = (host, int(port))

sents = [l.split() for l in open(os.path.join(workdir, "corpus.txt"))]
d = Dictionary.build(sents, min_count=1)
ids = [d.encode(s) for s in sents][rank::2]     # my half of the corpus
cfg = Word2VecConfig(embedding_size=32, window=4, negative=5, min_count=1,
                     sample=0, epochs=3, learning_rate=0.1,
                     optimizer="adagrad", block_words=2000, pipeline=False)
w2v = DistributedWord2Vec(cfg, d, svc, peers, rank=rank)
stats = w2v.train(ids)
print(f"rank {rank}: {stats['words']} words "
      f"at {stats['words_per_sec']:.0f} words/sec", flush=True)

if rank == 0:
    emb = w2v.embeddings()
    emb = emb / (np.linalg.norm(emb, axis=1, keepdims=True) + 1e-12)
    for word in ("a0", "b0"):
        wid = d.word2id[word]
        sims = emb @ emb[wid]
        top = np.argsort(-sims)[1:4]
        print(f"  {word} -> " +
              ", ".join(f"{d.words[i]} ({sims[i]:.2f})" for i in top),
              flush=True)
# hold the service open until the peer finishes too
with open(os.path.join(workdir, f"done{rank}"), "w") as f:
    f.write("ok")
while not os.path.exists(os.path.join(workdir, f"done{1 - rank}")):
    time.sleep(0.05)
mv.shutdown()
"""


def main() -> int:
    workdir = tempfile.mkdtemp(prefix="dw2v_")
    rng = np.random.default_rng(0)
    with open(os.path.join(workdir, "corpus.txt"), "w") as f:
        for i in range(400):
            topic = "a" if i % 2 == 0 else "b"
            f.write(" ".join(f"{topic}{rng.integers(0, 5)}"
                             for _ in range(12)) + "\n")
    script = os.path.join(workdir, "worker.py")
    with open(script, "w") as f:
        f.write(WORKER)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen([sys.executable, script, str(r), workdir],
                              env=env) for r in range(2)]
    rc = 0
    for p in procs:
        p.wait(timeout=600)
        rc |= p.returncode
    return rc


if __name__ == "__main__":
    sys.exit(main())
