"""Shared demo helper: probe the attached accelerator, fall back to CPU."""

import os
import subprocess
import sys


def pin_backend(probe_timeout: float = 60) -> None:
    """Use the attached accelerator when it answers quickly; otherwise pin
    CPU so demos run anywhere (the tunneled chip can be down). Skips the
    probe subprocess entirely when the environment already pins CPU."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
        return
    try:
        ok = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp;"
             "jax.jit(lambda: jnp.ones(4).sum())()"],
            capture_output=True, timeout=probe_timeout).returncode == 0
    except subprocess.TimeoutExpired:
        ok = False
    if not ok:
        import jax
        jax.config.update("jax_platforms", "cpu")
        print("(accelerator unreachable -- running on CPU)")
